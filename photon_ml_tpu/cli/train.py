"""Core GLM training driver.

Rebuild of the reference's staged train pipeline (``Driver.scala:76-570``):
INIT (config + output guard + logger) -> PREPROCESSED (Avro ingest, feature
indexing, data validation, feature summarization, normalization) -> TRAINED
(descending-lambda sweep with warm starts) -> VALIDATED (named metrics per
lambda, best-model selection) -> model/text/summary outputs. Run as

    python -m photon_ml_tpu.cli.train --config params.json [--flag value ...]

or programmatically via :func:`run_glm_training`.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.cli.config import GLMDriverParams, load_params
from photon_ml_tpu.cli.stages import DriverStage, StageTracker
from photon_ml_tpu.core.tasks import TaskType
from photon_ml_tpu.core.types import LabeledBatch
from photon_ml_tpu.core.validators import DataValidationType, sanity_check_data
from photon_ml_tpu.io.avro import read_avro_dir, read_avro_file
from photon_ml_tpu.io.models import save_glm_model
from photon_ml_tpu.io.vocab import FeatureVocabulary
from photon_ml_tpu.models.selection import select_best_model
from photon_ml_tpu.models.training import TrainedModel, train_glm
from photon_ml_tpu.ops import metrics as metrics_mod
from photon_ml_tpu.ops.stats import summarize_features
from photon_ml_tpu.utils.dates import DateRange, expand_date_paths
from photon_ml_tpu.utils.logging import PhotonLogger, timed


def driver_dtype(precision: str):
    """float64 when requested AND enabled; float32 otherwise (no warnings)."""
    import jax
    import jax.numpy as jnp

    if precision == "float64" and jax.config.jax_enable_x64:
        return jnp.float64
    return jnp.float32


def read_records(paths: List[str]) -> List[dict]:
    """Read TrainingExampleAvro records from files and/or directories."""
    records: List[dict] = []
    for p in paths:
        if os.path.isdir(p):
            _, recs = read_avro_dir(p)
        else:
            _, recs = read_avro_file(p)
        records.extend(recs)
    if not records:
        raise ValueError(f"no records found in {paths}")
    return records


def _hybridize(batch, params, logger):
    """Split an ELL batch into the dense-hot + bucketed sparse-cold
    representation (``ops.sparse.HybridFeatures``): the power-law head
    rides the MXU, the tail keeps the scatter path at near-zero padding
    (docs/PERF.md sparse section). The batch's row-aligned fields are
    permuted to the hybrid's stored order (training is row-order
    invariant)."""
    import dataclasses as _dc

    from photon_ml_tpu.ops.sparse import stored_cold_entries, to_hybrid

    hf = to_hybrid(batch.features, hot_columns=params.hot_columns)
    perm = np.asarray(hf.row_perm)
    widths = [seg.nnz_per_row for seg in hf.cold_segments]
    logger.info(
        f"hybrid split: {hf.dense.shape[1]} hot columns densified, "
        f"{stored_cold_entries(hf)} entries stay sparse over "
        f"{len(widths)} row buckets (widths {widths})"
    )
    return _dc.replace(
        batch,
        features=hf,
        labels=batch.labels[perm],
        offsets=batch.offsets[perm],
        weights=batch.weights[perm],
        mask=batch.mask[perm],
    )


def resolve_date_range(params) -> Optional[DateRange]:
    if params.date_range:
        return DateRange.from_dates(params.date_range)
    if params.date_range_days_ago:
        return DateRange.from_days_ago(params.date_range_days_ago)
    return None


def prepare_output_dir(path: str, overwrite: bool) -> None:
    """Refuse a pre-existing output directory unless overwriting — the
    reference's guard rail (``Driver.scala:520-526``)."""
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(
                f"output dir {path} exists; pass overwrite to replace"
            )
    else:
        os.makedirs(path)


def write_model_text(
    path: str, means: np.ndarray, vocab: FeatureVocabulary
) -> None:
    """Plain-text model (``GLMSuite.writeModelsInText``,
    ``GLMSuite.scala:355-400``): one "name\\tterm\\tvalue" line per nonzero
    coefficient (intercept always written)."""
    with open(path, "w", encoding="utf-8") as f:
        for i, v in enumerate(np.asarray(means)):
            if v == 0.0 and i != vocab.intercept_index:
                continue
            name, term = vocab.name_term(i)
            f.write(f"{name}\t{term}\t{float(v)}\n")


def write_feature_summary(
    path: str, summary, vocab: FeatureVocabulary
) -> None:
    """Per-feature summary TSV (the reference writes a feature-summary
    output from the same statistics, ``GLMSuite.scala:402+``)."""
    cols = ("mean", "variance", "min", "max", "norm_l1", "norm_l2",
            "mean_abs", "num_nonzeros")
    arrays = {c: np.asarray(getattr(summary, c)) for c in cols}
    with open(path, "w", encoding="utf-8") as f:
        f.write("name\tterm\t" + "\t".join(cols) + "\n")
        for i in range(len(vocab)):
            name, term = vocab.name_term(i)
            f.write(
                f"{name}\t{term}\t"
                + "\t".join(str(float(arrays[c][i])) for c in cols)
                + "\n"
            )


@dataclasses.dataclass
class GLMTrainingRun:
    """Everything a caller (or test) needs to inspect a completed run."""

    params: GLMDriverParams
    stages: List[DriverStage]
    vocab: FeatureVocabulary
    models: List[TrainedModel]
    best: Optional[TrainedModel]
    best_index: Optional[int]
    # positional, aligned with `models` (duplicate lambdas stay distinct)
    validation_metrics: List[Dict[str, float]]
    num_training_rows: int
    num_features: int
    summary: object


def run_glm_training(params) -> GLMTrainingRun:
    """Entry point: config load + the observability envelope (span
    tracer, periodic metrics snapshots, profiler window) around the
    actual driver body."""
    from photon_ml_tpu import obs

    params = load_params(params, GLMDriverParams)
    params.validate()
    # the output-dir guard must fire BEFORE the observe() envelope: a
    # metrics.json path inside output_dir makes the envelope mkdir it,
    # which the guard would then misread as a pre-existing run
    prepare_output_dir(params.output_dir, params.overwrite)
    metrics_path = None
    if params.trace_dir is None and (
        params.metrics_every > 0 or params.convergence_report
    ):
        metrics_path = os.path.join(params.output_dir, "metrics.json")
    conv_tracker = None
    if params.convergence_report:
        # per-solve tape decode even without a tracer (obs.convergence);
        # the aggregated report lands next to the models below
        conv_tracker = obs.install_convergence_tracker()
    # multi-host resilience envelope (docs/MULTIHOST.md): a watchdog
    # deadline on every host collective (mesh solves globalize metadata
    # through allgather_host) and a pod heartbeat monitor feeding the
    # pod.heartbeat.* gauges + the watchdog's straggler attribution
    from photon_ml_tpu.parallel import (
        configure_collective_resilience,
        install_monitor,
    )
    from photon_ml_tpu.parallel.heartbeat import HeartbeatMonitor

    prev_resilience = configure_collective_resilience(
        timeout_s=params.collective_timeout_s
    )
    # collective strategy (docs/PARALLEL.md): the knob is trace-time
    # env state (ops.sparse reads it while building mesh reductions), so
    # the driver pins it process-wide before any solve traces
    if params.collective_mode is not None:
        from photon_ml_tpu.parallel.overlap import COLLECTIVE_MODE_ENV

        os.environ[COLLECTIVE_MODE_ENV] = params.collective_mode
    monitor = None
    if params.heartbeat_s > 0:
        monitor = HeartbeatMonitor(interval_s=params.heartbeat_s).start()
        install_monitor(monitor)
    try:
        with obs.observe(
            trace_dir=params.trace_dir,
            metrics_path=metrics_path,
            metrics_every=params.metrics_every,
            profile_dir=params.profile_dir,
            hbm_every_s=params.hbm_every,
            process_name="photon_ml_tpu.train",
            flight_dir=params.flight_dir,
        ):
            return _run_glm_training(params)
    finally:
        if params.quality_fingerprint:
            # idempotent: normally uninstalled right after train ingest;
            # this covers the ingest-raised path so no collector leaks
            # into the next in-process run
            obs.quality.uninstall_fingerprint_collector()
        configure_collective_resilience(
            prev_resilience.timeout_s, prev_resilience.retries
        )
        if monitor is not None:
            install_monitor(None)
            monitor.stop()
        if conv_tracker is not None:
            try:
                conv_tracker.dump(
                    os.path.join(
                        params.output_dir, "convergence-report.json"
                    )
                )
            except OSError:
                pass
            obs.uninstall_convergence_tracker()


def _run_glm_training(params: GLMDriverParams) -> GLMTrainingRun:
    # output dir already prepared by run_glm_training (before the
    # observe envelope could create it)
    tracker = StageTracker()
    logger = PhotonLogger(
        os.path.join(params.output_dir, "log-message.txt"),
        level=params.log_level,
    )
    logger.info(f"GLM training driver: task={params.task} "
                f"optimizer={params.optimizer} reg={params.reg_type} "
                f"lambdas={params.reg_weights}")

    # ---- PREPROCESS ------------------------------------------------------
    with timed(logger, "preprocess"):
        from photon_ml_tpu.io.ingest import IngestSource

        date_range = resolve_date_range(params)
        train_paths = expand_date_paths(params.train_input, date_range)
        source = IngestSource(train_paths, params.field_names)

        if params.feature_file:
            vocab = FeatureVocabulary.load(params.feature_file)
        else:
            vocab = source.build_vocab(add_intercept=params.add_intercept)
        logger.info(f"feature space: {len(vocab)} columns "
                    f"(intercept={vocab.intercept_index})")

        # quality fingerprint: the io paths feed the installed collector
        # per ingest chunk (docs/OBSERVABILITY.md "Quality & drift");
        # installed for the TRAIN ingest only — validation rows are a
        # different distribution and must not blur the baseline
        from photon_ml_tpu.obs import quality as quality_mod

        fingerprint = None
        if params.quality_fingerprint:
            fingerprint = quality_mod.install_fingerprint_collector()

        task = TaskType[params.task]
        batch = None
        design = None
        summary = None
        if params.out_of_core:
            # decode + stage ONCE into host-resident uniform chunks;
            # every objective pass will stream them (docs/INGEST.md)
            from photon_ml_tpu.io.pipeline import (
                IngestPipeline,
                PipelineConfig,
                StreamedDesign,
            )

            with IngestPipeline(
                source.files,
                [vocab],
                label_field=source.label_field,
                config=PipelineConfig(
                    chunk_mb=params.ingest_chunk_mb,
                    decode_threads=params.decode_threads,
                    prefetch_depth=params.prefetch_depth,
                    stage_timeout_s=params.stage_timeout_s or None,
                    epoch_policy=params.epoch_policy,
                ),
            ) as pipe:
                design = StreamedDesign.from_pipeline(
                    pipe,
                    dtype=np.dtype(driver_dtype(params.precision)),
                )
            logger.info(
                f"out-of-core design: {design.n} rows x {design.d} "
                f"columns in {design.num_chunks} chunks of "
                f"{design.rows_per_chunk} rows "
                f"({design.bytes_per_epoch / 1e9:.2f} GB/epoch streamed); "
                "sanity checks and the feature summary need the in-core "
                "batch and are skipped"
            )
        elif params.streamed_ingest:
            if params.sparse:
                raise ValueError(
                    "streamed_ingest is dense-only (padded-ELL width is "
                    "a global property; decode sparse inputs whole)"
                )
            batch, _uids, _present = source.labeled_batch_streamed(
                vocab,
                dtype=driver_dtype(params.precision),
                chunk_mb=params.ingest_chunk_mb,
                decode_threads=params.decode_threads,
                prefetch_depth=params.prefetch_depth,
                stage_timeout_s=params.stage_timeout_s,
                epoch_policy=params.epoch_policy,
            )
        else:
            batch, _uids, _present = source.labeled_batch(
                vocab, sparse=params.sparse,
                dtype=driver_dtype(params.precision),
            )
        if batch is not None:
            logger.info(f"read {batch.labels.shape[0]} training records")
            if params.sparse and params.hot_columns:
                batch = _hybridize(batch, params, logger)
            sanity_check_data(
                batch, task, DataValidationType[params.data_validation]
            )
            summary = summarize_features(batch)
            write_feature_summary(
                os.path.join(params.output_dir, "feature-summary.tsv"),
                summary,
                vocab,
            )
        if fingerprint is not None:
            # train ingest is done — stop collecting (validation ingest
            # below must not enter the baseline)
            quality_mod.uninstall_fingerprint_collector()
            logger.info(
                f"quality fingerprint: {fingerprint.rows} rows sketched"
            )
    tracker.advance(DriverStage.PREPROCESSED)

    # ---- TRAIN -----------------------------------------------------------
    tracker.assert_at_least(DriverStage.PREPROCESSED)
    from photon_ml_tpu.utils.debug import debug_nans, profile_trace

    with timed(logger, "train"), profile_trace(
        os.path.join(params.output_dir, "profile") if params.profile else None
    ), debug_nans(params.debug_nans):
        cfg = dataclasses.replace(
            params.to_training_config(),
            intercept_index=vocab.intercept_index,
        )
        if params.constraint_file:
            from photon_ml_tpu.io.constraints import load_constraint_bounds

            lb, ub = load_constraint_bounds(params.constraint_file, vocab)
            cfg = dataclasses.replace(
                cfg, lower_bounds=lb, upper_bounds=ub
            )
        initial = None
        if params.initial_model_dir:
            from photon_ml_tpu.io.models import load_glm_model

            init_path = params.initial_model_dir
            if os.path.isdir(init_path):
                best = os.path.join(init_path, "best-model.avro")
                if os.path.exists(best):
                    init_path = best
                else:
                    # no-validation runs write only models/; accept a sole
                    # model there, refuse ambiguity (like cli/score.py)
                    mdir = os.path.join(init_path, "models")
                    candidates = (
                        sorted(
                            f for f in os.listdir(mdir)
                            if f.endswith(".avro")
                        )
                        if os.path.isdir(mdir)
                        else []
                    )
                    if len(candidates) != 1:
                        raise FileNotFoundError(
                            f"no best-model.avro in {init_path} and "
                            f"{len(candidates)} candidates in models/ — "
                            "point initial_model_dir at a specific .avro"
                        )
                    init_path = os.path.join(mdir, candidates[0])
            # coefficients remap by (name, term), so a drifted vocabulary
            # still warm-starts correctly (unknown features drop, new
            # features start at 0)
            initial, _ = load_glm_model(init_path, vocab)
            logger.info(f"warm-starting from {init_path}")
        if design is not None:
            # out-of-core: every objective pass streams the host chunks
            # through the fused per-chunk programs; the unmodified
            # TRON/LBFGS loops see the exact full-dataset objective
            from photon_ml_tpu.models.training import train_glm_streamed

            logger.info(
                f"out-of-core solve over {design.num_chunks} streamed "
                "chunks"
            )
            models = list(
                train_glm_streamed(
                    design, cfg, initial_coefficients=initial
                )
            )
        elif params.mesh_shape:
            # mesh-sharded solve: 'data' row-shards (GSPMD psum), adding
            # 'feature' also shards the coefficient axis (huge-d regime);
            # device-count validation lives in the mesh constructors
            from photon_ml_tpu.parallel import (
                distributed_train_glm,
                feature_sharded_train_glm,
                make_feature_mesh,
                make_mesh,
            )

            n_data = params.mesh_shape.get("data", 1)
            n_feat = params.mesh_shape.get("feature", 1)
            logger.info(f"mesh solve over {params.mesh_shape}")
            if n_feat > 1:
                models = list(
                    feature_sharded_train_glm(
                        batch,
                        cfg,
                        make_feature_mesh(n_data, n_feat),
                        initial_coefficients=initial,
                    )
                )
            else:
                models = list(
                    distributed_train_glm(
                        batch,
                        cfg,
                        make_mesh(n_data),
                        initial_coefficients=initial,
                    )
                )
        else:
            models = list(
                train_glm(batch, cfg, initial_coefficients=initial)
            )
        for tm in models:
            logger.info(
                f"lambda={tm.reg_weight}: iters={int(tm.result.iterations)} "
                f"value={float(tm.result.value):.6g}"
            )
    tracker.advance(DriverStage.TRAINED)

    # ---- VALIDATE --------------------------------------------------------
    best = None
    best_index = None
    validation_metrics: List[Dict[str, float]] = []
    if params.validate_input:
        tracker.assert_at_least(DriverStage.TRAINED)
        with timed(logger, "validate"):
            vbatch, _vuids, _vpresent = IngestSource(
                expand_date_paths(params.validate_input, date_range),
                params.field_names,
            ).labeled_batch(
                vocab, sparse=params.sparse,
                dtype=driver_dtype(params.precision),
            )
            if params.sparse and params.hot_columns:
                vbatch = _hybridize(vbatch, params, logger)
            for tm in models:
                margins = tm.model.compute_margin(
                    vbatch.features, vbatch.offsets
                )
                validation_metrics.append(
                    metrics_mod.evaluate(
                        task,
                        vbatch.labels,
                        margins,
                        vbatch.effective_weights(),
                    )
                )
            best, _scores = select_best_model(models, vbatch)
            best_index = next(
                i for i, tm in enumerate(models) if tm is best
            )
            logger.info(
                f"best lambda={best.reg_weight} (model #{best_index}, "
                f"metrics={validation_metrics[best_index]})"
            )
            if params.validate_per_iteration:
                # ModelTracker snapshots -> per-iteration validation
                # metrics (``Driver.scala:293-347``)
                per_iter: Dict[str, List[Dict[str, float]]] = {}
                for i, tm in enumerate(models):
                    if tm.result.w_history is None:
                        continue
                    # masked_history truncates the ModelTracker buffer
                    # past `iterations` (the entries-are-garbage
                    # contract, solvers/common.SolverResult)
                    hist = tm.result.masked_history()[2]
                    rows = []
                    for it in range(hist.shape[0]):
                        margins = (
                            vbatch.features @ hist[it] + vbatch.offsets
                        )
                        m = metrics_mod.evaluate(
                            task,
                            vbatch.labels,
                            margins,
                            vbatch.effective_weights(),
                        )
                        rows.append(m)
                        logger.info(
                            f"lambda={tm.reg_weight} iteration={it}: {m}"
                        )
                    per_iter[f"{i}_lambda_{tm.reg_weight:g}"] = rows
                with open(
                    os.path.join(
                        params.output_dir, "per-iteration-metrics.json"
                    ),
                    "w",
                ) as f:
                    json.dump(per_iter, f, indent=2)
        tracker.advance(DriverStage.VALIDATED)

    # ---- DIAGNOSE (``Driver.scala:424-474``) -----------------------------
    if params.diagnostics:
        tracker.assert_at_least(DriverStage.VALIDATED)
        with timed(logger, "diagnose"):
            from photon_ml_tpu.diagnostics.driver import (
                build_diagnostic_report,
            )
            from photon_ml_tpu.diagnostics.html import render_html

            report = build_diagnostic_report(
                params_dict=dataclasses.asdict(params),
                models=models,
                validation_metrics=validation_metrics,
                train_batch=batch,
                validation_batch=vbatch,
                vocab=vocab,
                summary=summary,
                training_config=cfg,
                training_diagnostics=params.training_diagnostics,
            )
            report_path = os.path.join(
                params.output_dir, "model-diagnostic.html"
            )
            with open(report_path, "w", encoding="utf-8") as f:
                f.write(render_html(report))
            logger.info(f"wrote diagnostic report to {report_path}")
        tracker.advance(DriverStage.DIAGNOSED)

    # ---- OUTPUT ----------------------------------------------------------
    with timed(logger, "write models"):
        if fingerprint is not None and fingerprint.rows > 0:
            # margin sketch: the shipped model's score distribution on
            # its own training data — what the serving DriftMonitor
            # compares live score distributions against. In-core only
            # (the out-of-core design holds no host batch to score).
            if batch is not None and models:
                chosen = best if best is not None else models[0]
                margins = chosen.model.compute_margin(
                    batch.features, batch.offsets
                )
                fingerprint.observe_margins(
                    np.asarray(margins),
                    np.asarray(batch.effective_weights()),
                )
            fp_path = fingerprint.save(params.output_dir)
            logger.info(f"wrote quality fingerprint to {fp_path}")
        vocab.save(os.path.join(params.output_dir, "feature-index.txt"))
        if params.model_output_mode != "NONE":
            to_write = (
                [best]
                if params.model_output_mode == "BEST" and best is not None
                else models
            )
            mdir = os.path.join(params.output_dir, "models")
            os.makedirs(mdir, exist_ok=True)
            for i, tm in enumerate(to_write):
                stem = os.path.join(mdir, f"{i}_lambda_{tm.reg_weight:g}")
                save_glm_model(
                    stem + ".avro", tm.model.coefficients, vocab, task
                )
                write_model_text(
                    stem + ".txt", tm.model.coefficients.means, vocab
                )
            if best is not None:
                save_glm_model(
                    os.path.join(params.output_dir, "best-model.avro"),
                    best.model.coefficients,
                    vocab,
                    task,
                )
        if validation_metrics:
            with open(
                os.path.join(params.output_dir, "validation-metrics.json"), "w"
            ) as f:
                json.dump(
                    {
                        f"{i}_lambda_{tm.reg_weight:g}": m
                        for i, (tm, m) in enumerate(
                            zip(models, validation_metrics)
                        )
                    },
                    f,
                    indent=2,
                )
    logger.close()

    return GLMTrainingRun(
        params=params,
        stages=tracker.history,
        vocab=vocab,
        models=models,
        best=best,
        best_index=best_index,
        validation_metrics=validation_metrics,
        num_training_rows=(
            design.n if design is not None else int(batch.labels.shape[0])
        ),
        num_features=len(vocab),
        summary=summary,
    )


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli.train",
        description="Train GLMs (logistic/linear/Poisson/smoothed-hinge) "
        "over a regularization path.",
    )
    p.add_argument("--config", help="JSON file of GLMDriverParams")
    p.add_argument("--train-input", nargs="+")
    p.add_argument("--validate-input", nargs="+")
    p.add_argument("--output-dir")
    p.add_argument("--task")
    p.add_argument("--optimizer")
    p.add_argument("--reg-type")
    p.add_argument("--reg-weights", nargs="+", type=float)
    p.add_argument("--normalization")
    p.add_argument("--max-iters", type=int)
    p.add_argument("--tolerance", type=float)
    p.add_argument("--sparse", action="store_true", default=None)
    p.add_argument(
        "--hot-columns", type=int, default=None,
        help="with --sparse: densify the N hottest columns (-1 = auto)",
    )
    p.add_argument(
        "--streamed-ingest", action="store_true", default=None,
        help="stream the dense dataset to the device through the ingest "
        "pipeline (parallel decode, ring staging, async prefetch; host "
        "memory stays the staging ring — docs/INGEST.md)",
    )
    p.add_argument(
        "--out-of-core", action="store_true", default=None,
        help="out-of-core training: keep the design host-side in "
        "uniform chunks and stream every objective pass through the "
        "fused per-chunk programs (exact full-dataset objective; "
        "TRON/LBFGS, normalization NONE — docs/INGEST.md)",
    )
    p.add_argument(
        "--ingest-chunk-mb", type=float, default=None,
        help="ingest pipeline: target decoded-chunk size in MB (file-"
        "group planning + uniform staged row blocks; default 64)",
    )
    p.add_argument(
        "--decode-threads", type=int, default=None,
        help="ingest pipeline: concurrent decode workers (0 = auto; "
        "PHOTON_DECODE_THREADS override honored)",
    )
    p.add_argument(
        "--prefetch-depth", type=int, default=None,
        help="ingest pipeline: chunks decode/staging may run ahead of "
        "the consumer; also sizes the staging ring (default 2)",
    )
    p.add_argument(
        "--stage-timeout-s", type=float, default=None,
        help="ingest pipeline watchdog: a decode/stage/transfer attempt "
        "stalled past this many seconds is cancelled and re-run through "
        "the retry seam (default: off — docs/ROBUSTNESS.md)",
    )
    p.add_argument(
        "--epoch-policy", choices=["fail", "skip"], default=None,
        help="what an exhausted ingest retry budget does to the epoch: "
        "fail (default) raises; skip logs+counts the lost group and "
        "continues with fewer rows",
    )
    p.add_argument("--overwrite", action="store_true", default=None)
    p.add_argument("--diagnostics", action="store_true", default=None)
    p.add_argument(
        "--training-diagnostics", action="store_true", default=None
    )
    p.add_argument("--profile", action="store_true", default=None)
    p.add_argument("--debug-nans", action="store_true", default=None)
    p.add_argument(
        "--trace-dir", default=None,
        help="emit a Chrome trace-event JSON + events.jsonl + metrics.json "
        "under this directory (docs/OBSERVABILITY.md)",
    )
    p.add_argument(
        "--metrics-every", type=float, default=None,
        help="seconds between periodic metrics.json registry snapshots "
        "(0 = final snapshot only)",
    )
    p.add_argument(
        "--profile-dir", default=None,
        help="capture a jax.profiler trace of the WHOLE run here "
        "(--profile captures only the train phase)",
    )
    p.add_argument(
        "--hbm-every", type=float, default=None,
        help="seconds between live HBM counter-track samples while "
        "tracing (0 disables; no-op without device memory stats)",
    )
    p.add_argument(
        "--flight-dir", default=None,
        help="crash flight recorder output directory: flight-<reason>"
        ".json dumps on preemption/crash (default: --trace-dir)",
    )
    p.add_argument(
        "--convergence-report", action="store_true", default=None,
        help="decode each solve's device-side tapes (reason / rate / "
        "plateau / per-iteration curves) into convergence.* metrics + "
        "events and <output-dir>/convergence-report.json",
    )
    p.add_argument(
        "--path-mode", choices=("scan", "loop"), default=None,
        help="regularization-path execution: 'scan' (default) runs the "
        "whole descending-lambda path as ONE device-resident dispatch; "
        "'loop' keeps the host loop of one dispatch per lambda",
    )
    p.add_argument(
        "--heartbeat-s", type=float, default=None,
        help="pod heartbeat interval in seconds (0 = off): feeds the "
        "pod.heartbeat.* liveness gauges and the collective watchdog's "
        "straggler attribution (docs/MULTIHOST.md)",
    )
    p.add_argument(
        "--collective-timeout-s", type=float, default=None,
        help="watchdog deadline on host-side collectives: a stalled "
        "exchange times out, retries with backoff, and emits straggler "
        "attribution instead of wedging the pod (default: no watchdog)",
    )
    p.add_argument(
        "--no-quality-fingerprint", dest="quality_fingerprint",
        action="store_false", default=None,
        help="skip the train-data quality fingerprint (per-feature/"
        "label/margin sketches written to quality-fingerprint.json; "
        "the drift-detection baseline — docs/OBSERVABILITY.md)",
    )
    p.add_argument(
        "--sharded-ckpt", action="store_true", default=None,
        help="per-process sharded checkpoint writes for any durability "
        "point this driver reaches (parity with game_train; the GLM "
        "path itself has no mid-run checkpoint cadence yet — "
        "docs/MULTIHOST.md)",
    )
    p.add_argument(
        "--collective-mode", dest="collective_mode",
        choices=("fused", "overlap"), default=None,
        help="collective reduction strategy for mesh solves "
        "(docs/PARALLEL.md): 'overlap' (default) row-balances blocked "
        "sparse designs and chunks the feature-space reduction into a "
        "reduce-scatter/all-gather pipeline that hides under the next "
        "row block's compute; 'fused' pins the single trailing "
        "all-reduce — the equivalence oracle",
    )
    p.add_argument(
        "--warm-from-watch-root", default=None, metavar="DIR",
        help="lifecycle warm start: resolve initial_model_dir to the "
        "newest manifest-bearing export under this serving watch root "
        "(the descending-lambda path then warm-starts from whatever "
        "is live — docs/LIFECYCLE.md; photon-retrain drives this "
        "automatically)",
    )
    return p


def params_from_args(args, cls) -> dict:
    base = {}
    if args.config:
        with open(args.config) as f:
            base = json.load(f)
    for key, value in vars(args).items():
        if key in ("config", "warm_from_watch_root") or value is None:
            continue
        base[key] = value
    warm_root = getattr(args, "warm_from_watch_root", None)
    if warm_root is not None:
        from photon_ml_tpu.lifecycle.orchestrator import (
            latest_version_dir,
        )

        warm = latest_version_dir(warm_root)
        if warm is None:
            raise ValueError(
                "--warm-from-watch-root: no manifest-bearing export "
                f"under {warm_root}"
            )
        base["initial_model_dir"] = warm
    return base


def main(argv=None) -> None:
    args = build_arg_parser().parse_args(argv)
    # after parse_args: --help / bad flags must not initialize the
    # accelerator backend or touch the cache directory
    from photon_ml_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    try:
        run_glm_training(params_from_args(args, GLMDriverParams))
    except BaseException as e:
        import sys

        from photon_ml_tpu.resilience import (
            HOST_LOSS_EXIT_CODE,
            is_host_loss,
        )

        # distinct exit contract: a dead peer (collective timeout past
        # its retry budget, heartbeat loss) means "restart me", not
        # "my code failed" (docs/MULTIHOST.md)
        if is_host_loss(e):
            print(
                f"host loss: {e} — exiting {HOST_LOSS_EXIT_CODE}",
                file=sys.stderr,
            )
            sys.exit(HOST_LOSS_EXIT_CODE)
        raise


if __name__ == "__main__":
    main()
