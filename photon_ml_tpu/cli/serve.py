"""Online scoring server: resident GAME engine behind a JSON-lines protocol.

The batch drivers (``cli/score.py``) load, score once, and exit; this
entrypoint keeps the model device-resident and answers requests as they
arrive, micro-batched (docs/SERVING.md). Run over stdin/stdout

    python -m photon_ml_tpu.cli.serve --model-dir out/game

or as a TCP socket server (one JSON object per line per connection):

    python -m photon_ml_tpu.cli.serve --model-dir out/game --socket 7474

or behind the production front end — async multiplexed connections,
multi-tenant admission, optional engine replication (docs/FRONTEND.md):

    python -m photon_ml_tpu.cli.serve --model-dir out/game \\
        --frontend-port 7575 --replicas 2 \\
        --tenant '{"name": "gold", "priority": 2, "quota": 256}' \\
        --tenant '{"name": "free", "priority": 0, "quota": 64}'

In frontend mode this protocol is the COMPAT ADMIN CHANNEL: the same
``{"cmd": ...}`` commands answer on stdin/--socket AND as passthrough
frames on the front end itself, plus ``{"cmd": "tenants"}`` (per-tenant
policy/accounting/SLO) and ``{"cmd": "replicas"}`` (per-replica
breaker/failover state); scoring lines on the compat channel ride the
shared tenant queue under the default tenant's policy.

Protocol (one JSON object per line):

    {"features": {"age": 0.7, "ctr\\u0001day7": 1.2},
     "entities": {"userId": "u123"}, "offset": 0.0,
     "deadline_ms": 50, "priority": 1}
        -> {"score": 1.234}
    {"cmd": "stats"}    -> latency/QPS/bucket snapshot (serving/stats.py)
    {"cmd": "metrics"}  -> {"prometheus": "<text exposition>"} — the full
                           metrics registry (docs/OBSERVABILITY.md)
    {"cmd": "slo"}      -> rolling-window p99 + error-budget snapshot
                           (serving.stats.SloTracker; --slo-p99-ms)
    {"cmd": "health"}   -> queue/shed/degraded state + the reload circuit
                           breaker snapshot (docs/ROBUSTNESS.md)
    {"cmd": "version"}  -> {"version": "<current model version>"}
    {"cmd": "reload", "path": "<export dir>"} -> {"reloaded": "<version>"}
                           (an explicit reload bypasses the breaker's
                           quarantine — the operator asked)
    {"cmd": "feedback", "label": 1, "score": 1.234, "weight": 1.0}
                        -> {"ok": true, "window_n": N} — the delayed-
                           label protocol: the client echoes the score
                           it was served once the true label arrives,
                           feeding rolling-window online AUC/calibration
                           gauges (quality.*; obs.quality.OnlineQuality)
    {"cmd": "quality"}  -> online-quality snapshot (window AUC — exact,
                           equal to the ops.metrics replay — plus
                           calibration error and window counts)
    {"cmd": "drift"}    -> current model's DriftMonitor snapshot (live
                           PSI vs the export's train-time baseline
                           fingerprint; docs/OBSERVABILITY.md)
    {"cmd": "exemplars"} -> tail-sampled exemplar rings: latency-bucket
                           -> recent trace ids for `photon-obs request`
                           (optional "ge_ms"/"class" filters;
                           obs/exemplars.py, --exemplar-fraction)

``deadline_ms`` (per request, or ``--default-deadline-ms``) drops a
request that can't start scoring in time — the Future answers
``{"error": ...}`` and no device work is burned; ``priority`` lets an
important request shed the oldest lower-priority queued one when the
bounded queue is full. Under sustained queue pressure the batcher
degrades to fixed-effect-only scoring (``--no-degrade`` disables).

``--serving-shards P`` serves through the entity-sharded engine (RE
tables mesh-partitioned over P devices by the sharded-checkpoint
ownership rule, shard-routed micro-batches, zero cross-shard
collectives); ``--hbm-cache-entities N`` serves through the tiered
HBM/host entity cache (hot Zipf head in HBM, misses score
fixed-effect-only while async promotion runs) — docs/SERVING.md.

Unknown feature keys are ignored per shard vocabulary (ingest semantics);
unknown entity ids score fixed-effect-only (cold start). SIGTERM/SIGINT
drain the micro-batcher — accepted requests finish, new ones are refused —
via the ``GracefulShutdown.register_drain`` hook; a FAILED drain logs the
undrained depth and exits nonzero so orchestrators see the dropped work.
With ``--watch-root``, new verified model exports under the directory
hot-reload automatically; exports that keep failing to load are
quarantined by the reload circuit breaker (backoff probes re-admit them)
while the last good version keeps serving.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
from typing import Optional

from photon_ml_tpu.serving.batcher import Backpressure, MicroBatcher
from photon_ml_tpu.serving.engine import ScoreRequest
from photon_ml_tpu.serving.registry import ModelRegistry
from photon_ml_tpu.serving.stats import ServingStats, SloTracker


def build_request(obj: dict) -> ScoreRequest:
    if not isinstance(obj, dict):
        raise ValueError(f"request must be a JSON object, got {type(obj)}")
    features = obj.get("features", {})
    if not isinstance(features, dict):
        raise ValueError("'features' must be an object of key -> value")
    return ScoreRequest(
        features=features,
        entities=obj.get("entities", {}),
        offset=float(obj.get("offset", 0.0)),
    )


def make_admin_handler(
    batcher,
    registry: Optional[ModelRegistry] = None,
    stats: Optional[ServingStats] = None,
    quality=None,
    tenants=None,
    replicas=None,
):
    """One ``{"cmd": ...} -> dict`` dispatcher shared by every channel:
    the original JSON-lines protocol (stdin and ``--socket``) and the
    async front end's admin passthrough — the old protocol IS the compat
    admin channel, so an operator's runbook works against either port.
    ``tenants`` (a :class:`~photon_ml_tpu.frontend.tenants.
    TenantManager`) adds ``{"cmd": "tenants"}``: per-tenant policy/
    accounting/SLO + the shared queue and compile ladder; ``replicas``
    (``{tenant: ReplicaRouter}``) adds ``{"cmd": "replicas"}``: per-
    replica breaker/outstanding/failover state."""

    def handle(obj: dict) -> dict:
        cmd = obj.get("cmd")
        try:
            if cmd == "stats":
                return (stats or batcher.stats).snapshot()
            if cmd == "metrics":
                # Prometheus text exposition of the serving registry
                # PLUS the process-default registry (solver/io/
                # resilience counters), so one scrape sees the whole
                # process (docs/OBSERVABILITY.md)
                from photon_ml_tpu import obs

                st = stats or batcher.stats
                text = st.registry.to_prometheus()
                if st.registry is not obs.registry():
                    text += obs.registry().to_prometheus()
                return {"prometheus": text}
            if cmd == "slo":
                slo = getattr(batcher, "slo", None)
                if slo is None:
                    return {"error": "no SLO tracker configured"}
                return slo.snapshot()
            if cmd == "health":
                # breaker/shed/queue state in one reply — the
                # orchestration probe (readiness, alerting)
                health = dict(batcher.health())
                if registry is not None:
                    health.update(registry.health())
                return health
            if cmd == "tenants":
                if tenants is None:
                    return {"error": "not serving multi-tenant"}
                return tenants.snapshot()
            if cmd == "replicas":
                if not replicas:
                    return {"error": "not serving replicated"}
                return {
                    name: router.health()
                    for name, router in replicas.items()
                }
            if cmd == "feedback":
                # delayed-label loop (docs/OBSERVABILITY.md "Quality &
                # drift"): the client echoes the served score once the
                # true label arrives
                if quality is None:
                    return {"error": "no online-quality tracker"}
                quality.record(
                    float(obj["label"]),
                    float(obj["score"]),
                    float(obj.get("weight", 1.0)),
                )
                return {"ok": True, "window_n": quality.window_n}
            if cmd == "quality":
                if quality is None:
                    return {"error": "no online-quality tracker"}
                return quality.snapshot()
            if cmd == "drift":
                v = registry.current if registry is not None else None
                monitor = (
                    getattr(v.engine, "drift", None)
                    if v is not None and v.engine is not None
                    else None
                )
                if monitor is None:
                    return {
                        "error": "no drift monitor (export has no "
                        "quality fingerprint)"
                    }
                return monitor.snapshot()
            if cmd == "exemplars":
                # tail-sampled exemplar rings (obs/exemplars.py): a
                # latency-histogram bucket resolves to live trace ids
                # for `photon-obs request`; optional "ge_ms" / "class"
                # narrow the lookup
                from photon_ml_tpu.obs import exemplars as _exemplars

                st = _exemplars.store()
                if st is None:
                    return {"error": "no exemplar store installed"}
                if obj.get("ge_ms") is not None or obj.get("class"):
                    return {
                        "exemplars": st.lookup(
                            ge_ms=obj.get("ge_ms"),
                            cls=obj.get("class"),
                        )
                    }
                return st.snapshot()
            if cmd == "version":
                return {"version": registry.version()}
            if cmd == "reload":
                # operator-explicit: bypass breaker quarantine
                v = registry.load(obj["path"], force=True)
                return {"reloaded": v.version_id}
            return {"error": f"unknown cmd {cmd!r}"}
        except Exception as e:  # noqa: BLE001 — keep serving
            return {"error": str(e)}

    return handle


def serve_lines(
    lines,
    out,
    batcher: MicroBatcher,
    registry: Optional[ModelRegistry] = None,
    stats: Optional[ServingStats] = None,
    shutdown=None,
    window: int = 128,
    default_deadline_ms: Optional[float] = None,
    quality=None,
    tenants=None,
    replicas=None,
) -> int:
    """Pump a JSON-lines stream through the batcher, writing one response
    line per request IN ORDER. A dedicated writer thread emits each
    response as soon as its (in-order) future resolves, so an interactive
    client gets its score promptly while a pipelining client can keep up
    to ``window`` requests outstanding (which is what fills micro-batches
    from a single stream). Commands execute at read time; their replies
    take their place in the output order. Returns the number of scored
    requests."""
    import queue as queue_mod

    outbox: "queue_mod.Queue" = queue_mod.Queue(maxsize=window)
    scored = [0]

    def writer() -> None:
        broken = False
        while True:
            item = outbox.get()
            if item is None:
                return
            kind, payload = item
            if kind == "score":
                try:
                    reply = json.dumps({"score": payload.result()})
                    scored[0] += 1
                except Exception as e:  # noqa: BLE001 — per-request reply
                    reply = json.dumps({"error": str(e)})
            else:
                reply = payload
            if broken:
                continue  # output gone: keep draining so readers don't block
            try:
                out.write(reply + "\n")
                out.flush()
            except Exception:  # noqa: BLE001 — e.g. client hung up
                broken = True

    wt = threading.Thread(target=writer, name="serve-writer", daemon=True)
    wt.start()

    def reply_now(obj: dict) -> None:
        outbox.put(("line", json.dumps(obj)))

    handle_cmd = make_admin_handler(
        batcher, registry, stats, quality=quality, tenants=tenants,
        replicas=replicas,
    )

    try:
        for line in lines:
            if shutdown is not None and shutdown.requested:
                break
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                reply_now({"error": f"bad JSON: {e}"})
                continue
            cmd = obj.get("cmd") if isinstance(obj, dict) else None
            if cmd is not None:
                reply_now(handle_cmd(obj))
                continue
            try:
                deadline_ms = obj.get("deadline_ms", default_deadline_ms)
                outbox.put(
                    (
                        "score",
                        batcher.submit(
                            build_request(obj),
                            deadline_ms=(
                                float(deadline_ms)
                                if deadline_ms is not None
                                else None
                            ),
                            priority=int(obj.get("priority", 0)),
                        ),
                    )
                )
            except (Backpressure, ValueError, TypeError) as e:
                reply_now({"error": str(e)})
    finally:
        outbox.put(None)
        wt.join()
    return scored[0]


class _CompatBatcher:
    """Batcher-shaped adapter over a TenantManager: the old per-line
    protocol (stdin / ``--socket``) keeps scoring in frontend mode, but
    through the SHARED tenant queue under ``tenant``'s policy — one
    admission control for both channels, not a side door around it."""

    def __init__(self, tm, tenant: str):
        self._tm = tm
        self.tenant = tenant
        self.stats = tm.stats
        self.slo = tm.batcher.slo

    def submit(self, request, *, deadline_ms=None, priority=None):
        return self._tm.submit(
            self.tenant, request,
            deadline_ms=deadline_ms, priority=priority,
        )

    def health(self):
        return self._tm.batcher.health()

    def queue_depth(self):
        return self._tm.batcher.queue_depth()

    def begin_drain(self):
        self._tm.begin_drain()

    def drain(self, timeout=30.0):
        return self._tm.drain(timeout)


def _watch_loop(registry, watch_root, poll_s, shutdown, logger):
    while not shutdown.requested:
        try:
            loaded = registry.poll(watch_root)
            if loaded is not None and logger is not None:
                logger.info(f"hot-reloaded version {loaded!r}")
        except Exception as e:  # noqa: BLE001 — watcher must survive
            if logger is not None:
                logger.warn(f"watch poll failed: {e}")
        shutdown._event.wait(poll_s)


def _serve_socket(
    port, batcher, registry, stats, shutdown, logger,
    default_deadline_ms=None, quality=None, tenants=None, replicas=None,
):
    import socketserver

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            lines = (raw.decode("utf-8") for raw in self.rfile)

            class _W:  # text adapter over the binary wfile
                def write(inner, s):
                    self.wfile.write(s.encode("utf-8"))

                def flush(inner):
                    pass

            serve_lines(
                lines, _W(), batcher, registry, stats, shutdown=shutdown,
                default_deadline_ms=default_deadline_ms,
                quality=quality, tenants=tenants, replicas=replicas,
            )

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with Server(("127.0.0.1", port), Handler) as server:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        if logger is not None:
            logger.info(f"serving on 127.0.0.1:{port}")
        shutdown._event.wait()  # SIGTERM/SIGINT or programmatic request
        server.shutdown()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli.serve",
        description="Serve a GAME model online (stdin or TCP JSON lines).",
    )
    p.add_argument("--model-dir", required=True)
    p.add_argument("--watch-root", help="poll for new model versions here")
    p.add_argument("--poll-s", type=float, default=5.0)
    p.add_argument("--socket", type=int, help="TCP port (default: stdin)")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--queue-depth", type=int, default=1024)
    p.add_argument("--min-bucket", type=int, default=8)
    p.add_argument(
        "--dtype", choices=["float32", "float64"], default="float32"
    )
    p.add_argument(
        "--slo-p99-ms", type=float, default=10.0,
        help="p99 latency target for the SLO tracker ({'cmd': 'slo'})",
    )
    p.add_argument(
        "--slo-objective", type=float, default=0.99,
        help="fraction of requests that must meet the target "
        "(error budget = 1 - objective)",
    )
    p.add_argument(
        "--slo-window-s", type=float, default=60.0,
        help="rolling SLO window in seconds",
    )
    p.add_argument(
        "--no-verify-manifest",
        action="store_true",
        help="serve exports without a sha256 manifest (NOT recommended)",
    )
    p.add_argument(
        "--default-deadline-ms", type=float, default=None,
        help="deadline applied to requests that don't carry their own "
        "deadline_ms; expired requests drop before batch assembly",
    )
    p.add_argument(
        "--no-degrade", action="store_true",
        help="disable degrade-to-fixed-effect-only scoring under "
        "sustained queue pressure",
    )
    p.add_argument(
        "--breaker-threshold", type=int, default=3,
        help="consecutive reload failures that quarantine an export dir",
    )
    p.add_argument(
        "--breaker-backoff-s", type=float, default=30.0,
        help="initial backoff before a quarantined export is re-probed "
        "(doubles per failed probe)",
    )
    p.add_argument(
        "--serving-shards", type=int, default=1,
        help="partition RE tables over an N-shard entity mesh (one "
        "shard per device; requests route to owning shards and partial "
        "scores merge — docs/SERVING.md). Default 1 = unsharded.",
    )
    p.add_argument(
        "--hbm-cache-entities", type=int, default=None,
        help="tiered entity cache: keep this many hot entities per RE "
        "key in HBM, the cold tail in host RAM with async promotion; a "
        "miss scores fixed-effect-only (cold-start semantics) while "
        "the promotion is in flight",
    )
    p.add_argument(
        "--admission-log", default=None,
        help="persist a bounded repeat-miss admission log here (entity "
        "key, miss count, last seen; atomic-swap writes) — the retrain "
        "orchestrator (photon-retrain) promotes repeat-missed entities "
        "into the next training set (docs/LIFECYCLE.md)",
    )
    p.add_argument(
        "--frontend-port", type=int, default=None,
        help="start the async multiplexing front end on this port "
        "(0 = ephemeral; the bound port is logged). The old JSON-lines "
        "protocol stays available — stdin/--socket and {'cmd': ...} "
        "frames on the front end itself are the compat admin channel "
        "(docs/FRONTEND.md)",
    )
    p.add_argument(
        "--replicas", type=int, default=1,
        help="serve each tenant through N engine replicas behind a "
        "least-outstanding-requests router with per-replica breakers "
        "and whole-replica failover (requires --frontend-port)",
    )
    p.add_argument(
        "--tenant", action="append", default=None, metavar="JSON",
        help="register one tenant (repeatable; requires "
        "--frontend-port): a JSON object like "
        '\'{"name": "gold", "model_dir": "out/game", "priority": 2, '
        '"deadline_ms": 50, "quota": 256, "p99_ms": 10}\'. '
        "model_dir defaults to --model-dir (same-shaped tenants share "
        "the AOT ladder via the process compile cache); the first "
        "tenant is the default for frames that name none. Without "
        "--tenant, one tenant 'default' serves --model-dir.",
    )
    p.add_argument(
        "--exemplar-fraction", type=float, default=0.01,
        help="fast-path sampling fraction for the tail-based exemplar "
        "store (errors/sheds/expiries/degraded/failovers and the "
        "rolling slow tail are always kept; negative disables the "
        "store entirely) — the {'cmd': 'exemplars'} surface",
    )
    p.add_argument("--stats-json", help="dump a stats snapshot here on exit")
    args = p.parse_args(argv)
    if args.serving_shards > 1 and args.hbm_cache_entities:
        p.error(
            "--hbm-cache-entities composes with the unsharded engine; "
            "on a sharded mesh each shard's slice is the resident set"
        )
    if args.frontend_port is None and (args.tenant or args.replicas != 1):
        p.error("--tenant and --replicas require --frontend-port")
    if args.replicas < 1:
        p.error("--replicas must be >= 1")
    tenant_specs = []
    for raw in args.tenant or []:
        try:
            spec = json.loads(raw)
            if not isinstance(spec, dict) or "name" not in spec:
                raise ValueError("need a JSON object with 'name'")
        except ValueError as e:
            p.error(f"bad --tenant {raw!r}: {e}")
        tenant_specs.append(spec)
    if args.frontend_port is not None and not tenant_specs:
        tenant_specs = [{"name": "default"}]
    # after parse_args: --help / bad flags must not initialize the backend
    import jax.numpy as jnp

    from photon_ml_tpu.resilience import GracefulShutdown
    from photon_ml_tpu.utils import PhotonLogger, enable_compilation_cache

    enable_compilation_cache()
    logger = PhotonLogger(None)
    stats = ServingStats()
    engine_extra = {}
    if args.frontend_port is not None:
        # frontend mode: every engine (all tenants, all replicas) shares
        # the process-wide AOT bucket-executable ladder — N same-shaped
        # models pay ONE warmup (docs/FRONTEND.md)
        from photon_ml_tpu.frontend.tenants import process_compile_cache

        engine_extra["compile_cache"] = process_compile_cache()

    def make_registry() -> ModelRegistry:
        return ModelRegistry(
            verify=not args.no_verify_manifest,
            warmup_max_batch=args.max_batch,
            warmup_degraded=not args.no_degrade,
            breaker_threshold=args.breaker_threshold,
            breaker_backoff_s=args.breaker_backoff_s,
            stats=stats,
            logger=logger,
            dtype={
                "float32": jnp.float32, "float64": jnp.float64
            }[args.dtype],
            min_bucket=args.min_bucket,
            serving_shards=args.serving_shards,
            **engine_extra,
            **(
                {"hbm_cache_entities": args.hbm_cache_entities}
                if args.hbm_cache_entities
                else {}
            ),
            **(
                {"admission_log_path": args.admission_log}
                if args.admission_log
                else {}
            ),
        )

    registry = make_registry()
    registry.load(args.model_dir)
    slo = SloTracker(
        target_p99_ms=args.slo_p99_ms,
        objective=args.slo_objective,
        window_s=args.slo_window_s,
        registry=stats.registry,
    )
    # online quality: delayed-label feedback -> rolling exact AUC /
    # calibration gauges (quality.*; the {"cmd": "feedback"} surface)
    from photon_ml_tpu.obs.quality import OnlineQuality

    quality = OnlineQuality(registry=stats.registry)
    # tail-based exemplar sampling: the batcher feeds every finished
    # request; the rings answer {"cmd": "exemplars"} with live trace ids
    if args.exemplar_fraction >= 0:
        from photon_ml_tpu.obs import exemplars as _exemplars

        _exemplars.install_store(fast_fraction=args.exemplar_fraction)
    tm = None
    routers = {}
    frontend = None
    if args.frontend_port is not None:
        from photon_ml_tpu.frontend import (
            FrontendServer,
            ReplicaRouter,
            TenantManager,
        )

        tm = TenantManager(
            max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            queue_depth=args.queue_depth,
            stats=stats,
            slo=slo,
        )
        primary_used = False
        for spec in tenant_specs:
            name = str(spec["name"])
            mdir = spec.get("model_dir", args.model_dir)
            regs = []
            for r in range(args.replicas):
                if mdir == args.model_dir and not primary_used:
                    reg = registry  # replica 0: the already-loaded one
                    primary_used = True
                else:
                    reg = make_registry()
                    reg.load(mdir)
                regs.append(reg)
            if len(regs) == 1:
                scorer = regs[0]  # keeps the registry on TenantState
            else:
                router = ReplicaRouter(
                    [(f"{name}/r{i}", rg.score) for i, rg in
                     enumerate(regs)],
                )
                routers[name] = router
                scorer = router.score
            tm.add_tenant(
                name, scorer,
                deadline_ms=spec.get(
                    "deadline_ms", args.default_deadline_ms
                ),
                priority=int(spec.get("priority", 0)),
                max_outstanding=spec.get("quota"),
                target_p99_ms=float(spec.get("p99_ms", args.slo_p99_ms)),
            )
        default_tenant = str(tenant_specs[0]["name"])
        batcher = _CompatBatcher(tm, default_tenant)
    else:
        batcher = MicroBatcher(
            registry.score,
            max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            queue_depth=args.queue_depth,
            stats=stats,
            slo=slo,
            degraded_score_fn=(
                None if args.no_degrade else registry.score_fixed_only
            ),
        )
    shutdown = GracefulShutdown(logger).install()
    shutdown.register_drain(batcher.begin_drain)
    if args.watch_root:
        threading.Thread(
            target=_watch_loop,
            args=(registry, args.watch_root, args.poll_s, shutdown, logger),
            daemon=True,
        ).start()
    try:
        if tm is not None:
            frontend = FrontendServer(
                tm.submit,
                port=args.frontend_port,
                admin_fn=make_admin_handler(
                    batcher, registry, stats, quality=quality,
                    tenants=tm, replicas=routers or None,
                ),
                default_tenant=default_tenant,
            )
            frontend.start()
            logger.info(
                f"frontend on 127.0.0.1:{frontend.port} "
                f"({len(tenant_specs)} tenant(s), "
                f"{args.replicas} replica(s))"
            )
        if args.socket:
            _serve_socket(
                args.socket, batcher, registry, stats, shutdown, logger,
                default_deadline_ms=args.default_deadline_ms,
                quality=quality, tenants=tm, replicas=routers or None,
            )
        elif tm is not None:
            # frontend is the data plane; no stdin pump — park until
            # SIGTERM/SIGINT (the compat channel is --socket or the
            # front end's own {"cmd": ...} passthrough)
            shutdown._event.wait()
        else:
            serve_lines(
                sys.stdin,
                sys.stdout,
                batcher,
                registry,
                stats,
                shutdown=shutdown,
                window=args.max_batch * 2,
                default_deadline_ms=args.default_deadline_ms,
                quality=quality,
            )
    finally:
        if frontend is not None:
            frontend.stop()
        drained = batcher.drain()
        if args.stats_json:
            stats.dump(args.stats_json)
        shutdown.uninstall()
        if not drained:
            # accepted requests are still queued — silently exiting 0
            # here is how dropped work hides from orchestrators
            depth = batcher.queue_depth()
            logger.warn(
                f"drain FAILED: {depth} accepted request(s) undrained "
                "at exit"
            )
            sys.exit(3)


if __name__ == "__main__":
    main()
