"""Drivers / CLI (the reference's L7): staged GLM training, GAME training
with grid sweeps, and scoring — the product surface over the library
(``Driver.scala``, ``cli/game/training/Driver.scala``,
``cli/game/scoring/Driver.scala``)."""

from photon_ml_tpu.cli.config import (
    CoordinateSpec,
    GLMDriverParams,
    GameDriverParams,
    ScoringParams,
    load_params,
)
from photon_ml_tpu.cli.stages import DriverStage, StageTracker

__all__ = [
    "GLMDriverParams",
    "GameDriverParams",
    "CoordinateSpec",
    "ScoringParams",
    "load_params",
    "DriverStage",
    "StageTracker",
]
