"""Standalone feature-indexing job: Avro inputs -> feature vocabulary files.

Rebuild of the reference's ``FeatureIndexingJob.scala:48-160`` (a separate
Spark job that scans training data for distinct (name, term) keys and
writes the off-heap PalDB index the drivers then load) and the
``NameAndTermFeatureSetContainer`` main. The TPU-side analog writes plain
text vocabularies (one key per line, ``io/vocab.py`` format) that the GLM
driver consumes via ``feature_file`` and the GAME driver via
``feature_shards``. The scan itself is the native parallel distinct-key
pass when the C++ decoder is available.

    python -m photon_ml_tpu.cli.build_index \\
        --input data/train --output-dir out \\
        --shard global --add-intercept

Run once per shard definition (a shard = a feature bag; rerun with a
different ``--name-prefix`` filter to build partitioned bags).
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

from photon_ml_tpu.io.ingest import IngestSource
from photon_ml_tpu.io.schemas import NAME_TERM_DELIMITER
from photon_ml_tpu.io.vocab import FeatureVocabulary


def build_index(
    inputs: List[str],
    output_dir: str,
    shard: Optional[str] = None,
    add_intercept: bool = False,
    name_prefix: Optional[str] = None,
    field_names: str = "TRAINING_EXAMPLE",
) -> str:
    """Scan inputs for distinct feature keys and write the vocabulary.

    ``name_prefix`` keeps only features whose NAME starts with the prefix
    — the lightweight analog of the reference's per-section feature bags
    (``NameAndTermFeatureSetContainer``): partition a shared namespace
    into shards without a section-key schema.

    Returns the written file path: ``feature-index.txt`` (GLM layout) or
    ``feature-index-<shard>.txt`` (GAME shard layout)."""
    source = IngestSource(inputs, field_names)
    vocab = source.build_vocab(add_intercept=add_intercept)
    if name_prefix is not None:
        # ONE scan; the prefix filter is a host-side key filter
        kept = [
            k
            for k in vocab.index_to_key
            if k.split(NAME_TERM_DELIMITER)[0].startswith(name_prefix)
        ]
        vocab = FeatureVocabulary(kept, add_intercept=add_intercept)
    os.makedirs(output_dir, exist_ok=True)
    fname = (
        f"feature-index-{shard}.txt" if shard else "feature-index.txt"
    )
    path = os.path.join(output_dir, fname)
    vocab.save(path)
    return path


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="photon_ml_tpu.cli.build_index",
        description="Build feature vocabulary files from Avro training "
        "data (the FeatureIndexingJob analog).",
    )
    p.add_argument("--input", nargs="+", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument(
        "--shard",
        help="write feature-index-<shard>.txt (GAME layout); omit for "
        "the GLM feature-index.txt",
    )
    p.add_argument("--add-intercept", action="store_true")
    p.add_argument(
        "--name-prefix",
        help="keep only features whose name starts with this prefix "
        "(partitioned feature bags)",
    )
    p.add_argument("--field-names", default="TRAINING_EXAMPLE")
    args = p.parse_args(argv)
    path = build_index(
        args.input,
        args.output_dir,
        shard=args.shard,
        add_intercept=args.add_intercept,
        name_prefix=args.name_prefix,
        field_names=args.field_names,
    )
    print(path)


if __name__ == "__main__":
    main()
