"""Block coordinate descent over GAME coordinates.

Rebuild of ``algorithm/CoordinateDescent.scala:39-198``: for each outer
iteration, update every coordinate in the configured sequence against the
residual of all the others (partial score = total - own), rescore, and
log the full training objective (loss + all regularization terms). The
reference's per-coordinate score RDDs with fullOuterJoin accumulation
(``CoordinateDescent.scala:115-123``) are dense (n,) device arrays here;
"sum of other coordinates' scores" is a subtraction.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_ml_tpu import obs
from photon_ml_tpu.core.tasks import TaskType
from photon_ml_tpu.resilience import faults as _faults
from photon_ml_tpu.ops import metrics as metrics_mod
from photon_ml_tpu.solvers.common import ConvergenceReason


@dataclasses.dataclass
class GameModel:
    """name -> parameters (fixed effect: (d,); random effect: (E, d)).
    The reference's ``model/Model.scala`` hierarchy collapses to this plus
    the coordinates' score() methods."""

    params: Dict[str, jax.Array]

    def copy(self) -> "GameModel":
        return GameModel(params=dict(self.params))


@dataclasses.dataclass
class CoordinateUpdateRecord:
    """One coordinate update's observability snapshot — the analog of the
    reference's per-coordinate logging + optimization trackers
    (``CoordinateDescent.scala:160-189``, ``optimization/game/*Tracker``)."""

    iteration: int
    coordinate: str
    objective: float
    # host wall time for THIS coordinate's update. In fused mode the whole
    # pass is one dispatch, so per-coordinate splits are unknowable: the
    # pass wall time is recorded on the FIRST coordinate's record and the
    # rest carry None (an even split would mislead anyone comparing
    # coordinate costs across fused/unfused runs).
    seconds: Optional[float]
    solver_iterations: float  # mean over entities for random effects
    convergence_histogram: Dict[str, int]
    # validation metric after this update, when a validation_fn is supplied
    # (``CoordinateDescent.scala:173-189``)
    validation_metric: Optional[float] = None
    # divergence-guard annotation: None for a normal update, "recovered"
    # when a non-finite update was rolled back and the damped retry
    # succeeded, "frozen" when the retry also failed and the coordinate
    # was excluded from further training (docs/ROBUSTNESS.md)
    event: Optional[str] = None


def _coordinate_reg_term(coord, params) -> jax.Array:
    """Penalty dispatch shared by the fused and unfused paths: the
    coordinate's own reg_term when it defines one (factored coordinates
    penalize gamma and B under different configs), else the config
    applied to the params."""
    if hasattr(coord, "reg_term"):
        return coord.reg_term(params)
    return _config_reg_term(coord.config, params)


def _config_reg_term(cfg, params) -> jax.Array:
    """loss-side penalty of one coordinate's params under its config —
    matches exactly what the coordinate's solver minimizes."""
    l2 = cfg.reg_weight * (1.0 - cfg.l1_ratio)
    l1 = cfg.reg_weight * cfg.l1_ratio
    leaves = jax.tree_util.tree_leaves(params)
    sq = sum(jnp.vdot(p, p) for p in leaves)
    ab = sum(jnp.sum(jnp.abs(p)) for p in leaves)
    return 0.5 * l2 * sq + l1 * ab


def _history_record(
    iteration,
    coordinate,
    objective,
    reasons,
    iterations,
    seconds,
    validation_metric=None,
    event=None,
) -> CoordinateUpdateRecord:
    """THE record builder both the sequential drain and the grid sweep
    use — one place for the reason histogram / solver-iteration
    aggregation semantics."""
    reasons = np.atleast_1d(np.asarray(reasons))
    iters_arr = np.asarray(iterations)
    return CoordinateUpdateRecord(
        iteration=iteration,
        coordinate=coordinate,
        objective=float(objective),
        seconds=seconds,
        validation_metric=validation_metric,
        event=event,
        solver_iterations=(
            float(np.mean(iters_arr)) if iters_arr.size else 0.0
        ),
        convergence_histogram={
            ConvergenceReason(int(r)).name: int(c)
            for r, c in zip(*np.unique(reasons, return_counts=True))
        },
    )


def _record_update_metrics(rec: CoordinateUpdateRecord) -> None:
    """Feed one materialized update record into the process metrics
    registry (docs/OBSERVABILITY.md taxonomy). Called at materialize()
    time — after the batched device->host drain — so the hot loop's
    deferred-stats pipelining is untouched."""
    reg = obs.registry()
    reg.inc("game.updates")
    reg.inc("game.solver_iterations", rec.solver_iterations)
    reg.set_gauge("game.objective", rec.objective)
    if rec.validation_metric is not None:
        reg.set_gauge("game.validation_metric", rec.validation_metric)
    if rec.seconds is not None:
        reg.observe("game.update_ms", rec.seconds * 1e3)
    if rec.event == "recovered":
        reg.inc("resilience.rollbacks")
    elif rec.event == "frozen":
        reg.inc("resilience.frozen_coordinates")


def _normalize_fuse_passes(fp):
    """True | False | 'coordinate', strictly. Bool-likes (np.bool_, 0/1)
    normalize to bool; anything else raises — an unrecognized value
    would otherwise silently select the slow plain loop. Applied at
    construction AND at run() (the attribute is assignable)."""
    if isinstance(fp, str):
        if fp != "coordinate":
            raise ValueError(
                f"fuse_passes must be True, False, or 'coordinate'; got "
                f"{fp!r}"
            )
        return fp
    if fp is True or fp is False:
        return fp
    if isinstance(fp, (int, np.bool_)) and fp in (0, 1):
        return bool(fp)
    raise ValueError(
        f"fuse_passes must be True, False, or 'coordinate'; got {fp!r}"
    )


def _pass_body(live, names, loss_fn, labels, base_offsets, weights,
               params, scores, key):
    """ONE full coordinate-descent pass, trace-safe: every coordinate's
    update_step + rescore + post-update training objective, in sequence.
    THE pass definition shared by the single-pass fused program
    (:meth:`CoordinateDescent._fused_pass_fn`) and the multi-pass
    superpass program (:meth:`CoordinateDescent._superpass_fn`), so the
    two dispatch granularities cannot drift numerically — including the
    PRNG stream (one split per coordinate, in update order)."""

    def reg_term(name, p):
        return _coordinate_reg_term(live[name], p)

    objs = []
    trackers = []
    for name in names:
        total = sum(scores.values())
        partial = total - scores[name]
        key, sub = jax.random.split(key)
        p, tr, s = live[name].update_step(params[name], partial, sub)
        params = {**params, name: p}
        scores = {**scores, name: s}
        reg = sum(reg_term(n, params[n]) for n in names)
        tot = sum(scores[n] for n in names)
        objs.append(loss_fn(labels, base_offsets + tot, weights) + reg)
        trackers.append(tr)
    return params, scores, key, tuple(objs), tuple(trackers)


def _tree_finite(tree) -> jax.Array:
    """Scalar bool: every inexact leaf of ``tree`` is finite — the
    divergence-guard *detection* predicate evaluated in-program (the
    non-finite-UPDATE half; the non-finite-OBJECTIVE half is a direct
    isfinite on the pass objectives)."""
    fin = jnp.bool_(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            fin = fin & jnp.all(jnp.isfinite(leaf))
    return fin


def _loss_fn_for_task(task: TaskType):
    if task == TaskType.LOGISTIC_REGRESSION:
        return metrics_mod.total_logistic_loss
    if task == TaskType.LINEAR_REGRESSION:
        return metrics_mod.total_squared_loss
    if task == TaskType.POISSON_REGRESSION:
        return metrics_mod.total_poisson_loss
    raise ValueError(f"no GAME training evaluator for {task}")


class _AsyncCheckpointWriter:
    """One-deep background checkpoint writer: the training loop hands a
    fully host-snapshotted write closure to :meth:`submit` and keeps
    dispatching device work while serialization + the atomic swap hit
    disk (epoch time bounded by device math, not checkpoint I/O —
    docs/INGEST.md's overlap principle applied to the output side).
    ``submit`` joins any previous write first, so writes serialize in
    step order and at most one is in flight.

    Failure contract: a background-write error SURFACES at the next
    ``submit``/``join`` — at the latest before ``run()`` returns — and
    ``join`` then falls back to re-running the retained write closure
    SYNCHRONOUSLY (``resilience.ckpt_async_fallback`` event + counter),
    so an async-path-only failure (the ``checkpoint.async_write`` chaos
    site: a dying writer thread, an fd lost to the background context)
    costs overlap, never durability. Only a fallback that ALSO fails
    raises — at that point the step genuinely cannot be written. An
    exception that unwinds ``run()`` between a submit and its join can
    at worst lose that one overlapped write, which resume tolerates by
    falling back to the previous VALID step
    (``io.checkpoint.latest_checkpoint``)."""

    def __init__(self):
        import threading

        self._threading = threading
        self._thread = None
        self._fn = None
        self._exc: Optional[BaseException] = None

    def submit(self, write_fn) -> None:
        self.join()
        self._fn = write_fn

        def run():
            try:
                # chaos seam: the background serialize/swap. Distinct
                # from checkpoint.save (probed inside save_checkpoint,
                # where the retry policy owns it): this site dies on the
                # WRITER THREAD, exercising the surface-at-join +
                # synchronous-fallback path.
                _faults.fire("checkpoint.async_write")
                write_fn()
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                self._exc = e

        t = self._threading.Thread(
            target=run, name="game-ckpt-writer", daemon=True
        )
        self._thread = t
        t.start()

    def join(self) -> None:
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._exc is not None:
            exc = self._exc
            fn = self._fn
            self._exc = None
            obs.registry().inc("resilience.ckpt_async_fallbacks")
            obs.emit_event(
                "resilience.ckpt_async_fallback",
                cat="resilience",
                error=repr(exc),
            )
            # durability boundary: whoever called join() is standing on a
            # point that PROMISED a checkpoint (next submit, preemption
            # marker, run return). Re-run the failed write synchronously;
            # only a double failure breaks the promise.
            fn()


class CoordinateDescent:
    """Owns the coordinates and the outer loop.

    coordinates: ordered mapping name -> coordinate (the reference's
    updating sequence, ``cli/game/training/Params.scala``). All coordinates
    must see the same rows in the same order (shared labels/offsets/weights).
    """

    def __init__(
        self,
        coordinates: Mapping[str, object],
        labels: jax.Array,
        base_offsets: jax.Array,
        weights: jax.Array,
        task: TaskType,
        fuse_passes=True,  # True | False | "coordinate"
    ):
        """``fuse_passes`` — dispatch granularity, identical math in all
        modes:

        - ``True`` (default): each full CD pass is ONE dispatch
          (:meth:`_fused_pass_fn`).
        - ``"coordinate"``: one dispatch PER COORDINATE UPDATE, with the
          rescore and training objective fused into it (K dispatches per
          pass for K coordinates). The chunked middle ground for shapes
          where the whole-pass program exceeds a toolchain limit (e.g.
          remote-compile request caps at the 1.2M-row flagship shape)
          but per-coordinate programs compile fine.
        - ``False``: plain loop (~3 dispatches per update: update+rescore,
          objective, eager score arithmetic)."""
        self.coordinates = dict(coordinates)
        self.labels = labels
        self.base_offsets = base_offsets
        self.weights = weights
        self.task = task
        self.fuse_passes = _normalize_fuse_passes(fuse_passes)
        loss_fn = _loss_fn_for_task(task)
        names = list(self.coordinates)

        # training objective from per-coordinate scores + params in ONE
        # dispatch: the reg-term composition would otherwise issue several
        # eager ops per coordinate per update — pure latency on a
        # remote/tunneled device. labels/offsets/weights ride as jit
        # ARGUMENTS: closed-over concrete arrays lower to HLO literals
        # and bloat remote-compile requests (see _fused_pass_fn)
        coords_ref = self.coordinates

        @jax.jit
        def full_objective(labels_, base_offsets_, weights_, scores_dict,
                           params_dict):
            reg = sum(
                _coordinate_reg_term(coords_ref[n], params_dict[n])
                for n in names
            )
            total = sum(scores_dict[n] for n in names)
            return loss_fn(labels_, base_offsets_ + total, weights_) + reg

        self._full_objective = lambda scores_dict, params_dict: (
            full_objective(
                self.labels, self.base_offsets, self.weights,
                scores_dict, params_dict,
            )
        )

    def _fused_pass_fn(self):
        """ONE jitted dispatch for a FULL coordinate-descent pass: every
        coordinate's update_step + rescore + per-update training objective,
        unrolled in sequence inside a single XLA program. On a tunneled /
        remote device each dispatch is a network round trip, so the
        unfused loop (2 updates + 2 objectives + score arithmetic) pays
        ~6 latencies per pass; this pays ONE. Used by run() whenever no
        validation_fn is supplied and every coordinate exposes the
        trace-safe update_step (all in-tree coordinates do).

        The pass must NOT close over the coordinates' device-resident
        design/batch arrays: concrete closed-over arrays are not tracers,
        so tracing inlines them as HLO LITERALS and the serialized
        program carries the whole dataset (observed: multi-hundred-MB
        remote-compile requests failing with HTTP 413 / broken pipes,
        and jax.closure_convert does NOT help — it only hoists captured
        tracers). Instead every coordinate exposes its arrays as an
        explicit ``fused_state()`` pytree, threaded through the jit as
        arguments; the per-update objective is likewise computed from
        argument-passed labels/offsets/weights."""
        names = list(self.coordinates)
        if getattr(self, "_fused_pass", None) is None:
            coords = self.coordinates
            loss_fn = _loss_fn_for_task(self.task)

            def one_pass(states, labels, base_offsets, weights, params,
                         scores, key):
                live = {
                    n: coords[n].with_fused_state(states[n]) for n in names
                }
                return _pass_body(
                    live, names, loss_fn, labels, base_offsets, weights,
                    params, scores, key,
                )

            self._fused_pass = jax.jit(one_pass)
        f = self._fused_pass
        # states are re-snapshotted on EVERY call: a caller that mutates a
        # coordinate between run() calls (reg_weights, design) must train
        # on the fresh state, same as the unfused loop would. fused_state()
        # returns already-resident device arrays, so the rebuild is a dict
        # construction; the jit cache still hits on identical shapes.
        states = {
            n: self.coordinates[n].fused_state() for n in names
        }

        def call(p, s, k):
            return f(
                states, self.labels, self.base_offsets, self.weights, p, s,
                k,
            )

        return call

    def _superpass_fn(self, k: int):
        """ONE jitted dispatch running up to ``k`` FULL coordinate-descent
        passes — the multi-pass extension of :meth:`_fused_pass_fn` (same
        ``_pass_body``, same PRNG stream, same state-threading contract).
        A bounded ``lax.while_loop`` carries (params, scores, key) across
        passes entirely in HBM and early-exits ON DEVICE when either

        - the objective-tolerance convergence check fires: the last
          coordinate's post-update objective moved <= tol * |objective at
          dispatch entry| relative to the previous pass (tol is a traced
          scalar; 0 disables), or
        - the divergence-guard DETECTION predicate fires: a non-finite
          pass objective or non-finite updated parameters. The failing
          pass is NOT committed — the returned (params, scores, key) are
          the last good state, so the host can replay exactly that pass
          through the per-update guarded loop (rollback / damped retry /
          freeze stay host-side policy, at dispatch granularity).

        Per-pass objectives and coordinate trackers write into fixed-size
        (k, ...) tape buffers (the PR-7 carry-tape idiom) whose entries
        past ``passes_done`` are garbage the host masks. Returns
        ``(params, scores, key, passes_done, guard, converged, objs_tape,
        tracker_tapes)``."""
        cache = getattr(self, "_superpass_progs", None)
        if cache is None:
            cache = self._superpass_progs = {}
        if k not in cache:
            names = list(self.coordinates)
            coords = self.coordinates
            loss_fn = _loss_fn_for_task(self.task)

            def superpass(states, labels, base_offsets, weights, params,
                          scores, key, tol, guard_on):
                live = {
                    n: coords[n].with_fused_state(states[n]) for n in names
                }

                def one_pass(params, scores, key):
                    return _pass_body(
                        live, names, loss_fn, labels, base_offsets,
                        weights, params, scores, key,
                    )

                # objective at dispatch entry: the convergence reference
                # scale, and the "previous objective" for the chunk's
                # first pass (cross-dispatch continuity: this equals the
                # previous chunk's final objective by construction)
                reg0 = sum(
                    _coordinate_reg_term(live[n], params[n]) for n in names
                )
                tot0 = sum(scores[n] for n in names)
                obj_in = loss_fn(labels, base_offsets + tot0, weights) + reg0

                out_sh = jax.eval_shape(one_pass, params, scores, key)
                obj_dtype = out_sh[3][0].dtype
                objs_tape0 = jnp.full((k, len(names)), jnp.nan, obj_dtype)
                tr_tapes0 = jax.tree_util.tree_map(
                    lambda s: jnp.zeros((k,) + s.shape, s.dtype), out_sh[4]
                )
                obj_in = obj_in.astype(obj_dtype)
                tol_c = jnp.asarray(tol, obj_dtype)

                def cond(carry):
                    i, stop = carry[0], carry[1]
                    return (i < k) & ~stop

                def body(carry):
                    (i, _stop, guard, conv, params, scores, key,
                     objs_tape, tr_tapes) = carry
                    p2, s2, k2, objs, trs = one_pass(params, scores, key)
                    objs_vec = jnp.stack(
                        [o.astype(obj_dtype) for o in objs]
                    )
                    finite = jnp.all(jnp.isfinite(objs_vec))
                    for n in names:
                        finite = finite & _tree_finite(p2[n])
                    # without the guard, non-finite passes COMMIT — the
                    # unguarded fused loop's semantics (one NaN poisons
                    # the run), and the host's passes_done == chunk
                    # assumption (it never reads the flags) stays true
                    commit = finite | ~guard_on
                    tripped = guard_on & ~finite
                    prev = jnp.where(
                        i == 0,
                        obj_in,
                        objs_tape[jnp.maximum(i - 1, 0), -1],
                    )
                    cur = objs_vec[-1]
                    converged = (
                        commit
                        & (tol_c > 0)
                        & (jnp.abs(prev - cur) <= tol_c * jnp.abs(obj_in))
                    )
                    objs_tape = objs_tape.at[i].set(
                        jnp.where(commit, objs_vec, objs_tape[i])
                    )
                    tr_tapes = jax.tree_util.tree_map(
                        lambda buf, v: buf.at[i].set(
                            jnp.where(commit, v, buf[i])
                        ),
                        tr_tapes,
                        trs,
                    )
                    sel = lambda a, b: jnp.where(commit, a, b)
                    params = jax.tree_util.tree_map(sel, p2, params)
                    scores = jax.tree_util.tree_map(sel, s2, scores)
                    key = jnp.where(commit, k2, key)
                    return (
                        i + commit.astype(i.dtype),
                        tripped | converged,
                        guard | tripped,
                        conv | converged,
                        params,
                        scores,
                        key,
                        objs_tape,
                        tr_tapes,
                    )

                init = (
                    jnp.int32(0), jnp.bool_(False), jnp.bool_(False),
                    jnp.bool_(False), params, scores, key, objs_tape0,
                    tr_tapes0,
                )
                (i, _stop, guard, conv, params, scores, key, objs_tape,
                 tr_tapes) = lax.while_loop(cond, body, init)
                return (
                    params, scores, key, i, guard, conv, objs_tape,
                    tr_tapes,
                )

            cache[k] = jax.jit(superpass)
        states = {
            n: self.coordinates[n].fused_state()
            for n in self.coordinates
        }

        def call(p, s, key, tol, guard_on):
            return cache[k](
                states, self.labels, self.base_offsets, self.weights,
                p, s, key, tol, guard_on,
            )

        return call, states

    def _coordinate_step_fns(self):
        """One jitted dispatch PER COORDINATE: update_step + rescore +
        the post-update training objective fused together — the chunked
        fallback for shapes where the whole-pass program exceeds a
        compile-request limit (VERDICT r4 #4). Shares the fused path's
        state-threading contract (coordinates' device arrays ride as jit
        ARGUMENTS, never as closed-over literals; see
        :meth:`_fused_pass_fn`); states are re-snapshotted per call like
        the fused path so coordinate mutations between runs are seen."""
        names = list(self.coordinates)
        if getattr(self, "_chunk_fns", None) is None:
            coords = self.coordinates
            loss_fn = _loss_fn_for_task(self.task)

            def make(name):
                def one_step(states, labels, base_offsets, weights,
                             params, scores, key):
                    live = {
                        n: coords[n].with_fused_state(states[n])
                        for n in names
                    }
                    total = sum(scores.values())
                    partial = total - scores[name]
                    p, tr, s = live[name].update_step(
                        params[name], partial, key
                    )
                    params = {**params, name: p}
                    scores = {**scores, name: s}
                    reg = sum(
                        _coordinate_reg_term(live[n], params[n])
                        for n in names
                    )
                    tot = sum(scores[n] for n in names)
                    obj = (
                        loss_fn(labels, base_offsets + tot, weights) + reg
                    )
                    return p, tr, s, obj

                return jax.jit(one_step)

            self._chunk_fns = {name: make(name) for name in names}
        states = {n: self.coordinates[n].fused_state() for n in names}
        return self._chunk_fns, states


    def _pass_cost(self, label: str, lower_thunk):
        """Cost-book record for one dispatch program (a chunked
        coordinate step or the fused whole-pass), lazily lowered via
        ``lower_thunk`` and cached on the instance — the lowering is a
        re-trace, so it runs once per (CD, program) and only when a
        tracer asked for attribution. Analysis uses the LOWERED stage:
        no backend compile, so the run's zero-recompile invariants
        (``xla.compiles``) are untouched. Returns None when the program
        cannot be analyzed; attribution is best-effort."""
        cache = getattr(self, "_pass_cost_records", None)
        if cache is None:
            cache = self._pass_cost_records = {}
        if label not in cache:
            try:
                cache[label] = obs.cost_book().record(
                    "game.update", lower_thunk(), bucket=label
                )
            except Exception:
                cache[label] = None
        return cache[label]

    def run(
        self,
        num_iterations: int,
        initial_model: Optional[GameModel] = None,
        seed: int = 0,
        validation_fn=None,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        resume: bool = True,
        divergence_guard: bool = False,
        stop_check=None,
        passes_per_dispatch: int = 1,
        convergence_tolerance: float = 0.0,
        sharded_checkpoints=False,
        entity_keys=None,
        heartbeat=None,
        freeze=None,
    ):
        """Returns (model, history). Objective is logged after every
        coordinate update like ``CoordinateDescent.scala:160-170``;
        `validation_fn(model) -> float`, when given, is evaluated after
        every coordinate update too (``CoordinateDescent.scala:173-189``).

        ``passes_per_dispatch`` (K): with ``fuse_passes=True`` and K > 1,
        up to K coordinate-descent passes run per XLA dispatch through
        the device-resident superpass program (:meth:`_superpass_fn`) —
        a run of P passes costs ceil(P/K) dispatches instead of P. K is
        capped by the checkpoint cadence (``checkpoint_every`` still
        fires on schedule; the dispatch chunk shrinks to hit each
        boundary), and checkpoint / preemption / validation semantics
        hold at DISPATCH boundaries — K is the checkpoint granularity.
        Identical math to K = 1 (asserted in tests/test_device_loops.py).

        ``convergence_tolerance``: with K > 1, an objective-tolerance
        convergence check runs IN-PROGRAM after every pass — when the
        full training objective moves less than tol * |objective at
        dispatch entry| between consecutive passes, the superpass
        early-exits on device and the run returns with the passes
        actually executed. 0 (default) disables the check.

        With ``divergence_guard`` AND K > 1, the guard's *detection*
        predicate (non-finite pass objective or updated params) also
        runs in-program: a failing pass is not committed, the superpass
        returns the last good state plus a guard flag, and the host
        replays exactly that pass through the per-update guarded loop —
        so rollback / damped-retry / freeze semantics are bit-identical
        to a fully host-guarded run while healthy passes stay fused.

        With ``checkpoint_dir``, the full training state (parameter tables,
        PRNG key, iteration counter, history) is written atomically every
        ``checkpoint_every`` outer iterations, and — when ``resume`` — a
        run restarted over the same directory continues from the latest
        completed pass with an identical PRNG stream, reproducing the
        uninterrupted run exactly (SURVEY §5.4; the reference has no
        analog, it leans on Spark lineage).

        ``divergence_guard`` (docs/ROBUSTNESS.md): after each coordinate
        update, check the training objective for non-finites; on failure
        roll the coordinate back to its pre-update state and retry once
        against a DAMPED residual (half the partial score), and if the
        retry also fails, FREEZE the coordinate — its params stay at the
        last finite state and it is skipped for the rest of the run (and
        of any resumed run: the frozen set rides in the checkpoint) while
        the remaining coordinates keep training. Guarded runs use the
        per-update dispatch loop (the check needs the objective on the
        host after every update), so the fused whole-pass dispatch is
        bypassed — enable it for resilience, not throughput.

        ``stop_check`` (preemption): a zero-arg callable polled at PASS
        boundaries (e.g. :class:`photon_ml_tpu.resilience.GracefulShutdown`
        wired to SIGTERM). When it turns true the loop writes a final
        checkpoint plus a ``preempted.json`` marker (with checkpoint_dir)
        and returns early; restarting with ``resume=True`` continues
        bit-for-bit, reproducing the uninterrupted run.

        ``sharded_checkpoints`` (docs/MULTIHOST.md): True writes
        per-process checkpoint shards (``io.checkpoint.
        save_checkpoint_sharded`` — each process writes only its shard,
        process 0 publishes the quorum manifest); an int N writes N
        shards from a single process (the emulation / shrunk-restart
        mode). ``entity_keys`` (coordinate -> global ordered entity-id
        list) labels entity-table rows so those tables shard by row and
        a restore onto a DIFFERENT process count or entity order
        re-shards BY KEY (``reindex_entity_params``) instead of by
        position. Resume accepts both formats interchangeably.

        ``heartbeat`` (:class:`photon_ml_tpu.parallel.heartbeat.
        HeartbeatMonitor`): polled at pass boundaries. On a detected
        peer loss, the loop writes a FINAL checkpoint at the current
        boundary plus a ``host-loss.json`` marker and re-raises
        :class:`~photon_ml_tpu.resilience.hostloss.HostLossDetected` —
        the drivers map it to the distinct host-loss exit code so a
        restart (same or smaller world size) resumes from the shard
        set.

        ``freeze``: coordinate names to EXCLUDE from updates for the
        whole run — they keep their (warm-started) params and still
        contribute their score. This seeds the same frozen set the
        divergence guard grows, so it rides checkpoints identically (a
        resumed run unions the checkpoint's casualties with the seed)
        and forces the per-update loop the same way a guard-frozen
        coordinate does. The lifecycle orchestrator uses it to retrain
        only convergence-unhealthy coordinates while healthy ones carry
        over bit-identical from the previous export."""
        names = list(self.coordinates)
        seed_frozen = set(freeze or ())
        unknown_frozen = seed_frozen - set(names)
        if unknown_frozen:
            raise ValueError(
                f"freeze names unknown coordinates: "
                f"{sorted(unknown_frozen)}"
            )
        if seed_frozen >= set(names):
            raise ValueError("freeze covers every coordinate — nothing "
                             "would train")
        model = (
            initial_model.copy()
            if initial_model is not None
            else GameModel(
                {n: self.coordinates[n].initial_params() for n in names}
            )
        )
        history: List[CoordinateUpdateRecord] = []
        key = jax.random.PRNGKey(seed)
        # Multi-process (multi-controller SPMD): every jit input must be
        # a GLOBAL array. The data arrays arrive global from the caller
        # (make_global_batch / make_global_re_design), but locally
        # created state — the PRNG key and zero-initialized parameter
        # tables — is a single-device process-local array that jit would
        # reject; re-place it replicated over the data mesh.
        if jax.process_count() > 1 and (
            isinstance(self.labels, jax.Array)
            and not self.labels.is_fully_addressable
        ):
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(self.labels.sharding.mesh, PartitionSpec())

            def _globalize(x):
                if isinstance(x, jax.Array) and not x.is_fully_addressable:
                    return x  # already global
                return jax.device_put(np.asarray(x), rep)

            model = GameModel(
                {
                    n: jax.tree_util.tree_map(_globalize, p)
                    for n, p in model.params.items()
                }
            )
            key = _globalize(key)
        start_it = 0
        # divergence-guard casualties + caller-frozen coordinates (both
        # skip updates; both ride checkpoints)
        frozen: set = set(seed_frozen)
        if checkpoint_dir is not None and resume:
            from photon_ml_tpu.io.checkpoint import latest_checkpoint

            ckpt = latest_checkpoint(checkpoint_dir)
            if ckpt is not None:
                missing = set(names) - set(ckpt.params)
                if missing:
                    raise ValueError(
                        f"checkpoint lacks coordinates {sorted(missing)}"
                    )
                if ckpt.step > num_iterations:
                    raise ValueError(
                        f"checkpoint at step {ckpt.step} exceeds "
                        f"num_iterations={num_iterations}; refusing to "
                        "return a longer run's state as if it were shorter"
                    )
                restored = ckpt.params
                if ckpt.entity_keys and entity_keys:
                    # restore-with-resharding: entity tables re-key onto
                    # THIS run's entity order (identical orders pass
                    # through untouched — bit-for-bit resume)
                    from photon_ml_tpu.io.checkpoint import (
                        reindex_entity_params,
                    )

                    restored = reindex_entity_params(
                        ckpt,
                        {n: list(k) for n, k in entity_keys.items()},
                    )
                model = GameModel(
                    {
                        n: jax.tree_util.tree_map(
                            jnp.asarray, restored[n]
                        )
                        for n in names
                    }
                )
                key = jnp.asarray(ckpt.rng_key, jnp.uint32)
                start_it = ckpt.step
                history = [
                    CoordinateUpdateRecord(**h) for h in ckpt.history
                ]
                frozen = (set(ckpt.frozen) & set(names)) | seed_frozen

        scores = {
            n: self.coordinates[n].score(model.params[n]) for n in names
        }

        # Per-update device stats stay ON DEVICE during the loop (objective
        # scalar, per-entity solver trackers) so consecutive updates
        # pipeline without a host sync each pass — the deferred analog of
        # the reference's post-hoc tracker collects. They materialize into
        # `history` lazily (before checkpoints and at return). With a
        # validation_fn the defer is moot: it returns a host float.
        pending: List[dict] = []

        def materialize():
            if not pending:
                return
            from photon_ml_tpu.obs import convergence as _conv

            # convergence decode is OPT-IN via the installed
            # --convergence-report tracker, not via plain tracing: the
            # per-update fleet decode (numpy aggregation + one
            # structured event per coordinate per pass) measurably eats
            # into the <5% tracing budget on smoke shapes, so it rides
            # the gate's dedicated tapes-on leg instead
            # (benchmarks/obs_overhead.py). Events still land in
            # events.jsonl when a tracer is ALSO active.
            conv_enabled = _conv.tracking_enabled()
            # ONE batched device->host transfer for the whole backlog:
            # individually materialized values cost a full tunnel RTT
            # EACH (measured ~0.1-0.36 s/fetch on this runtime vs ~0.16 s
            # for 24 values through one jax.device_get), and every pass
            # logs an objective scalar plus per-entity tracker arrays per
            # coordinate — fetched one by one, the stats drain was the
            # dominant wall of the cluster-scale GAME benches (r5).
            fetch = []
            for p in pending:
                r = p["result"]
                raw = getattr(r, "pending", None)
                if raw is not None:
                    # lazy RandomEffectUpdateSummary: per-bucket device
                    # (reason, iterations, final grad norm); valid-lane
                    # masks and entity indices are host-side
                    fetch.append(
                        (
                            p["objective"],
                            tuple(
                                (re_, it_, gn_)
                                for re_, it_, gn_, _, _ in raw
                            ),
                        )
                    )
                else:
                    # grad_norms tape rides the drain whole (tiny); the
                    # final-norm gather happens host-side below so the
                    # track_states=True case stays correct
                    fetch.append(
                        (
                            p["objective"],
                            (r.reason, r.iterations, r.grad_norms),
                        )
                    )
            if jax.process_count() > 1:
                # global arrays with non-addressable shards (entity-lane
                # sharded trackers) reshard to replicated ON DEVICE so
                # the single batched device_get below still carries
                # everything in one transfer
                from photon_ml_tpu.parallel.multihost import (
                    reshard_replicated,
                )

                fetch = jax.tree_util.tree_map(reshard_replicated, fetch)
            host = jax.device_get(fetch)
            for p, (obj, tr) in zip(pending, host):
                result = p.pop("result")
                raw = getattr(result, "pending", None)
                if raw is not None:
                    valid = [v for _, _, _, v, _ in raw]
                    reason = np.concatenate(
                        [
                            np.asarray(re_)[v]
                            for (re_, _, _), v in zip(tr, valid)
                        ]
                    )
                    iterations = np.concatenate(
                        [
                            np.asarray(it_)[v]
                            for (_, it_, _), v in zip(tr, valid)
                        ]
                    )
                    grad_norms = np.concatenate(
                        [
                            np.asarray(gn_)[v]
                            for (_, _, gn_), v in zip(tr, valid)
                        ]
                    )
                    entity_ids = np.concatenate(
                        [
                            np.asarray(ei)[v]
                            for (_, _, _, v, ei) in raw
                        ]
                    )
                else:
                    reason, iterations, gn_tape = tr
                    gn_arr = np.asarray(gn_tape)
                    it_arr = np.asarray(iterations)
                    idx = np.minimum(it_arr, gn_arr.shape[-1] - 1)
                    grad_norms = np.take_along_axis(
                        gn_arr, idx[..., None], axis=-1
                    )[..., 0]
                    entity_ids = None
                rec = _history_record(
                    p["iteration"],
                    p["coordinate"],
                    obj,
                    reason,
                    iterations,
                    p["seconds"],
                    p["validation_metric"],
                    p.get("event"),
                )
                history.append(rec)
                _record_update_metrics(rec)
                if conv_enabled:
                    # fleet summary per coordinate per pass: iterations
                    # histogram, non-converged count/fraction, worst-k
                    # entities by final grad norm -> convergence.*
                    # metrics + convergence.fleet events (which also
                    # ride the tracer hook into the flight recorder)
                    _conv.note_update(
                        coordinate=p["coordinate"],
                        iteration=p["iteration"],
                        reasons=reason,
                        iterations=iterations,
                        grad_norms=grad_norms,
                        entity_ids=entity_ids,
                    )
            pending.clear()

        # the fused path needs the FULL trace-safe surface, not just
        # update_step — a custom coordinate providing only update/score
        # must keep working through the plain loop
        _fused_surface = (
            "update_step", "fused_state", "with_fused_state", "wrap_tracker"
        )
        has_surface = all(
            all(hasattr(c, m) for m in _fused_surface)
            for c in self.coordinates.values()
        )
        mode = _normalize_fuse_passes(self.fuse_passes)
        # the guard needs every update's objective ON THE HOST before the
        # next update commits — incompatible with the fused whole-pass
        # dispatch (and with deferring the check), so guarded runs take
        # the plain per-update loop
        use_fused = (
            mode is True and validation_fn is None and has_surface
            and not divergence_guard
            # a resumed frozen set can't be excluded inside the one-dispatch
            # pass program; take a per-update loop that can skip
            and not frozen
        )
        use_chunked = (
            mode == "coordinate" and has_surface and not divergence_guard
        )
        # multi-pass superpass (K passes per dispatch): the fused-mode
        # surface requirements, but the guard is ALLOWED — its detection
        # predicate runs in-program and failing passes replay through
        # the per-update loop (force_plain below). A grown/resumed
        # frozen set still can't be excluded inside the program, so the
        # branch re-checks `frozen` every iteration.
        k_dispatch = max(1, int(passes_per_dispatch))
        use_super = (
            k_dispatch > 1
            and mode is True
            and validation_fn is None
            and has_surface
        )
        # Checkpoint writes OVERLAP the next dispatch chunk's device
        # math: the training state is snapshotted to host synchronously
        # (the write must capture THIS boundary, not whatever the next
        # pass mutates), then serialization + atomic swap run on a
        # background writer. At most one write is in flight; the
        # preemption paths and the run's return join() first, so every
        # durability guarantee those boundaries had under synchronous
        # writes still holds — a mid-pass hard crash can at worst lose
        # the overlapped write, which resume already tolerates (it
        # falls back to the previous VALID checkpoint and the
        # deterministic PRNG stream reproduces the run).
        ckpt_writer = _AsyncCheckpointWriter()

        def _ckpt_snapshot():
            # host snapshot: params / key / history copied now (the
            # write must capture THIS boundary, not whatever the next
            # pass mutates)
            params_host = {
                n: jax.tree_util.tree_map(
                    lambda a: np.asarray(a), model.params[n]
                )
                for n in names
            }
            return (
                params_host,
                np.asarray(key),
                [dataclasses.asdict(h) for h in history],
                sorted(frozen),
            )

        def _sharded_num_shards():
            return (
                None
                if sharded_checkpoints is True
                else int(sharded_checkpoints)
            )

        def _save_ckpt_local(step, wait: bool = False):
            """The legacy single-file writer on the overlapped
            background thread. NO collectives — the only cadence writer
            the host-loss handler may reach (photon-lint PL001: the
            sharded writer's digest exchange and swap barrier are
            full-world collectives)."""
            from photon_ml_tpu.io.checkpoint import save_checkpoint

            materialize()
            t0 = time.perf_counter()
            params_host, key_host, hist_host, frozen_host = (
                _ckpt_snapshot()
            )
            ckpt_writer.submit(
                lambda: save_checkpoint(
                    checkpoint_dir,
                    step,
                    # save_checkpoint handles plain tables AND
                    # FactoredParams
                    params_host,
                    key_host,
                    hist_host,
                    frozen=frozen_host,
                )
            )
            if wait:
                ckpt_writer.join()
            obs.registry().observe(
                "game.checkpoint.submit_ms",
                (time.perf_counter() - t0) * 1e3,
            )

        def _save_ckpt_sharded(step):
            """Per-process shard set + quorum manifest. On a pod the
            digest exchange + swap barrier are collective, so the
            write runs SYNCHRONOUSLY on the training thread (every
            process must reach the exchange together; a background
            thread would race the next pass's collectives)."""
            from photon_ml_tpu.io.checkpoint import (
                save_checkpoint_sharded,
            )

            materialize()
            t0 = time.perf_counter()
            params_host, key_host, hist_host, frozen_host = (
                _ckpt_snapshot()
            )
            ekeys_host = (
                {
                    n: [str(k) for k in v]
                    for n, v in entity_keys.items()
                }
                if entity_keys
                else None
            )
            ckpt_writer.join()  # any legacy overlapped write first
            save_checkpoint_sharded(
                checkpoint_dir,
                step,
                params_host,
                key_host,
                history=hist_host,
                frozen=frozen_host,
                entity_keys=ekeys_host,
                num_shards=_sharded_num_shards(),
            )
            obs.registry().observe(
                "game.checkpoint.submit_ms",
                (time.perf_counter() - t0) * 1e3,
            )

        def _save_ckpt(step, wait: bool = False):
            if sharded_checkpoints:
                _save_ckpt_sharded(step)
            else:
                _save_ckpt_local(step, wait)

        def _save_final_shards(step: int) -> None:
            """The pod survivors' final save — collective-free by
            contract: the normal sharded writer exchanges digests and
            barriers over the FULL world, which includes the peer just
            declared dead (it would hang forever without a watchdog, or
            exhaust its retries with one). One elected survivor writes
            the complete quorum step instead
            (``save_checkpoint_sharded_final``). The pending device
            stats are NOT materialized here — their drain may need a
            device collective (reshard of non-addressable trackers) the
            dead peer can no longer complete, and history is replay
            metadata, not math state."""
            from photon_ml_tpu.io.checkpoint import (
                save_checkpoint_sharded_final,
            )

            params_host = {
                n: jax.tree_util.tree_map(
                    lambda a: np.asarray(a), model.params[n]
                )
                for n in names
            }
            ckpt_writer.join()
            save_checkpoint_sharded_final(
                checkpoint_dir,
                step,
                params_host,
                np.asarray(key),
                history=[dataclasses.asdict(h) for h in history],
                frozen=sorted(frozen),
                entity_keys=(
                    {
                        n: [str(k) for k in v]
                        for n, v in entity_keys.items()
                    }
                    if entity_keys
                    else None
                ),
                num_shards=_sharded_num_shards(),
            )

        def _host_loss_boundary(step: int, saved: bool) -> None:
            """Pass-boundary heartbeat poll: on a detected peer loss the
            SURVIVORS' contract runs here — final durable checkpoint at
            this boundary (collective-free on a pod: the dead peer can
            no longer complete an exchange), host-loss marker, then
            surface the exception for the driver's distinct-exit-code
            mapping. The marker is written even when the final save
            FAILS — the restart then resumes from the newest complete
            quorum step instead."""
            if heartbeat is None:
                return
            try:
                heartbeat.check()
            except Exception as e:
                from photon_ml_tpu.resilience.hostloss import (
                    HostLossDetected,
                    write_host_loss_marker,
                )

                if not isinstance(e, HostLossDetected):
                    raise
                if checkpoint_dir is not None:
                    final_ok = True
                    try:
                        if saved:
                            # this boundary's cadence checkpoint already
                            # landed (all peers alive at that point)
                            ckpt_writer.join()
                        elif sharded_checkpoints:
                            # ANY world size: the single-publisher
                            # final writer — collective-free by
                            # construction. The normal sharded writer's
                            # digest exchange + completion barrier
                            # include the peer just declared dead (the
                            # PR-11 hang, photon-lint PL001); routing
                            # single-process emulation through the same
                            # path keeps the recovery writer in tier-1.
                            _save_final_shards(step)
                        else:
                            # legacy format: the overlapped local
                            # writer, no collectives
                            _save_ckpt_local(step, wait=True)
                    except Exception as save_err:  # noqa: BLE001
                        final_ok = False
                        obs.emit_event(
                            "resilience.host_loss_save_failed",
                            cat="resilience",
                            iteration=step,
                            error=repr(save_err),
                        )
                    write_host_loss_marker(
                        checkpoint_dir, step, e.peers, reason=e.reason,
                        final_checkpoint=final_ok,
                    )
                obs.emit_event(
                    "resilience.host_loss",
                    cat="resilience",
                    iteration=step,
                    peers=e.peers,
                )
                raise

        # count XLA backend compiles for the duration of the run: the
        # steady-state zero-recompile invariant of the cached pass/step
        # programs is only provable if something counts actual compiles
        # (obs.compile_events; idempotent global listener)
        obs.install_compile_listener()
        stopped = False
        it = start_it
        # guard replay: when a superpass reports a non-committed pass,
        # run exactly ONE pass through the per-update loop (which owns
        # the rollback/damp/freeze policy), then resume superpassing
        force_plain = False
        while it < num_iterations:
            tracer = obs.get_tracer()
            pass_t0 = time.perf_counter()
            pass_ts = tracer.now_us() if tracer is not None else 0.0
            pass_recs = []  # cost records of this pass's dispatches
            if use_super and not frozen and not force_plain:
                # dispatch chunk: K passes, shrunk to land exactly on
                # the checkpoint cadence and the run end — checkpoint /
                # preemption semantics live at dispatch boundaries
                chunk = min(k_dispatch, num_iterations - it)
                if checkpoint_dir is not None:
                    chunk = min(
                        chunk,
                        checkpoint_every
                        - ((it - start_it) % checkpoint_every),
                    )
                sp_call, sp_states = self._superpass_fn(chunk)
                params_in = {n: model.params[n] for n in names}
                tol_arr = jnp.asarray(float(convergence_tolerance))
                guard_arr = jnp.asarray(bool(divergence_guard))
                rec = None
                if tracer is not None:
                    rec = self._pass_cost(
                        f"superpass-{chunk}",
                        lambda: self._superpass_progs[chunk].lower(
                            sp_states, self.labels, self.base_offsets,
                            self.weights, params_in, scores, key,
                            tol_arr, guard_arr,
                        ),
                    )
                    pass_recs.append(rec)
                t0 = time.perf_counter()
                (params_out, scores, key, passes_dev, guard_dev,
                 conv_dev, objs_tape, tr_tapes) = sp_call(
                    params_in, scores, key, tol_arr, guard_arr
                )
                model.params.update(params_out)
                seconds = time.perf_counter() - t0
                if divergence_guard or convergence_tolerance > 0:
                    # the flags decide host control flow, so reading
                    # them synchronizes — ONE sync per K passes. With
                    # neither feature on they are statically known and
                    # the dispatch chain stays fully pipelined.
                    passes_done = int(passes_dev)
                    guard = bool(guard_dev)
                    converged = bool(conv_dev)
                else:
                    passes_done, guard, converged = chunk, False, False
                for p in range(passes_done):
                    for ci, name in enumerate(names):
                        pending.append(
                            {
                                "iteration": it + p,
                                "coordinate": name,
                                # dispatch wall on the chunk's FIRST
                                # record only — the dispatch is
                                # indivisible (fused-mode contract)
                                "seconds": (
                                    seconds if p == 0 and ci == 0
                                    else None
                                ),
                                "objective": objs_tape[p, ci],
                                "validation_metric": None,
                                "result": self.coordinates[
                                    name
                                ].wrap_tracker(
                                    jax.tree_util.tree_map(
                                        lambda a, _p=p: a[_p],
                                        tr_tapes[ci],
                                    )
                                ),
                            }
                        )
                if tracer is not None:
                    sp_args = {
                        "iteration": it,
                        "chunk": chunk,
                        "passes": passes_done,
                        "coordinates": len(names),
                        "guard": guard,
                        "converged": converged,
                        "timing": "wall",
                    }
                    try:
                        from photon_ml_tpu.kernels import kernel_mode

                        sp_args["sparse_kernel"] = kernel_mode()
                    except Exception:
                        pass
                    if rec is not None and passes_done:
                        # XLA's cost analysis counts the while body
                        # ONCE; scale by the passes that actually ran
                        sp_args.update(
                            rec.achieved(seconds, passes=passes_done)
                        )
                    tracer.add_span(
                        "game.superpass", pass_ts, seconds * 1e6,
                        cat="game", args=sp_args,
                    )
                    # per-pass / per-coordinate spans share even splits
                    # of the indivisible window (same contract as the
                    # fused pass's shared-window coordinate spans)
                    share = seconds * 1e6 / max(passes_done, 1)
                    for p in range(passes_done):
                        tracer.add_span(
                            "game.pass", pass_ts + p * share, share,
                            cat="game",
                            args={
                                "iteration": it + p,
                                "coordinates": len(names),
                                "fused": True,
                                "superpass": True,
                                "timing": "wall",
                            },
                        )
                        for name in names:
                            tracer.add_span(
                                "game.update", pass_ts + p * share,
                                share, cat="game",
                                args={
                                    "coordinate": name,
                                    "iteration": it + p,
                                    "fused": True,
                                    "superpass": True,
                                },
                            )
                    obs.sample_hbm()
                it += passes_done
                _reg = obs.registry()
                _reg.inc("game.dispatches")
                _reg.inc("game.superpasses")
                _reg.inc("game.passes", passes_done)
                if passes_done:
                    _reg.observe(
                        "game.pass_ms", seconds * 1e3 / passes_done
                    )
                if guard:
                    # in-program detection, host-side policy: replay the
                    # non-committed pass through the per-update loop
                    # (same PRNG key — the superpass never advanced it
                    # past the last committed pass, so the replay
                    # reproduces the exact failing updates)
                    force_plain = True
                    obs.emit_event(
                        "resilience.superpass_guard",
                        cat="resilience",
                        iteration=it,
                        passes_done=passes_done,
                    )
                saved = False
                if (
                    passes_done
                    and checkpoint_dir is not None
                    and (it - start_it) % checkpoint_every == 0
                ):
                    _save_ckpt(it)
                    saved = True
                _host_loss_boundary(it, saved)
                if stop_check is not None and stop_check():
                    stopped = True
                    if checkpoint_dir is not None:
                        # the marker promises a durable checkpoint at
                        # this step: drain the overlapped write first
                        if not saved:
                            _save_ckpt(it, wait=True)
                        else:
                            ckpt_writer.join()
                        from photon_ml_tpu.resilience.shutdown import (
                            write_preempted_marker,
                        )

                        write_preempted_marker(
                            checkpoint_dir,
                            it,
                            getattr(stop_check, "signum", None),
                        )
                    break
                if converged:
                    obs.emit_event(
                        "game.converged",
                        cat="game",
                        iteration=it,
                        tolerance=float(convergence_tolerance),
                    )
                    break
                continue
            if use_fused:
                params_in = {n: model.params[n] for n in names}
                fused = self._fused_pass_fn()
                if tracer is not None:
                    fstates = {
                        n: self.coordinates[n].fused_state() for n in names
                    }
                    pass_recs.append(
                        self._pass_cost(
                            "fused",
                            lambda: self._fused_pass.lower(
                                fstates, self.labels, self.base_offsets,
                                self.weights, params_in, scores, key,
                            ),
                        )
                    )
                t0 = time.perf_counter()
                params_out, scores, key, objs, trackers = fused(
                    params_in, scores, key
                )
                model.params.update(params_out)
                seconds = time.perf_counter() - t0
                if tracer is not None:
                    # the fused pass is ONE indivisible dispatch, so the
                    # per-coordinate spans share the pass window; args
                    # mark them fused so nobody reads the duration as a
                    # per-coordinate cost (same contract as the history
                    # records' first-record-only `seconds`)
                    for name in names:
                        tracer.add_span(
                            "game.update",
                            pass_ts,
                            seconds * 1e6,
                            cat="game",
                            args={
                                "coordinate": name,
                                "iteration": it,
                                "fused": True,
                            },
                        )
                for i, (name, obj, tr) in enumerate(
                    zip(names, objs, trackers)
                ):
                    pending.append(
                        {
                            "iteration": it,
                            "coordinate": name,
                            "objective": obj,
                            # full fused-pass wall time on the first record
                            # only; the dispatch is indivisible
                            "seconds": seconds if i == 0 else None,
                            "validation_metric": None,
                            "result": self.coordinates[name].wrap_tracker(
                                tr
                            ),
                        }
                    )
            elif use_chunked:
                fns, states = self._coordinate_step_fns()
                for name in names:
                    if name in frozen:
                        continue
                    with obs.span(
                        "game.update", cat="game",
                        coordinate=name, iteration=it,
                    ) as upd_span:
                        key, sub = jax.random.split(key)
                        params_in = {n: model.params[n] for n in names}
                        rec = None
                        if tracer is not None:
                            rec = self._pass_cost(
                                name,
                                lambda: fns[name].lower(
                                    states, self.labels,
                                    self.base_offsets, self.weights,
                                    params_in, scores, sub,
                                ),
                            )
                            pass_recs.append(rec)
                        t0 = time.perf_counter()
                        p, tr, s, obj = fns[name](
                            states,
                            self.labels,
                            self.base_offsets,
                            self.weights,
                            params_in,
                            scores,
                            sub,
                        )
                        model.params[name] = p
                        scores = {**scores, name: s}
                        seconds = time.perf_counter() - t0
                        # wall of the (async) dispatch window — flagged
                        # so nobody reads chunked-mode MFU as synced
                        # device time (the deferred-stats pipelining
                        # must not gain a block_until_ready here)
                        if rec is not None:
                            obs.annotate_span(
                                upd_span, rec, seconds=seconds
                            )
                            upd_span.set(timing="wall")
                        vmetric = (
                            float(validation_fn(model))
                            if validation_fn is not None
                            else None
                        )
                        pending.append(
                            {
                                "iteration": it,
                                "coordinate": name,
                                "objective": obj,
                                "seconds": seconds,
                                "validation_metric": vmetric,
                                "result": self.coordinates[
                                    name
                                ].wrap_tracker(tr),
                            }
                        )
            else:
                for name in names:
                    if name in frozen:
                        continue
                    update_span = obs.span(
                        "game.update", cat="game",
                        coordinate=name, iteration=it,
                    )
                    with update_span:
                        t0 = time.perf_counter()
                        coord = self.coordinates[name]
                        total = sum(scores.values())
                        partial = total - scores[name]

                        def _attempt(prev_p, residual, sub):
                            if hasattr(coord, "update_and_score"):
                                p, r, s = coord.update_and_score(
                                    prev_p, residual, sub
                                )
                            else:
                                p, r = coord.update(prev_p, residual, sub)
                                s = coord.score(p)
                            # fault site: corrupt-mode poisons the accepted
                            # update with non-finites — the drill for the
                            # divergence guard (and, unguarded, for the
                            # one-NaN-poisons-the-run failure mode)
                            if _faults.fire(
                                "descent.update", key=name
                            ).corrupt:
                                p = jax.tree_util.tree_map(
                                    lambda a: jnp.full_like(a, jnp.nan), p
                                )
                                s = jnp.full_like(s, jnp.nan)
                            return p, r, s

                        key, sub = jax.random.split(key)
                        params, result, new_scores = _attempt(
                            model.params[name], partial, sub
                        )
                        event = None
                        if divergence_guard:
                            cand_scores = {**scores, name: new_scores}
                            cand_params = {**model.params, name: params}
                            obj_host = float(
                                self._full_objective(
                                    cand_scores, cand_params
                                )
                            )
                            if not np.isfinite(obj_host):
                                # rollback to the pre-update state and retry
                                # once against a DAMPED residual (half the
                                # partial score): overshoot-driven overflow
                                # gets a gentler target, injected faults get
                                # a second probe
                                obs.emit_event(
                                    "resilience.rollback",
                                    cat="resilience",
                                    coordinate=name,
                                    iteration=it,
                                )
                                # flight recorder: the spans/metrics
                                # leading INTO the divergence are the
                                # post-mortem; dump them now, before the
                                # damped retry perturbs the state
                                obs.flight_dump("divergence")
                                key, sub = jax.random.split(key)
                                params, result, new_scores = _attempt(
                                    model.params[name], partial * 0.5, sub
                                )
                                cand_scores = {**scores, name: new_scores}
                                cand_params = {
                                    **model.params, name: params
                                }
                                obj_host = float(
                                    self._full_objective(
                                        cand_scores, cand_params
                                    )
                                )
                                if np.isfinite(obj_host):
                                    event = "recovered"
                                else:
                                    # graceful degradation: keep the last
                                    # finite state, exclude the coordinate
                                    # from further passes, keep training the
                                    # rest (the record's objective is the
                                    # retained finite state; event="frozen"
                                    # marks the failure)
                                    frozen.add(name)
                                    event = "frozen"
                                    params = model.params[name]
                                    new_scores = scores[name]
                                    obs.emit_event(
                                        "resilience.freeze",
                                        cat="resilience",
                                        coordinate=name,
                                        iteration=it,
                                    )
                                update_span.set(event=event)
                        model.params[name] = params
                        scores[name] = new_scores

                        obj = self._full_objective(scores, model.params)
                        # seconds measures host dispatch+update wall time;
                        # with deferred stats the device may still be
                        # draining
                        seconds = time.perf_counter() - t0
                        vmetric = (
                            float(validation_fn(model))
                            if validation_fn is not None
                            else None
                        )
                        pending.append(
                            {
                                "iteration": it,
                                "coordinate": name,
                                "objective": obj,
                                "seconds": seconds,
                                "validation_metric": vmetric,
                                "event": event,
                                # the result object is kept whole: reading
                                # .reason/.iterations on a
                                # RandomEffectUpdateSummary materializes
                                # device buffers, which must not happen
                                # until materialize()
                                "result": result,
                            }
                        )
            force_plain = False
            pass_seconds = time.perf_counter() - pass_t0
            if tracer is not None:
                pass_args = {"iteration": it, "coordinates": len(names)}
                # ELL backend the pass's programs traced with — GAME
                # random-effect batches ride ops.sparse's
                # PHOTON_SPARSE_KERNEL dispatch with zero call-site
                # changes, so traces must say which backend they measure
                try:
                    from photon_ml_tpu.kernels import kernel_mode

                    pass_args["sparse_kernel"] = kernel_mode()
                except Exception:
                    pass
                # hardware attribution of the WHOLE pass: the sum of
                # this pass's dispatch cost records (one fused program,
                # or one per chunked coordinate update) over the pass
                # wall — live MFU for coordinate passes in the trace
                flops = sum(
                    r.flops for r in pass_recs
                    if r is not None and r.flops
                )
                bytes_acc = sum(
                    r.bytes_accessed for r in pass_recs
                    if r is not None and r.bytes_accessed
                )
                if flops or bytes_acc:
                    from photon_ml_tpu.obs.xla_cost import CostRecord

                    pass_args["timing"] = "wall"
                    pass_args.update(
                        CostRecord(
                            name="game.pass",
                            bucket="",
                            flops=flops or None,
                            bytes_accessed=bytes_acc or None,
                        ).achieved(pass_seconds)
                    )
                tracer.add_span(
                    "game.pass",
                    pass_ts,
                    pass_seconds * 1e6,
                    cat="game",
                    args=pass_args,
                )
                # live HBM counter-track sample at the pass boundary
                # (graceful no-op where memory_stats is unsupported)
                obs.sample_hbm()
            _reg = obs.registry()
            _reg.inc("game.passes")
            _reg.observe("game.pass_ms", pass_seconds * 1e3)
            saved = False
            if (
                checkpoint_dir is not None
                and (it + 1 - start_it) % checkpoint_every == 0
            ):
                _save_ckpt(it + 1)
                saved = True
            # host-loss poll at the pass boundary — the only point where
            # the survivors hold a complete, checkpointable snapshot
            _host_loss_boundary(it + 1, saved)
            # preemption poll at the pass boundary — the only point where
            # the training state is a complete, checkpointable snapshot
            if stop_check is not None and stop_check():
                stopped = True
                if checkpoint_dir is not None:
                    # the marker promises a durable checkpoint at this
                    # step: drain the overlapped write first
                    if not saved:
                        _save_ckpt(it + 1, wait=True)
                    else:
                        ckpt_writer.join()
                    from photon_ml_tpu.resilience.shutdown import (
                        write_preempted_marker,
                    )

                    write_preempted_marker(
                        checkpoint_dir,
                        it + 1,
                        getattr(stop_check, "signum", None),
                    )
                break
            it += 1
        # the run's durability contract: every checkpoint submitted is
        # on disk (or has raised) before run() returns
        ckpt_writer.join()
        materialize()
        if checkpoint_dir is not None and not stopped:
            # the run reached its target: a stale marker from an earlier
            # preempted attempt no longer applies
            from photon_ml_tpu.resilience.shutdown import (
                clear_preempted_marker,
            )

            clear_preempted_marker(checkpoint_dir)
        return model, history

    def total_scores(self, model: GameModel) -> jax.Array:
        return sum(
            self.coordinates[n].score(model.params[n])
            for n in self.coordinates
        )


# stacked-leaf audit threshold: below this a broadcast miss costs noise;
# above it the grid multiplies a real buffer (designs, row features)
_GRID_STACK_WARN_BYTES = 1 << 20


def _warm_start_params(coords, names, initial_model):
    """Per-coordinate starting params: the warm start's table where one
    is given (a GameModel or a plain name->params mapping), the
    coordinate's cold ``initial_params()`` otherwise. Warm leaves must
    match the cold-start structure and shapes EXACTLY — callers hand us
    entity-keyed, already-remapped tables (load_game_model /
    reindex_entity_params); a shape mismatch here means a positional or
    stale warm start and is refused, never silently cold-started."""
    init = (
        getattr(initial_model, "params", initial_model)
        if initial_model is not None
        else None
    )
    out = {}
    for n in names:
        want = coords[n].initial_params()
        if init is None or n not in init:
            out[n] = want
            continue
        try:
            got = jax.tree_util.tree_map(
                lambda g, w: jnp.asarray(g, jnp.asarray(w).dtype),
                init[n],
                want,
            )
            bad = any(
                jnp.shape(g) != jnp.shape(w)
                for g, w in zip(
                    jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(want),
                )
            )
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"warm start for coordinate {n!r} does not match its "
                f"parameter structure ({e})"
            ) from e
        if bad:
            raise ValueError(
                f"warm start for coordinate {n!r} has mismatched "
                "shapes — warm starts re-key by entity id "
                "(reindex_entity_params / load_game_model), never by "
                "position"
            )
        out[n] = got
    return out


def run_grid(
    cd: CoordinateDescent,
    combos: Sequence[Mapping[str, float]],
    num_iterations: int,
    seed: int = 0,
    initial_model=None,
):
    """Train EVERY reg-weight combo simultaneously by vmapping the
    per-coordinate chunked dispatch over a combo axis (SURVEY §2.5.6,
    hyperparameter parallelism; VERDICT r4 #8).

    Grid entries share every shape — only reg weights differ — so the
    combo axis vmaps over (params, scores, reg-weight leaves) while the
    design/data arrays broadcast. This is valid exactly where the
    reference trains grid entries independently
    (``cli/game/training/Driver.scala:317-384``); it does NOT apply to
    the lambda-PATH-with-warm-starts semantics (sequential by
    definition) nor to per-update validation.

    Each combo's result is IDENTICAL to a sequential
    ``cd.run(num_iterations, seed=seed)`` with that combo's reg weights
    (same PRNG stream: every lane shares the split sequence, like the
    sequential runs each starting from the same seed).

    ``initial_model`` (a :class:`GameModel` or name->params mapping)
    warm-starts EVERY lane from the same tables — the lifecycle
    retrain's cheap in-cycle model selection: the previous export seeds
    all combos at once and each lane's result still matches
    ``cd.run(..., initial_model=...)`` with that combo. Warm tables
    must already be entity-keyed into THIS run's vocabulary; shape
    mismatches are refused (the positional warm-start bug class).

    Returns ``(models, history)``: ``models[c]`` is combo c's
    :class:`GameModel`; ``history[c]`` the combo's
    :class:`CoordinateUpdateRecord` list (fused-timing semantics —
    wall seconds on each pass's first record only).
    """
    names = list(cd.coordinates)
    coords = cd.coordinates
    combos = list(combos)
    n_combo = len(combos)
    if n_combo < 2:
        raise ValueError(
            f"run_grid needs >= 2 combos (got {n_combo}); run cd.run() "
            "for a single configuration"
        )
    for c in coords.values():
        if not hasattr(c, "fused_state_for_reg"):
            raise ValueError(
                f"{type(c).__name__} does not support grid vmapping "
                "(no fused_state_for_reg); run combos sequentially"
            )
    fns, _ = cd._coordinate_step_fns()

    # stack ONLY the leaves that vary with the reg weight; shared data
    # leaves broadcast (identified by object identity across two probe
    # states — the coordinate returns the SAME arrays for the invariant
    # parts)
    per_combo = [
        {n: coords[n].fused_state_for_reg(cb[n]) for n in names}
        for cb in combos
    ]
    probe_a = {n: coords[n].fused_state_for_reg(0.5) for n in names}
    probe_b = {n: coords[n].fused_state_for_reg(0.25) for n in names}
    axes = jax.tree_util.tree_map(
        lambda a, b: None if a is b else 0, probe_a, probe_b
    )

    # Same-OBJECT contract (``FixedEffectCoordinate.fused_state_for_reg``
    # documents it): combo-invariant leaves must come back as the
    # identical array object on every call so the identity test above
    # broadcasts them. A coordinate that rebuilds an invariant leaf per
    # call still trains CORRECTLY — but the leaf gets stacked n_combo
    # times, multiplying its footprint by the grid size. Detect the
    # miss for leaves where that costs real memory (value-equal across
    # the first two combos yet not the same object) and warn loudly
    # instead of silently burning HBM.
    def _stack_with_audit(path, *leaves):
        if all(l is leaves[0] for l in leaves):
            return leaves[0]
        stacked = jnp.stack(leaves)
        if (
            getattr(stacked, "nbytes", 0) >= _GRID_STACK_WARN_BYTES
            and np.array_equal(
                np.asarray(leaves[0]), np.asarray(leaves[1])
            )
        ):
            import warnings

            leaf_name = jax.tree_util.keystr(path)
            warnings.warn(
                f"run_grid: leaf {leaf_name} ({stacked.nbytes / 1e6:.1f}"
                f" MB stacked) is value-identical across combos but was "
                "returned as a fresh object by fused_state_for_reg, so "
                "it is stacked x{} instead of broadcast — return the "
                "SAME array object for combo-invariant leaves".format(
                    n_combo
                ),
                RuntimeWarning,
                stacklevel=3,
            )
        return stacked

    states = jax.tree_util.tree_map_with_path(
        _stack_with_audit, *per_combo
    )
    vfns = {
        n: jax.vmap(fns[n], in_axes=(axes, None, None, None, 0, 0, None))
        for n in names
    }

    def broadcast(p):
        return jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(
                a, (n_combo,) + jnp.shape(a)
            ),
            p,
        )

    starts = _warm_start_params(coords, names, initial_model)
    params = broadcast(starts)
    scores = broadcast(
        {n: coords[n].score(starts[n]) for n in names}
    )
    key = jax.random.PRNGKey(seed)
    records = []  # (iteration, name, objective (C,), trackers, seconds)
    for it in range(num_iterations):
        t0 = time.perf_counter()
        for i, name in enumerate(names):
            key, sub = jax.random.split(key)
            p, tr, s, obj = vfns[name](
                states, cd.labels, cd.base_offsets, cd.weights,
                params, scores, sub,
            )
            params = {**params, name: p}
            scores = {**scores, name: s}
            records.append([it, name, obj, tr, None])
        records[-len(names)][4] = time.perf_counter() - t0

    # ONE batched host drain for every combo's stats (docs/PERF.md r5)
    host = jax.device_get([(r[2], r[3]) for r in records])
    models = [
        GameModel(
            {
                n: jax.tree_util.tree_map(lambda a: a[c], params[n])
                for n in names
            }
        )
        for c in range(n_combo)
    ]
    history: List[List[CoordinateUpdateRecord]] = [
        [] for _ in range(n_combo)
    ]
    for (it, name, _, _, seconds), (objs, tr) in zip(records, host):
        for c in range(n_combo):
            tr_c = jax.tree_util.tree_map(lambda a: a[c], tr)
            summary = coords[name].wrap_tracker(tr_c)
            history[c].append(
                _history_record(
                    it,
                    name,
                    np.asarray(objs)[c],
                    summary.reason,
                    summary.iterations,
                    seconds,
                )
            )
    return models, history


def _lambda_segment_fn(cd: CoordinateDescent, length: int):
    """ONE device dispatch for a whole lambda-path segment: ``length``
    coordinate-descent passes ride a ``lax.scan`` through the same
    fused update surface as :func:`run_grid` (reg weights enter as jit
    ARGUMENTS via ``fused_state_for_reg``, so every combo on the path
    reuses this one executable — zero recompiles per lambda). Cached on
    the descent object per pass count."""
    cache = getattr(cd, "_lambda_segment_fns", None)
    if cache is None:
        cache = cd._lambda_segment_fns = {}
    fn = cache.get(length)
    if fn is not None:
        return fn
    names = list(cd.coordinates)
    coords = cd.coordinates
    loss_fn = _loss_fn_for_task(cd.task)

    def segment(states, labels, base_offsets, weights, params, scores,
                key):
        live = {
            n: coords[n].with_fused_state(states[n]) for n in names
        }

        def body(carry, _):
            params, scores, key = carry
            objs = []
            trackers = {}
            for name in names:
                key, sub = jax.random.split(key)
                total = sum(scores.values())
                partial = total - scores[name]
                p, tr, s = live[name].update_step(
                    params[name], partial, sub
                )
                params = {**params, name: p}
                scores = {**scores, name: s}
                reg = sum(
                    _coordinate_reg_term(live[n], params[n])
                    for n in names
                )
                tot = sum(scores[n] for n in names)
                objs.append(
                    loss_fn(labels, base_offsets + tot, weights) + reg
                )
                trackers[name] = tr
            return (params, scores, key), (jnp.stack(objs), trackers)

        (params, scores, key), ys = jax.lax.scan(
            body, (params, scores, key), None, length=length
        )
        return params, scores, ys

    fn = cache[length] = jax.jit(segment)
    return fn


def run_lambda_path(
    cd: CoordinateDescent,
    combos: Sequence[Mapping[str, float]],
    num_iterations: int,
    seed: int = 0,
    initial_model=None,
    scan: bool = True,
):
    """Warm-started lambda PATH over reg-weight combos — the sequential
    semantics :func:`run_grid` explicitly does not cover: combo c+1
    warm-starts from combo c's solution (order combos strongest-lambda
    first, the GLM driver's descending-path convention), so late combos
    converge from an already-good start. With ``initial_model`` the
    FIRST combo warm-starts too (the previous export, entity-keyed) —
    model selection cheap enough to run inside a lifecycle retrain
    cycle.

    Each segment rides the PR-8 scan path: ``scan=True`` runs all
    ``num_iterations`` passes of a combo as ONE device dispatch
    (``lax.scan`` over passes; :func:`_lambda_segment_fn`), compiled
    once for the whole path because reg weights are jit arguments.
    ``scan=False`` runs the identical math through the per-update
    chunked loop (one dispatch per coordinate update) — the lifecycle
    drill asserts scan==loop equivalence.

    Every combo restarts the PRNG stream from ``seed`` (matching a
    sequential ``cd.run(seed=seed)`` per combo and :func:`run_grid`'s
    lanes); only the warm start carries forward. Returns ``(models,
    history)`` shaped like :func:`run_grid` — one entry per combo, in
    path order."""
    names = list(cd.coordinates)
    coords = cd.coordinates
    combos = list(combos)
    if not combos:
        raise ValueError("run_lambda_path needs >= 1 combo")
    for c in coords.values():
        if not hasattr(c, "fused_state_for_reg"):
            raise ValueError(
                f"{type(c).__name__} does not support the lambda path "
                "(no fused_state_for_reg); run combos sequentially"
            )
    params = _warm_start_params(coords, names, initial_model)
    scores = {n: coords[n].score(params[n]) for n in names}
    fns, _ = cd._coordinate_step_fns()
    models: List[GameModel] = []
    raw: List[tuple] = []  # (combo idx, objs, trackers) device refs
    for cb in combos:
        states = {
            n: coords[n].fused_state_for_reg(cb[n]) for n in names
        }
        key = jax.random.PRNGKey(seed)
        if scan:
            t0 = time.perf_counter()
            params, scores, (objs, trackers) = _lambda_segment_fn(
                cd, num_iterations
            )(
                states, cd.labels, cd.base_offsets, cd.weights,
                params, scores, key,
            )
            raw.append((objs, trackers, time.perf_counter() - t0))
        else:
            objs_acc = []
            trackers_acc = {n: [] for n in names}
            t0 = time.perf_counter()
            for _ in range(num_iterations):
                it_objs = []
                for name in names:
                    key, sub = jax.random.split(key)
                    p, tr, s, obj = fns[name](
                        states, cd.labels, cd.base_offsets, cd.weights,
                        params, scores, sub,
                    )
                    params = {**params, name: p}
                    scores = {**scores, name: s}
                    it_objs.append(obj)
                    trackers_acc[name].append(tr)
                objs_acc.append(jnp.stack(it_objs))
            objs = jnp.stack(objs_acc)  # (T, N) like the scan output
            trackers = {
                n: jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *trackers_acc[n]
                )
                for n in names
            }
            raw.append((objs, trackers, time.perf_counter() - t0))
        models.append(GameModel(dict(params)))
    # ONE batched host drain for the whole path (docs/PERF.md r5)
    host = jax.device_get([(o, t) for o, t, _ in raw])
    history: List[List[CoordinateUpdateRecord]] = []
    for (objs, trackers), (_, _, seconds) in zip(host, raw):
        records: List[CoordinateUpdateRecord] = []
        for it in range(num_iterations):
            for i, name in enumerate(names):
                tr_it = jax.tree_util.tree_map(
                    lambda a: a[it], trackers[name]
                )
                summary = coords[name].wrap_tracker(tr_it)
                records.append(
                    _history_record(
                        it,
                        name,
                        np.asarray(objs)[it, i],
                        summary.reason,
                        summary.iterations,
                        seconds if it == 0 and i == 0 else None,
                    )
                )
        history.append(records)
    return models, history
