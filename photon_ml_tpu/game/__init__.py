"""GAME: Generalized Additive Mixed Effects, TPU-first.

Rebuild of the reference's experimental heart (SURVEY §2.3): one global
*fixed-effect* GLM plus many per-entity *random-effect* GLMs trained by
block coordinate descent with residual score offsets
(``algorithm/CoordinateDescent.scala:39-198``).

Architecture vs the reference:
  - Scores are dense (n,) device arrays indexed by row — the reference's
    KeyValueScore RDD joins (``data/KeyValueScore.scala:60-85``) become
    plain array arithmetic.
  - Random effects hold an (entities, dim) coefficient table; scoring is an
    embedding-style gather (missing entity -> index -1 -> score 0, the
    reference's semantic at ``model/RandomEffectModel.scala:117-146``).
  - Per-entity training data is bucketed into padded (entities, rows, dim)
    tensors at ingest (``RandomEffectDataSet``'s grouping/capping,
    ``data/RandomEffectDataSet.scala:172-380``) and solved by ONE vmapped
    jitted solver call — the reference's millions of independent in-executor
    solves (``algorithm/RandomEffectCoordinate.scala:185-213``) with zero
    scheduling overhead.
  - Down-sampling keeps static shapes: dropped rows get weight 0 and kept
    negatives are re-weighted (``sampler/BinaryClassificationDownSampler``).
"""

from photon_ml_tpu.game.data import (
    BucketedRandomEffectDesign,
    EntityRowPartition,
    EntityShardAssignment,
    GameData,
    RandomEffectDesign,
    build_bucketed_random_effect_design,
    build_random_effect_design,
    entity_partition_game_data,
    entity_partition_rows,
    entity_shard_assignment,
)
from photon_ml_tpu.game.coordinates import (
    CoordinateConfig,
    EntityShardedRandomEffectCoordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.descent import CoordinateDescent, GameModel
from photon_ml_tpu.game.factored import (
    FactoredConfig,
    FactoredParams,
    FactoredRandomEffectCoordinate,
    MatrixFactorizationModel,
)
from photon_ml_tpu.game.projected import (
    ProjectedRandomEffectCoordinate,
    build_index_map_columns,
    parse_projector_spec,
)

__all__ = [
    "FactoredConfig",
    "FactoredParams",
    "FactoredRandomEffectCoordinate",
    "MatrixFactorizationModel",
    "ProjectedRandomEffectCoordinate",
    "build_index_map_columns",
    "parse_projector_spec",
    "GameData",
    "RandomEffectDesign",
    "BucketedRandomEffectDesign",
    "build_random_effect_design",
    "build_bucketed_random_effect_design",
    "CoordinateConfig",
    "EntityRowPartition",
    "EntityShardAssignment",
    "EntityShardedRandomEffectCoordinate",
    "FixedEffectCoordinate",
    "RandomEffectCoordinate",
    "CoordinateDescent",
    "GameModel",
    "entity_partition_game_data",
    "entity_partition_rows",
    "entity_shard_assignment",
]
