"""GAME data layer: struct-of-arrays batches + per-entity bucketing.

Rebuild of the reference's L5 (``data/GameDatum.scala:32``,
``data/FixedEffectDataSet.scala``, ``data/RandomEffectDataSet.scala:39-381``,
``data/LocalDataSet.scala``). A GAME dataset here is:

  - feature shards: dict shard_id -> dense (n, d_shard) matrix, or a
    padded-ELL ``ops.sparse.SparseFeatures`` for wide shards (the
    reference's featureShardContainer, one Breeze vector per row per
    shard — sparse Breeze vectors map to the ELL container). Sparse
    shards serve FIXED-EFFECT coordinates; per-entity designs need the
    dense row gather and reject them.
  - response/offset/weight columns (n,)
  - entity columns: dict random_effect_id -> (n,) int32 entity indices
    (index -1 = entity unseen at vocabulary build; scores 0 like the
    reference's missing-entity cogroup)

Random-effect training data is bucketed ONCE at ingest into padded
(num_entities, rows_cap, d) tensors (`RandomEffectDesign`) — the TPU analog
of RandomEffectDataSet's groupByKey + reservoir capping + partitioner
placement. Rows beyond the cap stay out of the active tensors but are still
scored through the coefficient table (the reference's passive data,
``RandomEffectDataSet.scala:319-358``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.types import LabeledBatch, _pytree_dataclass


@dataclasses.dataclass
class GameData:
    """Host-side container for a scored dataset (plain arrays, not a pytree;
    device placement happens per coordinate)."""

    features: Dict[str, np.ndarray]  # shard -> (n, d_shard)
    labels: np.ndarray  # (n,)
    offsets: np.ndarray  # (n,)
    weights: np.ndarray  # (n,)
    entity_ids: Dict[str, np.ndarray]  # re_name -> (n,) int32, -1 = unknown

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    @staticmethod
    def create(
        features: Mapping[str, np.ndarray],
        labels,
        offsets=None,
        weights=None,
        entity_ids: Optional[Mapping[str, np.ndarray]] = None,
    ) -> "GameData":
        from photon_ml_tpu.ops.sparse import is_hybrid, is_structured

        labels = np.asarray(labels, np.float64)
        n = labels.shape[0]
        for name, v in {**features, **(entity_ids or {})}.items():
            if is_hybrid(v):
                # hybrid rows are permuted relative to every other column;
                # GAME joins shards/entities/scores BY ROW
                raise ValueError(
                    f"shard {name!r} is a HybridFeatures container; GAME "
                    "shards must be dense or plain ELL (row-aligned)"
                )
            rows = v.shape[0] if is_structured(v) else np.shape(v)[0]
            if rows != n:
                raise ValueError(
                    f"column {name!r} has {rows} rows, labels have {n}"
                )
        return GameData(
            features={
                k: (v if is_structured(v) else np.asarray(v))
                for k, v in features.items()
            },
            labels=labels,
            offsets=(
                np.zeros(n) if offsets is None else np.asarray(offsets, np.float64)
            ),
            weights=(
                np.ones(n) if weights is None else np.asarray(weights, np.float64)
            ),
            entity_ids={
                k: np.asarray(v, np.int32)
                for k, v in (entity_ids or {}).items()
            },
        )

    def fixed_effect_batch(self, shard: str, dtype=jnp.float32) -> LabeledBatch:
        """(n, d) LabeledBatch view for a fixed-effect coordinate
        (``data/FixedEffectDataSet.scala:31``)."""
        return LabeledBatch.create(
            self.features[shard],
            self.labels,
            offsets=self.offsets,
            weights=self.weights,
            dtype=dtype,
        )


@_pytree_dataclass
class RandomEffectDesign:
    """Padded per-entity active training tensors for one random effect.

    features: (E, R, d)   labels/weights/mask: (E, R)
    row_index: (E, R) int32 — global row each active slot came from (-1 pad),
    used to gather per-row residual offsets each coordinate pass without
    re-bucketing.
    """

    features: jax.Array
    labels: jax.Array
    weights: jax.Array
    mask: jax.Array
    row_index: jax.Array

    @property
    def num_entities(self) -> int:
        return self.features.shape[0]

    @property
    def rows_per_entity(self) -> int:
        return self.features.shape[1]

    @property
    def dim(self) -> int:
        return self.features.shape[2]

    def gather_offsets(self, full_offsets: jax.Array) -> jax.Array:
        """(n,) -> (E, R): route each row's current residual offset to its
        active slot. The reference does this with an RDD join per pass
        (``data/RandomEffectDataSet.scala:58-75``); here it is one gather."""
        safe = jnp.maximum(self.row_index, 0)
        return jnp.take(full_offsets, safe, axis=0) * self.mask


def _grouped_rows(eids: np.ndarray, seed: int):
    """Vectorized per-entity grouping with a uniform random shuffle inside
    each entity (the reservoir-sample analog; no Python per-entity loop).

    Returns (order, sorted_ids, slot, uniq, counts): `order` are row indices
    sorted by (entity, random), `slot` is each row's position within its
    entity, `uniq`/`counts` the entities present and their row counts.
    """
    rng = np.random.default_rng(seed)
    rand = rng.uniform(size=eids.shape[0])
    order = np.lexsort((rand, eids))
    sorted_ids = eids[order]
    valid = sorted_ids >= 0
    order, sorted_ids = order[valid], sorted_ids[valid]
    uniq, starts, counts = np.unique(
        sorted_ids, return_index=True, return_counts=True
    )
    slot = np.arange(order.size) - np.repeat(starts, counts)
    return order, sorted_ids, slot, uniq, counts


def _fill_design(
    data: GameData,
    shard: str,
    rows: np.ndarray,
    ent_rows: np.ndarray,
    slot_rows: np.ndarray,
    rescale_rows: np.ndarray,
    shape_e: int,
    cap: int,
    dtype,
) -> RandomEffectDesign:
    """Scatter kept rows into padded (shape_e, cap, d) tensors."""
    x = np.asarray(data.features[shard])
    d = x.shape[1]
    feats = np.zeros((shape_e, cap, d), np.float64)
    labels = np.zeros((shape_e, cap), np.float64)
    weights = np.zeros((shape_e, cap), np.float64)
    mask = np.zeros((shape_e, cap), np.float64)
    row_index = np.full((shape_e, cap), -1, np.int64)
    feats[ent_rows, slot_rows] = x[rows]
    labels[ent_rows, slot_rows] = data.labels[rows]
    weights[ent_rows, slot_rows] = data.weights[rows] * rescale_rows
    mask[ent_rows, slot_rows] = 1.0
    row_index[ent_rows, slot_rows] = rows
    return RandomEffectDesign(
        features=jnp.asarray(feats, dtype),
        labels=jnp.asarray(labels, dtype),
        weights=jnp.asarray(weights, dtype),
        mask=jnp.asarray(mask, dtype),
        row_index=jnp.asarray(row_index, jnp.int32),
    )


def pearson_correlation_scores(
    features: np.ndarray, labels: np.ndarray, mask: np.ndarray
) -> np.ndarray:
    """(E, R, d) design -> (E, d) per-entity Pearson |correlation| basis.

    Vectorized rebuild of ``LocalDataSet.computePearsonCorrelationScore``
    (``LocalDataSet.scala:198-259``): per entity, corr(feature_j, label)
    over its active rows; a present feature with ~zero variance is the
    intercept — the FIRST such gets score 1.0, later ones 0.0; features
    absent from the entity's rows score -inf (never selected).
    """
    m = mask > 0
    x = np.where(m[:, :, None], features, 0.0)
    y = np.where(m, labels, 0.0)
    n = m.sum(axis=1).astype(np.float64)[:, None]  # (E, 1)
    s1 = x.sum(axis=1)
    s2 = (x * x).sum(axis=1)
    sxy = (x * y[:, :, None]).sum(axis=1)
    ly = y.sum(axis=1)[:, None]
    lyy = (y * y).sum(axis=1)[:, None]
    numerator = n * sxy - s1 * ly
    feat_var = np.abs(n * s2 - s1 * s1)
    std = np.sqrt(feat_var)
    label_var = np.maximum(n * lyy - ly * ly, 0.0)
    denominator = std * np.sqrt(label_var)
    # constant labels: correlation is undefined, and a tiny-denominator
    # guard would amplify cancellation noise into garbage scores — force 0.
    # Thresholds are RELATIVE to the moment magnitudes (absolute epsilons
    # break under catastrophic cancellation at large n / large values).
    label_const = label_var < 1e-9 * np.maximum(n * lyy, 1.0)
    score = np.where(
        label_const, 0.0, numerator / (denominator + 1e-12)
    )

    present = s2 > 0.0
    constant = present & (feat_var < 1e-9 * np.maximum(n * s2, 1.0))
    # first constant (intercept-like) feature per entity scores 1.0
    first_const = constant & (
        np.cumsum(constant, axis=1) == 1
    )
    score = np.where(constant, 0.0, score)
    score = np.where(first_const, 1.0, score)
    return np.where(present, np.abs(score), -np.inf)


def filter_features_by_support(
    design: RandomEffectDesign, min_support: int
) -> RandomEffectDesign:
    """Per-entity support filter (``LocalDataSet.filterFeaturesBySupport``,
    ``LocalDataSet.scala:80-109``): a feature survives for an entity iff
    it is STORED (nonzero here — the dense analog of activeKeysIterator)
    in at least ``min_support`` of that entity's active rows. Dropped
    columns are zeroed so their coefficients solve to exactly 0. The
    cheap pre-filter the reference offers ahead of the Pearson ranking.

    Known divergence, by design: the reference counts EXPLICITLY-STORED
    entries (a stored 0.0 adds support there); the dense projected design
    cannot distinguish a stored zero from an absent one, so value != 0 is
    the storedness proxy. Entities whose features carry explicit zeros in
    the source Avro may keep fewer columns here. Exact parity would
    require threading the ELL padding mask through the projection."""
    if min_support <= 0:
        return design
    feats = np.asarray(design.features)
    mask = np.asarray(design.mask) > 0
    support = ((feats != 0.0) & mask[:, :, None]).sum(axis=1)  # (E, d)
    keep = support >= min_support
    return dataclasses.replace(
        design,
        features=jnp.asarray(
            np.where(keep[:, None, :], feats, 0.0), design.features.dtype
        ),
    )


def select_features_by_pearson(
    design: RandomEffectDesign, ratio: float
) -> RandomEffectDesign:
    """Per-entity feature selection: keep the top ceil(ratio * n_e)
    features by |Pearson corr|, zeroing the rest in the design so their
    coefficients solve to exactly 0 (the dense-rep analog of
    ``RandomEffectDataSet.featureSelectionOnActiveData``,
    ``RandomEffectDataSet.scala:360-380``)."""
    if ratio <= 0:
        raise ValueError(f"feature ratio must be positive, got {ratio}")
    feats = np.asarray(design.features, np.float64)
    mask = np.asarray(design.mask)
    score = pearson_correlation_scores(
        feats, np.asarray(design.labels, np.float64), mask
    )
    e, _, d = feats.shape
    n_e = (mask > 0).sum(axis=1)
    k_e = np.minimum(np.ceil(ratio * n_e).astype(np.int64), d)
    rank = np.argsort(np.argsort(-score, axis=1, kind="stable"), axis=1)
    keep = rank < k_e[:, None]  # (E, d)
    return dataclasses.replace(
        design,
        features=jnp.asarray(
            np.where(keep[:, None, :], feats, 0.0), design.features.dtype
        ),
    )


def build_random_effect_design(
    data: GameData,
    random_effect: str,
    shard: str,
    num_entities: int,
    active_cap: Optional[int] = None,
    seed: int = 0,
    dtype=jnp.float32,
    feature_ratio: Optional[float] = None,
    min_support: int = 0,
) -> RandomEffectDesign:
    """Group rows by entity into padded tensors (host-side, once per run).

    Semantics from ``RandomEffectDataSet.buildWithConfiguration``:
      - at most `active_cap` active rows per entity, chosen uniformly at
        random (the reference's reservoir sample, :247-308);
      - sampled rows get weight * count/cap so each entity's total active
        weight is preserved (:299-302);
      - rows of entities with index -1 (unknown) are dropped;
      - `num_entities` fixes the leading axis = the coefficient-table size.

    One global row cap means one hot entity inflates padding for all; use
    :func:`build_bucketed_random_effect_design` when entity sizes are skewed.
    """
    from photon_ml_tpu.ops.sparse import is_structured

    if is_structured(data.features[shard]):
        raise ValueError(
            f"random effect {random_effect!r}: per-entity designs gather "
            f"dense rows; shard {shard!r} is sparse (sparse shards serve "
            "fixed-effect coordinates only)"
        )
    eids = np.asarray(data.entity_ids[random_effect])
    if active_cap is not None and active_cap <= 0:
        raise ValueError(f"active_cap must be positive, got {active_cap}")
    order, sorted_ids, slot, uniq, counts = _grouped_rows(eids, seed)

    max_count = int(counts.max()) if counts.size else 1
    cap = min(max_count, active_cap) if active_cap is not None else max_count

    cap_of = np.minimum(counts, cap)
    keep = slot < np.repeat(cap_of, counts)
    rescale = np.repeat(np.where(counts > cap, counts / cap, 1.0), counts)
    design = _fill_design(
        data,
        shard,
        order[keep],
        sorted_ids[keep],
        slot[keep],
        rescale[keep],
        num_entities,
        cap,
        dtype,
    )
    # support filter first, Pearson ranking second (the reference's
    # LocalDataSet order: the cheap count-based cut precedes the ranking)
    design = filter_features_by_support(design, min_support)
    if feature_ratio is not None:
        design = select_features_by_pearson(design, feature_ratio)
    return design


@dataclasses.dataclass
class BucketedRandomEffectDesign:
    """Size-bucketed padded designs for one random effect.

    Entities are grouped by row count into a few buckets, each padded only
    to ITS max count — the TPU analog of the reference's load-balanced
    entity placement (``data/RandomEffectIdPartitioner.scala:65-99``): the
    greedy bin-pack balanced per-partition work; here the same skew problem
    is solved by making padding local to a size class, so one hot entity no
    longer inflates every entity's padded rows.

    buckets[b] tensors have shape (E_b, R_b, d); entity_index[b] maps bucket
    lane -> row of the global (num_entities, d) coefficient table. Lanes
    padded for entity-axis sharding carry sentinel `num_entities`, which
    gathers clip and scatters drop.
    """

    buckets: list  # List[RandomEffectDesign]
    entity_index: list  # List[np.ndarray (E_b,) int32]
    num_entities: int

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    @property
    def dim(self) -> int:
        return self.buckets[0].dim

    @property
    def active_slots(self) -> int:
        """Total padded (entity, row) slots across buckets — the memory and
        FLOP footprint a global-cap design would inflate."""
        return sum(b.num_entities * b.rows_per_entity for b in self.buckets)


def _split_minimizing_padding(sorted_counts: np.ndarray, max_buckets: int):
    """Optimal contiguous split of ascending per-entity row counts into at
    most `max_buckets` groups minimizing total padded slots
    Σ_b |entities_b| · max_count_b (exact DP over distinct counts — the
    number of distinct entity sizes is small even when entities number
    millions). Returns [(lo, hi)) index ranges into sorted_counts."""
    if sorted_counts.size == 0:
        return []
    values, first, nums = np.unique(
        sorted_counts, return_index=True, return_counts=True
    )
    m = values.size
    k = min(max_buckets, m)
    prefix = np.concatenate([[0], np.cumsum(nums)])
    INF = float("inf")
    # dp[j] = min cost covering distinct values [0, j) ; rebuilt per layer
    dp = np.full(m + 1, INF)
    dp[0] = 0.0
    choice = np.zeros((k, m + 1), np.int64)
    for layer in range(k):
        nxt = np.full(m + 1, INF)
        for j in range(1, m + 1):
            # bucket = distinct values [i, j) with cap values[j-1]
            costs = dp[:j] + (prefix[j] - prefix[:j]) * values[j - 1]
            i = int(np.argmin(costs))
            nxt[j] = costs[i]
            choice[layer, j] = i
        dp = nxt
    # backtrack
    bounds = []
    j = m
    layer = k - 1
    while j > 0:
        i = int(choice[layer, j])
        bounds.append((int(prefix[i]), int(prefix[j])))
        j = i
        layer -= 1
    return bounds[::-1]


def build_bucketed_random_effect_design(
    data: GameData,
    random_effect: str,
    shard: str,
    num_entities: int,
    num_buckets: int = 4,
    active_cap: Optional[int] = None,
    entity_multiple: int = 1,
    seed: int = 0,
    dtype=jnp.float32,
    feature_ratio: Optional[float] = None,
    min_support: int = 0,
) -> BucketedRandomEffectDesign:
    """Like :func:`build_random_effect_design` but with per-size-class row
    caps. Entities (those with data) are sorted by row count and split into
    `num_buckets` contiguous groups; each bucket's row cap is its own max
    count (still bounded by `active_cap`, with the same weight-preserving
    rescale). `entity_multiple` pads each bucket's entity axis up to a
    multiple (the entity-mesh-axis size) so buckets shard evenly."""
    from photon_ml_tpu.ops.sparse import is_structured

    if is_structured(data.features[shard]):
        raise ValueError(
            f"random effect {random_effect!r}: per-entity designs gather "
            f"dense rows; shard {shard!r} is sparse (sparse shards serve "
            "fixed-effect coordinates only)"
        )
    eids = np.asarray(data.entity_ids[random_effect])
    if active_cap is not None and active_cap <= 0:
        raise ValueError(f"active_cap must be positive, got {active_cap}")
    if entity_multiple <= 0:
        raise ValueError(f"entity_multiple must be positive, got {entity_multiple}")
    order, sorted_ids, slot, uniq, counts = _grouped_rows(eids, seed)

    if uniq.size == 0:
        # no rows with a known entity: one all-masked bucket so callers
        # (initial_params, update) keep working, like the global builder
        empty_idx = np.asarray([], np.int64)
        return BucketedRandomEffectDesign(
            buckets=[
                _fill_design(
                    data, shard, empty_idx, empty_idx, empty_idx,
                    np.asarray([]), entity_multiple, 1, dtype,
                )
            ],
            entity_index=[
                np.full(entity_multiple, num_entities, np.int32)
            ],
            num_entities=num_entities,
        )

    # per-entity active cap under the bucket policy
    by_count = np.argsort(counts, kind="stable")
    splits = _split_minimizing_padding(counts[by_count], num_buckets)
    splits = [by_count[lo:hi] for lo, hi in splits]

    cap_of_entity = np.zeros(num_entities, np.int64)
    bucket_of_entity = np.full(num_entities, -1, np.int64)
    local_of_entity = np.zeros(num_entities, np.int64)
    bucket_caps = []
    bucket_entities = []
    for b, split in enumerate(splits):
        ents = uniq[split]
        cmax = int(counts[split].max())
        cap_b = min(cmax, active_cap) if active_cap is not None else cmax
        bucket_caps.append(cap_b)
        bucket_entities.append(ents)
        cap_of_entity[ents] = np.minimum(counts[split], cap_b)
        bucket_of_entity[ents] = b
        local_of_entity[ents] = np.arange(ents.size)

    keep = slot < cap_of_entity[sorted_ids]
    full_count = np.zeros(num_entities, np.int64)
    full_count[uniq] = counts
    rescale_of_entity = np.where(
        full_count > cap_of_entity,
        full_count / np.maximum(cap_of_entity, 1),
        1.0,
    )

    rows = order[keep]
    ents = sorted_ids[keep]
    slots = slot[keep]

    buckets = []
    entity_index = []
    for b, (cap_b, ents_b) in enumerate(zip(bucket_caps, bucket_entities)):
        sel = bucket_of_entity[ents] == b
        e_pad = -(-ents_b.size // entity_multiple) * entity_multiple
        bucket = _fill_design(
            data,
            shard,
            rows[sel],
            local_of_entity[ents[sel]],
            slots[sel],
            rescale_of_entity[ents[sel]],
            e_pad,
            cap_b,
            dtype,
        )
        bucket = filter_features_by_support(bucket, min_support)
        if feature_ratio is not None:
            bucket = select_features_by_pearson(bucket, feature_ratio)
        buckets.append(bucket)
        idx = np.full(e_pad, num_entities, np.int64)
        idx[: ents_b.size] = ents_b
        entity_index.append(np.asarray(idx, np.int32))

    return BucketedRandomEffectDesign(
        buckets=buckets, entity_index=entity_index, num_entities=num_entities
    )


def build_entity_vocabulary(raw_ids: np.ndarray):
    """Map raw entity keys -> dense [0, E) indices (the analog of the
    reference's per-entity partitioner + index maps). Returns (vocab dict,
    (n,) int32 index column)."""
    uniq = np.unique(raw_ids)
    vocab = {k: i for i, k in enumerate(uniq.tolist())}
    idx = np.asarray([vocab[k] for k in raw_ids.tolist()], np.int32)
    return vocab, idx


def apply_entity_vocabulary(vocab: dict, raw_ids: np.ndarray) -> np.ndarray:
    """Index new data against an existing vocabulary; unknown -> -1
    (scores 0, ``model/RandomEffectModel.scala:117-146``)."""
    return np.asarray(
        [vocab.get(k, -1) for k in raw_ids.tolist()], np.int32
    )
