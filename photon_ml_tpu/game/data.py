"""GAME data layer: struct-of-arrays batches + per-entity bucketing.

Rebuild of the reference's L5 (``data/GameDatum.scala:32``,
``data/FixedEffectDataSet.scala``, ``data/RandomEffectDataSet.scala:39-381``,
``data/LocalDataSet.scala``). A GAME dataset here is:

  - feature shards: dict shard_id -> dense (n, d_shard) matrix (the
    reference's featureShardContainer, one Breeze vector per row per shard)
  - response/offset/weight columns (n,)
  - entity columns: dict random_effect_id -> (n,) int32 entity indices
    (index -1 = entity unseen at vocabulary build; scores 0 like the
    reference's missing-entity cogroup)

Random-effect training data is bucketed ONCE at ingest into padded
(num_entities, rows_cap, d) tensors (`RandomEffectDesign`) — the TPU analog
of RandomEffectDataSet's groupByKey + reservoir capping + partitioner
placement. Rows beyond the cap stay out of the active tensors but are still
scored through the coefficient table (the reference's passive data,
``RandomEffectDataSet.scala:319-358``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.types import LabeledBatch, _pytree_dataclass


@dataclasses.dataclass
class GameData:
    """Host-side container for a scored dataset (plain arrays, not a pytree;
    device placement happens per coordinate)."""

    features: Dict[str, np.ndarray]  # shard -> (n, d_shard)
    labels: np.ndarray  # (n,)
    offsets: np.ndarray  # (n,)
    weights: np.ndarray  # (n,)
    entity_ids: Dict[str, np.ndarray]  # re_name -> (n,) int32, -1 = unknown

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    @staticmethod
    def create(
        features: Mapping[str, np.ndarray],
        labels,
        offsets=None,
        weights=None,
        entity_ids: Optional[Mapping[str, np.ndarray]] = None,
    ) -> "GameData":
        labels = np.asarray(labels, np.float64)
        n = labels.shape[0]
        for name, v in {**features, **(entity_ids or {})}.items():
            if np.shape(v)[0] != n:
                raise ValueError(
                    f"column {name!r} has {np.shape(v)[0]} rows, labels "
                    f"have {n}"
                )
        return GameData(
            features={k: np.asarray(v) for k, v in features.items()},
            labels=labels,
            offsets=(
                np.zeros(n) if offsets is None else np.asarray(offsets, np.float64)
            ),
            weights=(
                np.ones(n) if weights is None else np.asarray(weights, np.float64)
            ),
            entity_ids={
                k: np.asarray(v, np.int32)
                for k, v in (entity_ids or {}).items()
            },
        )

    def fixed_effect_batch(self, shard: str, dtype=jnp.float32) -> LabeledBatch:
        """(n, d) LabeledBatch view for a fixed-effect coordinate
        (``data/FixedEffectDataSet.scala:31``)."""
        return LabeledBatch.create(
            self.features[shard],
            self.labels,
            offsets=self.offsets,
            weights=self.weights,
            dtype=dtype,
        )


@_pytree_dataclass
class RandomEffectDesign:
    """Padded per-entity active training tensors for one random effect.

    features: (E, R, d)   labels/weights/mask: (E, R)
    row_index: (E, R) int32 — global row each active slot came from (-1 pad),
    used to gather per-row residual offsets each coordinate pass without
    re-bucketing.
    """

    features: jax.Array
    labels: jax.Array
    weights: jax.Array
    mask: jax.Array
    row_index: jax.Array

    @property
    def num_entities(self) -> int:
        return self.features.shape[0]

    @property
    def rows_per_entity(self) -> int:
        return self.features.shape[1]

    @property
    def dim(self) -> int:
        return self.features.shape[2]

    def gather_offsets(self, full_offsets: jax.Array) -> jax.Array:
        """(n,) -> (E, R): route each row's current residual offset to its
        active slot. The reference does this with an RDD join per pass
        (``data/RandomEffectDataSet.scala:58-75``); here it is one gather."""
        safe = jnp.maximum(self.row_index, 0)
        return jnp.take(full_offsets, safe, axis=0) * self.mask


def build_random_effect_design(
    data: GameData,
    random_effect: str,
    shard: str,
    num_entities: int,
    active_cap: Optional[int] = None,
    seed: int = 0,
    dtype=jnp.float32,
) -> RandomEffectDesign:
    """Group rows by entity into padded tensors (host-side, once per run).

    Semantics from ``RandomEffectDataSet.buildWithConfiguration``:
      - at most `active_cap` active rows per entity, chosen uniformly at
        random (the reference's reservoir sample, :247-308);
      - sampled rows get weight * count/cap so each entity's total active
        weight is preserved (:299-302);
      - rows of entities with index -1 (unknown) are dropped;
      - `num_entities` fixes the leading axis = the coefficient-table size.
    """
    x = np.asarray(data.features[shard])
    eids = np.asarray(data.entity_ids[random_effect])
    n, d = x.shape
    rng = np.random.default_rng(seed)

    # stable grouping: row indices per entity
    order = np.argsort(eids, kind="stable")
    sorted_ids = eids[order]
    valid = sorted_ids >= 0
    order, sorted_ids = order[valid], sorted_ids[valid]
    uniq, starts, counts = np.unique(
        sorted_ids, return_index=True, return_counts=True
    )

    max_count = int(counts.max()) if counts.size else 1
    if active_cap is not None and active_cap <= 0:
        raise ValueError(f"active_cap must be positive, got {active_cap}")
    cap = min(max_count, active_cap) if active_cap is not None else max_count

    feats = np.zeros((num_entities, cap, d), np.float64)
    labels = np.zeros((num_entities, cap), np.float64)
    weights = np.zeros((num_entities, cap), np.float64)
    mask = np.zeros((num_entities, cap), np.float64)
    row_index = np.full((num_entities, cap), -1, np.int64)

    for e, s, c in zip(uniq, starts, counts):
        rows = order[s : s + c]
        if c > cap:
            rows = rng.choice(rows, size=cap, replace=False)
            rescale = c / cap  # preserve total weight (reference :299-302)
        else:
            rescale = 1.0
        k = len(rows)
        feats[e, :k] = x[rows]
        labels[e, :k] = data.labels[rows]
        weights[e, :k] = data.weights[rows] * rescale
        mask[e, :k] = 1.0
        row_index[e, :k] = rows

    return RandomEffectDesign(
        features=jnp.asarray(feats, dtype),
        labels=jnp.asarray(labels, dtype),
        weights=jnp.asarray(weights, dtype),
        mask=jnp.asarray(mask, dtype),
        row_index=jnp.asarray(row_index, jnp.int32),
    )


def build_entity_vocabulary(raw_ids: np.ndarray):
    """Map raw entity keys -> dense [0, E) indices (the analog of the
    reference's per-entity partitioner + index maps). Returns (vocab dict,
    (n,) int32 index column)."""
    uniq = np.unique(raw_ids)
    vocab = {k: i for i, k in enumerate(uniq.tolist())}
    idx = np.asarray([vocab[k] for k in raw_ids.tolist()], np.int32)
    return vocab, idx


def apply_entity_vocabulary(vocab: dict, raw_ids: np.ndarray) -> np.ndarray:
    """Index new data against an existing vocabulary; unknown -> -1
    (scores 0, ``model/RandomEffectModel.scala:117-146``)."""
    return np.asarray(
        [vocab.get(k, -1) for k in raw_ids.tolist()], np.int32
    )
