"""GAME data layer: struct-of-arrays batches + per-entity bucketing.

Rebuild of the reference's L5 (``data/GameDatum.scala:32``,
``data/FixedEffectDataSet.scala``, ``data/RandomEffectDataSet.scala:39-381``,
``data/LocalDataSet.scala``). A GAME dataset here is:

  - feature shards: dict shard_id -> dense (n, d_shard) matrix, or a
    padded-ELL ``ops.sparse.SparseFeatures`` for wide shards (the
    reference's featureShardContainer, one Breeze vector per row per
    shard — sparse Breeze vectors map to the ELL container). Sparse
    shards serve FIXED-EFFECT coordinates; per-entity designs need the
    dense row gather and reject them.
  - response/offset/weight columns (n,)
  - entity columns: dict random_effect_id -> (n,) int32 entity indices
    (index -1 = entity unseen at vocabulary build; scores 0 like the
    reference's missing-entity cogroup)

Random-effect training data is bucketed ONCE at ingest into padded
(num_entities, rows_cap, d) tensors (`RandomEffectDesign`) — the TPU analog
of RandomEffectDataSet's groupByKey + reservoir capping + partitioner
placement. Rows beyond the cap stay out of the active tensors but are still
scored through the coefficient table (the reference's passive data,
``RandomEffectDataSet.scala:319-358``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.types import LabeledBatch, _pytree_dataclass


@dataclasses.dataclass
class GameData:
    """Host-side container for a scored dataset (plain arrays, not a pytree;
    device placement happens per coordinate)."""

    features: Dict[str, np.ndarray]  # shard -> (n, d_shard)
    labels: np.ndarray  # (n,)
    offsets: np.ndarray  # (n,)
    weights: np.ndarray  # (n,)
    entity_ids: Dict[str, np.ndarray]  # re_name -> (n,) int32, -1 = unknown

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    @staticmethod
    def create(
        features: Mapping[str, np.ndarray],
        labels,
        offsets=None,
        weights=None,
        entity_ids: Optional[Mapping[str, np.ndarray]] = None,
    ) -> "GameData":
        from photon_ml_tpu.ops.sparse import is_hybrid, is_structured

        labels = np.asarray(labels, np.float64)
        n = labels.shape[0]
        for name, v in {**features, **(entity_ids or {})}.items():
            if is_hybrid(v):
                # hybrid rows are permuted relative to every other column;
                # GAME joins shards/entities/scores BY ROW
                raise ValueError(
                    f"shard {name!r} is a HybridFeatures container; GAME "
                    "shards must be dense or plain ELL (row-aligned)"
                )
            rows = v.shape[0] if is_structured(v) else np.shape(v)[0]
            if rows != n:
                raise ValueError(
                    f"column {name!r} has {rows} rows, labels have {n}"
                )
        return GameData(
            features={
                k: (v if is_structured(v) else np.asarray(v))
                for k, v in features.items()
            },
            labels=labels,
            offsets=(
                np.zeros(n) if offsets is None else np.asarray(offsets, np.float64)
            ),
            weights=(
                np.ones(n) if weights is None else np.asarray(weights, np.float64)
            ),
            entity_ids={
                k: np.asarray(v, np.int32)
                for k, v in (entity_ids or {}).items()
            },
        )

    def fixed_effect_batch(self, shard: str, dtype=jnp.float32) -> LabeledBatch:
        """(n, d) LabeledBatch view for a fixed-effect coordinate
        (``data/FixedEffectDataSet.scala:31``)."""
        return LabeledBatch.create(
            self.features[shard],
            self.labels,
            offsets=self.offsets,
            weights=self.weights,
            dtype=dtype,
        )


@_pytree_dataclass
class RandomEffectDesign:
    """Padded per-entity active training tensors for one random effect.

    features: (E, R, d)   labels/weights/mask: (E, R)
    row_index: (E, R) int32 — global row each active slot came from (-1 pad),
    used to gather per-row residual offsets each coordinate pass without
    re-bucketing.
    """

    features: jax.Array
    labels: jax.Array
    weights: jax.Array
    mask: jax.Array
    row_index: jax.Array

    @property
    def num_entities(self) -> int:
        return self.features.shape[0]

    @property
    def rows_per_entity(self) -> int:
        return self.features.shape[1]

    @property
    def dim(self) -> int:
        return self.features.shape[2]

    def gather_offsets(self, full_offsets: jax.Array) -> jax.Array:
        """(n,) -> (E, R): route each row's current residual offset to its
        active slot. The reference does this with an RDD join per pass
        (``data/RandomEffectDataSet.scala:58-75``); here it is one gather."""
        safe = jnp.maximum(self.row_index, 0)
        return jnp.take(full_offsets, safe, axis=0) * self.mask


def _grouped_rows(eids: np.ndarray, seed: int):
    """Vectorized per-entity grouping with a uniform random shuffle inside
    each entity (the reservoir-sample analog; no Python per-entity loop).

    Returns (order, sorted_ids, slot, uniq, counts): `order` are row indices
    sorted by (entity, random), `slot` is each row's position within its
    entity, `uniq`/`counts` the entities present and their row counts.
    """
    rng = np.random.default_rng(seed)
    rand = rng.uniform(size=eids.shape[0])
    order = np.lexsort((rand, eids))
    sorted_ids = eids[order]
    valid = sorted_ids >= 0
    order, sorted_ids = order[valid], sorted_ids[valid]
    uniq, starts, counts = np.unique(
        sorted_ids, return_index=True, return_counts=True
    )
    slot = np.arange(order.size) - np.repeat(starts, counts)
    return order, sorted_ids, slot, uniq, counts


def _fill_design(
    data: GameData,
    shard: str,
    rows: np.ndarray,
    ent_rows: np.ndarray,
    slot_rows: np.ndarray,
    rescale_rows: np.ndarray,
    shape_e: int,
    cap: int,
    dtype,
) -> RandomEffectDesign:
    """Scatter kept rows into padded (shape_e, cap, d) tensors."""
    x = np.asarray(data.features[shard])
    d = x.shape[1]
    feats = np.zeros((shape_e, cap, d), np.float64)
    labels = np.zeros((shape_e, cap), np.float64)
    weights = np.zeros((shape_e, cap), np.float64)
    mask = np.zeros((shape_e, cap), np.float64)
    row_index = np.full((shape_e, cap), -1, np.int64)
    feats[ent_rows, slot_rows] = x[rows]
    labels[ent_rows, slot_rows] = data.labels[rows]
    weights[ent_rows, slot_rows] = data.weights[rows] * rescale_rows
    mask[ent_rows, slot_rows] = 1.0
    row_index[ent_rows, slot_rows] = rows
    return RandomEffectDesign(
        features=jnp.asarray(feats, dtype),
        labels=jnp.asarray(labels, dtype),
        weights=jnp.asarray(weights, dtype),
        mask=jnp.asarray(mask, dtype),
        row_index=jnp.asarray(row_index, jnp.int32),
    )


def pearson_correlation_scores(
    features: np.ndarray, labels: np.ndarray, mask: np.ndarray
) -> np.ndarray:
    """(E, R, d) design -> (E, d) per-entity Pearson |correlation| basis.

    Vectorized rebuild of ``LocalDataSet.computePearsonCorrelationScore``
    (``LocalDataSet.scala:198-259``): per entity, corr(feature_j, label)
    over its active rows; a present feature with ~zero variance is the
    intercept — the FIRST such gets score 1.0, later ones 0.0; features
    absent from the entity's rows score -inf (never selected).
    """
    m = mask > 0
    x = np.where(m[:, :, None], features, 0.0)
    y = np.where(m, labels, 0.0)
    n = m.sum(axis=1).astype(np.float64)[:, None]  # (E, 1)
    s1 = x.sum(axis=1)
    s2 = (x * x).sum(axis=1)
    sxy = (x * y[:, :, None]).sum(axis=1)
    ly = y.sum(axis=1)[:, None]
    lyy = (y * y).sum(axis=1)[:, None]
    numerator = n * sxy - s1 * ly
    feat_var = np.abs(n * s2 - s1 * s1)
    std = np.sqrt(feat_var)
    label_var = np.maximum(n * lyy - ly * ly, 0.0)
    denominator = std * np.sqrt(label_var)
    # constant labels: correlation is undefined, and a tiny-denominator
    # guard would amplify cancellation noise into garbage scores — force 0.
    # Thresholds are RELATIVE to the moment magnitudes (absolute epsilons
    # break under catastrophic cancellation at large n / large values).
    label_const = label_var < 1e-9 * np.maximum(n * lyy, 1.0)
    score = np.where(
        label_const, 0.0, numerator / (denominator + 1e-12)
    )

    present = s2 > 0.0
    constant = present & (feat_var < 1e-9 * np.maximum(n * s2, 1.0))
    # first constant (intercept-like) feature per entity scores 1.0
    first_const = constant & (
        np.cumsum(constant, axis=1) == 1
    )
    score = np.where(constant, 0.0, score)
    score = np.where(first_const, 1.0, score)
    return np.where(present, np.abs(score), -np.inf)


def filter_features_by_support(
    design: RandomEffectDesign, min_support: int
) -> RandomEffectDesign:
    """Per-entity support filter (``LocalDataSet.filterFeaturesBySupport``,
    ``LocalDataSet.scala:80-109``): a feature survives for an entity iff
    it is STORED (nonzero here — the dense analog of activeKeysIterator)
    in at least ``min_support`` of that entity's active rows. Dropped
    columns are zeroed so their coefficients solve to exactly 0. The
    cheap pre-filter the reference offers ahead of the Pearson ranking.

    Known divergence, by design: the reference counts EXPLICITLY-STORED
    entries (a stored 0.0 adds support there); the dense projected design
    cannot distinguish a stored zero from an absent one, so value != 0 is
    the storedness proxy. Entities whose features carry explicit zeros in
    the source Avro may keep fewer columns here. Exact parity would
    require threading the ELL padding mask through the projection."""
    if min_support <= 0:
        return design
    feats = np.asarray(design.features)
    mask = np.asarray(design.mask) > 0
    support = ((feats != 0.0) & mask[:, :, None]).sum(axis=1)  # (E, d)
    keep = support >= min_support
    return dataclasses.replace(
        design,
        features=jnp.asarray(
            np.where(keep[:, None, :], feats, 0.0), design.features.dtype
        ),
    )


def select_features_by_pearson(
    design: RandomEffectDesign, ratio: float
) -> RandomEffectDesign:
    """Per-entity feature selection: keep the top ceil(ratio * n_e)
    features by |Pearson corr|, zeroing the rest in the design so their
    coefficients solve to exactly 0 (the dense-rep analog of
    ``RandomEffectDataSet.featureSelectionOnActiveData``,
    ``RandomEffectDataSet.scala:360-380``)."""
    if ratio <= 0:
        raise ValueError(f"feature ratio must be positive, got {ratio}")
    feats = np.asarray(design.features, np.float64)
    mask = np.asarray(design.mask)
    score = pearson_correlation_scores(
        feats, np.asarray(design.labels, np.float64), mask
    )
    e, _, d = feats.shape
    n_e = (mask > 0).sum(axis=1)
    k_e = np.minimum(np.ceil(ratio * n_e).astype(np.int64), d)
    rank = np.argsort(np.argsort(-score, axis=1, kind="stable"), axis=1)
    keep = rank < k_e[:, None]  # (E, d)
    return dataclasses.replace(
        design,
        features=jnp.asarray(
            np.where(keep[:, None, :], feats, 0.0), design.features.dtype
        ),
    )


def build_random_effect_design(
    data: GameData,
    random_effect: str,
    shard: str,
    num_entities: int,
    active_cap: Optional[int] = None,
    seed: int = 0,
    dtype=jnp.float32,
    feature_ratio: Optional[float] = None,
    min_support: int = 0,
) -> RandomEffectDesign:
    """Group rows by entity into padded tensors (host-side, once per run).

    Semantics from ``RandomEffectDataSet.buildWithConfiguration``:
      - at most `active_cap` active rows per entity, chosen uniformly at
        random (the reference's reservoir sample, :247-308);
      - sampled rows get weight * count/cap so each entity's total active
        weight is preserved (:299-302);
      - rows of entities with index -1 (unknown) are dropped;
      - `num_entities` fixes the leading axis = the coefficient-table size.

    One global row cap means one hot entity inflates padding for all; use
    :func:`build_bucketed_random_effect_design` when entity sizes are skewed.
    """
    from photon_ml_tpu.ops.sparse import is_structured

    if is_structured(data.features[shard]):
        raise ValueError(
            f"random effect {random_effect!r}: per-entity designs gather "
            f"dense rows; shard {shard!r} is sparse (sparse shards serve "
            "fixed-effect coordinates only)"
        )
    eids = np.asarray(data.entity_ids[random_effect])
    if active_cap is not None and active_cap <= 0:
        raise ValueError(f"active_cap must be positive, got {active_cap}")
    order, sorted_ids, slot, uniq, counts = _grouped_rows(eids, seed)

    max_count = int(counts.max()) if counts.size else 1
    cap = min(max_count, active_cap) if active_cap is not None else max_count

    cap_of = np.minimum(counts, cap)
    keep = slot < np.repeat(cap_of, counts)
    rescale = np.repeat(np.where(counts > cap, counts / cap, 1.0), counts)
    design = _fill_design(
        data,
        shard,
        order[keep],
        sorted_ids[keep],
        slot[keep],
        rescale[keep],
        num_entities,
        cap,
        dtype,
    )
    # support filter first, Pearson ranking second (the reference's
    # LocalDataSet order: the cheap count-based cut precedes the ranking)
    design = filter_features_by_support(design, min_support)
    if feature_ratio is not None:
        design = select_features_by_pearson(design, feature_ratio)
    return design


@dataclasses.dataclass
class BucketedRandomEffectDesign:
    """Size-bucketed padded designs for one random effect.

    Entities are grouped by row count into a few buckets, each padded only
    to ITS max count — the TPU analog of the reference's load-balanced
    entity placement (``data/RandomEffectIdPartitioner.scala:65-99``): the
    greedy bin-pack balanced per-partition work; here the same skew problem
    is solved by making padding local to a size class, so one hot entity no
    longer inflates every entity's padded rows.

    buckets[b] tensors have shape (E_b, R_b, d); entity_index[b] maps bucket
    lane -> row of the global (num_entities, d) coefficient table. Lanes
    padded for entity-axis sharding carry sentinel `num_entities`, which
    gathers clip and scatters drop.
    """

    buckets: list  # List[RandomEffectDesign]
    entity_index: list  # List[np.ndarray (E_b,) int32]
    num_entities: int

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    @property
    def dim(self) -> int:
        return self.buckets[0].dim

    @property
    def active_slots(self) -> int:
        """Total padded (entity, row) slots across buckets — the memory and
        FLOP footprint a global-cap design would inflate."""
        return sum(b.num_entities * b.rows_per_entity for b in self.buckets)


def _split_minimizing_padding(sorted_counts: np.ndarray, max_buckets: int):
    """Optimal contiguous split of ascending per-entity row counts into at
    most `max_buckets` groups minimizing total padded slots
    Σ_b |entities_b| · max_count_b (exact DP over distinct counts — the
    number of distinct entity sizes is small even when entities number
    millions). Returns [(lo, hi)) index ranges into sorted_counts."""
    if sorted_counts.size == 0:
        return []
    values, first, nums = np.unique(
        sorted_counts, return_index=True, return_counts=True
    )
    m = values.size
    k = min(max_buckets, m)
    prefix = np.concatenate([[0], np.cumsum(nums)])
    INF = float("inf")
    # dp[j] = min cost covering distinct values [0, j) ; rebuilt per layer
    dp = np.full(m + 1, INF)
    dp[0] = 0.0
    choice = np.zeros((k, m + 1), np.int64)
    for layer in range(k):
        nxt = np.full(m + 1, INF)
        for j in range(1, m + 1):
            # bucket = distinct values [i, j) with cap values[j-1]
            costs = dp[:j] + (prefix[j] - prefix[:j]) * values[j - 1]
            i = int(np.argmin(costs))
            nxt[j] = costs[i]
            choice[layer, j] = i
        dp = nxt
    # backtrack
    bounds = []
    j = m
    layer = k - 1
    while j > 0:
        i = int(choice[layer, j])
        bounds.append((int(prefix[i]), int(prefix[j])))
        j = i
        layer -= 1
    return bounds[::-1]


def build_bucketed_random_effect_design(
    data: GameData,
    random_effect: str,
    shard: str,
    num_entities: int,
    num_buckets: int = 4,
    active_cap: Optional[int] = None,
    entity_multiple: int = 1,
    seed: int = 0,
    dtype=jnp.float32,
    feature_ratio: Optional[float] = None,
    min_support: int = 0,
) -> BucketedRandomEffectDesign:
    """Like :func:`build_random_effect_design` but with per-size-class row
    caps. Entities (those with data) are sorted by row count and split into
    `num_buckets` contiguous groups; each bucket's row cap is its own max
    count (still bounded by `active_cap`, with the same weight-preserving
    rescale). `entity_multiple` pads each bucket's entity axis up to a
    multiple (the entity-mesh-axis size) so buckets shard evenly."""
    from photon_ml_tpu.ops.sparse import is_structured

    if is_structured(data.features[shard]):
        raise ValueError(
            f"random effect {random_effect!r}: per-entity designs gather "
            f"dense rows; shard {shard!r} is sparse (sparse shards serve "
            "fixed-effect coordinates only)"
        )
    eids = np.asarray(data.entity_ids[random_effect])
    if active_cap is not None and active_cap <= 0:
        raise ValueError(f"active_cap must be positive, got {active_cap}")
    if entity_multiple <= 0:
        raise ValueError(f"entity_multiple must be positive, got {entity_multiple}")
    order, sorted_ids, slot, uniq, counts = _grouped_rows(eids, seed)

    if uniq.size == 0:
        # no rows with a known entity: one all-masked bucket so callers
        # (initial_params, update) keep working, like the global builder
        empty_idx = np.asarray([], np.int64)
        return BucketedRandomEffectDesign(
            buckets=[
                _fill_design(
                    data, shard, empty_idx, empty_idx, empty_idx,
                    np.asarray([]), entity_multiple, 1, dtype,
                )
            ],
            entity_index=[
                np.full(entity_multiple, num_entities, np.int32)
            ],
            num_entities=num_entities,
        )

    # per-entity active cap under the bucket policy
    by_count = np.argsort(counts, kind="stable")
    splits = _split_minimizing_padding(counts[by_count], num_buckets)
    splits = [by_count[lo:hi] for lo, hi in splits]

    cap_of_entity = np.zeros(num_entities, np.int64)
    bucket_of_entity = np.full(num_entities, -1, np.int64)
    local_of_entity = np.zeros(num_entities, np.int64)
    bucket_caps = []
    bucket_entities = []
    for b, split in enumerate(splits):
        ents = uniq[split]
        cmax = int(counts[split].max())
        cap_b = min(cmax, active_cap) if active_cap is not None else cmax
        bucket_caps.append(cap_b)
        bucket_entities.append(ents)
        cap_of_entity[ents] = np.minimum(counts[split], cap_b)
        bucket_of_entity[ents] = b
        local_of_entity[ents] = np.arange(ents.size)

    keep = slot < cap_of_entity[sorted_ids]
    full_count = np.zeros(num_entities, np.int64)
    full_count[uniq] = counts
    rescale_of_entity = np.where(
        full_count > cap_of_entity,
        full_count / np.maximum(cap_of_entity, 1),
        1.0,
    )

    rows = order[keep]
    ents = sorted_ids[keep]
    slots = slot[keep]

    buckets = []
    entity_index = []
    for b, (cap_b, ents_b) in enumerate(zip(bucket_caps, bucket_entities)):
        sel = bucket_of_entity[ents] == b
        e_pad = -(-ents_b.size // entity_multiple) * entity_multiple
        bucket = _fill_design(
            data,
            shard,
            rows[sel],
            local_of_entity[ents[sel]],
            slots[sel],
            rescale_of_entity[ents[sel]],
            e_pad,
            cap_b,
            dtype,
        )
        bucket = filter_features_by_support(bucket, min_support)
        if feature_ratio is not None:
            bucket = select_features_by_pearson(bucket, feature_ratio)
        buckets.append(bucket)
        idx = np.full(e_pad, num_entities, np.int64)
        idx[: ents_b.size] = ents_b
        entity_index.append(np.asarray(idx, np.int32))

    return BucketedRandomEffectDesign(
        buckets=buckets, entity_index=entity_index, num_entities=num_entities
    )


# ---------------------------------------------------------------------------
# entity-sharded layout (docs/PARALLEL.md): shard_map'd GAME descent
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EntityShardAssignment:
    """Entity -> mesh-shard ownership for entity-sharded GAME descent.

    Ownership uses the SAME round-robin rule as the sharded checkpoint
    writer (``io.checkpoint.shard_rows``: shard p owns rows ``p::P`` of
    the global entity order), so the device layout and the checkpoint
    shard layout derive from one rule and compose entity-keyed: a
    restore at ANY width re-keys rows by entity
    (``reindex_entity_params``), pad rows re-initialize to zero.

    The device table stores entities SHARD-MAJOR (shard p's entities
    contiguous, each shard padded to ``rows_per_shard``) so a plain
    NamedSharding block split puts each shard's rows on its device.

    stored_to_global: (padded_rows,) int64 stored row -> global entity
                      (``num_entities`` = pad sentinel).
    global_to_stored: (num_entities + 1,) int64 inverse; the last slot
                      maps the global sentinel to the stored sentinel
                      ``padded_rows``.
    """

    num_entities: int
    num_shards: int
    rows_per_shard: int
    stored_to_global: np.ndarray
    global_to_stored: np.ndarray

    @property
    def padded_rows(self) -> int:
        return self.num_shards * self.rows_per_shard

    def shard_of_stored(self, stored: np.ndarray) -> np.ndarray:
        return np.minimum(
            np.asarray(stored, np.int64) // self.rows_per_shard,
            self.num_shards - 1,
        )

    def owner_of_global(self, entities: np.ndarray) -> np.ndarray:
        """Owning shard of each GLOBAL entity index — THE ownership
        lookup shared by entity-sharded training, sharded checkpoints,
        and shard-routed serving (all derive from ``shard_rows``).
        Callers pass valid indices in [0, num_entities)."""
        ents = np.asarray(entities, np.int64)
        return self.shard_of_stored(self.global_to_stored[ents])

    def local_of_global(self, entities: np.ndarray) -> np.ndarray:
        """Row of each GLOBAL entity index within its owner shard's
        block (the shard-LOCAL gather index a per-shard table slice
        uses)."""
        ents = np.asarray(entities, np.int64)
        stored = self.global_to_stored[ents]
        return (stored - self.shard_of_stored(stored) * self.rows_per_shard)

    def stored_entity_keys(self, global_keys) -> list:
        """Global entity-key list -> the STORED (shard-major) order the
        device table holds, pad rows keyed uniquely so checkpoint
        re-keying never aliases them onto real entities."""
        keys = list(global_keys)
        if len(keys) != self.num_entities:
            raise ValueError(
                f"{len(keys)} entity keys for {self.num_entities} entities"
            )
        return [
            (
                str(keys[g])
                if g < self.num_entities
                else f"__entity_pad__:{i}"
            )
            for i, g in enumerate(self.stored_to_global)
        ]

    def table_to_global(self, stored_table: np.ndarray) -> np.ndarray:
        """Stored (shard-major, padded) table -> global entity order."""
        stored_table = np.asarray(stored_table)
        return stored_table[self.global_to_stored[: self.num_entities]]

    def table_from_global(self, global_table: np.ndarray) -> np.ndarray:
        """Global entity order -> stored (shard-major, padded) layout;
        pad rows zero."""
        global_table = np.asarray(global_table)
        out = np.zeros(
            (self.padded_rows,) + global_table.shape[1:],
            global_table.dtype,
        )
        real = self.stored_to_global < self.num_entities
        out[real] = global_table[self.stored_to_global[real]]
        return out


def entity_shard_assignment(
    num_entities: int, num_shards: int
) -> EntityShardAssignment:
    """Build the round-robin entity -> shard assignment (shared rule:
    ``io.checkpoint.shard_rows``)."""
    from photon_ml_tpu.io.checkpoint import shard_rows

    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    per_shard = -(-num_entities // num_shards) if num_entities else 1
    padded = per_shard * num_shards
    stored_to_global = np.full(padded, num_entities, np.int64)
    for p in range(num_shards):
        rows = np.asarray(
            list(shard_rows(num_entities, p, num_shards)), np.int64
        )
        stored_to_global[
            p * per_shard : p * per_shard + rows.size
        ] = rows
    global_to_stored = np.full(num_entities + 1, padded, np.int64)
    real = stored_to_global < num_entities
    global_to_stored[stored_to_global[real]] = np.flatnonzero(real)
    return EntityShardAssignment(
        num_entities=num_entities,
        num_shards=num_shards,
        rows_per_shard=per_shard,
        stored_to_global=stored_to_global,
        global_to_stored=global_to_stored,
    )


@dataclasses.dataclass(frozen=True)
class EntityRowPartition:
    """Row-space permutation grouping batch rows by their entity's owner
    shard (entity-PARTITIONED rows — the device analog of the
    reference's ``RandomEffectIdPartitioner`` placement): shard p's rows
    sit in the contiguous block ``[p*R, (p+1)*R)``, padded with -1
    sentinel rows so every shard holds the same count. Applying the
    permutation ONCE at setup keeps every per-row array of the descent
    loop (labels, offsets, weights, scores, entity lanes) aligned with
    the 'entity' mesh axis — the random-effect update then never
    crosses shards.

    row_perm: (padded_rows,) int64 permuted position -> original row
              (-1 = pad).
    """

    num_shards: int
    rows_per_shard: int
    row_perm: np.ndarray

    @property
    def padded_rows(self) -> int:
        return self.num_shards * self.rows_per_shard

    def apply(self, column: np.ndarray, fill=0.0) -> np.ndarray:
        """Permute one per-row array into the sharded order (pad rows
        carry ``fill``)."""
        column = np.asarray(column)
        out = np.full(
            (self.padded_rows,) + column.shape[1:], fill, column.dtype
        )
        real = self.row_perm >= 0
        out[real] = column[self.row_perm[real]]
        return out

    def restore(self, column: np.ndarray) -> np.ndarray:
        """Inverse of :meth:`apply` (drops pad rows)."""
        column = np.asarray(column)
        n = int((self.row_perm >= 0).sum())
        out = np.zeros((n,) + column.shape[1:], column.dtype)
        real = self.row_perm >= 0
        out[self.row_perm[real]] = column[real]
        return out


def entity_partition_game_data(
    data: GameData, random_effect: str, assignment: EntityShardAssignment
):
    """Permute a :class:`GameData` into the entity-partitioned row order
    of ``random_effect`` (rows grouped by their entity's owner shard,
    pad rows masked by zero weight): the ONE-time layout step of
    entity-sharded GAME descent. Returns ``(permuted GameData,
    EntityRowPartition)``. Dense and padded-ELL feature shards both
    permute; other random effects' id columns ride along row-aligned
    (but only ``random_effect`` is shard-local — a second entity-sharded
    coordinate needs its own partition and therefore its own descent)."""
    from photon_ml_tpu.ops.sparse import SparseFeatures, is_sparse, is_structured

    part = entity_partition_rows(
        data.entity_ids[random_effect], assignment
    )

    def permute_features(v):
        if is_sparse(v):
            ind = np.asarray(v.indices)
            val = np.asarray(v.values)
            out_i = np.full(
                (part.padded_rows,) + ind.shape[1:], v.d, ind.dtype
            )
            out_v = np.zeros(
                (part.padded_rows,) + val.shape[1:], val.dtype
            )
            real = part.row_perm >= 0
            out_i[real] = ind[part.row_perm[real]]
            out_v[real] = val[part.row_perm[real]]
            return SparseFeatures(indices=out_i, values=out_v, d=v.d)
        if is_structured(v):
            raise ValueError(
                "entity partitioning permutes dense or plain-ELL "
                f"shards; got {type(v).__name__}"
            )
        return part.apply(v)

    permuted = GameData(
        features={
            k: permute_features(v) for k, v in data.features.items()
        },
        labels=part.apply(data.labels),
        offsets=part.apply(data.offsets),
        weights=part.apply(data.weights),  # pad rows weight 0: masked out
        entity_ids={
            k: part.apply(v, fill=-1) for k, v in data.entity_ids.items()
        },
    )
    return permuted, part


def entity_partition_rows(
    entity_ids: np.ndarray, assignment: EntityShardAssignment
) -> EntityRowPartition:
    """Group rows by their entity's owner shard (stable within a
    shard). Rows with unknown entities (-1) spread round-robin — they
    participate in no random-effect solve, so any shard balances."""
    eids = np.asarray(entity_ids, np.int64)
    n = eids.shape[0]
    known = eids >= 0
    owner = np.empty(n, np.int64)
    owner[known] = assignment.shard_of_stored(
        assignment.global_to_stored[eids[known]]
    )
    owner[~known] = np.arange(int((~known).sum())) % assignment.num_shards
    counts = np.bincount(owner, minlength=assignment.num_shards)
    per = int(counts.max()) if counts.size else 1
    row_perm = np.full(per * assignment.num_shards, -1, np.int64)
    order = np.argsort(owner, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
    slot = np.arange(n) - starts[owner[order]]
    row_perm[owner[order] * per + slot] = order
    return EntityRowPartition(
        num_shards=assignment.num_shards,
        rows_per_shard=per,
        row_perm=row_perm,
    )


def build_entity_vocabulary(raw_ids: np.ndarray):
    """Map raw entity keys -> dense [0, E) indices (the analog of the
    reference's per-entity partitioner + index maps). Returns (vocab dict,
    (n,) int32 index column)."""
    uniq = np.unique(raw_ids)
    vocab = {k: i for i, k in enumerate(uniq.tolist())}
    idx = np.asarray([vocab[k] for k in raw_ids.tolist()], np.int32)
    return vocab, idx


def apply_entity_vocabulary(vocab: dict, raw_ids: np.ndarray) -> np.ndarray:
    """Index new data against an existing vocabulary; unknown -> -1
    (scores 0, ``model/RandomEffectModel.scala:117-146``)."""
    return np.asarray(
        [vocab.get(k, -1) for k in raw_ids.tolist()], np.int32
    )
