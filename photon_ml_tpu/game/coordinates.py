"""GAME coordinates: fixed-effect and random-effect update/score units.

Rebuild of ``algorithm/Coordinate.scala:28-55`` and its concrete types.
A coordinate owns its (device-resident) training design and exposes:

  update(params, partial_scores, key) -> (params', SolverResult)
      solve the coordinate's subproblem with the OTHER coordinates' scores
      added to the offsets — the residual trick of
      ``algorithm/Coordinate.scala:45-48`` — warm-starting from the current
      parameters (``FixedEffectCoordinate.scala:69-71``)
  score(params) -> (n,) margins for the coordinate's own rows

FixedEffectCoordinate (``algorithm/FixedEffectCoordinate.scala:33-179``):
one global GLM solve; under a mesh the batch is 'data'-sharded and the
solve runs SPMD. Optional down-sampling is a weight transform (static
shapes; ``sampler/*DownSampler.scala`` semantics).

RandomEffectCoordinate (``algorithm/RandomEffectCoordinate.scala:36-214``):
ONE vmapped solver call over the padded (entities, rows, dim) design — the
reference's millions of per-entity in-executor solves. Per-entity
convergence reasons come back as an (E,) int array for the tracker
histogram (``RandomEffectOptimizationTracker.scala:33-110``).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

import numpy as np

from photon_ml_tpu.core.tasks import TaskType
from photon_ml_tpu.core.types import LabeledBatch
from photon_ml_tpu.game.data import (
    BucketedRandomEffectDesign,
    RandomEffectDesign,
)
from photon_ml_tpu.models.training import OptimizerType
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.solvers import (
    SolverConfig,
    minimize_lbfgs,
    minimize_newton,
    minimize_owlqn,
    minimize_tron,
)


@dataclasses.dataclass(frozen=True)
class CoordinateConfig:
    """Per-coordinate optimization knobs — the typed analog of the
    reference's GLMOptimizationConfiguration mini-DSL
    ("maxIter,tol,lambda,downSampleRate,optimizer,regType",
    ``optimization/game/GLMOptimizationConfiguration.scala:32-80``).
    Defaults per ``GLMOptimizationConfiguration.scala:33-38``."""

    shard: str
    task: TaskType = TaskType.LOGISTIC_REGRESSION
    optimizer: OptimizerType = OptimizerType.TRON
    reg_weight: float = 50.0
    l1_ratio: float = 0.0  # >0 selects OWL-QN (elastic-net alpha)
    max_iters: int = 20
    tolerance: float = 1e-5
    # fixed-effect only: None = no down-sampling; else keep rate in (0,1)
    down_sampling_rate: Optional[float] = None
    # random-effect only
    random_effect: Optional[str] = None
    active_cap: Optional[int] = None
    # per-iteration solver tapes (values/grad norms/radius/step — the
    # obs/convergence.py decode surface). Off by default: vmapped
    # per-entity solves would carry (entities, max_iters+1) tracker
    # state; the fleet summaries (reason/iterations/final grad norm)
    # don't need it.
    track_states: bool = False

    def solver_config(self) -> SolverConfig:
        return SolverConfig(
            max_iters=self.max_iters,
            tolerance=self.tolerance,
            track_states=self.track_states,
        )


def _make_solve(config: CoordinateConfig, batched: bool):
    """jitted solve(w0, reg_weight, features, labels, offsets, weights,
    mask) for one subproblem; vmapped over the leading axis when `batched`
    — reg_weight is a TRACED scalar (per-entity in the batched case, the
    honest analog of ``RandomEffectOptimizationProblem.scala:41-110``'s
    per-entity objective functions). The cache key zeroes reg_weight so a
    lambda grid sweep reuses ONE compilation."""
    return _make_solve_cached(
        dataclasses.replace(config, reg_weight=0.0), batched
    )


@lru_cache(maxsize=128)
def _make_solve_cached(config: CoordinateConfig, batched: bool):
    loss = loss_for_task(config.task)
    scfg = config.solver_config()
    use_owlqn = config.l1_ratio > 0.0
    use_tron = config.optimizer == OptimizerType.TRON
    use_newton = config.optimizer == OptimizerType.NEWTON
    if (use_tron or use_newton) and not loss.twice_differentiable:
        # the GLM driver's validate() never runs for GAME coordinates, so
        # enforce the second-order requirement here at build time
        raise ValueError(
            f"{config.task} is first-order only; {config.optimizer.name} "
            "needs a twice-differentiable loss (use LBFGS)"
        )

    def solve_one(w0, reg_weight, features, labels, offsets, weights, mask):
        l1 = reg_weight * config.l1_ratio
        l2 = reg_weight * (1.0 - config.l1_ratio)
        batch = LabeledBatch(features, labels, offsets, weights, mask)
        obj = GLMObjective(loss=loss, l2_weight=l2)
        vg = lambda w: obj.value_and_grad(w, batch)
        if use_owlqn:
            return minimize_owlqn(vg, w0, l1, scfg)
        if use_tron:
            hvp = lambda w, v: obj.hessian_vector(w, v, batch)
            return minimize_tron(
                vg, hvp, w0, scfg,
                hvp_at_fn=lambda c, v: obj.hessian_vector_at(c, v, batch),
                vgc_fn=lambda w: obj.value_grad_curvature(w, batch),
            )
        if use_newton:
            hess = lambda w: obj.hessian_full(w, batch)
            return minimize_newton(vg, hess, w0, scfg)
        return minimize_lbfgs(vg, w0, scfg)

    return jax.jit(jax.vmap(solve_one) if batched else solve_one)


def _downsample_budget(
    labels: np.ndarray, mask: np.ndarray, rate: float, binary: bool
) -> int:
    """Static row budget for the gathered down-sampled batch: expected
    keep count + 6 standard deviations of the Bernoulli draw, so overflow
    (kept rows beyond the budget, which are dropped) is vanishingly rare."""
    real = mask > 0
    n = int(real.sum())
    if binary:
        pos = int(((labels > 0) & real).sum())
        neg = n - pos
        mean = pos + rate * neg
        var = rate * (1.0 - rate) * neg
    else:
        mean = rate * n
        var = rate * (1.0 - rate) * n
    return min(n, int(np.ceil(mean + 6.0 * np.sqrt(max(var, 1.0)))) + 1)


def _make_gathered_solve(config: CoordinateConfig, budget: int):
    """jitted solve over the GATHERED down-sampled batch: rows with
    positive post-sampling weight are packed (stable order) into a
    (budget, d) batch; dropped and overflow rows carry weight 0. Cache
    key zeroes reg_weight (traced) like _make_solve."""
    return _make_gathered_solve_cached(
        dataclasses.replace(config, reg_weight=0.0), budget
    )


@lru_cache(maxsize=64)
def _make_gathered_solve_cached(config: CoordinateConfig, budget: int):
    solve = _make_solve(config, batched=False)

    @jax.jit
    def gather_solve(w, reg_weight, features, labels, offsets, weights, mask):
        kept = weights > 0.0
        # stable partition: kept-row indices first
        order = jnp.argsort(~kept)  # False (kept) sorts before True
        idx = order[:budget]
        valid = kept[idx]
        sub_mask = jnp.where(valid, mask[idx], 0.0)
        result = solve(
            w,
            reg_weight,
            features[idx],
            labels[idx],
            offsets[idx],
            jnp.where(valid, weights[idx], 0.0),
            sub_mask,
        )
        # rescore the FULL batch in the same dispatch
        return result, features @ result.w

    return gather_solve


def _make_fixed_update_and_score(config: CoordinateConfig):
    """solve + full-batch rescore in ONE dispatch (cache key zeroes the
    traced reg_weight like _make_solve)."""
    return _make_fixed_update_and_score_cached(
        dataclasses.replace(config, reg_weight=0.0)
    )


@lru_cache(maxsize=128)
def _make_fixed_update_and_score_cached(config: CoordinateConfig):
    solve = _make_solve(config, batched=False)

    @jax.jit
    def run(w, reg_weight, features, labels, offsets, weights, mask):
        result = solve(w, reg_weight, features, labels, offsets, weights, mask)
        return result, features @ result.w

    return run


def _make_fixed_update_and_score_permuted(config: CoordinateConfig):
    return _make_fixed_update_and_score_permuted_cached(
        dataclasses.replace(config, reg_weight=0.0)
    )


@lru_cache(maxsize=128)
def _make_fixed_update_and_score_permuted_cached(config: CoordinateConfig):
    """Hybrid-representation variant: the batch lives in the hybrid's
    stored (bucketed) row order, while partial scores arrive — and
    rescores must leave — in the GLOBAL row order the descent loop sums
    over. Both permutation gathers ride inside the single dispatch."""
    solve = _make_solve(config, batched=False)

    @jax.jit
    def run(w, reg_weight, features, labels, offsets_base, partial_scores,
            weights, mask, perm, inv):
        offsets = offsets_base + partial_scores[perm]
        result = solve(w, reg_weight, features, labels, offsets, weights, mask)
        return result, (features @ result.w)[inv]

    return run


class FixedEffectCoordinate:
    """Global GLM coordinate. Owns a device LabeledBatch (shard view).

    ``hot_columns`` (with a padded-ELL batch) re-represents the shard as
    dense-hot + bucketed-cold (``ops.sparse.to_hybrid``) INSIDE the
    coordinate: the hybrid's row permutation is private here — incoming
    partial scores and outgoing rescores are bridged by two in-dispatch
    gathers, so the descent loop keeps its global row order."""

    @staticmethod
    def hybridize_batch(batch: LabeledBatch, hot_columns: int):
        """(permuted hybrid batch, row_perm, inv_perm) — the host-side
        re-pack, exposed so grid sweeps can build it ONCE per coordinate
        (it depends on data + hot_columns, never on reg weight)."""
        from photon_ml_tpu.ops.sparse import is_sparse, to_hybrid

        if not is_sparse(batch.features):
            raise ValueError(
                "hot_columns requires a padded-ELL (sparse) shard"
            )
        hf = to_hybrid(batch.features, hot_columns=hot_columns)
        perm = np.asarray(hf.row_perm)
        batch = dataclasses.replace(
            batch,
            features=hf,
            labels=batch.labels[perm],
            offsets=batch.offsets[perm],
            weights=batch.weights[perm],
            mask=batch.mask[perm],
        )
        return batch, jnp.asarray(perm), jnp.asarray(np.argsort(perm))

    def __init__(
        self,
        batch: LabeledBatch,
        config: CoordinateConfig,
        hot_columns: int = 0,
        hybrid_pack=None,
    ):
        if config.random_effect is not None:
            raise ValueError("config names a random effect; wrong coordinate")
        self._row_perm = None
        self._inv_perm = None
        if hybrid_pack is not None:
            batch, self._row_perm, self._inv_perm = hybrid_pack
        elif hot_columns:
            batch, self._row_perm, self._inv_perm = self.hybridize_batch(
                batch, hot_columns
            )
        self.batch = batch
        self.config = config
        # the effective reg weight: a Python float normally, or a traced
        # scalar when a grid sweep threads per-combo weights through the
        # fused state (``descent.run_grid``). update_step reads THIS, so
        # one compilation serves every lambda.
        self._reg_weight = config.reg_weight
        self._update_and_score = (
            _make_fixed_update_and_score_permuted(config)
            if self._row_perm is not None
            else _make_fixed_update_and_score(config)
        )
        from photon_ml_tpu.ops.sparse import matvec as _matvec

        self._score = (
            jax.jit(lambda w, feats: _matvec(feats, w)[self._inv_perm])
            if self._row_perm is not None
            else jax.jit(lambda w, feats: feats @ w)
        )
        self._downsample = (
            jax.jit(_binary_downsample_weights, static_argnums=(3,))
            if config.down_sampling_rate is not None
            and config.task.is_classifier
            else jax.jit(_uniform_downsample_weights, static_argnums=(3,))
            if config.down_sampling_rate is not None
            else None
        )
        # Down-sampling must SAVE work, not just zero weights (the
        # reference's down-sampler cuts the fixed-effect solve cost —
        # ``sampler/BinaryClassificationDownSampler.scala:36-66``): kept
        # rows are gathered into a smaller STATIC batch sized for the
        # expected keep count plus a 6-sigma margin, so every pass reuses
        # one compilation. The dense path only — gathering padded-ELL rows
        # is the sparse container's own re-pack problem.
        from photon_ml_tpu.ops.sparse import is_structured

        self._ds_budget = None
        if self._downsample is not None and not is_structured(
            batch.features
        ):
            self._ds_budget = _downsample_budget(
                np.asarray(batch.labels),
                np.asarray(batch.mask),
                config.down_sampling_rate,
                binary=config.task.is_classifier,
            )
            self._gather_solve = _make_gathered_solve(
                config, self._ds_budget
            )

    @property
    def dim(self) -> int:
        return self.batch.num_features

    def initial_params(self) -> jax.Array:
        from photon_ml_tpu.models.training import solve_dtype

        return jnp.zeros((self.dim,), solve_dtype(self.batch))

    def update(
        self, w: jax.Array, partial_scores: jax.Array, key=None
    ) -> Tuple[jax.Array, object]:
        """Compatibility form of :meth:`update_and_score` (the descent loop
        uses the fused form; this one computes-and-drops the rescore)."""
        params, result, _ = self.update_and_score(w, partial_scores, key)
        return params, result

    def update_and_score(
        self, w: jax.Array, partial_scores: jax.Array, key=None
    ) -> Tuple[jax.Array, object, jax.Array]:
        """update + full-batch rescore, fused into one dispatch (on
        remote/tunneled devices each dispatch is a round trip; the
        coordinate-descent loop uses this form)."""
        return self.update_step(w, partial_scores, key)

    def wrap_tracker(self, tracker):
        """Fused-pass hook: raw tracker pytree -> history object (identity
        here; SolverResult is already what materialize() reads)."""
        return tracker

    def fused_state(self):
        """Device-resident arrays update_step reads, as an explicit
        pytree. The fused whole-pass jit threads these as ARGUMENTS:
        closed-over concrete arrays are not hoisted by tracing (they are
        not tracers) and lower to HLO literals — the serialized program
        would carry the whole dataset (observed: remote-compile requests
        rejected with HTTP 413). The reg weight rides along so grid
        sweeps can vmap a combo axis over it."""
        return self.fused_state_for_reg(self._reg_weight)

    def fused_state_for_reg(self, reg_weight):
        """The fused state with a specific reg weight — the grid-sweep
        axis (``descent.run_grid`` stacks these across combos). The
        scalar keeps the DEFAULT float width (f64 under x64) so fused
        modes see the exact same lambda the plain loop casts from the
        config float — a forced f32 here would silently perturb
        non-representable lambdas (e.g. 0.1) in float64 runs.

        SAME-OBJECT CONTRACT: every leaf that does not depend on
        ``reg_weight`` must be returned as the IDENTICAL array object
        on every call (here: ``self.batch``/perm attributes, never
        copies). ``run_grid`` discovers broadcastable leaves by object
        identity across two probe calls; a fresh-but-equal object is
        stacked once per combo instead of broadcast — n_combo x the
        leaf's HBM (run_grid warns when a large leaf trips this)."""
        return (
            self.batch,
            self._row_perm,
            self._inv_perm,
            jnp.asarray(reg_weight, jnp.result_type(float)),
        )

    def with_fused_state(self, state):
        import copy

        c = copy.copy(self)
        c.batch, c._row_perm, c._inv_perm, c._reg_weight = state
        return c

    def reg_term(self, params: jax.Array) -> jax.Array:
        """Penalty under the EFFECTIVE reg weight (grid sweeps thread it
        per combo; identical to the config formula otherwise)."""
        lam = jnp.asarray(self._reg_weight, params.dtype)
        l2 = lam * (1.0 - self.config.l1_ratio)
        l1 = lam * self.config.l1_ratio
        return 0.5 * l2 * jnp.vdot(params, params) + l1 * jnp.sum(
            jnp.abs(params)
        )

    def update_step(
        self, w: jax.Array, partial_scores: jax.Array, key=None
    ) -> Tuple[jax.Array, object, jax.Array]:
        """TRACE-SAFE update + rescore: pure function of device values
        (jit-inlinable), returning only pytrees — the unit the fused
        whole-pass CD dispatch composes (``descent.py``)."""
        weights = self.batch.weights
        if self._downsample is not None:
            if key is None:
                raise ValueError(
                    "down-sampling needs a PRNG key per update; a fixed "
                    "default would drop the SAME rows every pass"
                )
            weights = self._downsample(
                key,
                weights * self.batch.mask,
                self.batch.labels,
                self.config.down_sampling_rate,
            )
            if self._ds_budget is not None:
                result, scores = self._gather_solve(
                    w,
                    jnp.asarray(self._reg_weight, w.dtype),
                    self.batch.features,
                    self.batch.labels,
                    self.batch.offsets + partial_scores,
                    weights,
                    self.batch.mask,
                )
                return result.w, result, scores
        if self._row_perm is not None:
            # hybrid batch: partial scores arrive in global row order, the
            # batch lives in stored order — the permutation gathers ride
            # inside the dispatch
            result, scores = self._update_and_score(
                w,
                jnp.asarray(self._reg_weight, w.dtype),
                self.batch.features,
                self.batch.labels,
                self.batch.offsets,
                partial_scores,
                weights,
                self.batch.mask,
                self._row_perm,
                self._inv_perm,
            )
            return result.w, result, scores
        result, scores = self._update_and_score(
            w,
            jnp.asarray(self._reg_weight, w.dtype),
            self.batch.features,
            self.batch.labels,
            self.batch.offsets + partial_scores,
            weights,
            self.batch.mask,
        )
        return result.w, result, scores

    def score(self, w: jax.Array) -> jax.Array:
        """Broadcast-dot scoring (``FixedEffectCoordinate.scala:171-178``),
        WITHOUT the dataset offset (scores must sum across coordinates)."""
        return self._score(w, self.batch.features)


@dataclasses.dataclass
class RandomEffectUpdateSummary:
    """Per-entity tracker view of one (possibly multi-bucket) update —
    the fields CoordinateDescent's histogram consumes, concatenated over
    buckets with sharding-padding lanes removed
    (``RandomEffectOptimizationTracker.scala:33-110``).

    LAZY: holds device arrays until `.reason` / `.iterations` /
    `.grad_norms` is first read, so the coordinate-descent loop can
    enqueue the next update without a device->host sync per pass (the
    reference pays a collect per tracker read; we defer it to history
    materialization)."""

    # [(reason_dev (E_b,), iterations_dev (E_b,), grad_norm_dev (E_b,),
    #   valid mask, entity_index (E_b,)), ...]
    pending: list

    def _materialize(self):
        if self.pending is not None:
            self._reason = np.concatenate(
                [np.asarray(r)[v] for r, _, _, v, _ in self.pending]
            )
            self._iterations = np.concatenate(
                [np.asarray(i)[v] for _, i, _, v, _ in self.pending]
            )
            self._grad_norms = np.concatenate(
                [np.asarray(g)[v] for _, _, g, v, _ in self.pending]
            )
            self._entity_ids = np.concatenate(
                [np.asarray(e)[v] for _, _, _, v, e in self.pending]
            )
            self.pending = None

    @property
    def reason(self) -> np.ndarray:  # (E_active,) int32
        self._materialize()
        return self._reason

    @property
    def iterations(self) -> np.ndarray:  # (E_active,) int32
        self._materialize()
        return self._iterations

    @property
    def grad_norms(self) -> np.ndarray:  # (E_active,) final ||grad||
        self._materialize()
        return self._grad_norms

    @property
    def entity_ids(self) -> np.ndarray:  # (E_active,) table rows
        self._materialize()
        return self._entity_ids


def _make_multi_bucket_update(config: CoordinateConfig):
    """ONE jitted call updating ALL buckets of a random effect: per bucket,
    gather residual offsets and warm starts from the global table, solve
    the bucket's entities in one vmapped call, scatter solutions back.
    Sentinel indices (== num_entities) clip on gather and drop on scatter.

    Fusing the whole multi-bucket pass into a single dispatch matters on
    remote/tunneled devices where every dispatch pays a round trip (a
    4-bucket update would otherwise cost 4+ latencies per CD pass). Cache
    key zeroes reg_weight (traced per entity) so a lambda grid reuses one
    compile."""
    return _make_multi_bucket_update_cached(
        dataclasses.replace(config, reg_weight=0.0)
    )


@lru_cache(maxsize=128)
def _make_multi_bucket_update_cached(config: CoordinateConfig):
    solve = _make_solve(config, batched=True)
    from photon_ml_tpu.solvers.common import final_grad_norm

    @jax.jit
    def update_all(
        table, reg_weights, full_offsets, entity_indices, buckets,
        row_features, row_entities,
    ):
        trackers = []
        for eidx, bucket in zip(entity_indices, buckets):
            offsets = bucket.gather_offsets(full_offsets)
            w0 = jnp.take(table, eidx, axis=0, mode="clip")
            lam = jnp.take(reg_weights, eidx, mode="clip")
            result = solve(
                w0, lam, bucket.features, bucket.labels, offsets,
                bucket.weights, bucket.mask,
            )
            table = table.at[eidx].set(result.w, mode="drop")
            # final per-entity gradient norm rides the tracker tuple
            # (valid with tracking on or off), feeding the fleet-level
            # convergence summaries' worst-k signal for free — it is
            # computed in-program, no extra dispatch
            trackers.append(
                (result.reason, result.iterations, final_grad_norm(result))
            )
        # full-row rescore in the same dispatch
        scores = _score_rows_by_entity(table, row_features, row_entities)
        return table, tuple(trackers), scores

    return update_all


def _score_rows_by_entity(table, feats, ents):
    """Embedding-style per-row scoring with the -1 = unknown-entity ->
    score-0 convention (``model/RandomEffectModel.scala:117-146``). The
    ONE definition both the fused update path and score() use."""
    safe = jnp.maximum(ents, 0)
    per_row = jnp.einsum("nd,nd->n", feats, table[safe])
    return jnp.where(ents >= 0, per_row, 0.0)


class RandomEffectCoordinate:
    """Per-entity batched coordinate.

    Owns the padded active design plus full-row (features, entity index)
    for scoring. Scoring covers ALL rows — active and passive — through the
    coefficient table (``RandomEffectCoordinate.scala:116-170``).

    Accepts either a single global-cap :class:`RandomEffectDesign` or a
    :class:`BucketedRandomEffectDesign`; a plain design is treated as one
    bucket whose lanes ARE the table rows.
    """

    def __init__(
        self,
        design,  # RandomEffectDesign | BucketedRandomEffectDesign
        row_features: jax.Array,  # (n, d) full scoring view
        row_entities: jax.Array,  # (n,) int32, -1 = unknown entity
        full_offsets_base: jax.Array,  # (n,) data offsets
        config: CoordinateConfig,
        reg_weights: Optional[jax.Array] = None,  # (E,) per-entity lambdas
    ):
        if config.random_effect is None:
            raise ValueError("config lacks random_effect; wrong coordinate")
        if isinstance(design, RandomEffectDesign):
            design = BucketedRandomEffectDesign(
                buckets=[design],
                entity_index=[
                    np.arange(design.num_entities, dtype=np.int32)
                ],
                num_entities=design.num_entities,
            )
        self.design = design
        self.row_features = row_features
        self.row_entities = row_entities
        self.full_offsets_base = full_offsets_base
        self.config = config
        # (E,) per-entity regularization weights
        # (``RandomEffectOptimizationProblem.scala:41-110``: each entity may
        # carry a distinct objective); shared config weight by default
        self._uniform_reg = reg_weights is None
        if reg_weights is None:
            reg_weights = jnp.full(
                (design.num_entities,), config.reg_weight, jnp.float32
            )
        else:
            reg_weights = jnp.asarray(reg_weights, jnp.float32)
            if reg_weights.shape != (design.num_entities,):
                raise ValueError(
                    f"reg_weights must be ({design.num_entities},), got "
                    f"{reg_weights.shape}"
                )
        self.reg_weights = reg_weights
        self._update_all = _make_multi_bucket_update(config)
        self._entity_indices = tuple(
            jnp.asarray(ei) for ei in design.entity_index
        )
        # static per-bucket masks of real (non-sharding-pad) lanes
        self._valid_lanes = [
            np.asarray(ei) < design.num_entities
            for ei in design.entity_index
        ]

        self._score = jax.jit(_score_rows_by_entity)

    @property
    def num_entities(self) -> int:
        return self.design.num_entities

    @property
    def dim(self) -> int:
        return self.design.dim

    def initial_params(self) -> jax.Array:
        from photon_ml_tpu.models.training import solve_dtype

        return jnp.zeros(
            (self.num_entities, self.dim),
            solve_dtype(self.design.buckets[0]),
        )

    def update(
        self, table: jax.Array, partial_scores: jax.Array, key=None
    ) -> Tuple[jax.Array, object]:
        table, summary, _ = self.update_and_score(
            table, partial_scores, key=key
        )
        return table, summary

    def update_and_score(
        self, table: jax.Array, partial_scores: jax.Array, key=None
    ) -> Tuple[jax.Array, object, jax.Array]:
        """All bucket solves + the full-row rescore in ONE dispatch."""
        table, trackers, scores = self.update_step(
            table, partial_scores, key
        )
        return table, self.wrap_tracker(trackers), scores

    def update_step(
        self, table: jax.Array, partial_scores: jax.Array, key=None
    ) -> Tuple[jax.Array, tuple, jax.Array]:
        """Trace-safe form: returns the RAW per-bucket tracker tuple (a
        pytree) instead of the lazy summary object, so the fused CD pass
        can return it through jit."""
        return self._update_all(
            table,
            self.reg_weights,
            self.full_offsets_base + partial_scores,
            self._entity_indices,
            tuple(self.design.buckets),
            self.row_features,
            self.row_entities,
        )

    def wrap_tracker(self, trackers: tuple) -> "RandomEffectUpdateSummary":
        """Raw (reason, iterations, grad_norm) bucket tuple -> lazy
        history summary (valid-lane masks and the lanes' table-row
        indices are host-side statics attached here)."""
        pending = [
            (reason, iters, gnorm, valid, np.asarray(ei))
            for (reason, iters, gnorm), valid, ei in zip(
                trackers, self._valid_lanes, self.design.entity_index
            )
        ]
        return RandomEffectUpdateSummary(pending=pending)

    def fused_state(self):
        """See ``FixedEffectCoordinate.fused_state``."""
        return (
            self.reg_weights,
            self.full_offsets_base,
            self._entity_indices,
            tuple(self.design.buckets),
            self.row_features,
            self.row_entities,
        )

    def fused_state_for_reg(self, reg_weight):
        """The fused state with every entity's reg weight set to
        ``reg_weight`` — the grid-sweep axis (``descent.run_grid``).
        The grid REPLACES the coordinate-level lambda, like the
        reference's ``GLMOptimizationConfiguration`` grid; a coordinate
        built with CUSTOM per-entity weights refuses (silently
        discarding them would break run_grid's sequential-equivalence
        guarantee).

        SAME-OBJECT CONTRACT (see the fixed-effect counterpart): only
        the freshly-built per-entity weight vector may vary per call;
        the design buckets, row features, entity indices, and offsets
        must be the SAME objects every time so run_grid broadcasts them
        instead of stacking n_combo copies of the dataset."""
        if not getattr(self, "_uniform_reg", True):
            raise ValueError(
                "grid sweeps replace the coordinate's shared reg weight; "
                "this RandomEffectCoordinate carries CUSTOM per-entity "
                "reg_weights — run its combos sequentially instead"
            )
        return (
            jnp.full(
                (self.design.num_entities,), reg_weight, jnp.float32
            ),
            self.full_offsets_base,
            self._entity_indices,
            tuple(self.design.buckets),
            self.row_features,
            self.row_entities,
        )

    def with_fused_state(self, state):
        import copy

        c = copy.copy(self)
        (
            c.reg_weights,
            c.full_offsets_base,
            c._entity_indices,
            buckets,
            c.row_features,
            c.row_entities,
        ) = state
        c.design = dataclasses.replace(self.design, buckets=list(buckets))
        return c

    def score(self, table: jax.Array) -> jax.Array:
        return self._score(table, self.row_features, self.row_entities)

    def reg_term(self, table: jax.Array) -> jax.Array:
        """Penalty with PER-ENTITY weights — what the vmapped solves
        minimized (``RandomEffectOptimizationProblem.getRegularizationTermValue``)."""
        lam = self.reg_weights.astype(table.dtype)
        l2 = lam * (1.0 - self.config.l1_ratio)
        l1 = lam * self.config.l1_ratio
        sq = jnp.sum(table * table, axis=-1)
        ab = jnp.sum(jnp.abs(table), axis=-1)
        return jnp.sum(0.5 * l2 * sq + l1 * ab)


class EntityShardedRandomEffectCoordinate:
    """Entity-sharded random-effect coordinate: the per-entity vmapped
    solves run under ``shard_map`` over the 'entity' mesh axis with ZERO
    collectives in the update (docs/PARALLEL.md) — each shard gathers
    warm starts from ITS table block, solves ITS entities, scatters back
    locally, and rescores ITS rows. Only the fixed-effect coordinate's
    objective reduces across devices.

    Contract (``game.data``): entity ownership follows the sharded
    checkpoint writer's round-robin rule (``EntityShardAssignment``),
    the table is stored SHARD-MAJOR (pad rows zero), and the batch row
    space is entity-PARTITIONED (``EntityRowPartition``) so every
    entity's rows live on its owner shard — the device analog of the
    reference's ``RandomEffectIdPartitioner`` placement. All per-row
    inputs here are in the PERMUTED row order; sentinel lanes/rows mask
    to zero and their scattered solutions drop.

    Exposes the full fused surface (update_step / fused_state /
    with_fused_state / wrap_tracker), so whole-pass and superpass
    dispatches compose — the shard_map nests inside the pass jit.
    """

    def __init__(
        self,
        design,  # BucketedRandomEffectDesign on the PERMUTED rows, GLOBAL ids
        row_features: jax.Array,  # (n_pad, d) permuted
        row_entities: jax.Array,  # (n_pad,) permuted GLOBAL ids, -1 unknown
        full_offsets_base: jax.Array,  # (n_pad,) permuted
        config: CoordinateConfig,
        mesh,
        assignment,  # game.data.EntityShardAssignment
        partition,  # game.data.EntityRowPartition
        reg_weights: Optional[jax.Array] = None,  # (E,) GLOBAL order
    ):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from photon_ml_tpu.game.data import BucketedRandomEffectDesign
        from photon_ml_tpu.parallel.mesh import ENTITY_AXIS, shard_map

        if config.random_effect is None:
            raise ValueError("config lacks random_effect; wrong coordinate")
        if isinstance(design, RandomEffectDesign):
            design = BucketedRandomEffectDesign(
                buckets=[design],
                entity_index=[
                    np.arange(design.num_entities, dtype=np.int32)
                ],
                num_entities=design.num_entities,
            )
        n_shards = mesh.shape[ENTITY_AXIS]
        if assignment.num_shards != n_shards:
            raise ValueError(
                f"assignment built for {assignment.num_shards} shards, "
                f"mesh 'entity' axis has {n_shards}"
            )
        if partition.num_shards != n_shards:
            raise ValueError(
                f"row partition built for {partition.num_shards} shards, "
                f"mesh 'entity' axis has {n_shards}"
            )
        e_global = assignment.num_entities
        if design.num_entities != e_global:
            raise ValueError(
                f"design covers {design.num_entities} entities, "
                f"assignment {e_global}"
            )
        b_rows = assignment.rows_per_shard
        r_rows = partition.rows_per_shard
        n_pad = partition.padded_rows
        if int(np.shape(row_entities)[0]) != n_pad:
            raise ValueError(
                f"row arrays must be in the partitioned row space "
                f"({n_pad} rows), got {np.shape(row_entities)[0]}"
            )
        self.config = config
        self.mesh = mesh
        self.assignment = assignment
        self.partition = partition
        self.design = design

        ent_spec = lambda nd: NamedSharding(
            mesh, P(ENTITY_AXIS, *([None] * (nd - 1)))
        )

        def place(x):
            x = jnp.asarray(x)
            return jax.device_put(x, ent_spec(x.ndim))

        # per-entity reg weights, stored shard-major (pad rows keep the
        # config weight — their scattered solutions drop anyway)
        self._uniform_reg = reg_weights is None
        if reg_weights is None:
            reg_stored = np.full(
                (assignment.padded_rows,), config.reg_weight, np.float32
            )
        else:
            reg_weights = np.asarray(reg_weights, np.float32)
            if reg_weights.shape != (e_global,):
                raise ValueError(
                    f"reg_weights must be ({e_global},), got "
                    f"{reg_weights.shape}"
                )
            reg_stored = assignment.table_from_global(reg_weights)
        self.reg_weights = place(reg_stored)

        # regroup every bucket's lanes by owner shard: shard p's lanes
        # contiguous, padded to the max per-shard count; indices go
        # shard-LOCAL (table rows within the block, sentinel b_rows;
        # offset rows within the block, sentinel -1)
        g2s = assignment.global_to_stored
        buckets = []
        eidx_local = []
        self._valid_lanes = []
        self._lane_entities = []
        for bucket, eidx in zip(design.buckets, design.entity_index):
            eidx = np.asarray(eidx, np.int64)
            stored = np.where(
                eidx < e_global, g2s[np.minimum(eidx, e_global)],
                assignment.padded_rows,
            )
            owner = assignment.shard_of_stored(
                np.minimum(stored, assignment.padded_rows - 1)
            )
            owner = np.where(
                stored < assignment.padded_rows, owner, 0
            )  # sentinels balance onto shard 0's padding
            counts = np.bincount(owner, minlength=n_shards)
            l_b = max(int(counts.max()), 1)
            order = np.argsort(owner, kind="stable")
            starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
            slot = np.arange(eidx.size) - starts[owner[order]]
            lane_of = owner[order] * l_b + slot  # new lane of old lane
            new_lanes = n_shards * l_b
            new_stored = np.full(new_lanes, assignment.padded_rows, np.int64)
            new_stored[lane_of] = stored[order]
            local = np.where(
                new_stored < assignment.padded_rows,
                new_stored - (np.arange(new_lanes) // l_b) * b_rows,
                b_rows,
            ).astype(np.int32)

            def regroup(x, fill=0.0):
                x = np.asarray(x)
                out = np.full(
                    (new_lanes,) + x.shape[1:], fill, x.dtype
                )
                out[lane_of] = x[order]
                return out

            ri = np.asarray(bucket.row_index, np.int64)
            shard_of_lane = np.arange(new_lanes) // l_b
            ri_new = regroup(ri, fill=-1)
            ri_local = np.where(
                ri_new >= 0,
                ri_new - shard_of_lane[:, None] * r_rows,
                -1,
            ).astype(np.int32)
            buckets.append(
                RandomEffectDesign(
                    features=place(regroup(bucket.features)),
                    labels=place(regroup(bucket.labels)),
                    weights=place(regroup(bucket.weights)),
                    mask=place(regroup(bucket.mask)),
                    row_index=place(ri_local),
                )
            )
            eidx_local.append(place(local))
            self._valid_lanes.append(
                new_stored < assignment.padded_rows
            )
            glob = np.full(new_lanes, e_global, np.int64)
            real = new_stored < assignment.padded_rows
            glob[real] = assignment.stored_to_global[new_stored[real]]
            self._lane_entities.append(glob.astype(np.int32))
        self._buckets = tuple(buckets)
        self._entity_indices = tuple(eidx_local)

        # per-row scoring inputs, shard-local entity rows
        re_ids = np.asarray(row_entities, np.int64)
        known = re_ids >= 0
        ents_local = np.full(re_ids.shape, -1, np.int32)
        shard_of_row = np.arange(n_pad) // r_rows
        ents_local[known] = (
            g2s[re_ids[known]] - shard_of_row[known] * b_rows
        ).astype(np.int32)
        self.row_features = place(row_features)
        self.row_entities_local = place(ents_local)
        self.full_offsets_base = place(full_offsets_base)

        solve = _make_solve(config, batched=True)
        from photon_ml_tpu.solvers.common import final_grad_norm

        spec_of = lambda x: P(ENTITY_AXIS, *([None] * (jnp.ndim(x) - 1)))

        def update_all(table, reg, offsets, eidx, buckets_in, feats, ents):
            def update_shard(
                table_blk, reg_blk, off_blk, eidx_blk, bks, f_blk, e_blk
            ):
                trackers = []
                for li, bucket in zip(eidx_blk, bks):
                    offs = bucket.gather_offsets(off_blk)
                    w0 = jnp.take(table_blk, li, axis=0, mode="clip")
                    lam = jnp.take(reg_blk, li, mode="clip")
                    result = solve(
                        w0, lam, bucket.features, bucket.labels, offs,
                        bucket.weights, bucket.mask,
                    )
                    table_blk = table_blk.at[li].set(
                        result.w, mode="drop"
                    )
                    trackers.append(
                        (
                            result.reason,
                            result.iterations,
                            final_grad_norm(result),
                        )
                    )
                scores = _score_rows_by_entity(table_blk, f_blk, e_blk)
                return table_blk, tuple(trackers), scores

            args = (table, reg, offsets, eidx, buckets_in, feats, ents)
            in_specs = jax.tree_util.tree_map(spec_of, args)
            out_shape = jax.eval_shape(
                lambda *a: update_shard(*a), *args
            )
            out_specs = jax.tree_util.tree_map(spec_of, out_shape)
            return shard_map(
                update_shard,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                # per-shard solver while_loops have no replication rule;
                # every output is genuinely shard-varying anyway
                check_rep=False,
            )(*args)

        self._update_all = jax.jit(update_all)

        def score_fn(table, feats, ents):
            args = (table, feats, ents)
            in_specs = jax.tree_util.tree_map(spec_of, args)
            return shard_map(
                _score_rows_by_entity,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=P(ENTITY_AXIS),
                check_rep=False,
            )(*args)

        self._score = jax.jit(score_fn)

    @property
    def num_entities(self) -> int:
        return self.assignment.num_entities

    @property
    def dim(self) -> int:
        return self.design.dim

    def initial_params(self) -> jax.Array:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from photon_ml_tpu.models.training import solve_dtype
        from photon_ml_tpu.parallel.mesh import ENTITY_AXIS

        return jax.device_put(
            jnp.zeros(
                (self.assignment.padded_rows, self.dim),
                solve_dtype(self._buckets[0]),
            ),
            NamedSharding(self.mesh, P(ENTITY_AXIS, None)),
        )

    def global_table(self, table: jax.Array) -> np.ndarray:
        """Stored (shard-major, padded) table -> global entity order —
        the equivalence bridge to an unsharded RandomEffectCoordinate."""
        return self.assignment.table_to_global(np.asarray(table))

    def update(self, table, partial_scores, key=None):
        table, summary, _ = self.update_and_score(
            table, partial_scores, key=key
        )
        return table, summary

    def update_and_score(self, table, partial_scores, key=None):
        table, trackers, scores = self.update_step(
            table, partial_scores, key
        )
        return table, self.wrap_tracker(trackers), scores

    def update_step(self, table, partial_scores, key=None):
        """Trace-safe: the whole multi-bucket update + rescore is ONE
        shard_map'd program with no collective instructions (asserted in
        tests/test_partition.py via the compiled HLO)."""
        return self._update_all(
            table,
            self.reg_weights,
            self.full_offsets_base + partial_scores,
            self._entity_indices,
            self._buckets,
            self.row_features,
            self.row_entities_local,
        )

    def wrap_tracker(self, trackers: tuple) -> "RandomEffectUpdateSummary":
        pending = [
            (reason, iters, gnorm, valid, ents)
            for (reason, iters, gnorm), valid, ents in zip(
                trackers, self._valid_lanes, self._lane_entities
            )
        ]
        return RandomEffectUpdateSummary(pending=pending)

    def fused_state(self):
        """See ``FixedEffectCoordinate.fused_state``."""
        return (
            self.reg_weights,
            self.full_offsets_base,
            self._entity_indices,
            self._buckets,
            self.row_features,
            self.row_entities_local,
        )

    def with_fused_state(self, state):
        import copy

        c = copy.copy(self)
        (
            c.reg_weights,
            c.full_offsets_base,
            c._entity_indices,
            c._buckets,
            c.row_features,
            c.row_entities_local,
        ) = state
        return c

    def score(self, table: jax.Array) -> jax.Array:
        return self._score(
            table, self.row_features, self.row_entities_local
        )

    def reg_term(self, table: jax.Array) -> jax.Array:
        """Per-entity penalty; pad rows are zero so their lam is inert."""
        lam = self.reg_weights.astype(table.dtype)
        l2 = lam * (1.0 - self.config.l1_ratio)
        l1 = lam * self.config.l1_ratio
        sq = jnp.sum(table * table, axis=-1)
        ab = jnp.sum(jnp.abs(table), axis=-1)
        return jnp.sum(0.5 * l2 * sq + l1 * ab)


# -- down-samplers (``sampler/``) -------------------------------------------


def _binary_downsample_weights(key, weights, labels, rate: float):
    """Keep positives; keep negatives w.p. rate with weight / rate
    (``sampler/BinaryClassificationDownSampler.scala:36-66``). Static
    shapes: dropped rows get weight 0."""
    keep = jax.random.uniform(key, weights.shape) < rate
    neg = labels <= 0.0
    w = jnp.where(neg & keep, weights / rate, weights)
    return jnp.where(neg & ~keep, 0.0, w)


def _uniform_downsample_weights(key, weights, labels, rate: float):
    """Uniform Bernoulli down-sampling with reweighting
    (``sampler/DefaultDownSampler.scala:30``)."""
    keep = jax.random.uniform(key, weights.shape) < rate
    return jnp.where(keep, weights / rate, 0.0)
