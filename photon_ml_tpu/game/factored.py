"""Factored random effects + matrix-factorization scoring.

Rebuild of ``algorithm/FactoredRandomEffectCoordinate.scala:37-267``: when
entities are too many / data too thin for full per-entity coefficient
vectors, factor the random effect as  w_e = B gamma_e  with a shared
projection B (d x k) and per-entity latent coefficients gamma_e (k,).
Training alternates (numInnerIterations x):

  (a) project the active design through the current B and solve the
      per-entity latent GLMs (a RandomEffect solve in k dims);
  (b) re-fit B as ONE GLM whose virtual features are the Kronecker
      products x (x) gamma_e (``kroneckerProductFeaturesAndCoefficients``
      :251-266) — here the Kronecker design is NEVER materialized: margins,
      gradients, and Hessian-vector products contract X, gamma, and B
      directly by einsum, so phase (b) costs O(E R d k) FLOPs and
      O(E R d) memory instead of the O(E R d k) memory a materialized
      (E*R, d*k) matrix would need.

Accepts a :class:`BucketedRandomEffectDesign` (or a single global-cap
design, wrapped as one bucket): phase (a) runs per bucket with
gather/scatter against the global gamma table; phase (b) sums every
bucket's contribution into one shared-B objective.

``MatrixFactorizationModel`` (``model/MatrixFactorizationModel.scala:30-134``)
is the inference-side pairing: two latent tables scored by gathered dot.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.types import _pytree_dataclass
from photon_ml_tpu.game.coordinates import CoordinateConfig, _make_solve
from photon_ml_tpu.game.data import (
    BucketedRandomEffectDesign,
    RandomEffectDesign,
)
from photon_ml_tpu.models.training import OptimizerType
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.solvers import (
    minimize_lbfgs,
    minimize_owlqn,
    minimize_tron,
)


@_pytree_dataclass
class FactoredParams:
    """(per-entity latent table, shared projection)."""

    gamma: jax.Array  # (E, k)
    projection: jax.Array  # (d, k)


def is_factored_params(x) -> bool:
    """THE predicate for factored parameter containers — persistence and
    checkpointing dispatch on it."""
    return isinstance(x, FactoredParams)


@dataclasses.dataclass(frozen=True)
class FactoredConfig:
    """``MFOptimizationConfiguration.scala:24-46`` ("numInnerIter,latentDim")
    plus the two sub-configs (random-effect & latent-matrix) the reference
    parses from its triple-config string."""

    latent_dim: int
    num_inner_iterations: int = 1
    random_effect_config: Optional[CoordinateConfig] = None
    latent_factor_config: Optional[CoordinateConfig] = None

    def __post_init__(self):
        if self.latent_dim < 1:
            raise ValueError(f"latent_dim must be >= 1, got {self.latent_dim}")
        if self.num_inner_iterations < 1:
            raise ValueError(
                f"num_inner_iterations must be >= 1, got "
                f"{self.num_inner_iterations}"
            )


@lru_cache(maxsize=64)
def _make_latent_solve(config: CoordinateConfig, num_buckets: int):
    """jitted solve for the shared projection B over `num_buckets` bucket
    designs. The objective treats vec(B) as the coefficient vector of a
    GLM on the VIRTUAL Kronecker features x (x) gamma — contracted lazily:

      margin_er = einsum('erd,dk,ek->er', X_b, B, gamma_b)
      grad_dk   = einsum('er,erd,ek->dk', c, X_b, gamma_b) + lambda B
      (Hv)_dk   = same contraction with c2 * dmargin(V)

    Bucket tensors arrive as positional args (pytrees of varying shapes),
    so one compilation serves a whole training run."""
    loss = loss_for_task(config.task)
    scfg = config.solver_config()
    use_tron = config.optimizer == OptimizerType.TRON
    use_owlqn = config.l1_ratio > 0.0
    l2 = config.reg_weight * (1.0 - config.l1_ratio)
    l1 = config.reg_weight * config.l1_ratio
    lam = l2

    def solve(b0, gammas, buckets_offsets, buckets):
        d, k = b0.shape

        def margins(B, bucket, gamma_b, offsets):
            xb = jnp.einsum("erd,dk->erk", bucket.features, B)
            return jnp.einsum("erk,ek->er", xb, gamma_b) + offsets

        def value_and_grad(vecB):
            B = vecB.reshape(d, k)
            val = 0.5 * lam * jnp.vdot(B, B)
            grad = lam * B
            for bucket, gamma_b, offsets in zip(
                buckets, gammas, buckets_offsets
            ):
                w = bucket.weights * bucket.mask
                z = margins(B, bucket, gamma_b, offsets)
                val = val + jnp.sum(w * loss.value(z, bucket.labels))
                c = w * loss.d1(z, bucket.labels)
                cg = jnp.einsum("er,ek->erk", c, gamma_b)
                grad = grad + jnp.einsum(
                    "erd,erk->dk", bucket.features, cg
                )
            return val, grad.reshape(-1)

        def hvp(vecB, vecV):
            B = vecB.reshape(d, k)
            V = vecV.reshape(d, k)
            out = lam * V
            for bucket, gamma_b, offsets in zip(
                buckets, gammas, buckets_offsets
            ):
                w = bucket.weights * bucket.mask
                z = margins(B, bucket, gamma_b, offsets)
                dz = margins(V, bucket, gamma_b, jnp.zeros_like(offsets))
                c2 = w * loss.d2(z, bucket.labels) * dz
                cg = jnp.einsum("er,ek->erk", c2, gamma_b)
                out = out + jnp.einsum("erd,erk->dk", bucket.features, cg)
            return out.reshape(-1)

        if use_owlqn:
            return minimize_owlqn(value_and_grad, b0.reshape(-1), l1, scfg)
        if use_tron:
            return minimize_tron(value_and_grad, hvp, b0.reshape(-1), scfg)
        return minimize_lbfgs(value_and_grad, b0.reshape(-1), scfg)

    return jax.jit(solve)


class FactoredRandomEffectCoordinate:
    """Drop-in coordinate: update(params, partial_scores) / score(params)."""

    def __init__(
        self,
        design,  # RandomEffectDesign | BucketedRandomEffectDesign
        row_features: jax.Array,
        row_entities: jax.Array,
        full_offsets_base: jax.Array,
        re_config: CoordinateConfig,
        factored: FactoredConfig,
        seed: int = 0,
    ):
        if isinstance(design, RandomEffectDesign):
            design = BucketedRandomEffectDesign(
                buckets=[design],
                entity_index=[
                    np.arange(design.num_entities, dtype=np.int32)
                ],
                num_entities=design.num_entities,
            )
        self.design = design
        self.row_features = row_features
        self.row_entities = row_entities
        self.full_offsets_base = full_offsets_base
        self.config = re_config
        self.factored = factored
        self._seed = seed

        latent_cfg = factored.latent_factor_config or re_config
        self._latent_cfg = latent_cfg
        self._re_solve = _make_solve(
            dataclasses.replace(re_config, random_effect=None), batched=True
        )
        self._latent_solve = _make_latent_solve(
            dataclasses.replace(latent_cfg, random_effect=None),
            design.num_buckets,
        )

        @jax.jit
        def score_rows(params: FactoredParams, feats, ents):
            latent = feats @ params.projection  # (n, k)
            safe = jnp.maximum(ents, 0)
            per_row = jnp.einsum("nk,nk->n", latent, params.gamma[safe])
            return jnp.where(ents >= 0, per_row, 0.0)

        self._score = score_rows

    @property
    def num_entities(self) -> int:
        return self.design.num_entities

    @property
    def dim(self) -> int:
        """Original feature dimension of the underlying design."""
        return self.design.dim

    def initial_params(self) -> FactoredParams:
        """Gamma zeros; B a Gaussian N(0, 1/d) like the reference's random
        projection init (``FactoredRandomEffectOptimizationProblem``)."""
        from photon_ml_tpu.models.training import solve_dtype

        d = self.design.dim
        k = self.factored.latent_dim
        rng = np.random.default_rng(self._seed)
        b = rng.normal(0.0, 1.0 / np.sqrt(d), size=(d, k))
        dtype = solve_dtype(self.design.buckets[0])
        return FactoredParams(
            gamma=jnp.zeros((self.num_entities, k), dtype),
            projection=jnp.asarray(b, dtype),
        )

    def update(
        self, params: FactoredParams, partial_scores: jax.Array, key=None
    ) -> Tuple[FactoredParams, object]:
        design = self.design
        full_offsets = self.full_offsets_base + partial_scores
        bucket_offsets = [
            b.gather_offsets(full_offsets) for b in design.buckets
        ]
        gamma, b = params.gamma, params.projection
        lam_re = jnp.full(
            (design.num_entities,), self.config.reg_weight, gamma.dtype
        )
        result = None
        for _ in range(self.factored.num_inner_iterations):
            # (a) latent-space per-entity solves, bucket by bucket
            for bucket, entity_index, offsets in zip(
                design.buckets, design.entity_index, bucket_offsets
            ):
                eidx = jnp.asarray(entity_index)
                g0 = jnp.take(gamma, eidx, axis=0, mode="clip")
                lam_b = jnp.take(lam_re, eidx, mode="clip")
                latent_feats = jnp.einsum(
                    "erd,dk->erk", bucket.features, b
                )
                result = self._re_solve(
                    g0,
                    lam_b,
                    latent_feats,
                    bucket.labels,
                    offsets,
                    bucket.weights,
                    bucket.mask,
                )
                gamma = gamma.at[eidx].set(result.w, mode="drop")
            # (b) shared projection over ALL buckets, einsum-contracted
            gammas = tuple(
                jnp.take(gamma, jnp.asarray(ei), axis=0, mode="clip")
                for ei in design.entity_index
            )
            latent_result = self._latent_solve(
                b, gammas, tuple(bucket_offsets), tuple(design.buckets)
            )
            b = latent_result.w.reshape(b.shape)
        return FactoredParams(gamma=gamma, projection=b), result

    def score(self, params: FactoredParams) -> jax.Array:
        return self._score(params, self.row_features, self.row_entities)

    def update_step(
        self, params: FactoredParams, partial_scores: jax.Array, key=None
    ) -> Tuple[FactoredParams, object, jax.Array]:
        """Trace-safe update + rescore (the fused CD pass's unit): the
        alternating gamma/B loop above is pure jnp, so it inlines."""
        new_params, result = self.update(params, partial_scores, key)
        return new_params, result, self.score(new_params)

    def wrap_tracker(self, tracker):
        return tracker

    def fused_state(self):
        """See ``FixedEffectCoordinate.fused_state``. The (E,)-int
        entity_index lists stay trace-time constants (small next to the
        designs)."""
        return (
            tuple(self.design.buckets),
            self.row_features,
            self.row_entities,
            self.full_offsets_base,
        )

    def with_fused_state(self, state):
        import copy

        c = copy.copy(self)
        (
            buckets,
            c.row_features,
            c.row_entities,
            c.full_offsets_base,
        ) = state
        c.design = dataclasses.replace(self.design, buckets=list(buckets))
        return c

    def reg_term(self, params: FactoredParams) -> jax.Array:
        """gamma is penalized under the RE config, B under the latent-factor
        config — the exact quantities the two inner solves minimize."""
        from photon_ml_tpu.game.descent import _config_reg_term

        return _config_reg_term(self.config, params.gamma) + _config_reg_term(
            self._latent_cfg, params.projection
        )

    def to_full_table(self, params: FactoredParams) -> jax.Array:
        """Materialize w_e = B gamma_e: (E, d) — the reference's
        ``RandomEffectModelInProjectedSpace.toRandomEffectModel``."""
        return params.gamma @ params.projection.T


class MatrixFactorizationModel:
    """Two latent tables; score(row, col) = rowFactors[row] . colFactors[col]
    with either side missing scoring 0 (``MatrixFactorizationModel.scala``)."""

    def __init__(self, row_factors: jax.Array, col_factors: jax.Array):
        if row_factors.shape[1] != col_factors.shape[1]:
            raise ValueError("row/col latent dims differ")
        self.row_factors = row_factors
        self.col_factors = col_factors

        @jax.jit
        def score(rows, cols, rf, cf):
            safe_r = jnp.maximum(rows, 0)
            safe_c = jnp.maximum(cols, 0)
            s = jnp.einsum("nk,nk->n", rf[safe_r], cf[safe_c])
            return jnp.where((rows >= 0) & (cols >= 0), s, 0.0)

        self._score = score

    @property
    def latent_dim(self) -> int:
        return self.row_factors.shape[1]

    def score(self, row_ids: jax.Array, col_ids: jax.Array) -> jax.Array:
        return self._score(
            row_ids, col_ids, self.row_factors, self.col_factors
        )

    @staticmethod
    def random(
        num_rows: int, num_cols: int, latent_dim: int, seed: int = 0,
        dtype=jnp.float32,
    ) -> "MatrixFactorizationModel":
        rng = np.random.default_rng(seed)
        return MatrixFactorizationModel(
            jnp.asarray(rng.normal(size=(num_rows, latent_dim)), dtype),
            jnp.asarray(rng.normal(size=(num_cols, latent_dim)), dtype),
        )
