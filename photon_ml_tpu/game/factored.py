"""Factored random effects + matrix-factorization scoring.

Rebuild of ``algorithm/FactoredRandomEffectCoordinate.scala:37-267``: when
entities are too many / data too thin for full per-entity coefficient
vectors, factor the random effect as  w_e = B^T gamma_e  with a shared
projection B (d x k) and per-entity latent coefficients gamma_e (k,).
Training alternates (numInnerIterations x):

  (a) project the active design through the current B and solve the
      per-entity latent GLMs (a RandomEffect solve in k dims);
  (b) re-fit B as ONE fixed-effect-style GLM over Kronecker-product
      features x (x) gamma_e — vec(B) is the coefficient vector
      (``kroneckerProductFeaturesAndCoefficients`` :251-266).

Both phases are jitted; the Kronecker design is an einsum. Scoring:
margin_i = gamma_{e(i)} . (B^T x_i), unknown entities score 0.

``MatrixFactorizationModel`` (``model/MatrixFactorizationModel.scala:30-134``)
is the inference-side pairing: two latent tables scored by gathered dot.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.types import _pytree_dataclass
from photon_ml_tpu.game.coordinates import CoordinateConfig, _make_solve
from photon_ml_tpu.game.data import RandomEffectDesign


@_pytree_dataclass
class FactoredParams:
    """(per-entity latent table, shared projection)."""

    gamma: jax.Array  # (E, k)
    projection: jax.Array  # (d, k)


@dataclasses.dataclass(frozen=True)
class FactoredConfig:
    """``MFOptimizationConfiguration.scala:24-46`` ("numInnerIter,latentDim")
    plus the two sub-configs (random-effect & latent-matrix) the reference
    parses from its triple-config string."""

    latent_dim: int
    num_inner_iterations: int = 1
    random_effect_config: Optional[CoordinateConfig] = None
    latent_factor_config: Optional[CoordinateConfig] = None

    def __post_init__(self):
        if self.latent_dim < 1:
            raise ValueError(f"latent_dim must be >= 1, got {self.latent_dim}")
        if self.num_inner_iterations < 1:
            raise ValueError(
                f"num_inner_iterations must be >= 1, got "
                f"{self.num_inner_iterations}"
            )


class FactoredRandomEffectCoordinate:
    """Drop-in coordinate: update(params, partial_scores) / score(params)."""

    def __init__(
        self,
        design: RandomEffectDesign,
        row_features: jax.Array,
        row_entities: jax.Array,
        full_offsets_base: jax.Array,
        re_config: CoordinateConfig,
        factored: FactoredConfig,
        seed: int = 0,
    ):
        self.design = design
        self.row_features = row_features
        self.row_entities = row_entities
        self.full_offsets_base = full_offsets_base
        self.config = re_config
        self.factored = factored
        self._seed = seed

        latent_cfg = factored.latent_factor_config or re_config
        self._re_solve = _make_solve(
            dataclasses.replace(re_config, random_effect=None), batched=True
        )
        self._latent_solve = _make_solve(
            dataclasses.replace(latent_cfg, random_effect=None), batched=False
        )

        @jax.jit
        def score_rows(params: FactoredParams, feats, ents):
            latent = feats @ params.projection  # (n, k)
            safe = jnp.maximum(ents, 0)
            per_row = jnp.einsum("nk,nk->n", latent, params.gamma[safe])
            return jnp.where(ents >= 0, per_row, 0.0)

        self._score = score_rows

    @property
    def num_entities(self) -> int:
        return self.design.num_entities

    def initial_params(self) -> FactoredParams:
        """Gamma zeros; B a Gaussian N(0, 1/d) like the reference's random
        projection init (``FactoredRandomEffectOptimizationProblem``)."""
        d = self.design.dim
        k = self.factored.latent_dim
        rng = np.random.default_rng(self._seed)
        b = rng.normal(0.0, 1.0 / np.sqrt(d), size=(d, k))
        dtype = self.design.features.dtype
        return FactoredParams(
            gamma=jnp.zeros((self.num_entities, k), dtype),
            projection=jnp.asarray(b, dtype),
        )

    def update(
        self, params: FactoredParams, partial_scores: jax.Array, key=None
    ) -> Tuple[FactoredParams, object]:
        design = self.design
        offsets = design.gather_offsets(
            self.full_offsets_base + partial_scores
        )
        gamma, b = params.gamma, params.projection
        result = None
        for _ in range(self.factored.num_inner_iterations):
            # (a) latent-space per-entity solves
            latent_feats = design.features @ b  # (E, R, k)
            result = self._re_solve(
                gamma,
                latent_feats,
                design.labels,
                offsets,
                design.weights,
                design.mask,
            )
            gamma = result.w
            # (b) shared projection as one GLM over Kronecker features
            e, r, d = design.features.shape
            k = gamma.shape[1]
            kron = jnp.einsum(
                "erd,ek->erdk", design.features, gamma
            ).reshape(e * r, d * k)
            latent_result = self._latent_solve(
                b.reshape(-1),
                kron,
                design.labels.reshape(-1),
                offsets.reshape(-1),
                design.weights.reshape(-1),
                design.mask.reshape(-1),
            )
            b = latent_result.w.reshape(d, k)
        return FactoredParams(gamma=gamma, projection=b), result

    def score(self, params: FactoredParams) -> jax.Array:
        return self._score(params, self.row_features, self.row_entities)

    def reg_term(self, params: FactoredParams) -> jax.Array:
        """gamma is penalized under the RE config, B under the latent-factor
        config — the exact quantities the two inner solves minimize."""
        from photon_ml_tpu.game.descent import _config_reg_term

        latent_cfg = self.factored.latent_factor_config or self.config
        return _config_reg_term(self.config, params.gamma) + _config_reg_term(
            latent_cfg, params.projection
        )

    def to_full_table(self, params: FactoredParams) -> jax.Array:
        """Materialize w_e = B gamma_e: (E, d) — the reference's
        ``RandomEffectModelInProjectedSpace.toRandomEffectModel``."""
        return params.gamma @ params.projection.T


class MatrixFactorizationModel:
    """Two latent tables; score(row, col) = rowFactors[row] . colFactors[col]
    with either side missing scoring 0 (``MatrixFactorizationModel.scala``)."""

    def __init__(self, row_factors: jax.Array, col_factors: jax.Array):
        if row_factors.shape[1] != col_factors.shape[1]:
            raise ValueError("row/col latent dims differ")
        self.row_factors = row_factors
        self.col_factors = col_factors

        @jax.jit
        def score(rows, cols, rf, cf):
            safe_r = jnp.maximum(rows, 0)
            safe_c = jnp.maximum(cols, 0)
            s = jnp.einsum("nk,nk->n", rf[safe_r], cf[safe_c])
            return jnp.where((rows >= 0) & (cols >= 0), s, 0.0)

        self._score = score

    @property
    def latent_dim(self) -> int:
        return self.row_factors.shape[1]

    def score(self, row_ids: jax.Array, col_ids: jax.Array) -> jax.Array:
        return self._score(
            row_ids, col_ids, self.row_factors, self.col_factors
        )

    @staticmethod
    def random(
        num_rows: int, num_cols: int, latent_dim: int, seed: int = 0,
        dtype=jnp.float32,
    ) -> "MatrixFactorizationModel":
        rng = np.random.default_rng(seed)
        return MatrixFactorizationModel(
            jnp.asarray(rng.normal(size=(num_rows, latent_dim)), dtype),
            jnp.asarray(rng.normal(size=(num_cols, latent_dim)), dtype),
        )
