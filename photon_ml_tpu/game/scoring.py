"""Model-level GAME scoring, detached from training coordinates.

Rebuild of the scoring side of the GAME model hierarchy
(``model/FixedEffectModel.scala:31-88`` broadcast-dot,
``model/RandomEffectModel.scala:117-146`` cogroup-with-default-0) for data
that was NOT part of training — validation sets and the scoring driver
(``cli/game/scoring/Driver.scala:139-141``: total score = sum of sub-model
scores). Training-time scoring lives on the coordinates themselves, which
own device-resident designs; this path works from a plain parameter dict.
"""

from __future__ import annotations

import weakref
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.data import GameData


class CompactReTable(NamedTuple):
    """Pre-compacted wide random-effect coefficient table: per-entity
    ASCENDING column ids padded with d, matching values padded with 0 —
    exactly what ``_compact_table`` produces. Pass one of these as a
    coordinate's params to skip the host-side (E, d) densify+nonzero
    entirely (the back-projected tables GAME training emits can be
    compacted once and scored many times)."""

    columns: np.ndarray  # (E, k) int32
    values: np.ndarray  # (E, k)


@jax.jit
def _fixed_scores(w, feats):
    from photon_ml_tpu.ops.sparse import matvec

    return matvec(feats, w)


@jax.jit
def _random_scores(table, feats, ents):
    safe = jnp.maximum(ents, 0)
    per_row = jnp.einsum("nd,nd->n", feats, table[safe])
    return jnp.where(ents >= 0, per_row, 0.0)


def _compact_table(table: np.ndarray):
    """Host-side (E, d) -> padded (E, k) (columns, values) with k = max
    nonzeros per entity; column pad = d (sorts after every real id),
    value pad = 0. Per-entity columns come out ASCENDING (np.nonzero row
    order), which the searchsorted join below requires."""
    t = np.asarray(table)
    e, d = t.shape
    ent, col = np.nonzero(t)
    counts = np.bincount(ent, minlength=e)
    k = max(int(counts.max()) if counts.size else 1, 1)
    cols = np.full((e, k), d, np.int32)
    vals = np.zeros((e, k), t.dtype)
    starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
    slot = np.arange(ent.size) - starts[ent]
    cols[ent, slot] = col
    vals[ent, slot] = t[ent, col]
    return cols, vals


# compaction results keyed by id(table) with WEAK references: entries die
# with their table (no pinning of multi-GB originals), and the weakref
# identity check guards against id recycling. Live entries track live
# tables, so the cache is bounded by what the caller itself keeps alive.
_COMPACT_CACHE: Dict[int, tuple] = {}


def _compact_table_cached(p) -> CompactReTable:
    """Per-coordinate cache around ``_compact_table``: without it every
    ``score_game_data`` call re-densifies the full (E, d) table on host
    and re-runs np.nonzero — at the wide regime this path exists for
    (e.g. 30k x 60k) that is a multi-GB host pass paid per call.

    Only IMMUTABLE inputs are cached (jax.Array, or a numpy array marked
    non-writeable): a writeable numpy table mutated in place between
    calls must be re-compacted, as it always was. Callers who score the
    same wide table repeatedly can pre-compact once into a
    :class:`CompactReTable`."""
    # numpy is cacheable only when neither the array NOR any base it
    # views is writeable (a read-only view over a writeable base still
    # changes under the caller's feet)
    cacheable = isinstance(p, jax.Array) or (
        isinstance(p, np.ndarray)
        and not p.flags.writeable
        and (
            p.base is None
            or not getattr(p.base, "flags", np.ones(1).flags).writeable
        )
    )
    if not cacheable:
        cols, vals = _compact_table(np.asarray(p))
        return CompactReTable(cols, vals)
    key = id(p)
    hit = _COMPACT_CACHE.get(key)
    if hit is not None and hit[0]() is p:
        return hit[1]
    cols, vals = _compact_table(np.asarray(p))
    compact = CompactReTable(cols, vals)
    try:
        ref = weakref.ref(p, lambda _, k=key: _COMPACT_CACHE.pop(k, None))
    except TypeError:  # referent type without weakref support
        return compact
    _COMPACT_CACHE[key] = (ref, compact)
    return compact


@jax.jit
def _random_scores_sparse(cols_tab, vals_tab, feats, ents):
    """Wide random effect over a padded-ELL shard: x_i . w_{e_i} through
    the COMPACT per-entity coefficient tables ((E, k) columns + values —
    back-projected tables are zero outside each entity's active union,
    so k is small even when d is huge). A dense (E, d) device table at
    this regime (e.g. 30k x 60k f32 = 7.2 GB) would defeat the very
    memory ceiling the sparse path exists for; this gathers O(n * k)
    and joins by per-row searchsorted against the entity's sorted
    columns."""
    safe_e = jnp.maximum(ents, 0)
    ec = cols_tab[safe_e]  # (n, kt) the row's entity's active columns
    ev = vals_tab[safe_e]
    idx = feats.indices  # (n, ke); padding slots hold d
    loc = jax.vmap(jnp.searchsorted)(ec, idx)
    loc = jnp.clip(loc, 0, ec.shape[1] - 1)
    hit = jnp.take_along_axis(ec, loc, axis=1) == idx
    # entry padding (idx == d) can only hit a column pad (value 0) — 0
    # contribution either way
    coef = jnp.where(
        hit, jnp.take_along_axis(ev, loc, axis=1), 0.0
    )
    per_row = jnp.sum(feats.values * coef, axis=-1)
    return jnp.where(ents >= 0, per_row, 0.0)


@jax.jit
def _random_scores_compact_dense(cols_tab, vals_tab, feats, ents):
    """x_i . w_{e_i} through a :class:`CompactReTable` against DENSE per-row
    features: gather the entity's k active (column, value) pairs and pick
    those columns out of the dense row. O(n * k) work regardless of d, so a
    pre-compacted table serves dense rows (the online engine's featurized
    requests) as cheaply as sparse ones. Column pad d is out of range for
    the (n, d) row — clip the gather; its value pad 0 zeroes the term."""
    safe_e = jnp.maximum(ents, 0)
    ec = cols_tab[safe_e]  # (n, k) active columns of the row's entity
    ev = vals_tab[safe_e]  # (n, k) matching coefficients
    picked = jnp.take_along_axis(
        feats, jnp.minimum(ec, feats.shape[1] - 1), axis=1
    )
    per_row = jnp.sum(picked * ev, axis=-1)
    return jnp.where(ents >= 0, per_row, 0.0)


@jax.jit
def _factored_scores(gamma, projection, feats, ents):
    """score = (x B) . gamma_e without materializing B gamma^T
    (``FactoredRandomEffectCoordinate`` scoring contraction)."""
    latent = feats @ projection  # (n, k)
    safe = jnp.maximum(ents, 0)
    per_row = jnp.einsum("nk,nk->n", latent, gamma[safe])
    return jnp.where(ents >= 0, per_row, 0.0)


def score_game_data(
    params: Dict[str, jax.Array],
    shards: Dict[str, str],
    random_effects: Dict[str, Optional[str]],
    data: GameData,
    dtype=jnp.float64,
) -> jax.Array:
    """Sum of all coordinates' scores for every row (margins WITHOUT the
    data offsets; add ``data.offsets`` for the full margin). Rows whose
    entity is unknown to a random effect contribute 0 for that coordinate
    (``RandomEffectModel.scala:117-146``)."""
    from photon_ml_tpu.ops.sparse import cast_values, is_structured

    n = data.num_rows
    total = jnp.zeros((n,), dtype)
    from photon_ml_tpu.ops.sparse import is_hybrid

    for name, p in params.items():
        shard = shards[name]
        raw = data.features[shard]
        if is_hybrid(raw):
            # HybridFeatures rows live in a permuted order private to the
            # GLM training batch; GAME scoring sums coordinates by ROW
            raise ValueError(
                f"shard {shard!r} is a HybridFeatures container; GAME "
                "shards must be dense or plain ELL (row-aligned)"
            )
        feats = cast_values(raw, dtype)
        re_key = random_effects.get(name)
        if re_key is not None and is_structured(raw) and hasattr(p, "gamma"):
            raise ValueError(
                f"coordinate {name!r}: factored effects need the dense "
                f"per-row latent projection; shard {shard!r} is sparse"
            )
        if re_key is None:
            total = total + _fixed_scores(jnp.asarray(p, dtype), feats)
        elif hasattr(p, "gamma"):  # FactoredParams
            ents = jnp.asarray(data.entity_ids[re_key])
            total = total + _factored_scores(
                jnp.asarray(p.gamma, dtype),
                jnp.asarray(p.projection, dtype),
                feats,
                ents,
            )
        elif isinstance(p, CompactReTable) or is_structured(raw):
            ents = jnp.asarray(data.entity_ids[re_key])
            compact = (
                p
                if isinstance(p, CompactReTable)
                else _compact_table_cached(p)
            )
            kernel = (
                _random_scores_sparse
                if is_structured(raw)
                else _random_scores_compact_dense
            )
            total = total + kernel(
                jnp.asarray(np.asarray(compact.columns, np.int32)),
                jnp.asarray(compact.values, dtype),
                feats,
                ents,
            )
        else:
            ents = jnp.asarray(data.entity_ids[re_key])
            total = total + _random_scores(
                jnp.asarray(p, dtype), feats, ents
            )
    return total


def compact_table_rows(rows: np.ndarray, k: int):
    """Compact a BLOCK of dense table rows at a FORCED width ``k``
    (columns ascending, pad column = d, pad value = 0) — exactly
    ``_compact_table``'s per-row output, but with ``k`` imposed by the
    caller so every shard of a partitioned table compacts to ONE static
    executable shape. Raises when a row holds more than ``k`` nonzeros."""
    t = np.asarray(rows)
    e, d = t.shape
    cols = np.full((e, k), d, np.int32)
    vals = np.zeros((e, k), t.dtype)
    if e == 0:
        return cols, vals
    ent, col = np.nonzero(t)
    counts = np.bincount(ent, minlength=e)
    if counts.size and int(counts.max()) > k:
        raise ValueError(
            f"row with {int(counts.max())} nonzeros cannot compact at "
            f"width k={k}"
        )
    starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
    slot = np.arange(ent.size) - starts[ent]
    cols[ent, slot] = col
    vals[ent, slot] = t[ent, col]
    return cols, vals


def shard_compact_table(compact: CompactReTable, assignment) -> CompactReTable:
    """Reorder a GLOBAL :class:`CompactReTable` into the stored
    (shard-major, padded) layout of an ``EntityShardAssignment``: shard
    p's entities contiguous in block ``[p*R, (p+1)*R)``, pad rows all-
    zero values (they score 0 wherever gathered). The compact-shard
    bridge between the serving engine's mesh partitioning and the
    checkpoint/device ownership rule (docs/PARALLEL.md)."""
    cols = np.asarray(compact.columns, np.int32)
    vals = np.asarray(compact.values)
    out_c = np.zeros((assignment.padded_rows,) + cols.shape[1:], cols.dtype)
    out_v = np.zeros((assignment.padded_rows,) + vals.shape[1:], vals.dtype)
    real = assignment.stored_to_global < assignment.num_entities
    out_c[real] = cols[assignment.stored_to_global[real]]
    out_v[real] = vals[assignment.stored_to_global[real]]
    return CompactReTable(columns=out_c, values=out_v)


def precompact_model(params: Dict[str, object]) -> Dict[str, object]:
    """Replace every (E, d) random-effect coefficient table with its
    :class:`CompactReTable` — pre-compact ONCE instead of leaning on the
    id-keyed weakref cache per call. Fixed-effect vectors (1-D), factored
    params, and already-compact tables pass through unchanged. The compact
    form scores against sparse ELL shards and dense rows alike, so both
    the offline driver and the online serving engine share it."""
    out: Dict[str, object] = {}
    for name, p in params.items():
        if (
            isinstance(p, CompactReTable)
            or hasattr(p, "gamma")  # FactoredParams
            or np.ndim(p) != 2
        ):
            out[name] = p
        else:
            out[name] = _compact_table_cached(p)
    return out
