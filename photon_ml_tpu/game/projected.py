"""Random-effect training in projected space.

Rebuild of ``algorithm/RandomEffectCoordinateInProjectedSpace.scala:26-120``
+ ``model/RandomEffectModelInProjectedSpace.scala:31-97``: the coordinate
solves every per-entity subproblem in a reduced k-dimensional space (shared
Gaussian RANDOM projection, per-entity INDEX_MAP compaction, or IDENTITY),
and coefficients are projected back to the original feature space at model
extraction so on-disk models never know projection existed.

TPU-first shape: projection is applied ONCE to the padded bucketed design
at build time (a matmul or per-entity gather — not a per-row RDD map), the
inner :class:`RandomEffectCoordinate` is reused unchanged on the projected
tensors, and back-projection of the (E, k) table is a single matmul /
scatter.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.coordinates import (
    CoordinateConfig,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.data import (
    BucketedRandomEffectDesign,
    GameData,
    RandomEffectDesign,
)
from photon_ml_tpu.game.projectors import (
    IndexMapProjection,
    RandomProjection,
    build_random_projection,
)


def parse_projector_spec(spec: str) -> Tuple[str, Optional[int]]:
    """"IDENTITY" | "INDEX_MAP" | "RANDOM=<k>" -> (kind, k)
    (``projector/ProjectorType.scala:20-30``)."""
    s = spec.strip().upper()
    if s == "IDENTITY":
        return "IDENTITY", None
    if s == "INDEX_MAP":
        return "INDEX_MAP", None
    if s.startswith("RANDOM="):
        k = int(s.split("=", 1)[1])
        if k <= 0:
            raise ValueError(f"RANDOM projected dim must be positive: {spec}")
        return "RANDOM", k
    raise ValueError(
        f"unknown projector {spec!r}; expected IDENTITY, INDEX_MAP, or "
        "RANDOM=<k>"
    )


def build_index_map_columns(
    data: GameData,
    random_effect: str,
    shard: str,
    num_entities: int,
) -> IndexMapProjection:
    """Per-entity union of ACTIVE feature indices over all of the entity's
    rows (``IndexMapProjectorRDD.scala:113-120``), indexed by global entity
    id — usable against any bucketing of the same entities.

    O(nnz) in time and memory: works on the nonzero coordinates directly
    (never a dense (E, d) presence matrix, which would defeat INDEX_MAP's
    purpose in the wide-feature regime it exists for)."""
    from photon_ml_tpu.game.projectors import columns_from_active_pairs

    x = np.asarray(data.features[shard])
    d = x.shape[1]
    eids = np.asarray(data.entity_ids[random_effect])
    rows, feat_cols = np.nonzero(x)
    ent = eids[rows]
    known = ent >= 0
    cols = columns_from_active_pairs(
        ent[known], feat_cols[known], d, num_entities
    )
    return IndexMapProjection(columns=jnp.asarray(cols, jnp.int32))


def _project_design_bucket(
    projector, bucket: RandomEffectDesign, entity_index: np.ndarray,
    num_entities: int,
) -> RandomEffectDesign:
    if isinstance(projector, RandomProjection):
        return dataclasses.replace(
            bucket,
            features=projector.project_features(bucket.features),
        )
    # INDEX_MAP: gather this bucket's per-lane column tables (sentinel
    # lanes clip to entity num_entities-1's columns; their mask is 0 so the
    # garbage never enters a solve)
    cols = jnp.take(
        projector.columns, jnp.asarray(entity_index), axis=0, mode="clip"
    )  # (E_b, k)
    safe = jnp.maximum(cols, 0)
    gathered = jnp.take_along_axis(
        bucket.features, safe[:, None, :], axis=2
    )
    keep = (cols >= 0)[:, None, :]
    return dataclasses.replace(
        bucket, features=jnp.where(keep, gathered, 0.0)
    )


def project_design_and_rows(
    design: BucketedRandomEffectDesign,
    row_features: jax.Array,
    row_entities: jax.Array,
    projector,
):
    """The combo-invariant heavy lifting of a projected coordinate: project
    every bucket's design and the full row view ONCE. Cacheable across a
    reg-weight grid (projection depends on data, never on lambda)."""
    projected = BucketedRandomEffectDesign(
        buckets=[
            _project_design_bucket(projector, b, ei, design.num_entities)
            for b, ei in zip(design.buckets, design.entity_index)
        ],
        entity_index=design.entity_index,
        num_entities=design.num_entities,
    )
    if isinstance(projector, RandomProjection):
        proj_rows = projector.project_features(row_features)
    else:
        proj_rows = projector.project_row_features(
            row_features, row_entities
        )
    return projected, proj_rows


class ProjectedRandomEffectCoordinate:
    """A RandomEffectCoordinate whose solves happen in projected space.

    Drop-in member of a CoordinateDescent ``coordinates`` dict: exposes
    initial_params/update/score on the PROJECTED (E, k) table, plus
    :meth:`back_project` to map the trained table to original d-space for
    persistence (``RandomEffectModelInProjectedSpace.toRandomEffectModel``).
    """

    def __init__(
        self,
        design: BucketedRandomEffectDesign,
        row_features: jax.Array,  # (n, d) ORIGINAL-space scoring view
        row_entities: jax.Array,
        full_offsets_base: jax.Array,
        config: CoordinateConfig,
        projector: Union[RandomProjection, IndexMapProjection],
        original_dim: int,
        reg_weights: Optional[jax.Array] = None,
        prebuilt=None,  # (projected_design, projected_rows) from
        # :func:`project_design_and_rows` — reused across a lambda grid
    ):
        if isinstance(design, RandomEffectDesign):
            design = BucketedRandomEffectDesign(
                buckets=[design],
                entity_index=[
                    np.arange(design.num_entities, dtype=np.int32)
                ],
                num_entities=design.num_entities,
            )
        self.projector = projector
        self.original_dim = original_dim
        if prebuilt is not None:
            projected, proj_rows = prebuilt
        else:
            projected, proj_rows = project_design_and_rows(
                design, row_features, row_entities, projector
            )
        self.inner = RandomEffectCoordinate(
            design=projected,
            row_features=proj_rows,
            row_entities=row_entities,
            full_offsets_base=full_offsets_base,
            config=config,
            reg_weights=reg_weights,
        )

    @property
    def config(self) -> CoordinateConfig:
        """CoordinateDescent reads this for the objective's reg term — the
        L2 penalty applies to the projected table, exactly what the inner
        solves minimized."""
        return self.inner.config

    @property
    def num_entities(self) -> int:
        return self.inner.num_entities

    @property
    def dim(self) -> int:
        """Projected dimension (the solve space)."""
        return self.inner.dim

    def initial_params(self) -> jax.Array:
        return self.inner.initial_params()

    def update(self, table, partial_scores, key=None):
        return self.inner.update(table, partial_scores, key=key)

    def update_and_score(self, table, partial_scores, key=None):
        return self.inner.update_and_score(table, partial_scores, key=key)

    def reg_term(self, table: jax.Array) -> jax.Array:
        return self.inner.reg_term(table)

    def score(self, table: jax.Array) -> jax.Array:
        return self.inner.score(table)

    def back_project(self, table: jax.Array) -> jax.Array:
        """(E, k) projected table -> (E, d) original-space coefficients
        (``RandomEffectModelInProjectedSpace.scala:31-97``)."""
        if isinstance(self.projector, RandomProjection):
            return self.projector.project_coefficients_back(table)
        return self.projector.project_coefficients_back(
            table, self.original_dim
        )
