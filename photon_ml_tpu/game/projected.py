"""Random-effect training in projected space.

Rebuild of ``algorithm/RandomEffectCoordinateInProjectedSpace.scala:26-120``
+ ``model/RandomEffectModelInProjectedSpace.scala:31-97``: the coordinate
solves every per-entity subproblem in a reduced k-dimensional space (shared
Gaussian RANDOM projection, per-entity INDEX_MAP compaction, or IDENTITY),
and coefficients are projected back to the original feature space at model
extraction so on-disk models never know projection existed.

TPU-first shape: projection is applied ONCE to the padded bucketed design
at build time (a matmul or per-entity gather — not a per-row RDD map), the
inner :class:`RandomEffectCoordinate` is reused unchanged on the projected
tensors, and back-projection of the (E, k) table is a single matmul /
scatter.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.coordinates import (
    CoordinateConfig,
    RandomEffectCoordinate,
)
from photon_ml_tpu.game.data import (
    BucketedRandomEffectDesign,
    GameData,
    RandomEffectDesign,
)
from photon_ml_tpu.game.projectors import (
    IndexMapProjection,
    RandomProjection,
    build_random_projection,
)


def parse_projector_spec(spec: str) -> Tuple[str, Optional[int]]:
    """"IDENTITY" | "INDEX_MAP" | "RANDOM=<k>" -> (kind, k)
    (``projector/ProjectorType.scala:20-30``)."""
    s = spec.strip().upper()
    if s == "IDENTITY":
        return "IDENTITY", None
    if s == "INDEX_MAP":
        return "INDEX_MAP", None
    if s.startswith("RANDOM="):
        k = int(s.split("=", 1)[1])
        if k <= 0:
            raise ValueError(f"RANDOM projected dim must be positive: {spec}")
        return "RANDOM", k
    raise ValueError(
        f"unknown projector {spec!r}; expected IDENTITY, INDEX_MAP, or "
        "RANDOM=<k>"
    )


def build_index_map_columns(
    data: GameData,
    random_effect: str,
    shard: str,
    num_entities: int,
) -> IndexMapProjection:
    """Per-entity union of ACTIVE feature indices over all of the entity's
    rows (``IndexMapProjectorRDD.scala:113-120``), indexed by global entity
    id — usable against any bucketing of the same entities.

    O(nnz) in time and memory: works on the nonzero coordinates directly
    (never a dense (E, d) presence matrix, which would defeat INDEX_MAP's
    purpose in the wide-feature regime it exists for). Accepts dense OR
    padded-ELL (``SparseFeatures``) shards — the sparse case is the whole
    point of INDEX_MAP (wide shards whose per-entity active unions are
    small, ``RandomEffectCoordinateInProjectedSpace.scala:26-120``)."""
    from photon_ml_tpu.game.projectors import columns_from_active_pairs
    from photon_ml_tpu.ops import sparse as sparse_ops

    x = data.features[shard]
    eids = np.asarray(data.entity_ids[random_effect])
    if sparse_ops.is_sparse(x):
        ind = np.asarray(x.indices)
        d = x.d
        keep = (ind < d) & (eids[:, None] >= 0)
        rows = np.broadcast_to(
            np.arange(ind.shape[0])[:, None], ind.shape
        )[keep]
        ent = eids[rows]
        feat_cols = ind[keep]
    else:
        x = np.asarray(x)
        d = x.shape[1]
        rows, feat_cols = np.nonzero(x)
        ent = eids[rows]
        known = ent >= 0
        ent, feat_cols = ent[known], feat_cols[known]
    cols = columns_from_active_pairs(ent, feat_cols, d, num_entities)
    return IndexMapProjection(columns=jnp.asarray(cols, jnp.int32))


def project_sparse_rows(
    sf,
    entities: np.ndarray,
    projection: IndexMapProjection,
    dtype=np.float32,
) -> np.ndarray:
    """Project padded-ELL rows into each row's OWN entity's compact column
    space: (n, nnz) ELL -> dense (n, k) where k = max per-entity active
    columns. The sparse analog of
    ``IndexMapProjection.project_row_features`` — entries whose (entity,
    column) pair is outside the entity's active union are dropped (score
    0), exactly the reference's projected-space scoring semantics.
    Host-side, O(nnz log nnz), once per run."""
    from photon_ml_tpu.ops import sparse as sparse_ops

    if not sparse_ops.is_sparse(sf):
        raise ValueError("project_sparse_rows takes a SparseFeatures shard")
    cols_np = np.asarray(projection.columns)
    e_count, k = cols_np.shape
    d = sf.d
    valid = cols_np >= 0
    ent_of = np.broadcast_to(
        np.arange(e_count)[:, None], cols_np.shape
    )[valid]
    slot_of = np.broadcast_to(np.arange(k)[None, :], cols_np.shape)[valid]
    pair = ent_of.astype(np.int64) * d + cols_np[valid]
    order = np.argsort(pair, kind="stable")
    pair = pair[order]
    slot_sorted = slot_of[order]

    ind = np.asarray(sf.indices)
    val = np.asarray(sf.values)
    n = ind.shape[0]
    ents = np.asarray(entities).astype(np.int64)
    entry_ok = (ind < d) & (ents[:, None] >= 0)
    rows_e = np.broadcast_to(np.arange(n)[:, None], ind.shape)[entry_ok]
    epair = ents[rows_e] * d + ind[entry_ok].astype(np.int64)
    evals = val[entry_ok]
    loc = np.searchsorted(pair, epair)
    loc = np.clip(loc, 0, max(pair.size - 1, 0))
    hit = pair[loc] == epair if pair.size else np.zeros(epair.shape, bool)
    out = np.zeros((n, k), dtype)
    np.add.at(out, (rows_e[hit], slot_sorted[loc[hit]]), evals[hit])
    return out


def _project_design_bucket(
    projector, bucket: RandomEffectDesign, entity_index: np.ndarray,
    num_entities: int,
) -> RandomEffectDesign:
    if isinstance(projector, RandomProjection):
        return dataclasses.replace(
            bucket,
            features=projector.project_features(bucket.features),
        )
    # INDEX_MAP: gather this bucket's per-lane column tables (sentinel
    # lanes clip to entity num_entities-1's columns; their mask is 0 so the
    # garbage never enters a solve)
    cols = jnp.take(
        projector.columns, jnp.asarray(entity_index), axis=0, mode="clip"
    )  # (E_b, k)
    safe = jnp.maximum(cols, 0)
    gathered = jnp.take_along_axis(
        bucket.features, safe[:, None, :], axis=2
    )
    keep = (cols >= 0)[:, None, :]
    return dataclasses.replace(
        bucket, features=jnp.where(keep, gathered, 0.0)
    )


def project_design_and_rows(
    design: BucketedRandomEffectDesign,
    row_features: jax.Array,
    row_entities: jax.Array,
    projector,
):
    """The combo-invariant heavy lifting of a projected coordinate: project
    every bucket's design and the full row view ONCE. Cacheable across a
    reg-weight grid (projection depends on data, never on lambda)."""
    projected = BucketedRandomEffectDesign(
        buckets=[
            _project_design_bucket(projector, b, ei, design.num_entities)
            for b, ei in zip(design.buckets, design.entity_index)
        ],
        entity_index=design.entity_index,
        num_entities=design.num_entities,
    )
    if isinstance(projector, RandomProjection):
        proj_rows = projector.project_features(row_features)
    else:
        proj_rows = projector.project_row_features(
            row_features, row_entities
        )
    return projected, proj_rows


class ProjectedRandomEffectCoordinate:
    """A RandomEffectCoordinate whose solves happen in projected space.

    Drop-in member of a CoordinateDescent ``coordinates`` dict: exposes
    initial_params/update/score on the PROJECTED (E, k) table, plus
    :meth:`back_project` to map the trained table to original d-space for
    persistence (``RandomEffectModelInProjectedSpace.toRandomEffectModel``).
    """

    def __init__(
        self,
        design: BucketedRandomEffectDesign,
        row_features: jax.Array,  # (n, d) ORIGINAL-space scoring view
        row_entities: jax.Array,
        full_offsets_base: jax.Array,
        config: CoordinateConfig,
        projector: Union[RandomProjection, IndexMapProjection],
        original_dim: int,
        reg_weights: Optional[jax.Array] = None,
        prebuilt=None,  # (projected_design, projected_rows) from
        # :func:`project_design_and_rows` — reused across a lambda grid
    ):
        if isinstance(design, RandomEffectDesign):
            design = BucketedRandomEffectDesign(
                buckets=[design],
                entity_index=[
                    np.arange(design.num_entities, dtype=np.int32)
                ],
                num_entities=design.num_entities,
            )
        self.projector = projector
        self.original_dim = original_dim
        if prebuilt is not None:
            projected, proj_rows = prebuilt
        else:
            projected, proj_rows = project_design_and_rows(
                design, row_features, row_entities, projector
            )
        self.inner = RandomEffectCoordinate(
            design=projected,
            row_features=proj_rows,
            row_entities=row_entities,
            full_offsets_base=full_offsets_base,
            config=config,
            reg_weights=reg_weights,
        )

    @classmethod
    def from_sparse_shard(
        cls,
        data,  # GameData with a SparseFeatures shard
        random_effect: str,
        shard: str,
        num_entities: int,
        config: CoordinateConfig,
        num_buckets: int = 4,
        active_cap: Optional[int] = None,
        entity_multiple: int = 1,
        seed: int = 0,
        dtype=None,
        reg_weights: Optional[jax.Array] = None,
        feature_ratio: Optional[float] = None,
        min_support: int = 0,
    ) -> "ProjectedRandomEffectCoordinate":
        """Wide-sparse random effects: build an INDEX_MAP-projected
        coordinate STRAIGHT from a padded-ELL shard, never materializing
        the (E, rows, d) original-space design — the regime of
        ``RandomEffectCoordinateInProjectedSpace.scala:26-120`` +
        ``IndexMapProjectorRDD.scala:113-120``, where d is huge but each
        entity touches few columns.

        Pipeline (host-side, once per run): per-entity active-column
        union -> project every row into its own entity's compact space
        (dense (n, k), k = max union size) -> reuse the standard bucketed
        builder/capping/scoring machinery on that dense view. Training,
        scoring, reservoir caps, Pearson filters and checkpointing all
        work unchanged; ``back_project`` scatters the (E, k) table to
        original d-space for persistence."""
        import dataclasses as _dc
        import jax.numpy as jnp_

        from photon_ml_tpu.game.data import (
            build_bucketed_random_effect_design,
        )

        dtype = dtype or jnp_.float32
        projector = build_index_map_columns(
            data, random_effect, shard, num_entities
        )
        proj_rows_np = project_sparse_rows(
            data.features[shard],
            np.asarray(data.entity_ids[random_effect]),
            projector,
            dtype=np.dtype(jnp.dtype(dtype)),
        )
        proj_data = _dc.replace(
            data, features={**data.features, shard: proj_rows_np}
        )
        design = build_bucketed_random_effect_design(
            proj_data,
            random_effect,
            shard,
            num_entities,
            num_buckets=num_buckets,
            active_cap=active_cap,
            entity_multiple=entity_multiple,
            seed=seed,
            dtype=dtype,
            feature_ratio=feature_ratio,
            min_support=min_support,
        )
        proj_rows = jnp_.asarray(proj_rows_np, dtype)
        row_entities = jnp_.asarray(
            np.asarray(data.entity_ids[random_effect]), jnp_.int32
        )
        return cls(
            design=design,
            row_features=proj_rows,
            row_entities=row_entities,
            full_offsets_base=jnp_.asarray(data.offsets, dtype),
            config=config,
            projector=projector,
            original_dim=data.features[shard].d,
            reg_weights=reg_weights,
            prebuilt=(design, proj_rows),
        )

    def with_config(self, config: CoordinateConfig) -> "ProjectedRandomEffectCoordinate":
        """Same projected design/rows under a different optimization
        config — the grid-sweep reuse hook (designs and projections are
        combo-invariant; only the solver knobs change per combo).

        Per-entity reg weights: a UNIFORM vector (the default fill from
        the old config) is rebuilt from the new config's reg_weight; a
        CUSTOM per-entity vector is carried through unchanged — silently
        replacing it with the new uniform weight would discard the
        per-entity objectives the caller configured."""
        old = np.asarray(self.inner.reg_weights)
        uniform = np.allclose(old, self.inner.config.reg_weight)
        return ProjectedRandomEffectCoordinate(
            design=self.inner.design,
            row_features=self.inner.row_features,
            row_entities=self.inner.row_entities,
            full_offsets_base=self.inner.full_offsets_base,
            config=config,
            projector=self.projector,
            original_dim=self.original_dim,
            reg_weights=None if uniform else self.inner.reg_weights,
            prebuilt=(self.inner.design, self.inner.row_features),
        )

    @property
    def config(self) -> CoordinateConfig:
        """CoordinateDescent reads this for the objective's reg term — the
        L2 penalty applies to the projected table, exactly what the inner
        solves minimized."""
        return self.inner.config

    @property
    def num_entities(self) -> int:
        return self.inner.num_entities

    @property
    def dim(self) -> int:
        """Projected dimension (the solve space)."""
        return self.inner.dim

    def initial_params(self) -> jax.Array:
        return self.inner.initial_params()

    def update(self, table, partial_scores, key=None):
        return self.inner.update(table, partial_scores, key=key)

    def update_and_score(self, table, partial_scores, key=None):
        return self.inner.update_and_score(table, partial_scores, key=key)

    def update_step(self, table, partial_scores, key=None):
        return self.inner.update_step(table, partial_scores, key=key)

    def wrap_tracker(self, trackers):
        return self.inner.wrap_tracker(trackers)

    def fused_state(self):
        return self.inner.fused_state()

    def with_fused_state(self, state):
        import copy

        c = copy.copy(self)
        c.inner = self.inner.with_fused_state(state)
        return c

    def reg_term(self, table: jax.Array) -> jax.Array:
        return self.inner.reg_term(table)

    def score(self, table: jax.Array) -> jax.Array:
        return self.inner.score(table)

    def back_project(self, table: jax.Array) -> jax.Array:
        """(E, k) projected table -> (E, d) original-space coefficients
        (``RandomEffectModelInProjectedSpace.scala:31-97``)."""
        if isinstance(self.projector, RandomProjection):
            return self.projector.project_coefficients_back(table)
        return self.projector.project_coefficients_back(
            table, self.original_dim
        )
