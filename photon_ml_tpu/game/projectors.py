"""Per-entity dimensionality reduction: the reference's ``projector/``.

Three projector types (``projector/ProjectorType.scala:20-30``):

  IDENTITY   — no-op.
  RANDOM=k   — shared Gaussian random projection matrix, N(0, 1/k) for
               projected dimension k, with an optional intercept
               passthrough row (``projector/ProjectionMatrix.scala:96-126``).
  INDEX_MAP  — per-entity compaction onto the union of feature indices
               actually active in that entity's data
               (``projector/IndexMapProjector.scala:44``,
               ``projector/IndexMapProjectorRDD.scala:113-120``).

On TPU a projection is a matmul (RANDOM) or a gather (INDEX_MAP) applied to
the padded (entities, rows, dim) design once at ingest; coefficients are
projected back to the original space by the transpose operation
(``model/RandomEffectModelInProjectedSpace.scala:31-97``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.types import _pytree_dataclass
from photon_ml_tpu.game.data import RandomEffectDesign


@_pytree_dataclass
class RandomProjection:
    """Shared Gaussian projection (``ProjectionMatrix.scala:33-127``).

    matrix: (d, k) with entries N(0, 1/k); if intercept_index is set, that
    original dimension maps to a dedicated passthrough output column
    (the reference appends an identity row for the intercept).
    """

    matrix: jax.Array  # (d, k)

    @property
    def projected_dim(self) -> int:
        return self.matrix.shape[1]

    def project_features(self, features: jax.Array) -> jax.Array:
        """(..., d) -> (..., k)."""
        return features @ self.matrix

    def project_coefficients_back(self, coef: jax.Array) -> jax.Array:
        """(..., k) -> (..., d): w_orig = P w_proj so that
        x_orig . w_orig == (P^T x_orig) . w_proj."""
        return coef @ self.matrix.T


def build_random_projection(
    original_dim: int,
    projected_dim: int,
    seed: int = 0,
    intercept_index: Optional[int] = None,
    dtype=jnp.float32,
) -> RandomProjection:
    rng = np.random.default_rng(seed)
    k = projected_dim
    m = rng.normal(0.0, 1.0 / np.sqrt(k), size=(original_dim, k))
    if intercept_index is not None:
        # intercept passthrough: its own exclusive output column
        m = np.concatenate([m, np.zeros((original_dim, 1))], axis=1)
        m[intercept_index, :] = 0.0
        m[intercept_index, -1] = 1.0
    return RandomProjection(matrix=jnp.asarray(m, dtype))


@_pytree_dataclass
class IndexMapProjection:
    """Per-entity feature-index compaction.

    columns: (E, k) int32 — for each entity, the original feature indices
    kept (padded with -1). k = max active-feature count over entities.
    """

    columns: jax.Array

    @property
    def projected_dim(self) -> int:
        return self.columns.shape[1]

    def project_design(self, design: RandomEffectDesign) -> RandomEffectDesign:
        """(E, R, d) -> (E, R, k) by per-entity column gather."""
        safe = jnp.maximum(self.columns, 0)  # (E, k)
        gathered = jnp.take_along_axis(
            design.features, safe[:, None, :], axis=2
        )
        col_mask = (self.columns >= 0)[:, None, :]
        return dataclasses.replace(
            design, features=jnp.where(col_mask, gathered, 0.0)
        )

    def project_coefficients_back(
        self, table: jax.Array, original_dim: int
    ) -> jax.Array:
        """(E, k) -> (E, d): scatter back to original indices."""
        e, k = table.shape
        out = jnp.zeros((e, original_dim), table.dtype)
        safe = jnp.maximum(self.columns, 0)
        vals = jnp.where(self.columns >= 0, table, 0.0)
        return out.at[jnp.arange(e)[:, None], safe].add(vals)

    def project_row_features(
        self, features: jax.Array, entities: jax.Array
    ) -> jax.Array:
        """(n, d) rows -> (n, k) in each row's OWN entity's projected space
        (entity -1 rows produce zeros; they score 0 anyway)."""
        safe_e = jnp.maximum(entities, 0)
        cols = self.columns[safe_e]  # (n, k)
        safe_c = jnp.maximum(cols, 0)
        gathered = jnp.take_along_axis(features, safe_c, axis=1)
        keep = (cols >= 0) & (entities >= 0)[:, None]
        return jnp.where(keep, gathered, 0.0)


def columns_from_active_pairs(
    ent: np.ndarray, col: np.ndarray, d: int, num_entities: int
) -> np.ndarray:
    """(entity, feature) occurrence pairs -> (num_entities, k) per-entity
    sorted active-column table padded with -1, where k = max active-column
    count. O(nnz): the shared kernel of both INDEX_MAP builders."""
    pairs = np.unique(ent.astype(np.int64) * d + col.astype(np.int64))
    pair_ent = pairs // d
    pair_col = pairs % d
    _, starts, counts = np.unique(
        pair_ent, return_index=True, return_counts=True
    )
    k = max(int(counts.max()) if counts.size else 1, 1)
    cols = np.full((num_entities, k), -1, np.int64)
    slot = np.arange(pairs.size) - np.repeat(starts, counts)
    cols[pair_ent, slot] = pair_col
    return cols


def build_index_map_projection(
    design: RandomEffectDesign, dtype=jnp.int32
) -> IndexMapProjection:
    """Union of active feature indices per entity
    (``IndexMapProjectorRDD.scala:113-120``): a feature is kept for an
    entity iff it is nonzero in any of that entity's active rows.

    Design-tensor variant of ``projected.build_index_map_columns`` (which
    derives the same column sets straight from GameData); both share
    :func:`columns_from_active_pairs`."""
    feats = np.asarray(design.features)  # (E, R, d)
    mask = np.asarray(design.mask)
    e, _, d = feats.shape
    ent, row, col = np.nonzero(feats)
    keep = mask[ent, row] > 0
    cols = columns_from_active_pairs(ent[keep], col[keep], d, e)
    return IndexMapProjection(columns=jnp.asarray(cols, dtype))
