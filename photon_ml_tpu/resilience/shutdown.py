"""Preemption-safe shutdown: SIGTERM/SIGINT -> finish the pass, checkpoint,
exit resumable.

TPU slices die by preemption: the runtime sends SIGTERM and gives the
process a grace window. The reference's Spark driver simply loses the job
(lineage recompute restarts from input); here the descent loop polls a
flag at PASS BOUNDARIES — the only points where the training state is a
complete, checkpointable snapshot — writes a final checkpoint, drops a
``preempted.json`` marker in the checkpoint directory, and returns. A
restart with ``resume=True`` continues bit-for-bit.

Signal handlers only install on the main thread (Python restriction);
elsewhere — or in tests — ``request()`` / a custom ``stop_check``
callable triggers the same path.
"""

from __future__ import annotations

import json
import os
import signal
import threading
from typing import Optional

PREEMPTED_MARKER = "preempted.json"


class GracefulShutdown:
    """Context manager arming SIGTERM/SIGINT to set a flag instead of
    killing the process. ``requested`` is polled by the descent loop at
    pass boundaries; the previous handlers are restored on exit (a second
    signal during teardown behaves normally — operators can still kill a
    hung process)."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, logger=None):
        self._logger = logger
        self._event = threading.Event()
        self._prev = {}
        self._drain_hooks = []
        self.signum: Optional[int] = None

    # -- flag --------------------------------------------------------------

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self, signum: Optional[int] = None) -> None:
        """Programmatic trigger (tests, cluster-manager hooks)."""
        if signum is not None:
            self.signum = signum
        first = not self._event.is_set()
        self._event.set()
        if first:
            # obs note BEFORE draining: may run in signal-handler context,
            # and both calls are non-blocking (counter inc + list append)
            from photon_ml_tpu import obs

            obs.registry().inc("resilience.preemptions")
            obs.emit_event(
                "resilience.preemption_requested",
                cat="resilience",
                signum=signum,
            )
            # the tracer's flush loss-window guard + flight recorder:
            # a SIGTERM'd process must leave its last buffered span
            # records on disk and (when a recorder is installed) a
            # flight-preemption.json post-mortem. Both are bounded,
            # non-reentrant file writes — acceptable in the Python-level
            # handler context, and best-effort either way.
            try:
                tracer = obs.get_tracer()
                if tracer is not None:
                    tracer.flush()
                obs.flight_dump(
                    "preemption" if signum is not None else "shutdown"
                )
            except Exception:
                pass
            self.drain()

    # -- drain hooks -------------------------------------------------------

    def register_drain(self, hook):
        """Register a callable to run when shutdown is requested — the
        seam that lets subsystems with their own in-flight work (the
        serving micro-batcher, stats flushers) join the graceful exit
        WITHOUT re-installing signal handlers over the ones a driver
        already armed. Hooks may run in signal-handler context, so they
        must be non-blocking: set a flag, wake a worker — never join a
        thread or wait on a queue. Returns the hook (decorator-friendly).
        """
        self._drain_hooks.append(hook)
        return hook

    def drain(self) -> None:
        """Invoke every registered drain hook once (idempotent hooks are
        the hooks' responsibility). Called automatically on the first
        :meth:`request`; callable directly for tests and manual drains. A
        raising hook is logged and skipped — one bad hook must not stop
        the shutdown path."""
        for hook in list(self._drain_hooks):
            try:
                hook()
            except Exception as e:  # noqa: BLE001 — shutdown must proceed
                if self._logger is not None:
                    self._logger.warn(f"drain hook {hook!r} failed: {e}")

    def __call__(self) -> bool:
        """A GracefulShutdown IS a ``stop_check`` callable."""
        return self.requested

    # -- handler lifecycle -------------------------------------------------

    def _handle(self, signum, frame):
        self.request(signum)
        if self._logger is not None:
            try:
                name = signal.Signals(signum).name
            except ValueError:
                name = str(signum)
            self._logger.warn(
                f"received {name}: finishing current pass, then "
                "checkpointing and exiting resumable"
            )

    def install(self) -> "GracefulShutdown":
        if threading.current_thread() is not threading.main_thread():
            return self  # signal API is main-thread-only; flag still works
        for sig in self.SIGNALS:
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def __enter__(self) -> "GracefulShutdown":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


# -- resumable marker -------------------------------------------------------


def write_preempted_marker(
    checkpoint_dir: str, step: int, signum: Optional[int] = None
) -> str:
    """Record that the run exited early but resumable. The marker is
    advisory — resume works off the checkpoints alone — but lets drivers
    and operators distinguish 'finished' from 'preempted mid-run'."""
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, PREEMPTED_MARKER)
    with open(path, "w") as f:
        json.dump({"step": step, "signal": signum}, f)
    from photon_ml_tpu import obs

    obs.emit_event(
        "resilience.preempted_marker_written",
        cat="resilience",
        step=step,
        signum=signum,
    )
    return path


def read_preempted_marker(checkpoint_dir: str) -> Optional[dict]:
    path = os.path.join(checkpoint_dir, PREEMPTED_MARKER)
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def clear_preempted_marker(checkpoint_dir: str) -> None:
    try:
        os.remove(os.path.join(checkpoint_dir, PREEMPTED_MARKER))
    except FileNotFoundError:
        pass
