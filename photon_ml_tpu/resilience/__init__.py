"""Fault tolerance for the training runtime (docs/ROBUSTNESS.md).

The reference outsources durability to Spark lineage recompute — with a
documented fault-tolerance bug in that very strategy
(``data/RandomEffectDataSet.scala:282-286``, SURVEY §5.3-5.4). This
subsystem makes failure handling explicit and *testable*:

- :mod:`.faults`   — deterministic fault injection at named sites
- :mod:`.retry`    — bounded exponential backoff for transient I/O
- :mod:`.shutdown` — SIGTERM/SIGINT -> checkpoint + resumable exit
- :mod:`.hostloss` — dead-peer detection -> final shard set + distinct
  exit code -> elastic restart (docs/MULTIHOST.md)

Checkpoint integrity (sha256 manifests, newest-VALID fallback) lives with
the store in :mod:`photon_ml_tpu.io.checkpoint`; the divergence guard
(rollback / damped retry / coordinate freeze) in
:mod:`photon_ml_tpu.game.descent`.
"""

from photon_ml_tpu.resilience.faults import (
    KNOWN_SITES,
    FaultInjector,
    FaultSpec,
    InjectedFault,
    UnknownFaultSite,
    arm_from_env,
    corrupt_file,
    fire,
    inject,
    known_sites,
    parse_spec,
    register_site,
    registered_sites,
    registry,
)
from photon_ml_tpu.resilience.hostloss import (
    HOST_LOSS_EXIT_CODE,
    HOST_LOSS_MARKER,
    HostLossDetected,
    clear_host_loss_marker,
    is_host_loss,
    read_host_loss_marker,
    write_host_loss_marker,
)
from photon_ml_tpu.resilience.retry import (
    RetryBudgetExceeded,
    backoff_delays,
    retry_call,
)
from photon_ml_tpu.resilience.shutdown import (
    PREEMPTED_MARKER,
    GracefulShutdown,
    clear_preempted_marker,
    read_preempted_marker,
    write_preempted_marker,
)

__all__ = [
    "KNOWN_SITES",
    "FaultInjector",
    "FaultSpec",
    "InjectedFault",
    "UnknownFaultSite",
    "known_sites",
    "registered_sites",
    "register_site",
    "arm_from_env",
    "corrupt_file",
    "fire",
    "inject",
    "parse_spec",
    "registry",
    "RetryBudgetExceeded",
    "backoff_delays",
    "retry_call",
    "HOST_LOSS_EXIT_CODE",
    "HOST_LOSS_MARKER",
    "HostLossDetected",
    "clear_host_loss_marker",
    "is_host_loss",
    "read_host_loss_marker",
    "write_host_loss_marker",
    "PREEMPTED_MARKER",
    "GracefulShutdown",
    "clear_preempted_marker",
    "read_preempted_marker",
    "write_preempted_marker",
]
