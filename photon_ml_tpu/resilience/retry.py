"""Bounded exponential-backoff retry for transient I/O.

The reference gets retries for free from Spark task rescheduling; here
durability I/O (checkpoint writes, ingest file reads) goes through
:func:`retry_call` instead. Policy: exponential backoff with full jitter
(AWS-style — decorrelates a fleet of preempted workers re-reading the
same shard) bounded by both an attempt budget and a wall-clock deadline,
retrying only exception types that plausibly heal (``OSError`` — which
includes :class:`~photon_ml_tpu.resilience.faults.InjectedFault`, so
fault drills exercise this exact path).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from photon_ml_tpu import obs

DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (OSError,)


class RetryBudgetExceeded(Exception):
    """All attempts failed; carries the last error as ``__cause__``."""

    def __init__(self, label: str, attempts: int, elapsed: float):
        super().__init__(
            f"{label}: gave up after {attempts} attempts ({elapsed:.2f}s)"
        )
        self.attempts = attempts
        self.elapsed = elapsed


def backoff_delays(
    retries: int,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    jitter: float = 1.0,
    seed: Optional[int] = None,
):
    """Yield the sleep before each retry: ``min(max, base*factor**i)``
    scaled by a uniform full-jitter draw in ``[1-jitter/2, 1+jitter/2]``.
    ``seed`` pins the draws (tests assert exact schedules)."""
    rng = random.Random(seed)
    for i in range(retries):
        cap = min(max_delay, base_delay * factor**i)
        yield cap * (1.0 + jitter * (rng.random() - 0.5))


def retry_call(
    fn: Callable,
    *args,
    retries: int = 4,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    deadline: Optional[float] = None,
    jitter: float = 1.0,
    retry_on: Tuple[Type[BaseException], ...] = DEFAULT_RETRY_ON,
    logger=None,
    label: Optional[str] = None,
    seed: Optional[int] = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying ``retry_on`` failures.

    Gives up (raising :class:`RetryBudgetExceeded` from the last error)
    when ``retries`` re-attempts are spent OR the next sleep would cross
    ``deadline`` seconds of total elapsed time — a preempted worker must
    fail fast enough to still write its final checkpoint. Non-matching
    exceptions propagate immediately (a programming error is not
    transient)."""
    label = label or getattr(fn, "__name__", "call")
    t0 = time.monotonic()
    delays = backoff_delays(
        retries, base_delay, factor, max_delay, jitter, seed
    )
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            elapsed = time.monotonic() - t0
            sleep = next(delays, None)
            if sleep is None or (
                deadline is not None and elapsed + sleep > deadline
            ):
                obs.registry().inc("resilience.retry_exhausted")
                obs.emit_event(
                    "resilience.retry_exhausted",
                    cat="resilience",
                    label=label,
                    attempts=attempt,
                    elapsed_s=round(elapsed, 3),
                )
                raise RetryBudgetExceeded(label, attempt, elapsed) from e
            obs.registry().inc("resilience.retries")
            obs.emit_event(
                "resilience.retry",
                cat="resilience",
                label=label,
                attempt=attempt,
                error=repr(e),
                sleep_s=round(sleep, 3),
            )
            if logger is not None:
                logger.warn(
                    f"{label}: attempt {attempt} failed ({e!r}); "
                    f"retrying in {sleep:.3f}s"
                )
            time.sleep(sleep)
