"""Deterministic fault injection for the training runtime.

The reference's fault-tolerance story is Spark lineage recompute — and
``data/RandomEffectDataSet.scala:282-286`` documents a bug in exactly that
strategy (SURVEY §5.3-5.4). A TPU rebuild has no lineage, so every
durability path (checkpoint write/load, ingest reads, coordinate updates)
must be *testable under failure*. This module is that test harness: a
process-global registry of named fault SITES that production code probes
with :func:`fire`, and that tests (or an operator drill via the
``PHOTON_FAULTS`` env var) arm with :class:`FaultSpec` entries.

Sites in-tree today::

    checkpoint.save         between the temp-dir write and the atomic swap
    checkpoint.load         per step-directory load attempt
    checkpoint.async_write  the background serialize/swap of the overlapped
                            GAME checkpoint writer (key = none)
    ingest.read             per input-file decode
    descent.update          per coordinate update (key = coordinate name)
    serving.score           per device scoring call (key = padded bucket)
    serving.reload          per registry load/warmup attempt (key = version)
    pipeline.decode         per decode-pool group attempt (key = chunk index)
    pipeline.transfer       per staged-chunk device transfer (key = chunk)
    collective.allreduce    per multihost host-collective exchange
    collective.stall        inside each watchdogged collective attempt
                            (key = exchange label; delay = straggler host,
                            raise = peer died mid-exchange)
    heartbeat.miss          per peer per heartbeat poll (key = peer index;
                            raise = peer went silent, delay = straggler)
    checkpoint.shard_write  per per-process checkpoint shard write
                            (key = shard index; corrupt = torn shard)
    quality.baseline        per quality-fingerprint load attempt
                            (key = export dir name; raise = unreadable,
                            corrupt = torn/garbage fingerprint — serving
                            must continue WITHOUT drift monitoring)
    serving.shard_route     per shard per routed batch of the entity-
                            sharded engine (key = shard index; raise/
                            corrupt = shard down — its entities degrade
                            to fixed-effect-only, zero lost requests;
                            delay = a slow route leg)
    serving.cache_tier      per tiered-cache promotion batch (key = RE
                            key; raise = failed host->HBM copy — the
                            entities stay cold and serve fixed-effect-
                            only; delay = a slow tier)
    cache.admission_log     per admission-log flush (key = log path;
                            raise = failed atomic-swap write — entries
                            stay in memory and the next flush retries;
                            scoring is never touched)
    retrain.warm_start      per lifecycle warm-start load (key = export
                            dir; raise/corrupt = unreadable or torn
                            prior export — the cycle fails, the old
                            model keeps serving, the alarm stays
                            latched)
    retrain.export          per lifecycle re-export (key = output dir;
                            raise = export died mid-write — no manifest
                            lands, so the registry never loads the
                            partial dir; corrupt = torn payload the
                            manifest gate / reload breaker must catch)
    frontend.accept         per accepted front-end connection (key =
                            peer address; raise = drop the connection
                            at accept — the listener and every other
                            connection keep serving; delay = slow
                            accept path)
    tenant.quota            per tenant admission quota check (key =
                            tenant name; raise = quota check fails
                            CLOSED — the request is rejected, never
                            silently admitted past quota; corrupt =
                            force the over-quota mark)
    replica.route           per routed batch attempt (key = replica
                            name; raise = replica died mid-batch — the
                            router must fail over with zero lost
                            requests; delay = a slow replica skewing
                            the load view)

Arming a site OUTSIDE this list raises at arm time: a typo'd drill that
silently probes nothing would "pass" by testing nothing. Libraries that
grow new seams register them via :func:`register_site`.

Modes:

- ``raise``   — raise :class:`InjectedFault` (an ``OSError``, so the
  retry layer treats it as transient I/O).
- ``corrupt`` — signal the call site to corrupt its payload: flip bytes
  in a written file (torn write) or poison an update with non-finites.
- ``delay``   — sleep ``delay`` seconds (stall, for deadline tests).

Triggers are deterministic: ``nth`` fires on the Nth probe of the site
(1-based, ``count`` consecutive probes, ``count=-1`` forever), ``p``
fires with seeded probability per probe. Sites with no armed spec cost
one dict lookup — the registry is empty unless armed, so production
runs pay nothing.

Env spec grammar (``;``-separated)::

    PHOTON_FAULTS="checkpoint.save:raise@n=2;ingest.read:delay@p=0.1,seed=7,delay=0.2"
    PHOTON_FAULTS="descent.update:corrupt@n=3,count=-1,key=per-user"

Tests prefer the :func:`inject` context manager, which arms specs and
restores the previous registry state on exit.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import time
from typing import Dict, List, Optional

ENV_VAR = "PHOTON_FAULTS"

KNOWN_SITES = (
    "checkpoint.save",
    "checkpoint.load",
    "checkpoint.async_write",
    "ingest.read",
    "descent.update",
    "serving.score",
    "serving.reload",
    "pipeline.decode",
    "pipeline.transfer",
    "collective.allreduce",
    "collective.stall",
    "heartbeat.miss",
    "checkpoint.shard_write",
    "quality.baseline",
    "partition.shard_skew",
    "serving.shard_route",
    "serving.cache_tier",
    "cache.admission_log",
    "retrain.warm_start",
    "retrain.export",
    "frontend.accept",
    "tenant.quota",
    "replica.route",
)

MODES = ("raise", "corrupt", "delay")

# extension point: seams registered at runtime (plugins, tests for
# not-yet-promoted sites) — validated exactly like KNOWN_SITES
_EXTRA_SITES: set = set()


def register_site(site: str) -> None:
    """Declare an additional drillable site (idempotent). Arm-time
    validation accepts KNOWN_SITES plus everything registered here."""
    if not site or not isinstance(site, str):
        raise ValueError(f"fault site must be a non-empty string: {site!r}")
    _EXTRA_SITES.add(site)


def known_sites() -> tuple:
    """Every site an arm() will accept, sorted."""
    return tuple(sorted(set(KNOWN_SITES) | _EXTRA_SITES))


def registered_sites() -> tuple:
    """The machine-readable site registry: the single source of truth
    shared by arm-time validation, the ``photon-chaos sites`` listing,
    the docs/ROBUSTNESS.md site table, and the ``photon-lint`` PL003
    rule (a ``fire("...")``/``FaultSpec("...")`` literal outside this
    set is a build-time error, not an arm-time one). Alias of
    :func:`known_sites`, exported under the name the consumers bind."""
    return known_sites()


class UnknownFaultSite(ValueError):
    """Armed a site no production code probes — the drill would test
    nothing. Carries the valid-site list so the typo is obvious."""

    def __init__(self, site: str):
        super().__init__(
            f"unknown fault site {site!r}; known sites: "
            f"{', '.join(known_sites())} (register_site() adds new seams)"
        )
        self.site = site


def _note_injection(
    site: str, mode: str, call: int, key: Optional[str]
) -> None:
    """Record a triggered injection in the obs layer. Import is deferred:
    the registry/tracer are only touched when a drill actually fires, so
    unarmed production probes stay one dict lookup."""
    from photon_ml_tpu import obs

    obs.registry().inc("resilience.faults_injected")
    obs.registry().inc(f"resilience.faults_injected.{site}")
    obs.emit_event(
        "resilience.fault_injected",
        cat="resilience",
        site=site,
        mode=mode,
        call=call,
        key=key,
    )


class InjectedFault(OSError):
    """Raised by an armed ``raise``-mode fault. Subclasses OSError so the
    retry layer classifies it as transient I/O — injected crashes exercise
    the same recovery path as real ones."""

    def __init__(self, site: str, call: int):
        super().__init__(f"injected fault at site {site!r} (call #{call})")
        self.site = site
        self.call = call


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: where, how, and when it triggers."""

    site: str
    mode: str  # raise | corrupt | delay
    nth: Optional[int] = None  # 1-based call index to trigger on
    count: int = 1  # consecutive triggers from nth (-1 = forever)
    p: Optional[float] = None  # per-call probability (needs seed)
    seed: int = 0
    delay: float = 0.05  # seconds, delay mode
    key: Optional[str] = None  # context filter (e.g. coordinate name)

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"fault mode must be one of {MODES}: {self.mode!r}")
        if (self.nth is None) == (self.p is None):
            raise ValueError(
                f"exactly one of nth= / p= must be set: {self}"
            )
        self._rng = random.Random(self.seed)

    def triggers(self, call: int, key: Optional[str]) -> bool:
        if self.key is not None and key != self.key:
            return False
        if self.nth is not None:
            if call < self.nth:
                return False
            return self.count < 0 or call < self.nth + self.count
        return self._rng.random() < self.p


@dataclasses.dataclass
class FaultAction:
    """What :func:`fire` tells the call site to do. ``raise``/``delay``
    are handled inside fire(); ``corrupt`` is the site's job (only it
    knows its payload)."""

    corrupt: bool = False

    def __bool__(self) -> bool:
        return self.corrupt


class FaultInjector:
    """Registry + per-site probe counters. One process-global instance
    (:data:`registry`); tests swap state via :func:`inject`."""

    def __init__(self):
        self._specs: Dict[str, List[FaultSpec]] = {}
        self._calls: Dict[str, int] = {}

    def arm(self, spec: FaultSpec) -> None:
        # validate at arm time: a typo'd site would otherwise sit inert
        # in the registry and the drill would "pass" by testing nothing
        if spec.site not in KNOWN_SITES and spec.site not in _EXTRA_SITES:
            raise UnknownFaultSite(spec.site)
        self._specs.setdefault(spec.site, []).append(spec)

    def clear(self) -> None:
        self._specs.clear()
        self._calls.clear()

    def active(self) -> bool:
        return bool(self._specs)

    def calls(self, site: str) -> int:
        return self._calls.get(site, 0)

    def fire(self, site: str, key: Optional[str] = None) -> FaultAction:
        """Probe ``site``: increments its counter, raises / sleeps for
        armed raise/delay specs, and returns whether the site should
        corrupt its payload. No armed specs -> one dict lookup. Every
        TRIGGERED spec lands in the obs layer (counter + instant event):
        a drill whose injections aren't visible in the trace can't be
        told apart from a drill that never fired."""
        specs = self._specs.get(site)
        if not specs:
            return FaultAction()
        call = self._calls.get(site, 0) + 1
        self._calls[site] = call
        action = FaultAction()
        for spec in specs:
            if not spec.triggers(call, key):
                continue
            _note_injection(site, spec.mode, call, key)
            if spec.mode == "raise":
                raise InjectedFault(site, call)
            if spec.mode == "delay":
                time.sleep(spec.delay)
            elif spec.mode == "corrupt":
                action.corrupt = True
        return action


registry = FaultInjector()


def fire(site: str, key: Optional[str] = None) -> FaultAction:
    """Module-level probe used by production call sites."""
    return registry.fire(site, key)


def corrupt_file(path: str, offset: int = -16, nbytes: int = 8) -> None:
    """Flip ``nbytes`` bytes of ``path`` in place (default: near the end,
    the torn-write shape). The canonical payload corruption for
    ``corrupt``-mode faults on file-writing sites."""
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size == 0:
            return
        pos = offset if offset >= 0 else max(0, size + offset)
        f.seek(pos)
        chunk = f.read(nbytes)
        f.seek(pos)
        f.write(bytes(b ^ 0xFF for b in chunk))


def parse_spec(text: str) -> List[FaultSpec]:
    """Parse the ``PHOTON_FAULTS`` grammar into specs (see module doc)."""
    specs: List[FaultSpec] = []
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        try:
            site_mode, _, argstr = part.partition("@")
            site, _, mode = site_mode.partition(":")
            kwargs: Dict[str, object] = {}
            for kv in filter(None, argstr.split(",")):
                k, _, v = kv.partition("=")
                k = k.strip()
                if k in ("nth", "n"):
                    kwargs["nth"] = int(v)
                elif k == "count":
                    kwargs["count"] = int(v)
                elif k == "p":
                    kwargs["p"] = float(v)
                elif k == "seed":
                    kwargs["seed"] = int(v)
                elif k == "delay":
                    kwargs["delay"] = float(v)
                elif k == "key":
                    kwargs["key"] = v
                else:
                    raise ValueError(f"unknown fault arg {k!r}")
            specs.append(FaultSpec(site=site.strip(), mode=mode.strip(), **kwargs))
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"bad {ENV_VAR} entry {part!r}: {e}"
            ) from e
    return specs


def arm_from_env(injector: Optional[FaultInjector] = None) -> int:
    """Arm specs from ``PHOTON_FAULTS`` (operator drills on a real
    deployment). Returns the number of specs armed."""
    injector = injector or registry
    text = os.environ.get(ENV_VAR, "")
    specs = parse_spec(text) if text else []
    for s in specs:
        injector.arm(s)
    return len(specs)


@contextlib.contextmanager
def inject(*specs: FaultSpec):
    """Arm ``specs`` on the global registry for the block, restoring the
    previous specs AND counters on exit — tests never leak faults."""
    prev_specs = {k: list(v) for k, v in registry._specs.items()}
    prev_calls = dict(registry._calls)
    registry.clear()
    for s in specs:
        registry.arm(s)
    try:
        yield registry
    finally:
        registry.clear()
        registry._specs.update(prev_specs)
        registry._calls.update(prev_calls)
