"""Scripted chaos drills: fault schedules + asserted invariants.

A fault site that nothing drills is a fault site that silently rots —
the PR-1 harness proved the original four sites, and everything built
since (serving engine/batcher/registry, the streaming ingest pipeline,
the async checkpoint writer, the multihost collective seam) needs the
same treatment. This module is the shared drill engine behind

- ``benchmarks/chaos_lab.py`` — the scripted lab (``--smoke`` runs the
  full schedule on CPU in seconds and emits a BENCH-style JSON report),
- ``photon-chaos`` (``cli/chaos.py``) — the operator CLI (list sites,
  validate a ``PHOTON_FAULTS`` schedule, run drills),
- ``bench.py bench_overload`` — the sentinel-tracked overload metrics
  (``serving_shed_frac``, ``p99_under_overload_ms``,
  ``breaker_recovery_s``),
- ``tests/test_chaos.py`` — the tier-1 ``chaos``-marked smoke drill.

Every drill arms faults through :func:`photon_ml_tpu.resilience.faults.
inject`, so the registry (and its counters) is restored whatever the
drill does, and every drill asserts a RECOVERY invariant, not just that
the fault fired: no request lost outside the shed budget, checkpoints
always restorable, the breaker opens AND recloses, training results
bit-equal where faults were fully recovered.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

import numpy as np

from photon_ml_tpu import obs
from photon_ml_tpu.resilience.faults import (
    FaultSpec,
    InjectedFault,
    UnknownFaultSite,
    fire,
    inject,
    known_sites,
)

__all__ = [
    "DrillResult",
    "DRILLS",
    "MULTIHOST_DRILLS",
    "run_drills",
    "overload_run",
    "breaker_drill",
]


@dataclasses.dataclass
class DrillResult:
    """One scripted drill's outcome. ``skipped`` means an environment
    prerequisite was missing (e.g. the native reader for pipeline
    drills) — never a silent pass: the report says why."""

    name: str
    passed: bool
    duration_s: float
    skipped: bool = False
    reason: str = ""
    details: Dict[str, object] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class _Skip(Exception):
    """Raised by a drill whose environment prerequisite is missing."""


def _tolerance() -> float:
    import jax

    return 1e-10 if jax.config.jax_enable_x64 else 1e-5


# ---------------------------------------------------------------------------
# synthetic serving fixtures (self-contained: drills must not import tests)
# ---------------------------------------------------------------------------


def build_drill_engine(rng, d_fixed=16, d_user=6, n_users=64, dtype=None):
    """Tiny in-memory GAME model behind a ScoringEngine: one fixed
    effect, one random effect — enough to drill score/degrade paths."""
    from photon_ml_tpu.io.vocab import FeatureVocabulary, feature_key
    from photon_ml_tpu.serving.engine import ScoringEngine

    g_vocab = FeatureVocabulary(
        [feature_key(f"g{j}", "") for j in range(d_fixed)]
    )
    u_vocab = FeatureVocabulary(
        [feature_key(f"u{j}", "") for j in range(d_user)]
    )
    params = {
        "global": rng.normal(size=d_fixed),
        "per-user": rng.normal(size=(n_users, d_user)),
    }
    return ScoringEngine(
        params,
        shards={"global": "g", "per-user": "u"},
        random_effects={"global": None, "per-user": "userId"},
        shard_vocabs={"g": g_vocab, "u": u_vocab},
        re_vocabs={"userId": {f"user{i}": i for i in range(n_users)}},
        **({"dtype": dtype} if dtype is not None else {}),
    )


def make_drill_request(rng, d_fixed=16, d_user=6, n_users=64):
    from photon_ml_tpu.serving.engine import ScoreRequest

    feats = {
        f"g{int(j)}": float(rng.normal())
        for j in rng.integers(0, d_fixed, size=4)
    }
    feats[f"u{int(rng.integers(0, d_user))}"] = float(rng.normal())
    return ScoreRequest(
        features=feats,
        entities={"userId": f"user{int(rng.integers(0, n_users))}"},
    )


def _save_drill_export(root: str, rng, scale: float = 1.0) -> str:
    """A verifiable GAME model export on disk (manifest included) — the
    registry/breaker drill fixture."""
    from photon_ml_tpu.io.models import save_game_model, write_model_manifest
    from photon_ml_tpu.io.vocab import FeatureVocabulary, feature_key

    d = 4
    vocab = FeatureVocabulary([feature_key(f"f{j}", "") for j in range(d)])
    save_game_model(
        root,
        params={
            "global": scale * np.asarray(rng.normal(size=d)),
            "per-user": scale * np.asarray(rng.normal(size=(5, d))),
        },
        shards={"global": "s", "per-user": "s"},
        vocabs={"global": vocab, "per-user": vocab},
        entity_vocabs={"per-user": {f"u{i}": i for i in range(5)}},
        random_effects={"global": None, "per-user": "userId"},
    )
    vocab.save(os.path.join(root, "feature-index-s.txt"))
    write_model_manifest(root)
    return root


# ---------------------------------------------------------------------------
# drill: site registry hygiene + unarmed probe overhead
# ---------------------------------------------------------------------------


def drill_site_registry(smoke: bool = True) -> dict:
    """Arm-time validation rejects typo'd sites; unarmed probes stay a
    dict lookup (the obs_overhead gate's chaos-layer share)."""
    try:
        with inject(FaultSpec("serving.scoer", "raise", nth=1)):  # photon-lint: disable=PL003 deliberately typo'd site — this drill asserts arm-time validation rejects it
            raise AssertionError("typo'd site armed without error")
    except UnknownFaultSite as e:
        # classify by the structured field, not message text (PL002);
        # the near-miss must be carried verbatim and the real site must
        # be among the suggestions the error advertises
        assert e.site == "serving.scoer", "error must carry the typo"
        assert "serving.score" in known_sites(), (
            "the suggestion list the error prints must contain the "
            "real site"
        )
    # every site in the table is armable
    for site in known_sites():
        with inject(FaultSpec(site, "delay", nth=10**9, delay=0.0)):
            pass
    # unarmed probe cost: must be a dict miss, not an obs round trip
    n = 20_000 if smoke else 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        fire("serving.score")
    per_probe_ns = (time.perf_counter() - t0) / n * 1e9
    assert per_probe_ns < 20_000, (
        f"unarmed probe costs {per_probe_ns:.0f}ns — the cheap-when-"
        "unarmed contract is broken"
    )
    return {
        "known_sites": len(known_sites()),
        "unarmed_probe_ns": round(per_probe_ns, 1),
    }


# ---------------------------------------------------------------------------
# drill: serving.score faults surface to futures; engine recovers
# ---------------------------------------------------------------------------


def drill_serving_score(smoke: bool = True) -> dict:
    from photon_ml_tpu.serving.batcher import MicroBatcher

    rng = np.random.default_rng(7)
    engine = build_drill_engine(rng)
    engine.warmup(max_batch=8)
    batcher = MicroBatcher(engine.score, max_batch=8, max_wait_ms=0.5)
    try:
        # clean call first (compile out of the way)
        batcher.score_sync(make_drill_request(rng), timeout=30.0)
        with inject(FaultSpec("serving.score", "raise", nth=1)):
            try:
                batcher.score_sync(make_drill_request(rng), timeout=30.0)
                raise AssertionError("injected score fault did not surface")
            except InjectedFault:
                pass
        # the NEXT request scores clean: the engine carries no poisoned
        # state across a failed batch
        s = batcher.score_sync(make_drill_request(rng), timeout=30.0)
        assert np.isfinite(s), "engine did not recover after score fault"
        # corrupt-mode: NaN scores must be OBSERVABLE (not silently
        # served as numbers)
        with inject(FaultSpec("serving.score", "corrupt", nth=1)):
            bad = batcher.score_sync(make_drill_request(rng), timeout=30.0)
        assert not np.isfinite(bad), "corrupt-mode scores must be non-finite"
        ok = batcher.score_sync(make_drill_request(rng), timeout=30.0)
        assert np.isfinite(ok)
        errors = int(batcher.stats.errors)
    finally:
        batcher.drain(timeout=5.0)
    return {"errors": errors, "recovered": True}


# ---------------------------------------------------------------------------
# drill: reload fault -> breaker opens -> last-good serves -> probe recloses
# ---------------------------------------------------------------------------


def breaker_drill(
    threshold: int = 2, backoff_s: float = 0.25, smoke: bool = True
) -> dict:
    """The full breaker lifecycle under live traffic. Returns the
    measured ``breaker_recovery_s`` (open -> successful probe reload)."""
    import threading

    from photon_ml_tpu.serving.registry import ModelRegistry

    rng = np.random.default_rng(11)
    out: Dict[str, object] = {}
    with tempfile.TemporaryDirectory() as tmp:
        watch = os.path.join(tmp, "watch")
        v1 = _save_drill_export(os.path.join(watch, "v001"), rng, scale=1.0)
        reg = ModelRegistry(
            warmup_max_batch=8,
            breaker_threshold=threshold,
            breaker_backoff_s=backoff_s,
            breaker_max_backoff_s=backoff_s * 8,
        )
        reg.load(v1, version_id="v001")

        # background traffic for the WHOLE drill: zero dropped in-flight
        # requests across quarantine and recovery
        from photon_ml_tpu.serving.engine import ScoreRequest

        stop = threading.Event()
        client_errors: List[str] = []
        client_scores = [0]

        def client():
            while not stop.is_set():
                try:
                    reg.score(
                        [ScoreRequest(features={"f0": 1.0},
                                      entities={"userId": "u1"})]
                    )
                    client_scores[0] += 1
                except Exception as e:  # noqa: BLE001 — drill evidence
                    client_errors.append(repr(e))
        t = threading.Thread(target=client, daemon=True)
        t.start()
        try:
            # a broken v002 lands: manifest present but payload torn
            v2 = _save_drill_export(
                os.path.join(watch, "v002"), rng, scale=2.0
            )
            from photon_ml_tpu.resilience.faults import corrupt_file

            corrupt_file(
                os.path.join(
                    v2,
                    "fixed-effect", "global", "coefficients",
                    "part-00000.avro",
                )
            )
            # polls re-attempt until the breaker opens...
            attempts = 0
            for _ in range(threshold + 3):
                assert reg.poll(watch) is None
                attempts += 1
                if reg.breaker.state(v2) in ("open", "half_open"):
                    break
            assert reg.breaker.state(v2) == "open", (
                f"breaker did not open after {attempts} failing polls: "
                f"{reg.breaker.snapshot()}"
            )
            t_open = time.perf_counter()
            failures_at_open = int(reg.stats.reload_failures)
            # ...and once open, polls SKIP the broken export (no more
            # failure churn against live traffic)
            assert reg.poll(watch) is None
            assert int(reg.stats.reload_failures) == failures_at_open, (
                "open breaker must skip the quarantined export"
            )
            assert reg.version() == "v001", "last-good stopped serving"

            # publisher fixes the export; the next due probe recloses
            from photon_ml_tpu.io.models import write_model_manifest

            _save_drill_export(v2, rng, scale=2.0)
            write_model_manifest(v2)
            deadline = time.perf_counter() + 30.0
            loaded = None
            while loaded is None and time.perf_counter() < deadline:
                loaded = reg.poll(watch)
                if loaded is None:
                    time.sleep(backoff_s / 4)
            assert loaded == "v002", "probe did not recover the export"
            recovery_s = time.perf_counter() - t_open
            assert reg.breaker.state(v2) == "closed"
            assert reg.version() == "v002"
        finally:
            stop.set()
            t.join(timeout=5.0)
        assert not client_errors, (
            f"in-flight requests failed during quarantine/recovery: "
            f"{client_errors[:3]}"
        )
        assert client_scores[0] > 0, "traffic thread never scored"
        out = {
            "breaker_recovery_s": round(recovery_s, 4),
            "reload_failures": failures_at_open,
            "client_scores": client_scores[0],
            "client_errors": 0,
        }
    return out


def drill_reload_breaker(smoke: bool = True) -> dict:
    return breaker_drill(threshold=2, backoff_s=0.25, smoke=smoke)


# ---------------------------------------------------------------------------
# drill: overload -> deadlines expire, shed policy, degraded mode; no loss
# ---------------------------------------------------------------------------


def overload_run(
    *,
    total: int = 600,
    queue_depth: int = 32,
    max_batch: int = 8,
    batch_cost_ms: float = 2.0,
    deadline_floor_ms: float = 25.0,
    priority_every: int = 10,
    tight_deadline_every: int = 7,
    degrade: bool = True,
    rng=None,
) -> dict:
    """Open-loop overload against a real engine with a simulated device
    cost: submit ``total`` requests as fast as the host can into a
    ``queue_depth``-bounded batcher whose service rate is capped at
    ``max_batch / batch_cost_ms``. Every ``priority_every``-th request
    outranks the rest (exercising the shed policy); every
    ``tight_deadline_every``-th carries a deadline shorter than the
    loaded queue wait (exercising in-queue expiry). Every request is
    accounted for: scored, expired (deadline passed in queue), shed
    (evicted for a higher-priority request), or rejected at admission —
    nothing is lost. Returns the sentinel-tracked overload metrics."""
    from photon_ml_tpu.serving.batcher import (
        Backpressure,
        DeadlineExceeded,
        MicroBatcher,
        _DegradeController,
    )

    rng = rng if rng is not None else np.random.default_rng(23)
    engine = build_drill_engine(rng)
    engine.warmup(max_batch=max_batch, include_degraded=degrade)
    cost_s = batch_cost_ms / 1e3

    def slow_score(reqs):
        time.sleep(cost_s)  # simulated device time: bounds service rate
        return engine.score(reqs)

    def slow_score_fixed(reqs):
        time.sleep(cost_s / 2)
        return engine.score(reqs, fixed_only=True)

    # unloaded baseline: same scorer, idle queue
    base = MicroBatcher(
        slow_score, max_batch=max_batch, max_wait_ms=0.5,
        queue_depth=queue_depth,
    )
    lat = []
    for _ in range(30):
        t0 = time.perf_counter()
        base.score_sync(make_drill_request(rng), timeout=30.0)
        lat.append(time.perf_counter() - t0)
    base.drain(timeout=5.0)
    lat.sort()
    unloaded_p99_ms = lat[int(0.99 * (len(lat) - 1))] * 1e3
    deadline_ms = max(2.0 * unloaded_p99_ms, deadline_floor_ms)

    batcher = MicroBatcher(
        slow_score,
        max_batch=max_batch,
        max_wait_ms=0.5,
        queue_depth=queue_depth,
        degraded_score_fn=slow_score_fixed if degrade else None,
        degrade=(
            _DegradeController(
                high_water=0.5, low_water=0.2,
                degrade_after_s=0.02, recover_after_s=0.5,
            )
            if degrade
            else None
        ),
    )
    futures: List[Optional[Future]] = []
    rejected = 0
    for i in range(total):
        try:
            tight = i % tight_deadline_every == 0
            futures.append(
                batcher.submit(
                    make_drill_request(rng),
                    # the tight slice expires in the loaded queue: the
                    # expire-before-batch-assembly path under real load
                    deadline_ms=batch_cost_ms / 2 if tight else deadline_ms,
                    priority=1 if i % priority_every == 0 else 0,
                )
            )
        except Backpressure:
            rejected += 1
            futures.append(None)
    scored, expired, shed, errors = 0, 0, 0, 0
    for fut in futures:
        if fut is None:
            continue
        try:
            fut.result(timeout=60.0)
            scored += 1
        except DeadlineExceeded:
            expired += 1
        except Backpressure:
            shed += 1
        except Exception:  # noqa: BLE001 — accounted, not lost
            errors += 1
    batcher.drain(timeout=10.0)
    lost = total - (scored + expired + shed + rejected + errors)
    # true enqueue->result latency of every SCORED request (the batcher
    # stamps it at flush; a host-side collection loop would overcount)
    p99_loaded_ms = batcher.stats.request_ms.snapshot()["p99_ms"]
    return {
        "submitted": total,
        "scored": scored,
        "expired": expired,
        "shed": shed,
        "rejected": rejected,
        "errors": errors,
        "lost": lost,
        "unloaded_p99_ms": round(unloaded_p99_ms, 3),
        "deadline_ms": round(deadline_ms, 3),
        "p99_under_overload_ms": round(p99_loaded_ms, 3),
        "serving_shed_frac": round(
            (expired + shed + rejected) / max(total, 1), 4
        ),
        "degraded_batches": int(batcher.stats.degraded_batches),
        "degraded_configured": degrade,
    }


def drill_overload(smoke: bool = True) -> dict:
    out = overload_run(total=400 if smoke else 3000)
    assert out["lost"] == 0, f"requests lost under overload: {out}"
    assert out["errors"] == 0, f"scoring errors under overload: {out}"
    # the whole point of deadlines: whatever DID score met the latency
    # promise — overload shows up as shed fraction, not tail collapse.
    # A scored request can exceed the deadline by at most the batch it
    # rode (expiry gates run before the device call, not after); the
    # slack covers that service time plus timeshared-host jitter.
    slack_ms = 20.0
    assert out["p99_under_overload_ms"] <= out["deadline_ms"] + slack_ms, (
        f"scored p99 {out['p99_under_overload_ms']}ms blew past the "
        f"deadline {out['deadline_ms']}ms + slack {slack_ms}ms"
    )
    assert out["expired"] > 0, (
        "the tight-deadline slice never expired in queue — the "
        "drop-before-batch-assembly path went undrilled"
    )
    assert out["shed"] + out["rejected"] > 0, (
        "overload run never triggered admission control — not an overload"
    )
    return out


# ---------------------------------------------------------------------------
# drill: pipeline decode fault / stall -> retried group -> identical batch
# ---------------------------------------------------------------------------


def _pipeline_fixture(tmp: str, nfiles: int = 3, rows: int = 40):
    from photon_ml_tpu.io import native
    from photon_ml_tpu.io.avro import write_avro_file
    from photon_ml_tpu.io.ingest import make_training_example
    from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_SCHEMA
    from photon_ml_tpu.io.vocab import FeatureVocabulary

    if native.get_lib() is None:
        raise _Skip(f"native reader unavailable: {native.native_error()}")
    d = 12
    rng = np.random.default_rng(31)
    paths = []
    for i in range(nfiles):
        recs = [
            make_training_example(
                label=float(rng.integers(0, 2)),
                features={
                    (f"f{int(j)}", "t"): float(rng.standard_normal())
                    for j in rng.choice(d, 4, replace=False)
                },
            )
            for _ in range(rows)
        ]
        p = os.path.join(tmp, f"part-{i}.avro")
        write_avro_file(p, TRAINING_EXAMPLE_SCHEMA, recs, codec="null")
        paths.append(p)
    vocab = FeatureVocabulary(
        [f"f{i}\x01t" for i in range(d)], add_intercept=True
    )
    return paths, vocab


def _pipelined_batch(paths, vocab, **cfg_kw):
    import jax.numpy as jnp

    from photon_ml_tpu.io.pipeline import IngestPipeline, PipelineConfig

    # ~3.4KB per fixture file vs a 2KB group budget: every file is its
    # own decode group, so the key="0" skip drill drops ONE group
    config = PipelineConfig(chunk_mb=0.002, **cfg_kw)
    with IngestPipeline([*paths], [vocab], config=config) as pipe:
        batch, uids, present = pipe.labeled_batch(dtype=jnp.float64)
        return np.asarray(batch.features), np.asarray(batch.labels), pipe.stats


def drill_pipeline_decode(smoke: bool = True) -> dict:
    with tempfile.TemporaryDirectory() as tmp:
        paths, vocab = _pipeline_fixture(tmp)
        feats0, labels0, _ = _pipelined_batch(paths, vocab)
        # raise-mode mid-epoch: the group restarts through the retry
        # seam; the assembled batch is BIT-identical
        with inject(FaultSpec("pipeline.decode", "raise", nth=2)):
            feats1, labels1, _ = _pipelined_batch(paths, vocab)
        np.testing.assert_array_equal(feats0, feats1)
        np.testing.assert_array_equal(labels0, labels1)
        # stalled decoder: the watchdog abandons the attempt, the retry
        # redoes it — identical again
        with inject(
            FaultSpec("pipeline.decode", "delay", nth=1, delay=1.0)
        ):
            feats2, _, stats2 = _pipelined_batch(
                paths, vocab, stage_timeout_s=0.2
            )
        np.testing.assert_array_equal(feats0, feats2)
        # transfer fault: retried in place, staged ring intact
        with inject(FaultSpec("pipeline.transfer", "raise", nth=1)):
            feats3, _, _ = _pipelined_batch(paths, vocab)
        np.testing.assert_array_equal(feats0, feats3)
        # skip-and-log epoch policy: a permanently-failing group is
        # dropped, the epoch survives with fewer rows
        with inject(
            FaultSpec("pipeline.decode", "raise", nth=1, count=-1, key="0")
        ):
            featsS, _, statsS = _pipelined_batch(
                paths, vocab, epoch_policy="skip"
            )
        assert featsS.shape[0] < feats0.shape[0], (
            "skip policy dropped nothing"
        )
        assert statsS.groups_skipped >= 1
    return {
        "rows": int(feats0.shape[0]),
        "rows_after_skip": int(featsS.shape[0]),
        "bit_identical_after_retry": True,
        "watchdog_recovered": True,
    }


# ---------------------------------------------------------------------------
# drill: checkpoint.async_write -> surfaces at join, sync fallback holds
# ---------------------------------------------------------------------------


def _tiny_game(rng, dtype=None):
    import jax.numpy as jnp

    from photon_ml_tpu.core.tasks import TaskType
    from photon_ml_tpu.game import (
        CoordinateConfig,
        CoordinateDescent,
        FixedEffectCoordinate,
        GameData,
        RandomEffectCoordinate,
        build_random_effect_design,
    )
    from photon_ml_tpu.models.training import OptimizerType

    dtype = dtype or (
        jnp.float64 if jnp.zeros(()).dtype == jnp.float64 else jnp.float32
    )
    n_users, rows, d_g, d_u = 4, 8, 3, 2
    n = n_users * rows
    user = np.repeat(np.arange(n_users), rows)
    xg = rng.normal(size=(n, d_g))
    xu = rng.normal(size=(n, d_u))
    y = (rng.uniform(size=n) < 0.5).astype(float)
    data = GameData.create(
        features={"global": xg, "per_user": xu},
        labels=y,
        entity_ids={"userId": user},
    )
    fe_cfg = CoordinateConfig(
        shard="global", task=TaskType.LOGISTIC_REGRESSION,
        optimizer=OptimizerType.TRON, reg_weight=0.1, max_iters=10,
        tolerance=1e-8,
    )
    re_cfg = CoordinateConfig(
        shard="per_user", task=TaskType.LOGISTIC_REGRESSION,
        optimizer=OptimizerType.TRON, reg_weight=1.0, max_iters=10,
        tolerance=1e-8, random_effect="userId",
    )
    fixed = FixedEffectCoordinate(
        data.fixed_effect_batch("global", dtype), fe_cfg
    )
    design = build_random_effect_design(
        data, "userId", "per_user", n_users, dtype=dtype
    )
    random = RandomEffectCoordinate(
        design=design,
        row_features=jnp.asarray(data.features["per_user"], dtype),
        row_entities=jnp.asarray(data.entity_ids["userId"]),
        full_offsets_base=jnp.asarray(data.offsets, dtype),
        config=re_cfg,
    )
    return CoordinateDescent(
        coordinates={"fixed": fixed, "per-user": random},
        labels=jnp.asarray(data.labels, dtype),
        base_offsets=jnp.asarray(data.offsets, dtype),
        weights=jnp.asarray(data.weights, dtype),
        task=TaskType.LOGISTIC_REGRESSION,
    )


def drill_async_checkpoint(smoke: bool = True) -> dict:
    from photon_ml_tpu.io.checkpoint import latest_checkpoint

    tol = _tolerance()
    reg = obs.registry()
    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.default_rng(41)
        model_a, _ = _tiny_game(np.random.default_rng(41)).run(
            num_iterations=2, seed=3,
            checkpoint_dir=os.path.join(tmp, "a"), checkpoint_every=1,
        )
        before = reg.counter("resilience.ckpt_async_fallbacks").value
        rng = np.random.default_rng(41)
        with inject(FaultSpec("checkpoint.async_write", "raise", nth=1)):
            model_b, _ = _tiny_game(rng).run(
                num_iterations=2, seed=3,
                checkpoint_dir=os.path.join(tmp, "b"), checkpoint_every=1,
            )
        fallbacks = (
            reg.counter("resilience.ckpt_async_fallbacks").value - before
        )
        assert fallbacks >= 1, (
            "async-write fault never surfaced at a join"
        )
        # durability boundary held: the checkpoint is on disk AND loads
        ck = latest_checkpoint(os.path.join(tmp, "b"))
        assert ck is not None, "no restorable checkpoint after fallback"
        # a fully-recovered fault leaves the training result untouched
        for name in model_a.params:
            np.testing.assert_allclose(
                np.asarray(model_b.params[name]),
                np.asarray(model_a.params[name]),
                rtol=0, atol=tol, err_msg=name,
            )
    return {"fallbacks": int(fallbacks), "checkpoint_restorable": True}


# ---------------------------------------------------------------------------
# drill: the multihost collective seam fires
# ---------------------------------------------------------------------------


def drill_collective_seam(smoke: bool = True) -> dict:
    from photon_ml_tpu.parallel import multihost

    with inject(FaultSpec("collective.allreduce", "raise", nth=1)):
        try:
            multihost.allgather_host(np.arange(4))
            raise AssertionError("collective fault did not fire")
        except InjectedFault:
            pass
    # clean exchange afterwards (single-process identity)
    out = multihost.allgather_host(np.arange(4))
    np.testing.assert_array_equal(out, np.arange(4))
    # delay-mode: the straggler-host drill adds measurable wall
    t0 = time.perf_counter()
    with inject(
        FaultSpec("collective.allreduce", "delay", nth=1, delay=0.05)
    ):
        multihost.allgather_host(np.arange(4))
    straggler_s = time.perf_counter() - t0
    assert straggler_s >= 0.05
    return {"straggler_s": round(straggler_s, 4)}


# ---------------------------------------------------------------------------
# drill: collective watchdog — stall times out, retries, attributes
# ---------------------------------------------------------------------------


def drill_collective_stall(smoke: bool = True) -> dict:
    """A stalled ``allgather_host`` must TIME OUT and retry through the
    backoff seam instead of hanging the pod, recording the stall
    (``collective.stalls`` / ``collective.stall_ms``) with straggler
    attribution; a stall outliving the retry budget surfaces the
    host-loss contract (docs/MULTIHOST.md)."""
    from photon_ml_tpu import obs as _obs
    from photon_ml_tpu.parallel import multihost
    from photon_ml_tpu.parallel.heartbeat import (
        HeartbeatMonitor,
        InProcessHeartbeats,
        install_monitor,
    )
    from photon_ml_tpu.resilience.hostloss import is_host_loss
    from photon_ml_tpu.resilience.retry import RetryBudgetExceeded

    reg = _obs.registry()
    stalls_before = reg.counter("collective.stalls").value
    # a monitor so the stall event carries slowest-host attribution
    mon = HeartbeatMonitor(
        interval_s=0.01, miss_intervals=1e6,
        transport=InProcessHeartbeats(2), process_index=0, process_count=2,
    )
    mon.poll_once()
    prev_mon = install_monitor(mon)
    prev = multihost.configure_collective_resilience(
        timeout_s=0.1, retries=2
    )
    try:
        # one stalled attempt -> watchdog timeout -> retry succeeds;
        # wall stays bounded by the deadline, NOT the stall length
        t0 = time.perf_counter()
        with inject(
            FaultSpec("collective.stall", "delay", nth=1, delay=2.0)
        ):
            out = multihost.allgather_host(np.arange(8))
        recovery_s = time.perf_counter() - t0
        np.testing.assert_array_equal(out, np.arange(8))
        assert recovery_s < 1.9, (
            f"watchdog waited out the stall ({recovery_s:.2f}s) instead "
            "of abandoning the attempt"
        )
        stalls = reg.counter("collective.stalls").value - stalls_before
        assert stalls >= 1, "watchdog trip never recorded"
        # a PERSISTENT stall (dead peer) exhausts the budget and maps to
        # the host-loss exit contract instead of hanging
        try:
            with inject(
                FaultSpec(
                    "collective.stall", "delay", nth=1, count=-1, delay=0.5
                )
            ):
                multihost.allgather_host(np.arange(2))
            raise AssertionError("persistent stall did not surface")
        except RetryBudgetExceeded as e:
            assert isinstance(e.__cause__, multihost.CollectiveTimeout)
            assert is_host_loss(e), (
                "exhausted collective budget must map to host loss"
            )
    finally:
        multihost.configure_collective_resilience(
            prev.timeout_s, prev.retries
        )
        install_monitor(prev_mon)
    return {
        "collective_timeout_recovery_s": round(recovery_s, 4),
        "stalls_recorded": int(stalls),
    }


# ---------------------------------------------------------------------------
# drill: heartbeat monitor detects a silent peer
# ---------------------------------------------------------------------------


def drill_heartbeat_loss(smoke: bool = True) -> dict:
    """A peer that stops beating must be declared LOST within the miss
    threshold — gauges updated, ``heartbeat.peer_lost`` emitted, and
    ``check()`` raising :class:`HostLossDetected` (the pass-boundary
    signal the descent loop converts into a final shard set)."""
    from photon_ml_tpu import obs as _obs
    from photon_ml_tpu.parallel.heartbeat import (
        HeartbeatMonitor,
        InProcessHeartbeats,
    )
    from photon_ml_tpu.resilience.hostloss import HostLossDetected

    mon = HeartbeatMonitor(
        interval_s=1e-3, miss_intervals=1.0,
        transport=InProcessHeartbeats(3), process_index=0, process_count=3,
    )
    mon.poll_once()
    assert mon.lost_peers() == [], "healthy pod reported losses"
    time.sleep(0.01)
    with inject(
        FaultSpec("heartbeat.miss", "raise", nth=1, count=-1, key="2")
    ):
        time.sleep(0.01)
        mon.poll_once()
    assert mon.lost_peers() == [2], (
        f"expected peer 2 lost, got {mon.lost_peers()}"
    )
    try:
        mon.check()
        raise AssertionError("check() did not raise on a lost peer")
    except HostLossDetected as e:
        assert e.peers == [2]
    # losses latch: a zombie peer beating again must NOT resurrect
    mon.poll_once()
    assert mon.lost_peers() == [2], "lost peer un-lost itself"
    reg = _obs.registry()
    assert reg.counter("pod.heartbeat.misses").value >= 1
    return {
        "lost_peers": mon.lost_peers(),
        "slowest": list(mon.slowest() or ()),
    }


# ---------------------------------------------------------------------------
# drill: host kill -> final shard set + marker -> shrunk restart resumes
# ---------------------------------------------------------------------------


def drill_host_loss_recovery(smoke: bool = True) -> dict:
    """The full elastic contract (docs/MULTIHOST.md): a peer dies
    mid-run -> survivors write a FINAL sharded checkpoint + host-loss
    marker and surface the distinct exit code -> a restart at a SMALLER
    world size resumes from the shard set and matches the uninterrupted
    run to solver tolerance."""
    from photon_ml_tpu.io.checkpoint import latest_checkpoint
    from photon_ml_tpu.parallel.heartbeat import (
        HeartbeatMonitor,
        InProcessHeartbeats,
    )
    from photon_ml_tpu.resilience.hostloss import (
        HOST_LOSS_EXIT_CODE,
        HostLossDetected,
        read_host_loss_marker,
    )

    tol = _tolerance()
    ekeys = {"per-user": [f"user{i}" for i in range(4)]}
    with tempfile.TemporaryDirectory() as tmp:
        # oracle: uninterrupted 4-pass run (sharded checkpoints too, so
        # the formats match end to end)
        model_a, _ = _tiny_game(np.random.default_rng(41)).run(
            num_iterations=4, seed=3,
            checkpoint_dir=os.path.join(tmp, "a"), checkpoint_every=1,
            sharded_checkpoints=2, entity_keys=ekeys,
        )
        # "host kill": peer 1 goes silent from the third boundary poll
        mon = HeartbeatMonitor(
            interval_s=1e-4, miss_intervals=1.0,
            transport=InProcessHeartbeats(2),
            process_index=0, process_count=2,
        )
        ckdir = os.path.join(tmp, "b")
        died = None
        with inject(
            FaultSpec("heartbeat.miss", "raise", nth=3, count=-1, key="1")
        ):
            try:
                _tiny_game(np.random.default_rng(41)).run(
                    num_iterations=4, seed=3,
                    checkpoint_dir=ckdir, checkpoint_every=1,
                    sharded_checkpoints=2, entity_keys=ekeys,
                    heartbeat=mon,
                )
                raise AssertionError("peer loss never surfaced")
            except HostLossDetected as e:
                died = e
        assert died.peers == [1]
        marker = read_host_loss_marker(ckdir)
        assert marker is not None, "no host-loss marker"
        assert marker["exit_code"] == HOST_LOSS_EXIT_CODE
        assert HOST_LOSS_EXIT_CODE not in (0, 1, 2, 3), (
            "host-loss exit code must be distinct from the existing "
            "exit taxonomy"
        )
        ck = latest_checkpoint(ckdir)
        assert ck is not None and ck.shards == 2, (
            "survivors left no restorable shard set"
        )
        assert ck.step == marker["step"], (
            f"marker step {marker['step']} != final shard set {ck.step}"
        )
        # elastic restart at a SMALLER world size (2 shards -> 1):
        # entity-keyed shards reassemble + re-shard by key
        model_b, _ = _tiny_game(np.random.default_rng(41)).run(
            num_iterations=4, seed=3,
            checkpoint_dir=ckdir, checkpoint_every=1,
            sharded_checkpoints=1, entity_keys=ekeys, resume=True,
        )
        for name in model_a.params:
            np.testing.assert_allclose(
                np.asarray(model_b.params[name]),
                np.asarray(model_a.params[name]),
                rtol=0, atol=tol, err_msg=name,
            )
    return {
        "died_at_step": marker["step"],
        "lost_peers": died.peers,
        "exit_code": HOST_LOSS_EXIT_CODE,
        "resumed_world_size": 1,
        "bit_identical_resume": True,
    }


# ---------------------------------------------------------------------------
# drill: torn / missing shard -> quorum falls back to newest complete step
# ---------------------------------------------------------------------------


def drill_torn_shard(smoke: bool = True) -> dict:
    """Sharded-checkpoint quorum: a step whose shard set is torn
    (corrupt-mode ``checkpoint.shard_write``) or incomplete must NEVER
    restore — ``latest_checkpoint`` falls back to the newest step with a
    complete, digest-verified shard set."""
    from photon_ml_tpu.io.checkpoint import (
        latest_checkpoint,
        save_checkpoint_sharded,
    )

    rng = np.random.default_rng(17)
    params = {
        "fixed": rng.normal(size=6),
        "per-user": rng.normal(size=(5, 3)),
    }
    ekeys = {"per-user": [f"u{i}" for i in range(5)]}
    key = np.zeros(2, np.uint32)
    with tempfile.TemporaryDirectory() as tmp:
        save_checkpoint_sharded(
            tmp, 1, params, key, entity_keys=ekeys, num_shards=2, keep=5
        )
        # torn shard: digest recorded, payload flipped afterwards
        with inject(FaultSpec("checkpoint.shard_write", "corrupt", nth=2)):
            save_checkpoint_sharded(
                tmp, 2, params, key, entity_keys=ekeys, num_shards=2,
                keep=5,
            )
        ck = latest_checkpoint(tmp)
        assert ck is not None and ck.step == 1, (
            f"torn step 2 restored (got step {ck and ck.step})"
        )
        # missing shard: the quorum manifest lists it but it's gone
        save_checkpoint_sharded(
            tmp, 3, params, key, entity_keys=ekeys, num_shards=2, keep=5
        )
        os.remove(os.path.join(tmp, "step-3", "shard-1-of-2.npz"))
        ck = latest_checkpoint(tmp)
        assert ck is not None and ck.step == 1, (
            f"incomplete step 3 restored (got step {ck and ck.step})"
        )
        # raise-mode shard write retries through the backoff seam and
        # still produces a loadable step
        with inject(FaultSpec("checkpoint.shard_write", "raise", nth=1)):
            save_checkpoint_sharded(
                tmp, 4, params, key, entity_keys=ekeys, num_shards=2,
                keep=5,
            )
        ck = latest_checkpoint(tmp)
        assert ck.step == 4
        np.testing.assert_array_equal(ck.params["per-user"],
                                      params["per-user"])
    return {"fallback_step": 1, "retried_step_restored": 4}


# ---------------------------------------------------------------------------
# drill: PR-1 legacy sites still hold their invariants
# ---------------------------------------------------------------------------


def drill_checkpoint_integrity(smoke: bool = True) -> dict:
    from photon_ml_tpu.io.checkpoint import latest_checkpoint, save_checkpoint

    with tempfile.TemporaryDirectory() as tmp:
        key = np.zeros(2, np.uint32)
        save_checkpoint(tmp, 1, {"w": np.arange(3.0)}, key)
        # a CORRUPTED later step must fall back to the newest valid one
        with inject(FaultSpec("checkpoint.save", "corrupt", nth=1)):
            save_checkpoint(tmp, 2, {"w": np.arange(3.0) * 2}, key)
        ck = latest_checkpoint(tmp)
        assert ck is not None, "no valid checkpoint to fall back to"
        assert ck.step == 1, f"fell back to step {ck.step}, wanted 1"
    return {"fallback_step": int(ck.step)}


# ---------------------------------------------------------------------------
# the schedule
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# drill: covariate shift raises drift.alarm; unshifted replay stays quiet
# ---------------------------------------------------------------------------


def drill_drift_alarm(smoke: bool = True) -> dict:
    """Model-quality drill (docs/OBSERVABILITY.md "Quality & drift"):
    a train-time baseline fingerprint + a DriftMonitor on the scoring
    engine must (1) stay QUIET on unshifted replay traffic, (2) raise
    ``drift.alarm`` within a bounded request count once the feature
    distribution shifts, (3) land the alarm in the flight-recorder
    dump, and (4) degrade to serve-without-monitoring — not crash —
    when the ``quality.baseline`` fault site makes the fingerprint
    unreadable or corrupt."""
    import json as _json

    from photon_ml_tpu.obs.metrics import MetricsRegistry
    from photon_ml_tpu.obs.quality import (
        BaselineFingerprint,
        DriftMonitor,
        try_load_fingerprint,
    )

    rng = np.random.default_rng(7)
    d_fixed, d_user, n_users = 8, 4, 32
    engine = build_drill_engine(rng, d_fixed, d_user, n_users)

    def traffic(n, shift=0.0):
        return {
            "g": rng.normal(size=(n, d_fixed)) + shift,
            "u": rng.normal(size=(n, d_user)) + shift,
        }

    # train-time baseline: sketches of the unshifted distribution plus
    # the model's own score distribution over it
    baseline = BaselineFingerprint(max_features=16)
    base_rows = 2048 if smoke else 16384
    feats = traffic(base_rows)
    baseline.observe_batch(feats["g"], np.zeros(base_rows), shard="g")
    baseline.observe_rows("u", feats["u"])
    baseline.observe_margins(
        engine.score_arrays({k: v[:512] for k, v in feats.items()})
    )
    engine.drift = DriftMonitor(
        baseline,
        registry=engine.stats.registry,
        check_every_rows=256,
        min_rows=128,
        psi_alarm=0.25,
        sample_every=1,  # the drill asserts a TIGHT alarm-latency bound
    )

    batch_rows = 64
    max_shifted_rows = 1024  # the bounded-request-count contract
    with tempfile.TemporaryDirectory() as tmp:
        with obs.observe(flight_dir=tmp, flight_records=512):
            # phase 1: unshifted replay — NO false alarm across checks
            for _ in range(8):
                engine.score_arrays(traffic(batch_rows))
            quiet_checks = engine.drift.checks
            assert quiet_checks >= 1, "quiet phase never reached a check"
            assert engine.drift.alarms == 0, (
                f"false drift alarm on unshifted replay "
                f"(psi_max={engine.drift.last_report['psi_max']})"
            )
            # phase 2: shifted traffic — alarm within the bound
            shifted_rows = 0
            while engine.drift.alarms == 0:
                assert shifted_rows < max_shifted_rows, (
                    f"no drift.alarm within {max_shifted_rows} shifted "
                    "requests"
                )
                engine.score_arrays(traffic(batch_rows, shift=3.0))
                shifted_rows += batch_rows
            alarm_report = engine.drift.last_report
            assert alarm_report["alarm"] and alarm_report["flagged"], (
                f"alarm fired without flagged features: {alarm_report}"
            )
            dump_path = obs.flight_dump("drift-drill", flight_dir=tmp)
            assert dump_path is not None, "flight dump failed"
            with open(dump_path, encoding="utf-8") as f:
                dump = _json.load(f)
            alarm_records = [
                r
                for r in dump["records"]
                if r.get("name") == "drift.alarm"
            ]
            assert alarm_records, (
                "flight-recorder dump holds no drift.alarm snapshot"
            )
            assert alarm_records[-1].get("worst"), (
                "drift.alarm flight record carries no offender snapshot"
            )

        # phase 3: the quality.baseline fault site — a broken
        # fingerprint degrades to no-monitoring, never an exception
        export = os.path.join(tmp, "export")
        os.makedirs(export)
        baseline.save(export)
        reg = MetricsRegistry()
        assert try_load_fingerprint(export, registry=reg) is not None
        with inject(FaultSpec("quality.baseline", "raise", nth=1)):
            assert try_load_fingerprint(export, registry=reg) is None
        assert reg.counter("quality.baseline_missing").value == 1
        with inject(FaultSpec("quality.baseline", "corrupt", nth=1)):
            assert try_load_fingerprint(export, registry=reg) is None
        assert reg.counter("quality.baseline_errors").value == 1

    return {
        "quiet_checks": quiet_checks,
        "alarm_latency_rows": shifted_rows,
        "psi_max_at_alarm": alarm_report["psi_max"],
        "flagged_features": len(alarm_report["flagged"]),
        "flight_alarm_records": len(alarm_records),
    }


def drill_shard_skew(smoke: bool = True) -> dict:
    """Entity-sharded GAME under a deliberately SLOW shard
    (docs/PARALLEL.md): one shard's pass-boundary sync stalls past the
    collective watchdog deadline — the run must COMPLETE (retry through
    the backoff seam, result equal to the unskewed run) and the stall
    must be ATTRIBUTABLE: a ``collective.stall`` event naming the skewed
    shard's sync label, counters bumped. An unattributable straggler is
    the pod failure mode where one slow host silently caps every pass
    and nobody knows which."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu import obs as _obs
    from photon_ml_tpu.parallel import multihost

    reg = _obs.registry()
    stalls_before = reg.counter("collective.stalls").value

    n_shards = 2
    prev = multihost.configure_collective_resilience(
        timeout_s=0.15, retries=2
    )
    try:
        # (1) the skewed pass-boundary sync: shard 1 stalls 2s against a
        # 0.15s watchdog -> timeout -> retry (fault is one-shot) -> the
        # sync completes; wall bounded by the deadline, not the skew
        t0 = time.perf_counter()
        with inject(
            # the site's call counter is global across shard keys:
            # shard-0 syncs first (call 1, filtered by key), shard-1's
            # first sync is call 2 — the one that stalls; its retry
            # (call 3) runs clean
            FaultSpec(
                "partition.shard_skew", "delay", key="shard-1",
                nth=2, delay=2.0,
            )
        ):
            for p in range(n_shards):
                def sync(p=p):
                    fire("partition.shard_skew", key=f"shard-{p}")
                    return p

                got = multihost.resilient_host_exchange(
                    f"shard_sync.h{p}", sync
                )
                assert got == p
        skew_recovery_s = time.perf_counter() - t0
        assert skew_recovery_s < 1.9, (
            f"watchdog waited out the skewed shard "
            f"({skew_recovery_s:.2f}s) instead of abandoning the attempt"
        )
        stalls = reg.counter("collective.stalls").value - stalls_before
        assert stalls >= 1, "skewed shard never recorded a stall"

        # (2) the run completes with the correct result: a tiny
        # entity-sharded descent on a 2-shard entity mesh (skipped on a
        # single-device backend — the layout needs real shards)
        completed = False
        if jax.device_count() >= n_shards:
            import numpy as _np

            from photon_ml_tpu.core.tasks import TaskType
            from photon_ml_tpu.game import (
                CoordinateConfig,
                CoordinateDescent,
                EntityShardedRandomEffectCoordinate,
                GameData,
                build_bucketed_random_effect_design,
                entity_partition_game_data,
                entity_shard_assignment,
            )
            from photon_ml_tpu.parallel.mesh import (
                batch_sharding,
                make_entity_mesh,
            )

            rng = _np.random.default_rng(11)
            n_users, rows = 6, 5
            users = _np.repeat(_np.arange(n_users), rows).astype(_np.int32)
            n = users.size
            xu = rng.normal(size=(n, 3))
            y = (rng.uniform(size=n) < 0.5).astype(float)
            data = GameData.create(
                features={"u": xu}, labels=y,
                entity_ids={"userId": users},
            )
            cfg = CoordinateConfig(
                shard="u", random_effect="userId", reg_weight=1.0,
                max_iters=8, tolerance=1e-8,
            )
            mesh = make_entity_mesh(
                n_shards, devices=jax.devices()[:n_shards]
            )
            assignment = entity_shard_assignment(n_users, n_shards)
            pdata, part = entity_partition_game_data(
                data, "userId", assignment
            )
            design = build_bucketed_random_effect_design(
                pdata, "userId", "u", n_users, num_buckets=1,
                dtype=jnp.float64,
            )
            re = EntityShardedRandomEffectCoordinate(
                design=design,
                row_features=jnp.asarray(pdata.features["u"], jnp.float64),
                row_entities=jnp.asarray(pdata.entity_ids["userId"]),
                full_offsets_base=jnp.asarray(pdata.offsets, jnp.float64),
                config=cfg, mesh=mesh, assignment=assignment,
                partition=part,
            )
            put = lambda x: jax.device_put(
                jnp.asarray(x), batch_sharding(mesh, _np.ndim(x))
            )
            cd = CoordinateDescent(
                {"per-user": re},
                labels=put(pdata.labels),
                base_offsets=put(pdata.offsets),
                weights=put(pdata.weights),
                task=TaskType.LOGISTIC_REGRESSION,
            )
            model, history = cd.run(num_iterations=1)
            assert history and _np.isfinite(history[-1].objective)
            completed = True
    finally:
        multihost.configure_collective_resilience(
            prev.timeout_s, prev.retries
        )
    return {
        "skew_recovery_s": round(skew_recovery_s, 4),
        "stalls_recorded": int(stalls),
        "sharded_run_completed": completed,
    }


def drill_shard_fault(smoke: bool = True) -> dict:
    """Entity-sharded serving under a single-shard fault
    (docs/SERVING.md): with ``serving.shard_route`` armed raise-mode for
    ONE shard, every request still completes (zero lost), the faulted
    shard's entities degrade to fixed-effect-only scores — bit-equal to
    the engine's degraded executable on those rows — other shards'
    entities stay exact, the latency ledger stays honest (every scored
    request is in the histogram), and the next batch after the fault
    clears is exact again. Also drills ``serving.cache_tier``: a failed
    promotion batch leaves its entities cold (served fixed-effect-only),
    and the retry after the fault clears promotes them to exact scores."""
    import jax

    from photon_ml_tpu.serving.batcher import MicroBatcher
    from photon_ml_tpu.serving.engine import ScoringEngine
    from photon_ml_tpu.serving.sharding import ShardedScoringEngine

    from photon_ml_tpu.io.vocab import FeatureVocabulary, feature_key

    rng = np.random.default_rng(13)
    n_users, d_fixed, d_user = 24, 8, 4
    params = {
        "global": rng.normal(size=d_fixed),
        "per-user": rng.normal(size=(n_users, d_user)),
    }
    kw = dict(
        shards={"global": "g", "per-user": "u"},
        random_effects={"global": None, "per-user": "userId"},
        shard_vocabs={
            "g": FeatureVocabulary(
                [feature_key(f"g{j}", "") for j in range(d_fixed)]
            ),
            "u": FeatureVocabulary(
                [feature_key(f"u{j}", "") for j in range(d_user)]
            ),
        },
        re_vocabs={"userId": {f"user{i}": i for i in range(n_users)}},
    )
    n_shards = min(2, jax.device_count())
    engine = ShardedScoringEngine(params, num_shards=n_shards, **kw)
    reference = ScoringEngine(params, **kw)
    b = 16
    feats = {
        "g": rng.normal(size=(b, d_fixed)),
        "u": rng.normal(size=(b, d_user)),
    }
    ents = {"userId": rng.integers(0, n_users, size=b).astype(np.int32)}
    exact = reference.score_arrays(feats, ents)
    fixed_only = reference.score_arrays(feats, ents, fixed_only=True)
    owner = engine.assignments["userId"].owner_of_global(ents["userId"])
    victim = 0
    on_victim = owner == victim

    # (1) single-shard fault: victim's entities degrade, nothing is lost
    with inject(
        FaultSpec(
            "serving.shard_route", "raise", nth=1, count=-1,
            key=str(victim),
        )
    ):
        scores = engine.score_arrays(feats, ents)
    assert np.all(np.isfinite(scores)), "requests lost to the shard fault"
    assert np.allclose(
        scores[on_victim], fixed_only[on_victim], atol=1e-10
    ), "faulted shard's entities must serve fixed-effect-only"
    if n_shards > 1 and np.any(~on_victim):
        assert np.allclose(
            scores[~on_victim], exact[~on_victim], atol=1e-10
        ), "healthy shards' entities must stay exact under the fault"
    degraded_rows = int(
        engine.stats.registry.counter("serving.shard.degraded_rows").value
    )

    # (2) recovery: the next routed batch is exact everywhere
    recovered = engine.score_arrays(feats, ents)
    assert np.allclose(recovered, exact, atol=1e-10)

    # (3) honest ledger under the fault, through the batcher: every
    # accepted request scores AND lands in the latency histogram
    batcher = MicroBatcher(engine.score, max_batch=8, max_wait_ms=0.5)
    try:
        with inject(
            FaultSpec(
                "serving.shard_route", "raise", nth=1, count=-1,
                key=str(victim),
            )
        ):
            futs = [
                batcher.submit(make_drill_request(rng, d_fixed, d_user,
                                                  n_users))
                for _ in range(24)
            ]
            results = [f.result(timeout=30.0) for f in futs]
        assert all(np.isfinite(r) for r in results)
        snap = batcher.stats.snapshot()
        assert snap["request_latency"]["count"] == len(results), (
            "p99 ledger must count every degraded-mode request"
        )
    finally:
        batcher.drain(timeout=5.0)

    # (4) cache tier fault: promotion fails -> entities stay cold
    # (fixed-effect-only), retry after the fault clears promotes
    cached = ScoringEngine(params, hbm_cache_entities=4, **kw)
    try:
        cold = np.asarray([20, 21, 22, 23], np.int32)  # outside the head
        cold_feats = {k: v[:4] for k, v in feats.items()}
        cold_exact = reference.score_arrays(
            cold_feats, {"userId": cold}
        )
        cold_fixed = reference.score_arrays(
            cold_feats, {"userId": cold}, fixed_only=True
        )
        with inject(
            FaultSpec("serving.cache_tier", "raise", nth=1, count=-1)
        ):
            got = cached.score_arrays(cold_feats, {"userId": cold})
            for cache in cached._caches.values():
                cache.flush()
            still_cold = cached.score_arrays(
                cold_feats, {"userId": cold}
            )
        assert np.allclose(got, cold_fixed, atol=1e-10)
        assert np.allclose(still_cold, cold_fixed, atol=1e-10), (
            "a failed promotion must leave entities cold, not corrupt"
        )
        tier_errors = int(
            cached.stats.registry.counter(
                "serving.cache.tier_errors"
            ).value
        )
        assert tier_errors >= 1, "failed promotion must be counted"
        # the failed batch's entities re-enqueue on their NEXT miss;
        # with the fault cleared the retry promotes them
        cached.score_arrays(cold_feats, {"userId": cold})
        for cache in cached._caches.values():
            cache.flush()
        promoted = cached.score_arrays(cold_feats, {"userId": cold})
        assert np.allclose(promoted, cold_exact, atol=1e-10), (
            "cleared fault must let the retry promote to exact scores"
        )
    finally:
        cached.close()
    return {
        "serving_shards": n_shards,
        "degraded_rows": degraded_rows,
        "batched_requests": len(results),
        "cache_tier_errors": tier_errors,
    }


# ---------------------------------------------------------------------------
# drill: the self-healing lifecycle loop, end to end (docs/LIFECYCLE.md)
# ---------------------------------------------------------------------------


def drill_lifecycle(smoke: bool = True) -> dict:
    """The survival drill for the whole lifecycle loop: train -> export
    -> serve -> inject +3 sigma covariate shift -> drift alarm -> warm-
    started ENTITY-KEYED retrain (admitted repeat-miss entities join the
    training set) -> manifest-gated re-export -> hot-reload under live
    traffic with ZERO dropped requests -> post-retrain drift quiet. Then
    the failure-injected variants, each proving its defined degraded
    outcome (old model keeps serving, alarm stays latched, backoff
    gates the next cycle):

    - ``retrain.warm_start`` corrupt: the finiteness gate refuses the
      poisoned prior export; the cycle fails at the retrain stage.
    - ``retrain.export`` raise: the export dies mid-write — no manifest
      lands, so the partial directory is invisible to registry polls.
    - ``retrain.export`` corrupt: a torn-but-manifest-sealed export
      fails the orchestrator's export gate; the leftover bad directory
      is breaker-quarantined by serving polls, and a SUBSEQUENT good
      retrain export still loads (the quarantine is scoped to the bad
      directory, never the watch root)."""
    import threading

    from photon_ml_tpu.io.models import load_game_model_auto
    from photon_ml_tpu.lifecycle.orchestrator import (
        RetrainOrchestrator,
        export_retrained_model,
        latest_version_dir,
        load_warm_start,
        next_version_dir,
        registry_drift_trigger,
    )
    from photon_ml_tpu.obs.quality import (
        BaselineFingerprint,
        DriftMonitor,
        compare_fingerprints,
        try_load_fingerprint,
    )
    from photon_ml_tpu.serving.engine import ScoreRequest
    from photon_ml_tpu.serving.registry import ModelRegistry

    rng = np.random.default_rng(23)
    d = 4
    shift = 3.0
    fit_rows = 512 if smoke else 4096

    def fresh_data(n, mu):
        X = rng.normal(size=(n, d)) + mu
        return X

    def fit_global(X, warm):
        """A few full-batch logistic steps from the warm start — a
        genuinely warm-started (if tiny) refit."""
        w = np.array(warm, dtype=float)
        y = (X @ np.ones(d) > mu_sum(X)).astype(float)
        for _ in range(10):
            p = 1.0 / (1.0 + np.exp(-(X @ w)))
            w -= 0.5 * (X.T @ (p - y)) / len(X)
        return w

    def mu_sum(X):
        return float(np.mean(X @ np.ones(d)))

    def make_retrain(watch, data_mu):
        def retrain(plan):
            assert plan.warm_start_dir, "plan lost the warm-start source"
            params, shards, res, shard_vocabs, re_vocabs = (
                load_warm_start(plan.warm_start_dir)
            )
            old_vocab = re_vocabs["userId"]  # {raw key: row}
            admitted = plan.admitted.get("userId", [])
            # DIFFERENT key ordering than the prior export on purpose:
            # a positional carry would misalign every row
            new_keys = sorted(set(old_vocab) | set(admitted))
            new_vocab = {k: i for i, k in enumerate(new_keys)}
            old_table = np.asarray(params["per-user"])
            new_table = np.zeros((len(new_keys), d))
            for k, i in new_vocab.items():
                if k in old_vocab:  # carried BY KEY; admitted rows cold
                    new_table[i] = old_table[old_vocab[k]]
            X = fresh_data(fit_rows, data_mu)
            g = fit_global(X, np.asarray(params["global"]))
            fp = BaselineFingerprint(max_features=8)
            fp.observe_rows("s", X)
            # no margin sketch: the drill's live traffic mixes cold and
            # per-entity scores, so a fixed-effect margin baseline would
            # read as score drift — the post-retrain quiet property here
            # is the FEATURE distribution (margin fidelity is
            # drill_drift_alarm's subject)
            return export_retrained_model(
                next_version_dir(watch),
                params={"global": g, "per-user": new_table},
                shards=shards,
                vocabs={n: shard_vocabs[shards[n]] for n in shards},
                entity_vocabs={"per-user": new_vocab},
                random_effects=res,
                fingerprint=fp,
            )

        return retrain

    out: Dict[str, object] = {}
    with tempfile.TemporaryDirectory() as tmp:
        watch = os.path.join(tmp, "watch")
        adm_path = os.path.join(tmp, "admission.json")
        traffic_fp_dir = os.path.join(tmp, "traffic-fp")
        os.makedirs(traffic_fp_dir)

        # -- train + export v0001 on the UNSHIFTED distribution ---------
        v1 = os.path.join(watch, "v0001")
        X0 = fresh_data(fit_rows, 0.0)
        g0 = fit_global(X0, np.zeros(d))
        fp0 = BaselineFingerprint(max_features=8)
        fp0.observe_rows("s", X0)
        fp0.observe_margins(X0 @ g0)
        from photon_ml_tpu.io.vocab import FeatureVocabulary, feature_key

        vocab = FeatureVocabulary(
            [feature_key(f"f{j}", "") for j in range(d)]
        )
        export_retrained_model(
            v1,
            params={
                "global": g0,
                "per-user": rng.normal(size=(5, d)),
            },
            shards={"global": "s", "per-user": "s"},
            vocabs={"global": vocab, "per-user": vocab},
            entity_vocabs={"per-user": {f"u{i}": i for i in range(5)}},
            random_effects={"global": None, "per-user": "userId"},
            fingerprint=fp0,
        )

        # -- serve it, with the admission log riding the engine ---------
        reg = ModelRegistry(
            warmup_max_batch=8,
            breaker_threshold=2,
            breaker_backoff_s=0.2,
            breaker_max_backoff_s=1.6,
            admission_log_path=adm_path,
        )
        reg.load(v1, version_id="v0001")

        stop = threading.Event()
        client_errors: List[str] = []
        client_scores = [0]

        def client():
            # live traffic drawn from the CURRENT (shifted) distribution
            # — these rows land in the drift window too, like real
            # serving traffic would (own generator: rng isn't shared
            # across threads)
            crng = np.random.default_rng(29)
            while not stop.is_set():
                try:
                    reg.score([
                        ScoreRequest(
                            features={
                                f"f{j}": float(crng.normal() + shift)
                                for j in range(d)
                            },
                            entities={"userId": "u1"},
                        )
                    ])
                    client_scores[0] += 1
                except Exception as e:  # noqa: BLE001 — drill evidence
                    client_errors.append(repr(e))

        t = threading.Thread(target=client, daemon=True)
        t.start()
        try:
            # -- online admission: repeat-missed unknown entities -------
            for _ in range(2):
                reg.score([
                    ScoreRequest(features={"f0": 0.5},
                                 entities={"userId": k})
                    for k in ("newuser1", "newuser2")
                ])
            v = reg.acquire()
            try:
                assert v.engine.admission_log is not None
                assert v.engine.admission_log.flush(), (
                    "admission log failed to persist"
                )
            finally:
                reg.release(v)

            # -- inject +3 sigma drift until the live monitor alarms ----
            eng = reg.current.engine
            eng.drift = DriftMonitor(
                try_load_fingerprint(v1),
                registry=eng.stats.registry,
                psi_alarm=0.25,
                check_every_rows=256,
                min_rows=128,
                sample_every=1,
            )
            shifted_rows = 0
            while eng.drift.alarms == 0:
                assert shifted_rows < 2048, "no drift alarm within bound"
                eng.score_arrays({"s": fresh_data(64, shift)})
                shifted_rows += 64
            # the live-traffic fingerprint photon-obs drift would use
            fp_live = BaselineFingerprint(max_features=8)
            fp_live.observe_rows("s", fresh_data(fit_rows, shift))
            fp_live.save(traffic_fp_dir)

            # -- the orchestrator: trigger -> retrain -> reload ---------
            forced = {"on": False}

            def trigger():
                if forced["on"]:
                    return {"source": "drill-forced"}
                return registry_drift_trigger(reg)()

            def verify():
                base = try_load_fingerprint(latest_version_dir(watch))
                cur = try_load_fingerprint(traffic_fp_dir)
                if base is None or cur is None:
                    return None
                return compare_fingerprints(base, cur)

            orch = RetrainOrchestrator(
                trigger,
                make_retrain(watch, shift),
                lambda d_: reg.poll(watch),
                verify_fn=verify,
                watch_root=watch,
                admission_log_path=adm_path,
                admission_min_misses=2,
                max_stage_attempts=2,
                stage_backoff_s=0.01,
                cycle_backoff_s=0.2,
                max_cycle_backoff_s=2.0,
            )
            result = orch.run_cycle()
            assert result.ok and result.triggered, (
                f"happy-path cycle failed: {result}"
            )
            assert reg.version() == "v0002", "hot-reload did not land"
            assert not orch.alarm_latched, "clean cycle must clear latch"
            retrain_cycle_s = result.cycle_s
            admitted = result.plan.admitted.get("userId", [])
            assert set(admitted) >= {"newuser1", "newuser2"}, (
                f"repeat-missed entities not promoted: {admitted}"
            )

            # entity-keyed carry: same KEY -> same row, despite the new
            # export's reordered entity vocabulary
            old = load_game_model_auto(v1)
            new = load_game_model_auto(os.path.join(watch, "v0002"))
            ov, nv = old[4]["userId"], new[4]["userId"]
            assert set(nv) > set(ov), "admitted entities missing"
            for k in ov:
                np.testing.assert_allclose(
                    np.asarray(new[0]["per-user"])[nv[k]],
                    np.asarray(old[0]["per-user"])[ov[k]],
                    atol=1e-12,
                    err_msg=f"entity {k!r} row not carried by key",
                )
            # admitted entities now score through the RE path
            assert "newuser1" in reg.current.engine.re_vocabs["userId"]

            # -- post-retrain drift: quiet on the SAME shifted traffic --
            eng2 = reg.current.engine
            eng2.drift = DriftMonitor(
                try_load_fingerprint(os.path.join(watch, "v0002")),
                registry=eng2.stats.registry,
                psi_alarm=0.25,
                check_every_rows=256,
                min_rows=128,
                sample_every=1,
            )
            for _ in range(8):
                eng2.score_arrays({"s": fresh_data(64, shift)})
            assert eng2.drift.checks >= 1 and eng2.drift.alarms == 0, (
                "post-retrain drift still alarming: "
                f"{eng2.drift.last_report}"
            )
            psi_after = eng2.drift.last_report["psi_max"]
            assert psi_after < 0.25

            # -- variant 1: corrupt warm start -> finiteness gate -------
            forced["on"] = True
            with inject(
                FaultSpec("retrain.warm_start", "corrupt", nth=1, count=4)
            ):
                r1 = orch.run_cycle()
            assert not r1.ok and r1.stage == "retrain", r1
            assert reg.version() == "v0002", "old model must keep serving"
            assert orch.alarm_latched, "failed cycle must latch the alarm"

            # backoff gates the next cycle until force overrides
            r_skip = orch.run_cycle()
            assert r_skip.skipped and r_skip.next_retry_s > 0, r_skip

            # -- variant 2: export dies mid-write -> no manifest --------
            with inject(
                FaultSpec("retrain.export", "raise", nth=1, count=4)
            ):
                r2 = orch.run_cycle(force=True)
            assert not r2.ok and r2.stage == "retrain", r2
            assert latest_version_dir(watch).endswith("v0002"), (
                "a manifest-less partial export must stay invisible"
            )
            assert reg.version() == "v0002"

            # -- variant 3: torn-but-sealed export -> gate + breaker ----
            with inject(
                FaultSpec("retrain.export", "corrupt", nth=1)
            ):
                r3 = orch.run_cycle(force=True)
            assert not r3.ok and r3.stage == "export_gate", r3
            # the torn export is sealed (manifest-bearing) and newest
            bad = latest_version_dir(watch)
            assert not bad.endswith("v0002"), "torn export not on disk"
            # serving polls hit the torn export until its breaker opens
            for _ in range(4):
                assert reg.poll(watch) is None
                if reg.breaker.state(bad) in ("open", "half_open"):
                    break
            assert reg.breaker.state(bad) == "open", (
                reg.breaker.snapshot()
            )
            assert reg.version() == "v0002"

            # -- recovery: a good retrain is NOT blocked by the
            # quarantined bad directory (quarantine is per-dir)
            r4 = orch.run_cycle(force=True)
            assert r4.ok, f"recovery cycle failed: {r4}"
            good = os.path.basename(r4.export_dir.rstrip(os.sep))
            assert reg.version() == good, (
                "good export must load past the quarantined one"
            )
            assert not orch.alarm_latched
        finally:
            stop.set()
            t.join(timeout=5.0)

        assert not client_errors, (
            f"dropped requests during lifecycle: {client_errors[:3]}"
        )
        assert client_scores[0] > 0, "traffic thread never scored"
        out = {
            "retrain_cycle_s": round(retrain_cycle_s, 4),
            "alarm_latency_rows": shifted_rows,
            "admitted_entities": len(admitted),
            "psi_after_retrain": psi_after,
            "client_scores": client_scores[0],
            "client_errors": 0,
            "failed_cycles": 3,
        }
    return out


# ---------------------------------------------------------------------------
# drill: whole-replica loss under live multi-tenant load (docs/FRONTEND.md)
# ---------------------------------------------------------------------------


def drill_replica_loss(smoke: bool = True) -> dict:
    """The serving fabric's survival drill: kill one of two engine
    replicas (``replica.route`` armed raise-mode) while live multi-
    tenant load flows through the TenantManager's shared admission
    queue. The router must fail every batch over to the survivor with
    ZERO lost requests, record the failover, open the dead replica's
    breaker (no traffic burned on a corpse), keep every tenant's SLO
    ledger honest (every accepted request lands in a tracker), and —
    once the fault clears and the backoff elapses — route traffic back
    to the recovered replica."""
    import threading

    from photon_ml_tpu.frontend.replicas import ReplicaRouter
    from photon_ml_tpu.frontend.tenants import TenantManager
    from photon_ml_tpu.serving.engine import SharedCompileCache

    rng = np.random.default_rng(17)
    cache = SharedCompileCache()
    engines = [
        build_drill_engine(np.random.default_rng(17))
        for _ in range(2)
    ]
    # same-shaped replicas share the AOT ladder: give both the process-
    # style cache and count the shared hits as part of the drill
    for e in engines:
        e._shared_cache = cache
    calls = {"r0": 0, "r1": 0}
    clock = threading.Lock()

    def replica_fn(name, engine):
        def score(reqs):
            with clock:
                calls[name] += 1
            return engine.score(reqs)
        return score

    router = ReplicaRouter(
        [(n, replica_fn(n, e)) for n, e in zip(("r0", "r1"), engines)],
        failure_threshold=2,
        backoff_s=0.3,
    )
    failovers: List[tuple] = []
    router.on_failover = lambda frm, to, err: failovers.append((frm, to))
    tm = TenantManager(max_batch=16, max_wait_ms=0.5, queue_depth=4096)
    tm.add_tenant("gold", router.score, priority=2, max_outstanding=512)
    tm.add_tenant("free", router.score, priority=0, max_outstanding=512)
    n_req = 120 if smoke else 600
    try:
        # (1) warm both replicas under clean load
        warm = [
            tm.submit(
                "gold" if i % 2 else "free", make_drill_request(rng)
            )
            for i in range(32)
        ]
        for f in warm:
            assert np.isfinite(f.result(timeout=30.0))
        with clock:
            assert calls["r0"] > 0 and calls["r1"] > 0, (
                "least-outstanding routing must spread clean load"
            )
            before = dict(calls)

        # (2) kill r0 mid-flight: every request still completes
        futs = []
        with inject(
            FaultSpec("replica.route", "raise", nth=1, count=-1, key="r0")
        ):
            for i in range(n_req):
                futs.append(
                    tm.submit(
                        "gold" if i % 2 else "free",
                        make_drill_request(rng),
                    )
                )
            results = [f.result(timeout=60.0) for f in futs]
        assert all(np.isfinite(r) for r in results), (
            "requests lost to the replica loss"
        )
        assert router.failovers >= 1 and failovers, (
            "the router must record the failover"
        )
        assert router.last_failover_s is not None
        health = router.health()
        assert health["replicas"]["r0"]["state"] == "open", (
            "the dead replica's breaker must open"
        )
        assert health["up"] == 1

        # (3) honest per-tenant ledgers: every accepted request is in
        # its tenant's SLO tracker, none vanished
        slo = tm.slo_snapshot()
        counted = sum(s["total_requests"] for s in slo.values())
        submitted = sum(
            st.submitted for st in tm.tenants().values()
        )
        assert counted == submitted == n_req + 32, (
            f"SLO ledger counted {counted} of {submitted} accepted"
        )

        # (4) fault cleared: r0 takes traffic again once its backoff
        # elapses. The backoff DOUBLES on every failed half-open probe,
        # and under suite load the fault window in (2) can outlast the
        # initial 0.3s — burning one or more probes — so a fixed sleep
        # is a race. Poll with a generous wall-clock deadline instead:
        # send small waves until the breaker closes and r0's call
        # counter moves.
        with clock:
            r0_at_fault_end = calls["r0"]
        n_rec = 0
        deadline = time.monotonic() + 15.0
        while True:
            rec = [
                tm.submit("gold", make_drill_request(rng))
                for _ in range(8)
            ]
            n_rec += len(rec)
            for f in rec:
                assert np.isfinite(f.result(timeout=30.0))
            with clock:
                rejoined = calls["r0"] > r0_at_fault_end
            if (
                rejoined
                and router.health()["replicas"]["r0"]["state"] == "closed"
            ):
                break
            assert time.monotonic() < deadline, (
                "recovered replica must rejoin the rotation"
            )
            time.sleep(0.1)
        shared = cache.snapshot()
        return {
            "requests": n_req + 32 + n_rec,
            "failovers": int(router.failovers),
            "last_failover_s": float(router.last_failover_s),
            "replica_calls": dict(calls),
            "shared_compile_hits": int(shared["hits"]),
            "shared_compiles": int(shared["compiles"]),
            "tenant_p99_ms": {
                t: s["p99_ms"] for t, s in tm.slo_snapshot().items()
            },
        }
    finally:
        tm.drain(timeout=10.0)


# ---------------------------------------------------------------------------
# drill: trace survival under replica loss (docs/OBSERVABILITY.md)
# ---------------------------------------------------------------------------


def drill_trace_loss(smoke: bool = True) -> dict:
    """Request-causality survival drill: traced load flows through the
    full fabric (frontend -> tenant envelope -> shared batcher ->
    replica router) while one replica is killed mid-stream, then BOTH
    are killed for a window. Afterwards the event log must reconstruct:

    - a COMPLETE timeline for every request that scored (including the
      failover-touched ones, flagged ``failover`` with the failed hop
      recorded),
    - an explicitly-marked TRUNCATED timeline for every request the
      fabric lost (errored batches — no silent disappearance),
    - ZERO orphan spans: every request-scoped record is claimed by
      exactly the timeline of the trace that caused it,
    - 100%-kept exemplars for the error/failover classes, so the rings
      hand back exactly the trace ids worth investigating.
    """
    import json as _json
    import tempfile as _tempfile

    from photon_ml_tpu.frontend.replicas import ReplicaRouter
    from photon_ml_tpu.frontend.server import (
        FrontendClient,
        FrontendServer,
    )
    from photon_ml_tpu.frontend.tenants import TenantManager
    from photon_ml_tpu.obs import exemplars as _exemplars
    from photon_ml_tpu.obs import reqtrace as _reqtrace

    n_phase = 16 if smoke else 64

    def scorer(reqs):
        return np.asarray(
            [float(r.offset) for r in reqs], dtype=np.float64
        )

    prev_store = _exemplars.set_store(
        _exemplars.ExemplarStore(fast_fraction=1.0, ring_size=64)
    )
    td = _tempfile.mkdtemp(prefix="trace-loss-")
    try:
        with obs.trace(td):
            router = ReplicaRouter(
                [("r0", scorer), ("r1", scorer)],
                failure_threshold=2,
                backoff_s=30.0,  # the corpse stays benched for the drill
            )
            tm = TenantManager(max_batch=8, max_wait_ms=0.5)
            tm.add_tenant("t0", router.score)
            with FrontendServer(tm.submit, default_tenant="t0") as srv:
                with FrontendClient("127.0.0.1", srv.port) as cli:
                    # phase A: clean traced load
                    for i in range(n_phase):
                        r = cli.call({
                            "trace": f"tA-{i}", "offset": float(i),
                            "features": {},
                        })
                        assert r.get("score") == float(i), r
                    # phase B: r0 dies mid-stream -> failover, requests
                    # still complete
                    with inject(FaultSpec(
                        "replica.route", "raise", nth=1, count=-1,
                        key="r0",
                    )):
                        for i in range(n_phase):
                            r = cli.call({
                                "trace": f"tB-{i}", "offset": float(i),
                                "features": {},
                            })
                            assert r.get("score") == float(i), r
                    # phase C: EVERY replica dies -> the fabric answers
                    # with errors, never silently drops
                    with inject(FaultSpec(
                        "replica.route", "raise", nth=1, count=-1,
                    )):
                        for i in range(n_phase):
                            r = cli.call({
                                "trace": f"tC-{i}", "offset": float(i),
                                "features": {},
                            })
                            assert "error" in r and "score" not in r, r
            tm.drain(timeout=10.0)

        with open(os.path.join(td, "events.jsonl"), encoding="utf-8") as f:
            records = [_json.loads(line) for line in f if line.strip()]

        timelines = []
        failovers = 0
        for tid in _reqtrace.trace_ids(records):
            tl = _reqtrace.reconstruct_timeline(records, tid)
            assert tl is not None, tid
            timelines.append(tl)
            phase = tl["trace"][:2]
            if phase in ("tA", "tB"):
                assert tl["complete"] and not tl["truncated"], (
                    f"{tid}: scored request must reconstruct complete: "
                    f"{tl['events']}"
                )
            else:
                assert tl["truncated"] and not tl["complete"], (
                    f"{tid}: lost request must be explicitly truncated"
                )
                assert tl["error"], f"{tid}: truncation carries no error"
            if tl["failover"]:
                failovers += 1
                assert any(h["error"] for h in tl["hops"]), (
                    f"{tid}: failover flag without a failed hop"
                )
        assert len(timelines) == 3 * n_phase, (
            f"{len(timelines)} timelines for {3 * n_phase} requests"
        )
        assert failovers >= 1, "no failover-touched timeline captured"
        orphans = _reqtrace.find_orphans(records, timelines)
        assert not orphans, (
            f"{len(orphans)} orphan record(s): "
            f"{[o.get('name') for o in orphans[:5]]}"
        )

        st = _exemplars.store()
        error_traces = {
            e["trace"] for e in st.lookup(cls="error")
        }
        assert any(t.startswith("tC-") for t in error_traces), (
            "error exemplar ring holds no lost-request trace"
        )
        failover_traces = {
            e["trace"] for e in st.lookup(cls="failover")
        }
        assert any(t.startswith("tB-") for t in failover_traces), (
            "failover exemplar ring holds no failover-touched trace"
        )
        return {
            "requests": 3 * n_phase,
            "complete_timelines": sum(
                1 for t in timelines if t["complete"]
            ),
            "truncated_timelines": sum(
                1 for t in timelines if t["truncated"]
            ),
            "failover_timelines": failovers,
            "orphan_records": 0,
            "error_exemplars": len(error_traces),
        }
    finally:
        _exemplars.set_store(prev_store)


DRILLS: Dict[str, Callable[[bool], dict]] = {
    "site_registry": drill_site_registry,
    "serving_score": drill_serving_score,
    "reload_breaker": drill_reload_breaker,
    "overload_shed": drill_overload,
    "pipeline_decode": drill_pipeline_decode,
    "async_checkpoint": drill_async_checkpoint,
    "collective_seam": drill_collective_seam,
    "checkpoint_integrity": drill_checkpoint_integrity,
    # multi-host resilience (docs/MULTIHOST.md) — all run single-process
    # on CPU via armed collective.stall / heartbeat.miss /
    # checkpoint.shard_write faults
    "collective_stall": drill_collective_stall,
    "heartbeat_loss": drill_heartbeat_loss,
    "host_loss_recovery": drill_host_loss_recovery,
    "torn_shard": drill_torn_shard,
    # overlap-scaled partitioning (docs/PARALLEL.md): one deliberately
    # slow shard -> straggler-attributed collective.stall, run completes
    "shard_skew": drill_shard_skew,
    # model-quality observability (docs/OBSERVABILITY.md): covariate
    # shift alarms, quiet unshifted replay, flight-recorded snapshot,
    # quality.baseline fault degradation
    "drift_alarm": drill_drift_alarm,
    # entity-sharded serving + tiered cache (docs/SERVING.md): a single-
    # shard routing fault degrades its entities to fixed-effect-only
    # with zero lost requests and an honest p99 ledger; a failed cache
    # promotion leaves entities cold, never corrupt
    "shard_fault": drill_shard_fault,
    # serving fabric (docs/FRONTEND.md): a whole replica dies under
    # live multi-tenant load -> the router fails over with zero lost
    # requests, the corpse's breaker opens, SLO ledgers stay honest,
    # and the recovered replica rejoins after its backoff
    "replica_loss": drill_replica_loss,
    # request causality (docs/OBSERVABILITY.md): mid-stream replica kill
    # under traced load -> complete timelines for everything that
    # scored, explicitly-truncated ones for what the fabric lost, zero
    # orphan spans, error/failover exemplars 100%-kept
    "trace_loss": drill_trace_loss,
    # the self-healing lifecycle loop (docs/LIFECYCLE.md): drift alarm
    # -> entity-keyed warm-started retrain with admitted entities ->
    # manifest-gated export -> breaker-guarded hot-reload, zero dropped
    # requests; failure variants leave the old model serving
    "lifecycle": drill_lifecycle,
}

# the subset `photon-chaos drill --multihost-smoke` runs: every drill of
# the elastic multi-host layer, nothing else — the schedule an operator
# points at a pod deployment host before trusting it with a long run
MULTIHOST_DRILLS = (
    "collective_seam",
    "collective_stall",
    "heartbeat_loss",
    "host_loss_recovery",
    "torn_shard",
    "shard_skew",
)


def run_drills(
    smoke: bool = True,
    include: Optional[List[str]] = None,
    logger=None,
) -> dict:
    """Execute the scripted schedule; returns the drill report. A drill
    failure is captured (the remaining drills still run) and flips
    ``ok`` — the lab exits nonzero on any failed drill."""
    results: List[DrillResult] = []
    names = include if include else list(DRILLS)
    unknown = [n for n in names if n not in DRILLS]
    if unknown:
        raise ValueError(
            f"unknown drill(s) {unknown}; available: {sorted(DRILLS)}"
        )
    for name in names:
        t0 = time.perf_counter()
        try:
            details = DRILLS[name](smoke)
            res = DrillResult(
                name=name,
                passed=True,
                duration_s=round(time.perf_counter() - t0, 3),
                details=details,
            )
        except _Skip as e:
            res = DrillResult(
                name=name,
                passed=True,
                skipped=True,
                duration_s=round(time.perf_counter() - t0, 3),
                reason=str(e),
            )
        except BaseException as e:  # noqa: BLE001 — reported, not raised
            res = DrillResult(
                name=name,
                passed=False,
                duration_s=round(time.perf_counter() - t0, 3),
                reason=f"{type(e).__name__}: {e}",
            )
        if logger is not None:
            status = (
                "SKIP" if res.skipped else "PASS" if res.passed else "FAIL"
            )
            logger(f"[{status}] {name} ({res.duration_s}s) {res.reason}")
        results.append(res)
    ran = [r for r in results if not r.skipped]
    return {
        "drills": [r.to_dict() for r in results],
        "ran": len(ran),
        "passed": sum(r.passed for r in ran),
        "skipped": sum(r.skipped for r in results),
        "ok": all(r.passed for r in ran),
    }
