"""Host-loss recovery contract for elastic multi-host training.

The reference outlives a dead executor because Spark reschedules its
tasks; a multi-controller SPMD pod has no scheduler — when one process
dies or wedges, every surviving process's next collective blocks
forever. This module defines what the survivors do instead
(docs/MULTIHOST.md):

1. DETECT — the heartbeat monitor (:mod:`photon_ml_tpu.parallel.
   heartbeat`) or a collective watchdog timeout (:mod:`photon_ml_tpu.
   parallel.multihost`) raises :class:`HostLossDetected` at a pass
   boundary.
2. CHECKPOINT — the training loop writes a FINAL sharded checkpoint
   (each survivor writes its shard; the quorum manifest makes the step
   restorable) plus a ``host-loss.json`` marker.
3. EXIT — the driver exits with :data:`HOST_LOSS_EXIT_CODE`, distinct
   from success (0), generic failure (1), config errors (2), and the
   failed-drain serving exit (3), so a cluster manager can tell "restart
   me, possibly smaller" from "do not retry".
4. RESUME — a restart at the SAME OR SMALLER world size loads the
   sharded checkpoint (entity-keyed shards re-shard onto the new
   layout) and reproduces the uninterrupted run bit-for-bit.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

# Distinct restart-me exit status (see module doc for the taxonomy).
# Past the conventional shell/signal codes so it cannot collide with a
# Python traceback exit (1), argparse (2), or the serving drain exit (3).
HOST_LOSS_EXIT_CODE = 43

HOST_LOSS_MARKER = "host-loss.json"


class HostLossDetected(RuntimeError):
    """A peer process is dead or unreachable (missed heartbeats, or a
    collective that timed out past its retry budget). Carries the lost
    peer indices so markers/logs can attribute the loss."""

    def __init__(self, peers: Sequence[int], reason: str = "heartbeat"):
        peers = sorted(int(p) for p in peers)
        super().__init__(
            f"host loss detected ({reason}): peer process(es) {peers} "
            "missing — survivors checkpoint and exit "
            f"{HOST_LOSS_EXIT_CODE} for an elastic restart"
        )
        self.peers: List[int] = list(peers)
        self.reason = reason


def is_host_loss(exc: BaseException) -> bool:
    """True when ``exc`` should map to the host-loss exit contract:
    a :class:`HostLossDetected`, or a collective failure whose cause
    chain bottoms out in one (retry wrappers re-raise with the original
    as ``__cause__``)."""
    # import the real classes lazily (keeps resilience free of a
    # parallel dependency at import time) and match by isinstance — a
    # name match would misclassify an unrelated library's
    # "CollectiveTimeout" as host loss and trigger the restart-me exit
    try:
        from photon_ml_tpu.parallel.multihost import (
            CollectiveAbandoned,
            CollectiveTimeout,
        )

        collective_types: tuple = (CollectiveTimeout, CollectiveAbandoned)
    except ImportError:
        collective_types = ()
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, HostLossDetected):
            return True
        if collective_types and isinstance(exc, collective_types):
            return True
        exc = exc.__cause__ or exc.__context__
    return False


def write_host_loss_marker(
    checkpoint_dir: str,
    step: int,
    peers: Sequence[int],
    reason: str = "heartbeat",
    final_checkpoint: bool = True,
) -> str:
    """Record that the run exited on host loss. Advisory, like
    ``preempted.json`` — resume works off the checkpoints alone — but
    tells operators and restart tooling WHY the run ended and which
    peers were lost. ``final_checkpoint`` False records that the
    survivors' final save FAILED (the marker is still written — resume
    then falls back to the newest complete quorum step)."""
    os.makedirs(checkpoint_dir, exist_ok=True)
    path = os.path.join(checkpoint_dir, HOST_LOSS_MARKER)
    with open(path, "w") as f:
        json.dump(
            {
                "step": int(step),
                "peers": sorted(int(p) for p in peers),
                "reason": reason,
                "exit_code": HOST_LOSS_EXIT_CODE,
                "final_checkpoint": bool(final_checkpoint),
            },
            f,
        )
    from photon_ml_tpu import obs

    obs.registry().inc("resilience.host_losses")
    obs.emit_event(
        "resilience.host_loss_marker_written",
        cat="resilience",
        step=int(step),
        peers=sorted(int(p) for p in peers),
        reason=reason,
    )
    return path


def read_host_loss_marker(checkpoint_dir: str) -> Optional[dict]:
    path = os.path.join(checkpoint_dir, HOST_LOSS_MARKER)
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def clear_host_loss_marker(checkpoint_dir: str) -> None:
    try:
        os.remove(os.path.join(checkpoint_dir, HOST_LOSS_MARKER))
    except FileNotFoundError:
        pass
