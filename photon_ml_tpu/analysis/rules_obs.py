"""PL006 obs-taxonomy: span/event/metric name literals must be
documented names.

Origin: the observability layer records ANY dotted name happily —
``reg.inc("sevring.request_ms")`` compiles, runs, and silently orphans
its dashboard panel, its Prometheus series, and its sentinel direction
rule. The taxonomy that makes the obs surface navigable lived in
docs/OBSERVABILITY.md prose; ``obs.taxonomy`` (the machine-readable
registry this rule binds) turned it into code, and this rule makes a
name outside it a build-time error.

Checked call shapes:

- ``obs.span("...")`` / ``span("...")`` and ``obs.emit_event`` — the
  tracer surface;
- registry instrument calls — ``.inc`` / ``.set_gauge`` / ``.observe``
  / ``.counter`` / ``.gauge`` / ``.histogram`` on a receiver that is
  recognizably a metrics registry (``reg``, ``registry()``,
  ``obs.registry()``, ``self._registry``, ...).

f-string names validate their STATIC prefix (``f"hbm.{label}.peak"``
passes via the ``hbm.`` subsystem); fully-dynamic names are skipped —
the rule gates what it can see, not what it can't.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from photon_ml_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    call_name,
    dotted_name,
)

__all__ = ["ObsTaxonomyRule"]

_TRACER_FNS = frozenset({"span", "emit_event"})
_REGISTRY_METHODS = frozenset(
    {"inc", "set_gauge", "observe", "counter", "gauge", "histogram"}
)
# receivers that denote a metrics registry: the canonical accessor
# (…registry()) or a conventional binding of it
_REGISTRY_RECEIVER_RE = re.compile(
    r"(^|\.)(registry\(\)|_?reg|_registry|metrics_registry)$"
)


def _static_name(node: ast.AST) -> Optional[str]:
    """The literal name, or the static PREFIX of an f-string name
    (marked with a trailing '{'), or None when fully dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        head = []
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(
                part.value, str
            ):
                head.append(part.value)
            else:
                prefix = "".join(head)
                return prefix + "{" if prefix else None
        return "".join(head)
    return None


class ObsTaxonomyRule(Rule):
    id = "PL006"
    name = "obs-taxonomy"
    severity = "warning"
    hint = (
        "use a documented <subsystem>.<thing> name (obs.taxonomy "
        "lists the subsystems; docs/OBSERVABILITY.md describes them) "
        "or, for a genuinely new subsystem, add its entry to "
        "obs.taxonomy.TAXONOMY alongside its doc blurb"
    )
    origin = (
        "Metric/span names are the JOIN KEY between the code, the "
        "dashboards, photon-obs merge, Prometheus, and the bench "
        "sentinel's direction rules — and the registry accepts any "
        "string. A typo'd subsystem records forever and renders "
        "nowhere; the taxonomy existed only as prose until "
        "obs.taxonomy made it checkable."
    )

    def _name_arg(self, call: ast.Call) -> Optional[ast.AST]:
        last, full = call_name(call)
        if last in _TRACER_FNS:
            # plain span()/emit_event() or obs.span()/tracer-qualified
            if call.args:
                return call.args[0]
            return None
        if last in _REGISTRY_METHODS and full and "." in full:
            receiver = full.rsplit(".", 1)[0]
            if _REGISTRY_RECEIVER_RE.search(receiver):
                if call.args:
                    return call.args[0]
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        from photon_ml_tpu.obs import taxonomy

        for call in ctx.walk_calls():
            name_node = self._name_arg(call)
            if name_node is None:
                continue
            name = _static_name(name_node)
            if name is None:
                continue
            if name.endswith("{"):
                prefix = name[:-1]
                if taxonomy.valid_prefix(prefix):
                    continue
                shown = prefix + "…"
            else:
                if taxonomy.matches(name):
                    continue
                shown = name
            yield self.finding(
                ctx,
                call,
                f"obs name {shown!r} is outside the documented "
                "taxonomy (obs.taxonomy / docs/OBSERVABILITY.md): it "
                "will record but never render — dashboards, merge "
                "output, and sentinel rules key on documented "
                "subsystem prefixes",
            )
