"""PL004 trace-unsafe-host-op: host operations inside traced code.

JAX traces ``jit``/``shard_map``/``lax.scan``/``lax.while_loop`` bodies
ONCE and replays the compiled program; host operations inside them
either crash on a tracer (``.item()``, ``float()``), silently freeze a
trace-time value into the compiled program (``time.time()``,
``np.asarray`` on a constant), or fire once at trace time and never
again (``print``). This repo has paid the tab repeatedly: the PR 9
streamed solver had to drop to ``disable_jit`` because a host callback
dispatched nested jit from the runtime thread; the PR 8 device-resident
loops only work because every per-pass decision (divergence guard,
tolerance check) was rebuilt as in-program lax ops rather than host
reads.

Detection is one level interprocedural: a function passed to a tracing
transform is traced; local functions it calls (same module) are checked
too. Functions handed to the SANCTIONED host escapes
(``jax.pure_callback`` / ``io_callback`` / ``jax.debug.callback`` /
``jax.debug.print``) are exempt — those run host-side by design.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from photon_ml_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    call_name,
    dotted_name,
)

__all__ = ["TraceUnsafeHostOp"]

# tracing transforms: (last callee name) -> indexes of the traced
# function arguments
_TRACING_ARG_INDEXES: Dict[str, Tuple[int, ...]] = {
    "jit": (0,),
    "shard_map": (0,),
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "vmap": (0,),
    "pmap": (0,),
}

_TRACING_DECORATORS = frozenset({"jit", "shard_map", "vmap", "pmap"})

# the sanctioned host escapes: functions passed here RUN on host
_CALLBACK_SINKS = frozenset(
    {"pure_callback", "io_callback", "callback", "host_callback"}
)

_TIME_FNS = frozenset(
    {"time", "perf_counter", "monotonic", "sleep", "process_time"}
)
_NUMPY_BASES = frozenset({"np", "numpy", "onp"})
_JNP_BASES = frozenset({"jnp", "jax.numpy"})


def _decorator_traces(dec: ast.AST) -> bool:
    """@jit / @jax.jit / @partial(jax.jit, ...) and friends."""
    if isinstance(dec, ast.Call):
        last, _ = call_name(dec)
        if last in _TRACING_DECORATORS:
            return True
        if last == "partial" and dec.args:
            inner = dotted_name(dec.args[0])
            if inner and inner.rsplit(".", 1)[-1] in _TRACING_DECORATORS:
                return True
        return False
    name = dotted_name(dec)
    return bool(name) and name.rsplit(".", 1)[-1] in _TRACING_DECORATORS


class TraceUnsafeHostOp(Rule):
    id = "PL004"
    name = "trace-unsafe-host-op"
    severity = "warning"
    hint = (
        "keep the body pure jax: jax.debug.print for prints, "
        "jax.pure_callback/io_callback for genuine host work, carry "
        "values in the loop state instead of .item()/float() reads, "
        "and hoist time/np host ops outside the traced function"
    )
    origin = (
        "The PR 9 streamed solver deadlocked dispatching nested jit "
        "from a callback thread and had to fall back to disable_jit; "
        "PR 8's device-resident loops exist because every host read "
        "inside the solve path (objective checks, guards) forced a "
        "dispatch round trip. Host ops inside traced bodies either "
        "crash on tracers, freeze trace-time values into the program, "
        "or fire once at trace time — none of which the author meant."
    )

    def _module_functions(
        self, ctx: ModuleContext
    ) -> Dict[str, ast.AST]:
        fns: Dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.setdefault(node.name, node)
        return fns

    def _traced_and_exempt(
        self, ctx: ModuleContext, fns: Dict[str, ast.AST]
    ) -> Tuple[List[Tuple[ast.AST, str]], Set[str]]:
        """([(function node, why-traced)], exempt function names)."""
        traced: List[Tuple[ast.AST, str]] = []
        traced_ids: Set[int] = set()
        exempt: Set[str] = set()

        def add(fn_node: ast.AST, why: str) -> None:
            if id(fn_node) not in traced_ids:
                traced_ids.add(id(fn_node))
                traced.append((fn_node, why))

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _decorator_traces(dec):
                        add(node, f"decorated with a tracing transform")
            if not isinstance(node, ast.Call):
                continue
            last, _ = call_name(node)
            if last in _CALLBACK_SINKS:
                for arg in node.args:
                    name = dotted_name(arg)
                    if name:
                        exempt.add(name.rsplit(".", 1)[-1])
                continue
            indexes = _TRACING_ARG_INDEXES.get(last or "")
            if not indexes:
                continue
            for i in indexes:
                if i >= len(node.args):
                    continue
                arg = node.args[i]
                if isinstance(arg, ast.Lambda):
                    add(arg, f"passed to {last}()")
                else:
                    name = dotted_name(arg)
                    fn_node = fns.get(name.rsplit(".", 1)[-1]) if name else None
                    if fn_node is not None:
                        add(fn_node, f"passed to {last}()")
        return traced, exempt

    def _params_of(self, fn: ast.AST) -> Set[str]:
        if isinstance(fn, ast.Lambda):
            a = fn.args
        elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = fn.args
        else:
            return set()
        names = set()
        for group in (a.posonlyargs, a.args, a.kwonlyargs):
            names.update(p.arg for p in group)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
        return names

    def _host_op(
        self, call: ast.Call, params: Set[str]
    ) -> Optional[str]:
        """A description of the host op, or None."""
        # method-shaped host ops fire on ANY receiver (state[0].item()
        # has no resolvable dotted name but is exactly the bug)
        if isinstance(call.func, ast.Attribute):
            if call.func.attr == "item":
                return (
                    ".item() forces a device sync and crashes on tracers"
                )
            if call.func.attr == "block_until_ready":
                return (
                    ".block_until_ready() forces a host sync inside a "
                    "trace"
                )
        last, full = call_name(call)
        if last is None:
            return None
        base = full.rsplit(".", 1)[0] if full and "." in full else ""
        if last == "print":
            return "print() fires at trace time only (use jax.debug.print)"
        if last == "device_get" or full == "jax.device_get":
            return "jax.device_get() materializes on host mid-trace"
        if last in _TIME_FNS and base.rsplit(".", 1)[-1] == "time":
            return (
                f"time.{last}() reads the HOST clock at trace time and "
                "freezes it into the compiled program"
            )
        if last in ("asarray", "array") and base.rsplit(".", 1)[-1] in (
            _NUMPY_BASES
        ):
            return (
                f"{base}.{last}() materializes a host numpy array "
                "(crashes on tracers; freezes constants otherwise)"
            )
        if last in ("float", "int") and len(call.args) == 1:
            arg = call.args[0]
            if isinstance(arg, ast.Name) and arg.id in params:
                return (
                    f"{last}() on a traced-function parameter "
                    "concretizes a tracer (TracerConversionError at "
                    "trace time)"
                )
            if isinstance(arg, ast.Call):
                inner = dotted_name(arg.func)
                if inner and inner.split(".", 1)[0] in ("jnp", "jax"):
                    return (
                        f"{last}() on a jax expression concretizes a "
                        "tracer"
                    )
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        fns = self._module_functions(ctx)
        traced, exempt = self._traced_and_exempt(ctx, fns)
        if not traced:
            return
        # one interprocedural level: local functions called from traced
        # bodies, minus the sanctioned callback sinks' targets
        seen_fn_ids: Set[int] = {id(fn) for fn, _ in traced}
        second_level: List[Tuple[ast.AST, str]] = []
        for fn_node, why in traced:
            for node in ast.walk(fn_node):
                if not isinstance(node, ast.Call):
                    continue
                # follow PLAIN-name calls only: resolving `obj.method()`
                # by last name would bind to any same-named method of
                # any class in the module (observed false positive:
                # a jitted chunk pass's obj.hessian_diagonal() resolved
                # to StreamingObjective's HOST-side method of the same
                # name)
                if not isinstance(node.func, ast.Name):
                    continue
                last, _ = call_name(node)
                callee = fns.get(last or "")
                if (
                    callee is not None
                    and last not in exempt
                    and id(callee) not in seen_fn_ids
                ):
                    seen_fn_ids.add(id(callee))
                    fn_name = getattr(fn_node, "name", "<lambda>")
                    second_level.append(
                        (callee, f"called from traced {fn_name}()")
                    )
        for fn_node, why in traced + second_level:
            if getattr(fn_node, "name", None) in exempt:
                continue
            params = self._params_of(fn_node)
            # nested defs inside the traced body that are THEMSELVES
            # handed to callback sinks stay exempt; everything else in
            # the body is trace-context
            exempt_here: Set[int] = set()
            for node in ast.walk(fn_node):
                if isinstance(node, ast.Call):
                    last, _ = call_name(node)
                    if last in _CALLBACK_SINKS:
                        for arg in node.args:
                            name = dotted_name(arg)
                            if name and name.rsplit(".", 1)[-1] in fns:
                                exempt_here.add(
                                    id(fns[name.rsplit(".", 1)[-1]])
                                )
            skip_subtrees = exempt_here
            stack = list(
                ast.iter_child_nodes(fn_node)
            )
            while stack:
                node = stack.pop()
                if id(node) in skip_subtrees:
                    continue
                if isinstance(node, ast.Call):
                    desc = self._host_op(node, params)
                    if desc is not None:
                        yield self.finding(
                            ctx,
                            node,
                            f"host op inside traced code "
                            f"({why}): {desc}",
                        )
                stack.extend(ast.iter_child_nodes(node))
        return
