"""PL002 exception-match-by-name and PL007 swallowed-retryable.

Two exception-handling bug classes from this repo's own history:

- **PL002**: classifying an exception by its type NAME
  (``type(e).__name__ == "CollectiveTimeout"``) or by message
  containment (``"..." in str(e)``) instead of ``isinstance``. The
  ``is_host_loss`` bug (PR 11 review #5): a foreign library's
  same-named error anywhere in a cause chain triggered the restart-me
  exit 43.

- **PL007**: an ``except Exception/OSError/bare: pass``-or-log-only
  handler wrapping calls the resilience layer classifies as transient
  (OSError-shaped durability I/O). The retry seam
  (``resilience.retry.retry_call``) exists precisely for those calls;
  swallowing them hides real turbulence from the retry budget, the
  obs counters, and the chaos drills.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from photon_ml_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    call_name,
    dotted_name,
)

__all__ = ["ExceptionMatchByName", "SwallowedRetryable"]


def _is_type_name_expr(node: ast.AST) -> bool:
    """``type(x).__name__`` or ``x.__class__.__name__``."""
    if not (isinstance(node, ast.Attribute) and node.attr == "__name__"):
        return False
    base = node.value
    if isinstance(base, ast.Call):
        last, _ = call_name(base)
        return last == "type"
    return isinstance(base, ast.Attribute) and base.attr == "__class__"


def _is_str_of(node: ast.AST, names: Set[str]) -> bool:
    """``str(x)`` where x is one of ``names``."""
    if not isinstance(node, ast.Call):
        return False
    last, _ = call_name(node)
    if last != "str" or len(node.args) != 1:
        return False
    arg = node.args[0]
    return isinstance(arg, ast.Name) and arg.id in names


class ExceptionMatchByName(Rule):
    id = "PL002"
    name = "exception-match-by-name"
    severity = "error"
    hint = (
        "classify with isinstance() against the real class (lazy-import "
        "it if the dependency direction is awkward — see "
        "resilience.hostloss.is_host_loss), or attach structured fields "
        "to the exception and match those; type names and message text "
        "are neither unique nor stable"
    )
    origin = (
        "PR 11 review #5: is_host_loss matched 'CollectiveTimeout' by "
        "type NAME anywhere in the cause chain, so an unrelated "
        "library's same-named error mapped to the host-loss restart "
        "exit (43) and restarted runs that should have failed loudly. "
        "Fixed by lazy-importing the real classes and matching with "
        "isinstance."
    )

    def _except_bound_names(self, ctx: ModuleContext) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.name:
                names.add(node.name)
        return names

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        exc_names = self._except_bound_names(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Compare):
                continue
            operands = [node.left] + list(node.comparators)
            if any(_is_type_name_expr(op) for op in operands):
                yield self.finding(
                    ctx,
                    node,
                    "exception classified by type NAME "
                    "(type(e).__name__ / __class__.__name__ "
                    "comparison): any library can define a class with "
                    "the same name, and renames break the match "
                    "silently",
                )
                continue
            # `<lit> in str(e)` / `str(e) == <lit>` on an except-bound
            # name: message-text matching is the same trap one step
            # further (messages are not API)
            if any(_is_str_of(op, exc_names) for op in operands):
                yield self.finding(
                    ctx,
                    node,
                    "exception classified by MESSAGE text "
                    "(str(e) comparison/containment): messages are "
                    "not a stable API and collide across libraries",
                )


# os/shutil/file-object operations that raise OSError and that the
# resilience layer (resilience.retry.DEFAULT_RETRY_ON) classifies as
# transient — the calls a swallow-only handler most often hides
_RETRYABLE_CALL_NAMES = frozenset(
    {
        "open",
        "remove",
        "unlink",
        "rename",
        "replace",
        "makedirs",
        "mkdir",
        "rmdir",
        "listdir",
        "rmtree",
        "copyfile",
        "copytree",
        "move",
        "feed_file",
        "read_columnar",
        "save_checkpoint",
        "save_checkpoint_sharded",
        "dump",
    }
)

_BROAD_HANDLER_TYPES = frozenset(
    {"Exception", "BaseException", "OSError", "IOError"}
)

def _is_log_call(call: ast.Call) -> bool:
    """print/warn calls, or any method on a logger-ish receiver. The
    receiver must LOOK like a logger — matching bare method names like
    .error()/.info() would classify `findings.append(error)`-shaped
    bookkeeping as logging."""
    last, full = call_name(call)
    if last in ("print", "warn", "warning"):
        return True
    if (last or "").lstrip("_") == "log":
        return True
    receiver = (
        full.rsplit(".", 1)[0].lower() if full and "." in full else ""
    )
    return "log" in receiver or "warn" in receiver


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for ty in types:
        name = dotted_name(ty)
        if name and name.rsplit(".", 1)[-1] in _BROAD_HANDLER_TYPES:
            return True
    return False


def _body_only_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler body is pass/continue/log-only: nothing is
    re-raised, returned, recorded in state, or recovered."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            if _is_log_call(stmt.value):
                continue
            return False
        return False
    return True


class SwallowedRetryable(Rule):
    id = "PL007"
    name = "swallowed-retryable"
    severity = "warning"
    hint = (
        "route the call through resilience.retry.retry_call (it "
        "backs off, records resilience.retries, and surfaces "
        "RetryBudgetExceeded), or catch the SPECIFIC terminal "
        "exception you mean to tolerate (e.g. FileNotFoundError) "
        "instead of a broad swallow"
    )
    origin = (
        "The PR 1 resilience layer exists because durability I/O fails "
        "transiently and MUST be retried against a budget, not "
        "swallowed: a try/except-pass around a checkpoint write 'works' "
        "in every test and then loses the only copy of a 40-minute "
        "run's state in production. Every broad swallow around "
        "OSError-shaped I/O found since was either a real durability "
        "hole or deserved an explicit suppression explaining why "
        "best-effort is correct there."
    )

    def _retryable_call(self, try_node: ast.Try) -> Optional[ast.Call]:
        for stmt in try_node.body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    last, _ = call_name(node)
                    if last in _RETRYABLE_CALL_NAMES:
                        return node
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            culprit = self._retryable_call(node)
            if culprit is None:
                continue
            last, _ = call_name(culprit)
            for handler in node.handlers:
                if not _handler_is_broad(handler):
                    continue
                if not _body_only_swallows(handler):
                    continue
                yield self.finding(
                    ctx,
                    handler,
                    f"broad except swallows {last}() — a call the "
                    "resilience layer classifies as transient "
                    "(OSError-shaped I/O): failures here never reach "
                    "the retry seam, its budget, or the "
                    "resilience.retries counters",
                )
