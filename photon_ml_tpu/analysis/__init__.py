"""photon-lint: a JAX/SPMD-aware static analyzer for this tree.

The reference leaned on scalac's type system to keep a 32k-LoC
distributed trainer honest; the Python/JAX rebuild catches its worst
bug classes at runtime (arm-time fault validation, the native-handle
census, collective watchdogs) — or in review. This package turns each
class the repo has ACTUALLY shipped into a build-time gate
(docs/ANALYSIS.md has the full catalog with origin stories):

====== ============================ =========================================
rule   name                         the bug it generalizes
====== ============================ =========================================
PL001  spmd-collective-divergence   PR 11 review: host-loss save ran
                                    full-world collectives from a handler
PL002  exception-match-by-name      is_host_loss matched 'CollectiveTimeout'
                                    by type NAME across libraries
PL003  unknown-fault-site           pre-PR-10 typo'd drills that tested
                                    nothing, moved from arm time to lint time
PL004  trace-unsafe-host-op         host ops inside jit/shard_map/scan/
                                    while_loop bodies (PR 8/9 lessons)
PL005  unmanaged-native-handle      PR 9 handle census, static form
PL006  obs-taxonomy                 dashboard-orphaning metric name typos
PL007  swallowed-retryable          broad swallows hiding the retry seam
PL008  span-context-drop            PR 19 trace seam: a hand-off that drops
                                    the trace id orphans the request timeline
====== ============================ =========================================

``photon-lint check`` (cli/lint.py) runs the registry over a tree,
subtracts the committed ratchet baseline
(``photon_ml_tpu/analysis/baseline.json``), and exits 1 on anything
new; ``tests/test_analysis.py::test_tree_is_clean`` runs it over
``photon_ml_tpu/`` in tier-1.
"""

from __future__ import annotations

from typing import List

from photon_ml_tpu.analysis.core import (
    AnalysisResult,
    Analyzer,
    Finding,
    ModuleContext,
    Rule,
    iter_py_files,
)
from photon_ml_tpu.analysis.baseline import (
    EMPTY_BASELINE_RULES,
    Baseline,
    BaselineEntry,
    default_baseline_path,
)

__all__ = [
    "AnalysisResult",
    "Analyzer",
    "Finding",
    "ModuleContext",
    "Rule",
    "iter_py_files",
    "Baseline",
    "BaselineEntry",
    "EMPTY_BASELINE_RULES",
    "default_baseline_path",
    "default_rules",
    "rule_catalog",
]


def default_rules() -> List[Rule]:
    """Fresh instances of the full rule registry (rules carry per-run
    scan state, so every Analyzer gets its own)."""
    from photon_ml_tpu.analysis.rules_errors import (
        ExceptionMatchByName,
        SwallowedRetryable,
    )
    from photon_ml_tpu.analysis.rules_faults import UnknownFaultSiteRule
    from photon_ml_tpu.analysis.rules_handles import UnmanagedNativeHandle
    from photon_ml_tpu.analysis.rules_obs import ObsTaxonomyRule
    from photon_ml_tpu.analysis.rules_reqtrace import SpanContextDrop
    from photon_ml_tpu.analysis.rules_spmd import SpmdCollectiveDivergence
    from photon_ml_tpu.analysis.rules_trace import TraceUnsafeHostOp

    return [
        SpmdCollectiveDivergence(),
        ExceptionMatchByName(),
        UnknownFaultSiteRule(),
        TraceUnsafeHostOp(),
        UnmanagedNativeHandle(),
        ObsTaxonomyRule(),
        SwallowedRetryable(),
        SpanContextDrop(),
    ]


def rule_catalog() -> List[Rule]:
    """The registry in id order (for ``photon-lint explain`` and docs)."""
    return sorted(default_rules(), key=lambda r: r.id)
