"""PL001 spmd-collective-divergence: collectives under per-process
control flow.

Origin: the PR 11 review's HIGH finding. The host-loss FINAL checkpoint
ran the normal pod writer — whose digest allgather and completion
barrier include the peer just declared dead — from the recovery path,
so the survivors' "final save" hung forever (or burned the whole retry
budget) and the promised shard set never landed. The general shape: a
collective is only safe when EVERY process reaches it in the same
order. Two static contexts break that guarantee:

- an ``except`` handler: only the processes that saw the exception
  enter it, so a collective there desyncs the collective streams
  (survivors wait on a peer that never calls);
- a branch whose condition depends on ``process_index()`` /
  ``process_id``: by construction different processes take different
  arms (branching on ``process_count`` is uniform and fine).

One level of the call graph is followed: a local function that
(directly) calls a collective is itself treated as collective-calling,
so hiding the allgather one ``def`` down doesn't evade the rule.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set, Tuple

from photon_ml_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    call_name,
)

__all__ = ["SpmdCollectiveDivergence", "COLLECTIVE_NAMES"]

# the host/device collective seam (docs/MULTIHOST.md): host-blocking
# exchanges, the pod barrier, traced reductions, and the sharded
# checkpoint writer (whose digest exchange + completion barrier are
# full-world collectives — the literal PR-11 bug)
COLLECTIVE_NAMES = frozenset(
    {
        "allgather_host",
        "allgather_strings",
        "emit_pod_sync",
        "psum",
        "pmean",
        "process_allgather",
        "sync_global_devices",
        "save_checkpoint_sharded",
    }
)

_PER_PROCESS_NAMES = frozenset({"process_index", "process_id"})


def _test_is_per_process(test: ast.AST) -> bool:
    """True when a branch condition references process_index/process_id
    (call, bare name, or attribute) — different processes evaluate it
    differently."""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in _PER_PROCESS_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _PER_PROCESS_NAMES:
            return True
    return False


class SpmdCollectiveDivergence(Rule):
    id = "PL001"
    name = "spmd-collective-divergence"
    severity = "error"
    hint = (
        "hoist the collective out of the divergent arm so every process "
        "reaches it unconditionally; for recovery paths use a "
        "collective-free protocol (the save_checkpoint_sharded_final "
        "single-publisher pattern) or gate on uniform state "
        "(process_count, a pre-exchanged flag) instead of process_index"
    )
    origin = (
        "PR 11 review (HIGH): the host-loss final save ran the pod "
        "checkpoint writer — digest allgather + completion barrier "
        "including the dead peer — from the except-handler recovery "
        "path; survivors hung forever waiting for a process that would "
        "never join the exchange. Fixed by the collective-free "
        "single-publisher final writer. This rule makes the whole class "
        "(collectives reachable from per-process control flow) a "
        "build-time error."
    )

    def __init__(self):
        # module path -> local function names that call a collective
        # directly (the one-level call graph)
        self._collective_fns: Dict[str, Set[str]] = {}

    # -- phase 1: collect local collective-calling functions ------------

    def scan(self, ctx: ModuleContext) -> None:
        local: Set[str] = set()
        for call in ctx.walk_calls():
            last, _ = call_name(call)
            if last in COLLECTIVE_NAMES:
                fn = ctx.enclosing_function(call)
                if fn is not None:
                    local.add(fn.name)
        self._collective_fns[ctx.rel_path] = local

    # -- phase 2: flag collectives in divergent contexts ----------------

    def _divergent_context(
        self, ctx: ModuleContext, node: ast.AST
    ) -> Optional[Tuple[str, int]]:
        """(description, line) of the innermost divergent construct the
        node sits in, or None. Walking up stops at the enclosing
        function boundary: a collective inside a nested ``def`` is
        attributed when that function is CALLED, not where it's
        defined."""
        for anc, child in ctx.ancestry(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
            if isinstance(anc, ast.ExceptHandler):
                return ("an except handler", anc.lineno)
            if isinstance(anc, (ast.If, ast.While)):
                in_branch = child is not anc.test
                if in_branch and _test_is_per_process(anc.test):
                    return (
                        "a process_index()-dependent branch",
                        anc.lineno,
                    )
            if isinstance(anc, ast.IfExp):
                in_branch = child is not anc.test
                if in_branch and _test_is_per_process(anc.test):
                    return (
                        "a process_index()-dependent conditional "
                        "expression",
                        anc.lineno,
                    )
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        local_collective_fns = self._collective_fns.get(ctx.rel_path, set())
        for call in ctx.walk_calls():
            last, full = call_name(call)
            if last is None:
                continue
            via: Optional[str] = None
            if last in COLLECTIVE_NAMES:
                what = f"collective {last}()"
            elif last in local_collective_fns:
                via = last
                what = (
                    f"{last}(), which calls a collective (one call-graph "
                    "level down)"
                )
            else:
                continue
            where = self._divergent_context(ctx, call)
            if where is None:
                continue
            desc, ctx_line = where
            yield self.finding(
                ctx,
                call,
                f"{what} is reached inside {desc} (opened at line "
                f"{ctx_line}): processes that don't take this arm never "
                "join the exchange, so the pod's collective streams "
                "desync or hang",
            )
