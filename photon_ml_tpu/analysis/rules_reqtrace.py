"""PL008 span-context-drop: spawned work that loses its trace id.

The request-causality layer (obs/reqtrace.py, docs/OBSERVABILITY.md)
only works because every layer that accepts a trace id passes it to the
next one: wire frame -> tenant envelope -> batcher item -> retro-span.
The chain has a single failure mode, and it is silent: a function that
RECEIVES the context (a ``trace``/``trace_id``/``span_ctx`` parameter)
and then hands work to another thread of control — ``threading.Thread``,
an executor ``.submit``, ``loop.create_task``, ``ensure_future``,
``run_in_executor`` — without the context in the hand-off. Nothing
crashes. The request still scores. But every span the spawned work emits
is orphaned: ``photon-obs request`` shows a timeline that ends at the
hand-off, the failover/degraded keep-classes in obs/exemplars.py never
see the request, and the ``trace_loss`` drill's zero-orphan assertion is
the only thing that would ever notice.

Detection is scoped to functions that visibly hold a context parameter
(one of the conventional names below) — the one case where dropping it
is unambiguous. The spawn call is clean when its argument subtree
references the context by name (``args=(trace,)``, ``trace=trace``), or
forwards a locally-defined function/lambda that closes over it, or
forwards opaque ``*args``/``**kwargs`` (we cannot see inside; the
ratchet stays quiet rather than guessing).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from photon_ml_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    call_name,
)

__all__ = ["SpanContextDrop"]

# the conventional trace/span-context parameter names (the repo's own
# seam uses `trace`; the rest are the usual suspects in ported code)
_CTX_PARAM_NAMES = frozenset(
    {"trace", "trace_id", "span_ctx", "span_context", "trace_ctx"}
)

# hand-off sinks: (last callee component) -> what it spawns
_SPAWN_SINKS = {
    "Thread": "a thread (threading.Thread)",
    "submit": "executor work (.submit)",
    "create_task": "an event-loop task (create_task)",
    "ensure_future": "an event-loop task (ensure_future)",
    "run_in_executor": "executor work (run_in_executor)",
}


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names: Set[str] = set()
    for group in (a.posonlyargs, a.args, a.kwonlyargs):
        names.update(p.arg for p in group)
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def _body_references(fn: ast.AST, names: Set[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in names:
            return True
    return False


class SpanContextDrop(Rule):
    id = "PL008"
    name = "span-context-drop"
    severity = "warning"
    hint = (
        "forward the context into the spawned work: pass it in "
        "args=/kwargs (Thread(target=fn, args=(trace,)), "
        "pool.submit(fn, trace)), thread it through the submit "
        "keyword (batcher.submit(req, trace=trace)), or close over "
        "it in a locally-defined worker function"
    )
    origin = (
        "The PR 19 trace seam: a request's id rides wire frame -> "
        "tenant envelope -> batcher item -> retro-span, and every hop "
        "is a hand-off to another thread of control. Dropping the id "
        "at any hop fails silently — scoring still works, but the "
        "spawned work's spans are orphans, `photon-obs request` shows "
        "a timeline truncated at the hand-off, and the exemplar "
        "keep-classes (error/failover/degraded) never see the "
        "request. Only the trace_loss drill's zero-orphan assertion "
        "catches it at runtime; this rule catches it in review."
    )

    def _spawn_drops_context(
        self, call: ast.Call, carriers: Set[str]
    ) -> Optional[str]:
        """The sink description when this call is a hand-off that
        references NO carrier name, else None."""
        last, _ = call_name(call)
        desc = _SPAWN_SINKS.get(last or "")
        if desc is None:
            return None
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for node in ast.walk(arg):
                if isinstance(node, ast.Name) and node.id in carriers:
                    return None
        # opaque forwarding — *args / **kwargs may carry the context;
        # stay quiet rather than guess
        if any(isinstance(a, ast.Starred) for a in call.args):
            return None
        if any(kw.arg is None for kw in call.keywords):
            return None
        return desc

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            ctx_params = _param_names(fn) & _CTX_PARAM_NAMES
            if not ctx_params:
                continue
            # names that carry the context forward: the parameters
            # themselves, plus locally-defined functions whose bodies
            # close over one (Thread(target=worker) with `worker`
            # reading `trace` IS forwarding)
            carriers = set(ctx_params)
            for node in ast.walk(fn):
                if (
                    isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)
                    )
                    and node is not fn
                    and _body_references(node, ctx_params)
                ):
                    carriers.add(node.name)
                elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Lambda
                ):
                    if _body_references(node.value, ctx_params):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                carriers.add(tgt.id)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                # nested defs report against their own ctx params when
                # they have them; a spawn inside a nested def that holds
                # no context itself is the OUTER function's hand-off
                # only if the call is lexically inside fn — ast.walk
                # gives us that for free, and double-reporting is
                # prevented because the nested def without ctx params
                # is skipped by the `ctx_params` gate above
                desc = self._spawn_drops_context(node, carriers)
                if desc is not None:
                    yield self.finding(
                        ctx,
                        node,
                        f"{fn.name}() holds a trace context "
                        f"({', '.join(sorted(ctx_params))}) but spawns "
                        f"{desc} without forwarding it — the spawned "
                        "work's spans will be orphaned from the "
                        "request timeline",
                    )
