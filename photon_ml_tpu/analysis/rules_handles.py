"""PL005 unmanaged-native-handle: native handles need static ownership.

Origin: the PR 9 handle census. ``NativeAvroReader`` /
``NativeVocabSet`` own C++-side buffers and shared vocab hash maps; a
leaked reader held the maps alive (and, with the PR 10 watchdogs, a
freed-under-a-stray-thread map segfaulted the process). PR 9 converted
every entry point to ``with``-style and added the runtime census
(``live_native_handles`` == 0 after every entry point, drilled in
tier-1). This rule is the census's static form: every construction must
have a visible owner AT THE CONSTRUCTION SITE —

- the context expression of a ``with`` (directly, or via
  ``contextlib.closing`` / ``ExitStack.enter_context``);
- a local that the SAME function later manages (``with vocabset:``,
  ``enter_context(vocabset)``, or a ``finally:``-reachable
  ``vocabset.close()``);
- an attribute of an object that itself defines ``close``/``__exit__``
  (ownership transferred to a managed container, e.g. the ingest
  pipeline's ``self._vocabset``).

Anything else is a handle whose lifetime depends on the garbage
collector and whoever reads the code next.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from photon_ml_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    call_name,
)

__all__ = ["UnmanagedNativeHandle", "NATIVE_HANDLE_TYPES"]

NATIVE_HANDLE_TYPES = frozenset({"NativeAvroReader", "NativeVocabSet"})

_MANAGER_WRAPPERS = frozenset({"closing", "enter_context", "push"})


class UnmanagedNativeHandle(Rule):
    id = "PL005"
    name = "unmanaged-native-handle"
    severity = "error"
    hint = (
        "construct the handle in a `with` statement (or hand it "
        "straight to contextlib.closing / ExitStack.enter_context), "
        "manage the local with `with handle:` / try-finally close(), "
        "or store it on an object that itself defines close()/__exit__ "
        "and is managed by its owner"
    )
    origin = (
        "PR 9's native-handle census: leaked NativeAvroReader handles "
        "kept shared C++ vocab maps alive, and PR 10 found close() "
        "freeing those maps under a watchdog-abandoned decode thread "
        "(a real segfault). The runtime census asserts zero live "
        "handles after every entry point; this rule asserts the same "
        "ownership discipline statically, at the construction site."
    )

    def _assigned_name(self, ctx: ModuleContext, call: ast.Call):
        """(kind, name) when the call's value is bound: ('local', n) for
        `n = C()`, ('attr', attr) for `self.x = C()`, else (None, None)."""
        parent = ctx.parent(call)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            target = parent.targets[0]
        elif isinstance(parent, (ast.AnnAssign,)) and parent.value is call:
            target = parent.target
        else:
            return None, None
        if isinstance(target, ast.Name):
            return "local", target.id
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ):
            if target.value.id == "self":
                return "attr", target.attr
        return None, None

    def _in_with_item(self, ctx: ModuleContext, call: ast.Call) -> bool:
        """The call (possibly wrapped in closing()/enter_context()) is a
        with-statement context expression or a manager-wrapper arg."""
        node: ast.AST = call
        parent = ctx.parent(node)
        while parent is not None:
            if isinstance(parent, ast.withitem):
                return True
            if isinstance(parent, ast.Call):
                last, _ = call_name(parent)
                if last in _MANAGER_WRAPPERS:
                    return True
            if not isinstance(parent, (ast.Call, ast.Starred)):
                break
            node = parent
            parent = ctx.parent(node)
        return False

    def _scope_manages_local(
        self, ctx: ModuleContext, call: ast.Call, name: str
    ) -> bool:
        scope = ctx.enclosing_function(call) or ctx.tree
        for node in ast.walk(scope):
            if isinstance(node, ast.withitem):
                expr = node.context_expr
                if isinstance(expr, ast.Name) and expr.id == name:
                    return True
            if isinstance(node, ast.Call):
                last, _ = call_name(node)
                if last in _MANAGER_WRAPPERS and any(
                    isinstance(a, ast.Name) and a.id == name
                    for a in node.args
                ):
                    return True
            if isinstance(node, ast.Try) and node.finalbody:
                for fin in node.finalbody:
                    for sub in ast.walk(fin):
                        if isinstance(sub, ast.Call):
                            _, full = call_name(sub)
                            if full in (
                                f"{name}.close",
                                f"{name}.free",
                            ):
                                return True
        return False

    def _owner_class_manages(
        self, ctx: ModuleContext, call: ast.Call
    ) -> bool:
        for anc, _ in ctx.ancestry(call):
            if isinstance(anc, ast.ClassDef):
                methods: Set[str] = {
                    n.name
                    for n in anc.body
                    if isinstance(
                        n, (ast.FunctionDef, ast.AsyncFunctionDef)
                    )
                }
                return bool({"close", "__exit__"} & methods)
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call in ctx.walk_calls():
            last, _ = call_name(call)
            if last not in NATIVE_HANDLE_TYPES:
                continue
            if self._in_with_item(ctx, call):
                continue
            kind, name = self._assigned_name(ctx, call)
            if kind == "local" and name and self._scope_manages_local(
                ctx, call, name
            ):
                continue
            if kind == "attr" and self._owner_class_manages(ctx, call):
                continue
            yield self.finding(
                ctx,
                call,
                f"{last}() constructed without a static owner: no "
                "`with`, no enter_context/closing, no finally-close in "
                "this scope, and no managed container — the C++ buffers "
                "this handle owns now free whenever the GC feels like "
                "it (the bug class PR 9's runtime handle census exists "
                "to catch)",
            )
