"""PL003 unknown-fault-site: fault-site literals checked at LINT time.

Origin: before PR 10's arm-time validation, a typo'd drill site sat
inert in the injector and the drill "passed" by testing nothing.
Arm-time validation (``UnknownFaultSite``) closed that for RUNTIME
arming — but a typo'd ``fire("serving.scoer")`` probe in production
code, or a bad ``PHOTON_FAULTS`` schedule literal, still waits for an
execution path to notice. This rule moves the same check to the build:
every ``fire("...")`` / ``FaultSpec("...")`` / schedule-string literal
is validated against ``resilience.faults.registered_sites()`` (the
machine-readable registry both the runtime validator and
docs/ROBUSTNESS.md bind), plus any ``register_site("...")`` literals
the analyzed tree itself declares.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Set

from photon_ml_tpu.analysis.core import (
    Finding,
    ModuleContext,
    Rule,
    call_name,
)

__all__ = ["UnknownFaultSiteRule"]

# a PHOTON_FAULTS schedule fragment: site:mode@args
_SCHEDULE_RE = re.compile(
    r"^[a-z0-9_.]+:(raise|corrupt|delay)(@|$)", re.IGNORECASE
)


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class UnknownFaultSiteRule(Rule):
    id = "PL003"
    name = "unknown-fault-site"
    severity = "error"
    hint = (
        "use a site from resilience.faults.registered_sites() "
        "(photon-chaos sites lists them), or declare the new seam with "
        "faults.register_site(...) next to the code that probes it"
    )
    origin = (
        "Pre-PR-10, a typo'd fault site armed nothing and the drill "
        "passed by testing nothing; arm-time validation "
        "(UnknownFaultSite) fixed the runtime half. The lint half "
        "catches the probe side — a fire()/FaultSpec()/PHOTON_FAULTS "
        "literal naming a site no registry knows — before anything "
        "runs."
    )

    def __init__(self, extra_sites: Optional[Set[str]] = None):
        self._declared: Set[str] = set(extra_sites or ())
        self._known: Optional[Set[str]] = None

    def _known_sites(self) -> Set[str]:
        if self._known is None:
            from photon_ml_tpu.resilience.faults import registered_sites

            self._known = set(registered_sites())
        return self._known | self._declared

    # -- phase 1: collect register_site("...") declarations -------------

    def scan(self, ctx: ModuleContext) -> None:
        for call in ctx.walk_calls():
            last, _ = call_name(call)
            if last == "register_site" and call.args:
                lit = _literal_str(call.args[0])
                if lit:
                    self._declared.add(lit)

    # -- phase 2: validate probe/arm/schedule literals -------------------

    def _site_of_call(self, call: ast.Call) -> Optional[ast.AST]:
        """The site-literal node of a fire()/FaultSpec() call, if any."""
        last, _ = call_name(call)
        if last == "fire" and call.args:
            return call.args[0]
        if last == "FaultSpec":
            if call.args:
                return call.args[0]
            for kw in call.keywords:
                if kw.arg == "site":
                    return kw.value
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        known = self._known_sites()
        docstrings = ctx.docstring_nodes()
        seen: Set[int] = set()
        for call in ctx.walk_calls():
            site_node = self._site_of_call(call)
            if site_node is None:
                continue
            site = _literal_str(site_node)
            if site is None or site in known:
                continue
            seen.add(id(site_node))
            yield self.finding(
                ctx,
                call,
                f"fault site {site!r} is not in "
                "resilience.faults.registered_sites(): this probe/arm "
                "would raise UnknownFaultSite at runtime (or, pre-"
                "validation, silently drill nothing)",
            )
        # PHOTON_FAULTS schedule literals outside docstrings: validate
        # each ;-segment's site against the registry
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
            ):
                continue
            if id(node) in docstrings or id(node) in seen:
                continue
            text = node.value
            segments = [s.strip() for s in text.split(";") if s.strip()]
            if not segments or not all(
                _SCHEDULE_RE.match(s) for s in segments
            ):
                continue
            for seg in segments:
                site = seg.split(":", 1)[0]
                if site not in known:
                    yield self.finding(
                        ctx,
                        node,
                        f"PHOTON_FAULTS schedule names unknown site "
                        f"{site!r}: arming it raises UnknownFaultSite "
                        "before the job starts",
                    )
