"""photon-lint core: findings, rule registry, module contexts, engine.

The analyzer is pure stdlib-``ast`` (no new dependencies, no imports of
the analyzed modules except the two registries it validates literals
against: ``resilience.faults.registered_sites()`` and
``obs.taxonomy``). Each rule distills one bug class this repo actually
shipped and later caught at runtime or in review — docs/ANALYSIS.md
tells each rule's origin story; ``photon-lint explain PLxxx`` prints it.

Mechanics:

- every analyzed file parses ONCE into a :class:`ModuleContext`
  (AST + parent links + suppression comments + per-scope indexes);
- rules run in two phases: ``scan`` (collect cross-file facts, e.g.
  ``register_site("...")`` literals for PL003) then ``check`` (emit
  :class:`Finding`\\ s);
- inline suppression is ``# photon-lint: disable=PLxxx <reason>`` on
  the finding's line — the reason is REQUIRED (a bare disable is
  ignored and reported, so "shut it up" always leaves a paper trail);
- the committed ratchet baseline (:mod:`.baseline`) grandfathers
  existing findings by (rule, path, source-line text) so line drift
  doesn't resurrect them; anything not in the baseline fails the build.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import time
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

__all__ = [
    "Finding",
    "Rule",
    "ModuleContext",
    "Analyzer",
    "AnalysisResult",
    "iter_py_files",
    "dotted_name",
    "call_name",
    "SUPPRESS_RE",
]

SEVERITIES = ("error", "warning")

# `# photon-lint: disable=PL001,PL004 <reason>` — reason required for
# the suppression to take effect
SUPPRESS_RE = re.compile(
    r"#\s*photon-lint:\s*disable=(?P<rules>PL\d{3}(?:\s*,\s*PL\d{3})*)"
    r"(?P<reason>.*)$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str  # "PL001"
    path: str  # repo-relative, forward slashes
    line: int  # 1-based
    col: int  # 0-based
    severity: str  # error | warning
    message: str
    hint: str  # how to fix it
    text: str = ""  # stripped source line (baseline identity)

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"[{self.severity}] {self.message}\n    fix: {self.hint}"
        )


class Rule:
    """One lint rule. Subclasses set the class attributes and implement
    :meth:`check`; :meth:`scan` is the optional cross-file collect phase
    (runs over EVERY module before any check)."""

    id: str = "PL000"
    name: str = "unnamed"
    severity: str = "error"
    hint: str = ""
    # the bug that taught us the rule (photon-lint explain / docs)
    origin: str = ""

    def scan(self, ctx: "ModuleContext") -> None:
        return None

    def check(self, ctx: "ModuleContext") -> Iterator[Finding]:
        return iter(())

    def finding(
        self,
        ctx: "ModuleContext",
        node: ast.AST,
        message: str,
        hint: Optional[str] = None,
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=self.id,
            path=ctx.rel_path,
            line=line,
            col=getattr(node, "col_offset", 0),
            severity=self.severity,
            message=message,
            hint=hint if hint is not None else self.hint,
            text=ctx.line_text(line),
        )


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains; Call heads keep their name
    with ``()`` appended (``obs.registry()``); anything else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Call):
        base = dotted_name(node.func)
        return f"{base}()" if base else None
    return None


def call_name(call: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    """(last component, full dotted form) of a call's callee."""
    full = dotted_name(call.func)
    if full is None:
        return None, None
    return full.rsplit(".", 1)[-1], full


class ModuleContext:
    """One parsed module plus the indexes every rule needs."""

    def __init__(self, path: str, rel_path: str, source: str):
        self.path = path
        self.rel_path = rel_path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        # line -> {rule_id: reason}; reasonless disables are recorded
        # with reason None (they do NOT suppress — see engine)
        self.suppressions: Dict[int, Dict[str, Optional[str]]] = {}
        self._parse_suppressions()

    # -- suppressions ---------------------------------------------------

    def _parse_suppressions(self) -> None:
        for i, raw in enumerate(self.lines, start=1):
            if "photon-lint" not in raw:
                continue
            m = SUPPRESS_RE.search(raw)
            if not m:
                continue
            reason = m.group("reason").strip() or None
            for rule_id in re.split(r"\s*,\s*", m.group("rules")):
                self.suppressions.setdefault(i, {})[rule_id] = reason

    def suppression_reason(
        self, line: int, rule_id: str
    ) -> Tuple[bool, Optional[str]]:
        """(has_disable_comment, reason) for ``rule_id`` on ``line``."""
        per = self.suppressions.get(line)
        if per is None or rule_id not in per:
            return False, None
        return True, per[rule_id]

    # -- tree navigation ------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def ancestry(
        self, node: ast.AST
    ) -> Iterator[Tuple[ast.AST, ast.AST]]:
        """(ancestor, child-we-came-through) pairs, innermost first."""
        child = node
        parent = self.parent(child)
        while parent is not None:
            yield parent, child
            child = parent
            parent = self.parent(child)

    def enclosing_function(
        self, node: ast.AST
    ) -> Optional[ast.AST]:
        for anc, _ in self.ancestry(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def walk_calls(self) -> Iterator[ast.Call]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                yield node

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def docstring_nodes(self) -> set:
        """ids of Constant nodes that are docstrings (skipped by rules
        that scan raw string literals)."""
        out = set()
        for node in ast.walk(self.tree):
            if isinstance(
                node,
                (
                    ast.Module,
                    ast.FunctionDef,
                    ast.AsyncFunctionDef,
                    ast.ClassDef,
                ),
            ):
                body = getattr(node, "body", [])
                if (
                    body
                    and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)
                ):
                    out.add(id(body[0].value))
        return out


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]
    files: int
    wall_s: float
    suppressed: int
    # reasonless `disable=` comments found (they suppress nothing)
    bare_suppressions: List[Tuple[str, int]]
    parse_errors: List[Finding]

    def by_rule(self) -> Dict[str, List[Finding]]:
        out: Dict[str, List[Finding]] = {}
        for f in self.findings:
            out.setdefault(f.rule, []).append(f)
        return out


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into sorted .py file paths. Build
    artifacts and caches are skipped."""
    skip_dirs = {"__pycache__", ".git", "_build", ".pytest_cache"}
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in skip_dirs)
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


class Analyzer:
    """Run a rule set over a file tree: parse once, scan phase, check
    phase, suppression accounting."""

    def __init__(self, rules: Optional[List[Rule]] = None, base: str = "."):
        if rules is None:
            from photon_ml_tpu.analysis import default_rules

            rules = default_rules()
        self.rules = rules
        self.base = os.path.abspath(base)

    def rule(self, rule_id: str) -> Optional[Rule]:
        for r in self.rules:
            if r.id == rule_id:
                return r
        return None

    def _rel(self, path: str) -> str:
        ap = os.path.abspath(path)
        try:
            rel = os.path.relpath(ap, self.base)
        except ValueError:  # different drive (windows) — keep absolute
            return ap.replace(os.sep, "/")
        if rel.startswith(".."):
            return ap.replace(os.sep, "/")
        return rel.replace(os.sep, "/")

    def run(self, paths: Iterable[str]) -> AnalysisResult:
        t0 = time.perf_counter()
        contexts: List[ModuleContext] = []
        parse_errors: List[Finding] = []
        files = 0
        for path in iter_py_files(paths):
            files += 1
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                contexts.append(
                    ModuleContext(path, self._rel(path), source)
                )
            except (SyntaxError, ValueError, OSError) as e:
                # an unparseable file can hide anything — surface it as
                # a finding instead of silently narrowing coverage
                parse_errors.append(
                    Finding(
                        rule="PL000",
                        path=self._rel(path),
                        line=getattr(e, "lineno", 1) or 1,
                        col=0,
                        severity="error",
                        message=f"file does not parse: {e}",
                        hint="fix the syntax error; photon-lint cannot "
                        "analyze what ast.parse cannot read",
                        text="",
                    )
                )
        for rule in self.rules:
            for ctx in contexts:
                rule.scan(ctx)
        findings: List[Finding] = []
        suppressed = 0
        bare: List[Tuple[str, int]] = []
        for ctx in contexts:
            for rule in self.rules:
                for finding in rule.check(ctx):
                    has, reason = ctx.suppression_reason(
                        finding.line, finding.rule
                    )
                    if has and reason:
                        suppressed += 1
                        continue
                    if has and not reason:
                        # recorded once per (file, line): the disable is
                        # inert until it says WHY
                        bare.append((ctx.rel_path, finding.line))
                    findings.append(finding)
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return AnalysisResult(
            findings=findings + parse_errors,
            files=files,
            wall_s=time.perf_counter() - t0,
            suppressed=suppressed,
            bare_suppressions=sorted(set(bare)),
            parse_errors=parse_errors,
        )
