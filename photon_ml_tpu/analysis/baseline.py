"""The ratchet baseline: existing findings grandfathered, new ones fail.

A static analyzer retrofitted onto a living tree faces a cold-start
problem: day one it reports every pre-existing occurrence, and "fix 40
findings before the gate turns on" means the gate never turns on. The
baseline solves it the way large-repo linters do — a committed JSON
file (``photon_ml_tpu/analysis/baseline.json``) enumerating the
findings that existed when the rule shipped. ``photon-lint check``
fails only on findings NOT in the baseline, so the count can only
ratchet down:

- fixing a baselined finding makes its entry STALE (reported, and
  ``photon-lint baseline --prune`` deletes it so it cannot mask a
  future regression at the same spot);
- new code must be clean from its first commit.

Entries match by ``(rule, path, stripped source-line text)`` — not by
line number — so unrelated edits that shift lines don't resurrect
grandfathered findings, while EDITING the offending line (you were
there; fix it) un-grandfathers it. Duplicate texts in one file are
matched as a multiset: adding a second identical violation to a file
that had one baselined is still a new finding.

PL001/PL002/PL003/PL008 ship with ZERO baseline entries by policy:
those classes (collective divergence, by-name exception matching,
unknown fault sites, dropped span contexts) each caused a real hang or
masked-bug in this repo's history and are cheap to fix on contact;
docs/ANALYSIS.md documents the policy and ``tests/test_analysis.py``
enforces it.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Sequence, Tuple

from photon_ml_tpu.analysis.core import Finding

__all__ = [
    "BaselineEntry",
    "Baseline",
    "default_baseline_path",
    "EMPTY_BASELINE_RULES",
]

# rules whose baseline must stay empty (enforced by tests and by
# `photon-lint baseline`, which refuses to grandfather them). PL008
# joins the policy from birth: the trace seam it guards shipped clean
# in the same PR, so there is nothing to grandfather — and a dropped
# span context is always cheap to fix on contact (forward one value).
EMPTY_BASELINE_RULES = ("PL001", "PL002", "PL003", "PL008")

VERSION = 1


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str
    line: int  # advisory (drifts); identity is (rule, path, text)
    text: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.text)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Baseline:
    """The committed grandfather list plus the matching logic."""

    def __init__(self, entries: Sequence[BaselineEntry] = ()):
        self.entries: List[BaselineEntry] = list(entries)

    # -- persistence ----------------------------------------------------

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Missing file = empty baseline (a fresh tree is all-new). A
        corrupt file raises: silently linting against nothing would
        report every grandfathered finding as new and train people to
        ignore the gate."""
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except FileNotFoundError:
            return cls()
        if not isinstance(doc, dict) or "entries" not in doc:
            raise ValueError(
                f"baseline {path!r} is not a photon-lint baseline "
                "(expected an object with an 'entries' list)"
            )
        entries = [
            BaselineEntry(
                rule=str(e["rule"]),
                path=str(e["path"]),
                line=int(e.get("line", 0)),
                text=str(e.get("text", "")),
            )
            for e in doc["entries"]
        ]
        return cls(entries)

    def save(self, path: str) -> None:
        doc = {
            "version": VERSION,
            "entries": [
                e.to_json()
                for e in sorted(
                    self.entries, key=lambda e: (e.path, e.line, e.rule)
                )
            ],
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)

    # -- matching -------------------------------------------------------

    def split(
        self, findings: Sequence[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
        """(new, grandfathered, stale_entries) for a finding set.

        Multiset semantics per (rule, path, text): N baseline entries
        absorb at most N identical findings."""
        budget: Dict[Tuple[str, str, str], int] = {}
        for e in self.entries:
            budget[e.key()] = budget.get(e.key(), 0) + 1
        new: List[Finding] = []
        old: List[Finding] = []
        for f in findings:
            key = (f.rule, f.path, f.text)
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                old.append(f)
            else:
                new.append(f)
        stale: List[BaselineEntry] = []
        remaining = dict(budget)
        for e in self.entries:
            if remaining.get(e.key(), 0) > 0:
                remaining[e.key()] -= 1
                stale.append(e)
        return new, old, stale

    # -- updates --------------------------------------------------------

    @classmethod
    def from_findings(cls, findings: Sequence[Finding]) -> "Baseline":
        """A full regenerate — grandfather everything CURRENT except the
        empty-by-policy rules (those must be fixed, not baselined)."""
        return cls(
            [
                BaselineEntry(
                    rule=f.rule, path=f.path, line=f.line, text=f.text
                )
                for f in findings
                if f.rule not in EMPTY_BASELINE_RULES
            ]
        )

    def pruned(self, findings: Sequence[Finding]) -> "Baseline":
        """Drop stale entries (no matching current finding — the code
        was fixed or deleted) WITHOUT grandfathering anything new. Kept
        entries get their advisory line refreshed to the current match."""
        _, grandfathered, _ = self.split(findings)
        return Baseline(
            [
                BaselineEntry(
                    rule=f.rule, path=f.path, line=f.line, text=f.text
                )
                for f in grandfathered
            ]
        )
