// Native Avro -> columnar ingest core.
//
// The reference parses Avro on JVM executors (avro/AvroIOUtils.scala:46-139,
// avro/data/DataProcessingUtils.scala:34-131); feeding a TPU pod from a
// single host makes ingest throughput the bottleneck instead (SURVEY §7
// hard-part 6), and the pure-Python codec in io/avro.py decodes ~10^5
// records/s. This translation unit is the native replacement for the hot
// READ path: container-file framing + deflate, a schema "op program"
// interpreter (the Python side compiles the schema JSON into flat opcodes,
// so C++ never parses JSON), and the vocabulary join (feature (name, term)
// -> column id) done with a native hash map so Python touches no per-record
// values at all. Output is columnar: scalar field arrays, COO feature
// triplets per vocabulary, and entity-string pools.
//
// Exposed as a C ABI for ctypes (no pybind11 in the image). All memory is
// owned by a Reader handle; numpy copies out and frees it.

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <memory>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include <zlib.h>

// libdeflate inflates ~2-3x faster than zlib; the Python builder tries
// -DPML_USE_LIBDEFLATE -ldeflate first and falls back to plain zlib.
#ifdef PML_USE_LIBDEFLATE
#include <libdeflate.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// schema op program (must mirror photon_ml_tpu/io/native.py)
// ---------------------------------------------------------------------------
// Each record field compiles to one op. Optional fields (union [null, X])
// set the OPTIONAL bit; the decoder then reads the union branch index first.
enum Op : int32_t {
  OP_SCALAR_COL = 1,   // double/float/int/long/boolean -> f64 column `arg`
  OP_UID = 2,          // string -> uid string pool
  OP_FEATURES = 3,     // array<record{name,term,value,...}> -> COO triplets
  OP_METADATA = 4,     // map<string> -> entity columns for requested keys
  OP_SKIP = 5,         // any field we don't consume
};
constexpr int32_t OPTIONAL_BIT = 1 << 8;
// When set, the union's null branch is index 1 ([X, null]) instead of 0.
constexpr int32_t NULL_SECOND_BIT = 1 << 9;

// Wire type of the underlying value, for both scalar decode and skipping.
enum Wire : int32_t {
  W_NULL = 0,
  W_BOOLEAN = 1,
  W_INT = 2,
  W_LONG = 3,
  W_FLOAT = 4,
  W_DOUBLE = 5,
  W_STRING = 6,
  W_BYTES = 7,
  W_FEATURE_ARRAY = 8,  // array of feature records
  W_STRING_MAP = 9,     // map<string>
};

// One compiled field: op | OPTIONAL_BIT?, wire, arg (column index), and for
// OP_FEATURES the wire codes of the feature-record's own fields follow via
// the shared feature descriptor (name/term/value positions + extra skips).
struct FieldProg {
  int32_t op;
  int32_t wire;
  int32_t arg;
};

struct Slice {
  const uint8_t* p;
  size_t n;
  size_t off = 0;
  bool fail = false;

  bool need(size_t k) {
    if (off + k > n) {
      fail = true;
      return false;
    }
    return true;
  }
};

int64_t read_long(Slice& s) {
  uint64_t acc = 0;
  int shift = 0;
  while (true) {
    if (!s.need(1)) return 0;
    uint8_t b = s.p[s.off++];
    acc |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
    if (shift > 63) {
      s.fail = true;
      return 0;
    }
  }
  return static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
}

double read_double(Slice& s) {
  if (!s.need(8)) return 0.0;
  double v;
  std::memcpy(&v, s.p + s.off, 8);
  s.off += 8;
  return v;
}

float read_float(Slice& s) {
  if (!s.need(4)) return 0.0f;
  float v;
  std::memcpy(&v, s.p + s.off, 4);
  s.off += 4;
  return v;
}

std::string_view read_string(Slice& s) {
  int64_t len = read_long(s);
  if (len < 0 || !s.need(static_cast<size_t>(len))) {
    s.fail = true;
    return {};
  }
  std::string_view v(reinterpret_cast<const char*>(s.p + s.off),
                     static_cast<size_t>(len));
  s.off += static_cast<size_t>(len);
  return v;
}

void skip_wire(Slice& s, int32_t wire);

void skip_blocks(Slice& s, const std::vector<int32_t>& item_wires) {
  // arrays and maps share the block framing: count (negative => byte size
  // follows, skippable wholesale), items, terminated by count 0.
  while (!s.fail) {
    int64_t count = read_long(s);
    if (count == 0) break;
    if (count < 0) {
      int64_t nbytes = read_long(s);
      if (nbytes < 0 || !s.need(static_cast<size_t>(nbytes))) {
        s.fail = true;
        return;
      }
      s.off += static_cast<size_t>(nbytes);
      continue;
    }
    for (int64_t i = 0; i < count && !s.fail; ++i)
      for (int32_t w : item_wires) skip_wire(s, w);
  }
}

void skip_wire(Slice& s, int32_t wire) {
  switch (wire) {
    case W_NULL:
      break;
    case W_BOOLEAN:
      if (s.need(1)) s.off += 1;
      break;
    case W_INT:
    case W_LONG:
      read_long(s);
      break;
    case W_FLOAT:
      if (s.need(4)) s.off += 4;
      break;
    case W_DOUBLE:
      if (s.need(8)) s.off += 8;
      break;
    case W_STRING:
    case W_BYTES:
      read_string(s);
      break;
    case W_STRING_MAP: {
      std::vector<int32_t> kv = {W_STRING, W_STRING};
      skip_blocks(s, kv);
      break;
    }
    default:
      s.fail = true;
  }
}

double read_scalar(Slice& s, int32_t wire) {
  switch (wire) {
    case W_BOOLEAN:
      return s.need(1) ? static_cast<double>(s.p[s.off++] != 0) : 0.0;
    case W_INT:
    case W_LONG:
      return static_cast<double>(read_long(s));
    case W_FLOAT:
      return static_cast<double>(read_float(s));
    case W_DOUBLE:
      return read_double(s);
    default:
      s.fail = true;
      return 0.0;
  }
}

// ---------------------------------------------------------------------------
// vocabulary
// ---------------------------------------------------------------------------

// FNV-1a, spread over the (name, '\x01', term) spans so lookups never
// materialize the concatenated key (the decode hot path's former top cost).
constexpr uint64_t kFnvSeed = 0xcbf29ce484222325ull;

inline uint64_t fnv1a(uint64_t h, const char* p, size_t n) {
  for (size_t i = 0; i < n; ++i)
    h = (h ^ static_cast<uint8_t>(p[i])) * 0x100000001b3ull;
  return h;
}

struct Vocab {
  // open-addressing flat table over the key blob: entries are
  // (hash, blob offset, byte length, column id); linear probing, ~50%
  // load. Replaces std::unordered_map<string_view,...> — no per-feature
  // key concatenation, no node indirection, hash compared before memcmp.
  struct Entry {
    uint64_t hash;
    int64_t off;
    int32_t len;   // -1 = empty slot
    int32_t value;
  };
  std::string storage;
  std::vector<Entry> table;
  uint64_t mask = 0;
  int32_t intercept = -1;  // intercept column: injected by Python, not here

  void build(int32_t count, const int64_t* lo_offsets, int64_t base) {
    size_t cap = 16;
    while (cap < static_cast<size_t>(count) * 2) cap <<= 1;
    table.assign(cap, Entry{0, 0, -1, 0});
    mask = cap - 1;
    for (int32_t i = 0; i < count; ++i) {
      int64_t a = lo_offsets[i] - base;
      int64_t b = lo_offsets[i + 1] - base;
      uint64_t h = fnv1a(kFnvSeed, storage.data() + a,
                         static_cast<size_t>(b - a));
      size_t slot = static_cast<size_t>(h) & mask;
      while (table[slot].len >= 0) slot = (slot + 1) & mask;
      table[slot] = Entry{h, a, static_cast<int32_t>(b - a), i};
    }
  }

  // find by (name, term) without concatenating: hash and compare the two
  // spans against the stored key bytes (name + '\x01' + term).
  int32_t find(std::string_view name, std::string_view term) const {
    if (table.empty()) return -1;
    uint64_t h = fnv1a(kFnvSeed, name.data(), name.size());
    const char sep = '\x01';
    h = fnv1a(h, &sep, 1);
    h = fnv1a(h, term.data(), term.size());
    int32_t want = static_cast<int32_t>(name.size() + 1 + term.size());
    size_t slot = static_cast<size_t>(h) & mask;
    while (true) {
      const Entry& e = table[slot];
      if (e.len < 0) return -1;
      if (e.hash == h && e.len == want) {
        const char* k = storage.data() + e.off;
        if (std::memcmp(k, name.data(), name.size()) == 0 &&
            k[name.size()] == sep &&
            std::memcmp(k + name.size() + 1, term.data(), term.size()) == 0)
          return e.value;
      }
      slot = (slot + 1) & mask;
    }
  }
};

// Immutable after construction; shared READ-ONLY by every reader (one
// build per ingest, not per file/thread).
struct VocabSet {
  std::vector<Vocab> vocabs;
};

// ---------------------------------------------------------------------------
// reader state
// ---------------------------------------------------------------------------

struct StringPool {
  std::string bytes;
  std::vector<int64_t> offsets{0};  // n+1 offsets

  void push(std::string_view v) {
    bytes.append(v.data(), v.size());
    offsets.push_back(static_cast<int64_t>(bytes.size()));
  }
  void push_empty() { offsets.push_back(static_cast<int64_t>(bytes.size())); }
};

struct Reader {
  std::string error;

  std::vector<FieldProg> prog;
  // feature-record layout: wires of its fields in order; positions of
  // name/term/value within them (-1 when absent, e.g. no term).
  std::vector<int32_t> feat_wires;
  std::vector<uint8_t> feat_optional;
  int32_t feat_name = -1, feat_term = -1, feat_value = -1;

  const VocabSet* vocabset = nullptr;  // non-owning, shared, read-only
  std::vector<std::string> entity_keys;

  int64_t nrecords = 0;
  int64_t nscalars = 0;
  std::vector<std::vector<double>> scalar_cols;   // [col][record]
  std::vector<std::vector<uint8_t>> scalar_seen;  // optional present flags
  StringPool uids;
  std::vector<StringPool> entities;  // per entity key

  // per-vocab COO triplets
  std::vector<std::vector<int32_t>> coo_rows;
  std::vector<std::vector<int32_t>> coo_cols;
  std::vector<std::vector<double>> coo_vals;

  // vocabulary-building mode (FeatureIndexingJob / DefaultIndexMap analog,
  // util/DefaultIndexMap.scala:23): collect distinct feature keys natively.
  bool collect_keys = false;
  std::unordered_set<std::string> keyset;

  std::string scratch_key;
  std::vector<uint8_t> inflate_buf;
  std::vector<std::string_view> meta_found;
  std::vector<uint8_t> meta_hit;
};

#ifdef PML_USE_LIBDEFLATE
bool inflate_raw(const uint8_t* src, size_t srclen, std::vector<uint8_t>& out) {
  // Avro deflate codec = raw deflate stream; libdeflate wants the output
  // size up front, so guess and grow (container blocks are ~64KB-4MB)
  thread_local std::unique_ptr<libdeflate_decompressor,
                               void (*)(libdeflate_decompressor*)>
      dec(libdeflate_alloc_decompressor(), libdeflate_free_decompressor);
  if (!dec) return false;
  size_t cap = std::max<size_t>(srclen * 4, 1 << 16);
  while (true) {
    out.resize(cap);
    size_t actual = 0;
    libdeflate_result rc = libdeflate_deflate_decompress(
        dec.get(), src, srclen, out.data(), cap, &actual);
    if (rc == LIBDEFLATE_SUCCESS) {
      out.resize(actual);
      return true;
    }
    if (rc != LIBDEFLATE_INSUFFICIENT_SPACE || cap > (size_t{1} << 34))
      return false;
    cap *= 2;
  }
}
#else
bool inflate_raw(const uint8_t* src, size_t srclen, std::vector<uint8_t>& out) {
  // Avro deflate codec = raw deflate stream (no zlib header, no checksum)
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  out.clear();
  out.resize(std::max<size_t>(srclen * 4, 1 << 16));
  zs.next_in = const_cast<Bytef*>(src);
  zs.avail_in = static_cast<uInt>(srclen);
  size_t written = 0;
  int rc = Z_OK;
  while (rc != Z_STREAM_END) {
    if (written == out.size()) out.resize(out.size() * 2);
    zs.next_out = out.data() + written;
    zs.avail_out = static_cast<uInt>(out.size() - written);
    rc = inflate(&zs, Z_NO_FLUSH);
    written = out.size() - zs.avail_out;
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return false;
    }
    if (rc == Z_OK && zs.avail_in == 0 && zs.avail_out != 0) break;
  }
  inflateEnd(&zs);
  out.resize(written);
  return true;
}
#endif

bool decode_record(Reader& r, Slice& s) {
  int64_t row = r.nrecords;
  for (const FieldProg& f : r.prog) {
    int32_t op = f.op & 0xFF;
    int32_t wire = f.wire;
    if (f.op & OPTIONAL_BIT) {
      int64_t branch = read_long(s);
      if (s.fail) return false;
      int64_t null_branch = (f.op & NULL_SECOND_BIT) ? 1 : 0;
      if (branch == null_branch) {
        if (op == OP_SCALAR_COL) r.scalar_seen[f.arg].push_back(0);
        if (op == OP_UID) r.uids.push_empty();
        if (op == OP_METADATA)
          for (auto& pool : r.entities) pool.push_empty();
        continue;
      }
    }
    switch (op) {
      case OP_SCALAR_COL: {
        double v = read_scalar(s, wire);
        r.scalar_cols[f.arg].push_back(v);
        r.scalar_seen[f.arg].push_back(1);
        break;
      }
      case OP_UID:
        r.uids.push(read_string(s));
        break;
      case OP_SKIP:
        skip_wire(s, wire);
        break;
      case OP_FEATURES: {
        while (!s.fail) {
          int64_t count = read_long(s);
          if (count == 0) break;
          if (count < 0) {
            read_long(s);  // byte size, unused: we decode items anyway
            count = -count;
          }
          for (int64_t i = 0; i < count && !s.fail; ++i) {
            std::string_view name, term;
            double value = 0.0;
            bool has_value = false;
            for (size_t fi = 0; fi < r.feat_wires.size(); ++fi) {
              int32_t fw = r.feat_wires[fi];
              if (r.feat_optional[fi]) {
                int64_t branch = read_long(s);
                if (branch == 0) continue;
              }
              if (static_cast<int32_t>(fi) == r.feat_name)
                name = read_string(s);
              else if (static_cast<int32_t>(fi) == r.feat_term)
                term = read_string(s);
              else if (static_cast<int32_t>(fi) == r.feat_value) {
                value = read_scalar(s, fw);
                has_value = true;
              } else
                skip_wire(s, fw);
            }
            if (s.fail || !has_value) continue;
            if (r.collect_keys) {
              // key = name + "\x01" + term (io/vocab.feature_key); only
              // the vocabulary-building pass materializes it
              r.scratch_key.assign(name.data(), name.size());
              r.scratch_key.push_back('\x01');
              r.scratch_key.append(term.data(), term.size());
              r.keyset.insert(r.scratch_key);
            }
            const auto& vocabs = r.vocabset->vocabs;
            for (size_t vi = 0; vi < vocabs.size(); ++vi) {
              int32_t col = vocabs[vi].find(name, term);
              if (col < 0 || col == vocabs[vi].intercept) continue;
              r.coo_rows[vi].push_back(static_cast<int32_t>(row));
              r.coo_cols[vi].push_back(col);
              r.coo_vals[vi].push_back(value);
            }
          }
        }
        break;
      }
      case OP_METADATA: {
        // map<string>: route requested keys into entity pools, in one pass.
        auto& found = r.meta_found;
        auto& hit = r.meta_hit;
        found.assign(r.entity_keys.size(), {});
        hit.assign(r.entity_keys.size(), 0);
        while (!s.fail) {
          int64_t count = read_long(s);
          if (count == 0) break;
          if (count < 0) {
            read_long(s);
            count = -count;
          }
          for (int64_t i = 0; i < count && !s.fail; ++i) {
            std::string_view k = read_string(s);
            std::string_view v = read_string(s);
            for (size_t ei = 0; ei < r.entity_keys.size(); ++ei)
              if (k == r.entity_keys[ei]) {
                found[ei] = v;
                hit[ei] = 1;
              }
          }
        }
        for (size_t ei = 0; ei < r.entity_keys.size(); ++ei) {
          if (hit[ei])
            r.entities[ei].push(found[ei]);
          else
            r.entities[ei].push_empty();
        }
        break;
      }
      default:
        r.error = "bad op in field program";
        return false;
    }
    if (s.fail) return false;
  }
  // optional scalar columns: default-fill rows where the field was null
  for (int32_t c = 0; c < r.nscalars; ++c)
    if (static_cast<int64_t>(r.scalar_cols[c].size()) <= row)
      r.scalar_cols[c].push_back(0.0);
  r.nrecords += 1;
  return true;
}

}  // namespace

extern "C" {

// Build an immutable vocabulary set. Keys arrive as one concatenated byte
// blob with an explicit cumulative offset table (total_keys + 1 entries
// spanning every vocab, in order) — offsets, not separators, so keys may
// contain ANY byte ('\x01' separates name/term inside a key; names can
// embed newlines). Shared read-only by any number of readers/threads;
// freed by the caller AFTER every reader using it.
void* pml_vocabset_new(const char* vocab_blob, const int64_t* key_offsets,
                       const int32_t* vocab_counts,
                       const int32_t* vocab_intercepts, int32_t nvocabs) {
  VocabSet* vs = new VocabSet();
  // build each Vocab in place: the map's string_views point into
  // v.storage, so the string must never move after the views are taken
  // (short storage is SSO-inline and does NOT survive a move).
  vs->vocabs.reserve(static_cast<size_t>(nvocabs));
  int64_t key_base = 0;  // index into the global offset table
  for (int32_t vi = 0; vi < nvocabs; ++vi) {
    vs->vocabs.emplace_back();
    Vocab& v = vs->vocabs.back();
    int32_t count = vocab_counts[vi];
    int64_t lo = key_offsets[key_base];
    int64_t hi = key_offsets[key_base + count];
    v.storage.assign(vocab_blob + lo, static_cast<size_t>(hi - lo));
    v.intercept = vocab_intercepts[vi];
    v.build(count, key_offsets + key_base, lo);
    key_base += count;
  }
  return vs;
}

void pml_vocabset_free(void* handle) {
  delete static_cast<VocabSet*>(handle);
}

// Create a reader bound to a (shared) vocabulary set. field_prog: flat
// int32 triples (op, wire, arg). feat_desc: [nfields, name_pos, term_pos,
// value_pos, wire0, opt0, wire1, opt1, ...]. entity_blob/entity_offsets
// carry the requested metadataMap keys offset-framed like vocab keys.
void* pml_reader_new(const int32_t* field_prog, int32_t nfields,
                     const int32_t* feat_desc, void* vocabset,
                     const char* entity_blob, const int64_t* entity_offsets,
                     int32_t nentities, int32_t collect_keys) {
  Reader* r = new Reader();
  r->collect_keys = collect_keys != 0;
  r->vocabset = static_cast<const VocabSet*>(vocabset);
  int32_t nvocabs = static_cast<int32_t>(r->vocabset->vocabs.size());
  // the Python contract reserves columns 0..2 (label/offset/weight) even
  // when the schema lacks some of those fields; absent columns read as
  // all-default with seen=0.
  int64_t nscalars = 3;
  for (int32_t i = 0; i < nfields; ++i) {
    FieldProg f{field_prog[3 * i], field_prog[3 * i + 1],
                field_prog[3 * i + 2]};
    r->prog.push_back(f);
    if ((f.op & 0xFF) == OP_SCALAR_COL && f.arg >= nscalars)
      nscalars = f.arg + 1;
  }
  r->nscalars = nscalars;
  r->scalar_cols.resize(nscalars);
  r->scalar_seen.resize(nscalars);

  int32_t nf = feat_desc[0];
  r->feat_name = feat_desc[1];
  r->feat_term = feat_desc[2];
  r->feat_value = feat_desc[3];
  for (int32_t i = 0; i < nf; ++i) {
    r->feat_wires.push_back(feat_desc[4 + 2 * i]);
    r->feat_optional.push_back(static_cast<uint8_t>(feat_desc[5 + 2 * i]));
  }

  r->coo_rows.resize(nvocabs);
  r->coo_cols.resize(nvocabs);
  r->coo_vals.resize(nvocabs);

  for (int32_t i = 0; i < nentities; ++i)
    r->entity_keys.emplace_back(
        entity_blob + entity_offsets[i],
        static_cast<size_t>(entity_offsets[i + 1] - entity_offsets[i]));
  r->entities.resize(r->entity_keys.size());
  return r;
}

// Feed one container-file BLOCK (already framed by Python: count + payload).
// codec: 0 = null, 1 = deflate. Returns records decoded, or -1 on error.
int64_t pml_reader_feed(void* handle, const uint8_t* data, int64_t len,
                        int64_t count, int32_t codec) {
  Reader* r = static_cast<Reader*>(handle);
  const uint8_t* payload = data;
  size_t payload_len = static_cast<size_t>(len);
  if (codec == 1) {
    if (!inflate_raw(data, static_cast<size_t>(len), r->inflate_buf)) {
      r->error = "deflate decompression failed";
      return -1;
    }
    payload = r->inflate_buf.data();
    payload_len = r->inflate_buf.size();
  }
  Slice s{payload, payload_len};
  for (int64_t i = 0; i < count; ++i) {
    if (!decode_record(*r, s)) {
      if (r->error.empty()) r->error = "malformed record";
      return -1;
    }
  }
  return count;
}

// Feed an entire container-file BODY (everything after the header's sync
// marker): iterates blocks natively — count, byte size, payload, sync —
// so Python makes ONE GIL-releasing call per file. Returns total records
// decoded, -1 on decode error, -2 on framing/sync error.
int64_t pml_reader_feed_blocks(void* handle, const uint8_t* data,
                               int64_t start, int64_t len, int32_t codec,
                               const uint8_t* sync) {
  // `start` lets Python pass the whole mapped file and skip the header
  // without slicing a second full-size bytes object.
  Reader* r = static_cast<Reader*>(handle);
  Slice s{data + start, static_cast<size_t>(len - start)};
  int64_t total = 0;
  while (s.off < s.n) {
    int64_t count = read_long(s);
    int64_t nbytes = read_long(s);
    if (s.fail || count < 0 || nbytes < 0 ||
        !s.need(static_cast<size_t>(nbytes))) {
      r->error = "bad block framing";
      return -2;
    }
    const uint8_t* payload = s.p + s.off;
    s.off += static_cast<size_t>(nbytes);
    if (!s.need(16)) {
      r->error = "truncated sync marker";
      return -2;
    }
    if (std::memcmp(s.p + s.off, sync, 16) != 0) {
      r->error = "bad sync marker (corrupt file)";
      return -2;
    }
    s.off += 16;
    int64_t got = pml_reader_feed(handle, payload, nbytes, count, codec);
    if (got < 0) return -1;
    total += got;
  }
  return total;
}

}  // extern "C"

namespace {

struct BlockRef {
  const uint8_t* payload;
  int64_t nbytes;
  int64_t count;
};

bool scan_blocks(Slice& s, const uint8_t* sync, std::vector<BlockRef>& out,
                 std::string& err) {
  while (s.off < s.n) {
    int64_t count = read_long(s);
    int64_t nbytes = read_long(s);
    if (s.fail || count < 0 || nbytes < 0 ||
        !s.need(static_cast<size_t>(nbytes))) {
      err = "bad block framing";
      return false;
    }
    const uint8_t* payload = s.p + s.off;
    s.off += static_cast<size_t>(nbytes);
    if (!s.need(16)) {
      err = "truncated sync marker";
      return false;
    }
    if (std::memcmp(s.p + s.off, sync, 16) != 0) {
      err = "bad sync marker (corrupt file)";
      return false;
    }
    s.off += 16;
    out.push_back(BlockRef{payload, nbytes, count});
  }
  return true;
}

Reader* clone_config(const Reader& src) {
  Reader* r = new Reader();
  r->prog = src.prog;
  r->feat_wires = src.feat_wires;
  r->feat_optional = src.feat_optional;
  r->feat_name = src.feat_name;
  r->feat_term = src.feat_term;
  r->feat_value = src.feat_value;
  r->vocabset = src.vocabset;
  r->entity_keys = src.entity_keys;
  r->nscalars = src.nscalars;
  r->scalar_cols.resize(static_cast<size_t>(src.nscalars));
  r->scalar_seen.resize(static_cast<size_t>(src.nscalars));
  r->entities.resize(src.entity_keys.size());
  r->coo_rows.resize(src.coo_rows.size());
  r->coo_cols.resize(src.coo_cols.size());
  r->coo_vals.resize(src.coo_vals.size());
  r->collect_keys = src.collect_keys;
  return r;
}

// Append a sub-reader's accumulators in record order: columnar memcpy,
// string-pool offset shift, COO row-id shift by the running record base.
void merge_into(Reader& dst, Reader& sub) {
  int64_t row_base = dst.nrecords;
  for (int32_t c = 0; c < dst.nscalars; ++c) {
    auto& d = dst.scalar_cols[c];
    auto& s2 = sub.scalar_cols[c];
    d.insert(d.end(), s2.begin(), s2.end());
    auto& ds = dst.scalar_seen[c];
    auto& ss = sub.scalar_seen[c];
    ds.insert(ds.end(), ss.begin(), ss.end());
  }
  auto merge_pool = [](StringPool& d, const StringPool& s2) {
    int64_t base = static_cast<int64_t>(d.bytes.size());
    d.bytes.append(s2.bytes);
    for (size_t i = 1; i < s2.offsets.size(); ++i)
      d.offsets.push_back(s2.offsets[i] + base);
  };
  merge_pool(dst.uids, sub.uids);
  for (size_t e = 0; e < dst.entities.size(); ++e)
    merge_pool(dst.entities[e], sub.entities[e]);
  for (size_t vi = 0; vi < dst.coo_rows.size(); ++vi) {
    auto& dr = dst.coo_rows[vi];
    dr.reserve(dr.size() + sub.coo_rows[vi].size());
    for (int32_t row : sub.coo_rows[vi])
      dr.push_back(static_cast<int32_t>(row + row_base));
    auto& dc = dst.coo_cols[vi];
    dc.insert(dc.end(), sub.coo_cols[vi].begin(), sub.coo_cols[vi].end());
    auto& dv = dst.coo_vals[vi];
    dv.insert(dv.end(), sub.coo_vals[vi].begin(), sub.coo_vals[vi].end());
  }
  if (dst.collect_keys) dst.keyset.merge(sub.keyset);
  dst.nrecords += sub.nrecords;
}

}  // namespace

extern "C" {

// Multithreaded body decode: container blocks are independently framed
// (count + payload + sync) and the framing carries the record counts, so
// blocks scan cheaply up front, decode on `nthreads` threads into private
// accumulators, and merge back IN ORDER — output is bit-identical to the
// sequential path. This is the within-host analog of the reference
// decoding Avro across Spark executors (``avro/AvroIOUtils.scala:46-139``).
int64_t pml_reader_feed_blocks_mt(void* handle, const uint8_t* data,
                                  int64_t start, int64_t len, int32_t codec,
                                  const uint8_t* sync, int32_t nthreads) {
  Reader* r = static_cast<Reader*>(handle);
  Slice s{data + start, static_cast<size_t>(len - start)};
  std::vector<BlockRef> blocks;
  if (!scan_blocks(s, sync, blocks, r->error)) return -2;
  if (nthreads <= 1 || blocks.size() < 2) {
    int64_t total = 0;
    for (const BlockRef& b : blocks) {
      int64_t got = pml_reader_feed(handle, b.payload, b.nbytes, b.count,
                                    codec);
      if (got < 0) return -1;
      total += got;
    }
    return total;
  }
  size_t T = std::min<size_t>(static_cast<size_t>(nthreads), blocks.size());
  int64_t total_bytes = 0;
  for (const BlockRef& b : blocks) total_bytes += b.nbytes;
  // contiguous chunks balanced by compressed payload bytes
  std::vector<std::pair<size_t, size_t>> ranges;
  {
    size_t bi = 0;
    int64_t acc = 0;
    for (size_t t = 0; t < T && bi < blocks.size(); ++t) {
      int64_t target =
          total_bytes * static_cast<int64_t>(t + 1) / static_cast<int64_t>(T);
      size_t st = bi;
      while (bi < blocks.size() && (acc < target || bi == st))
        acc += blocks[bi++].nbytes;
      if (t == T - 1) bi = blocks.size();
      ranges.emplace_back(st, bi);
    }
  }
  std::vector<Reader*> subs;
  subs.reserve(ranges.size());
  for (size_t t = 0; t < ranges.size(); ++t) subs.push_back(clone_config(*r));
  std::vector<int64_t> rcs(ranges.size(), 0);
  std::vector<std::thread> threads;
  threads.reserve(ranges.size());
  for (size_t t = 0; t < ranges.size(); ++t) {
    threads.emplace_back([&, t]() {
      Reader* sr = subs[t];
      for (size_t b = ranges[t].first; b < ranges[t].second; ++b) {
        int64_t got = pml_reader_feed(sr, blocks[b].payload,
                                      blocks[b].nbytes, blocks[b].count,
                                      codec);
        if (got < 0) {
          rcs[t] = -1;
          return;
        }
        rcs[t] += got;
      }
    });
  }
  for (auto& th : threads) th.join();
  int64_t total = 0;
  bool fail = false;
  for (size_t t = 0; t < subs.size(); ++t) {
    if (rcs[t] < 0) {
      fail = true;
      if (r->error.empty())
        r->error = subs[t]->error.empty() ? "malformed record"
                                          : subs[t]->error;
    }
  }
  if (!fail) {
    for (Reader* sr : subs) {
      merge_into(*r, *sr);
      total += sr->nrecords;
    }
  }
  for (Reader* sr : subs) delete sr;
  return fail ? -1 : total;
}

int64_t pml_reader_nrecords(void* handle) {
  return static_cast<Reader*>(handle)->nrecords;
}

// sizes: out[0]=uid_bytes, then per entity key: bytes; per vocab: nnz
void pml_reader_sizes(void* handle, int64_t* out) {
  Reader* r = static_cast<Reader*>(handle);
  int k = 0;
  out[k++] = static_cast<int64_t>(r->uids.bytes.size());
  for (auto& e : r->entities) out[k++] = static_cast<int64_t>(e.bytes.size());
  for (auto& c : r->coo_rows) out[k++] = static_cast<int64_t>(c.size());
}

void pml_reader_scalar(void* handle, int32_t col, double* out,
                       uint8_t* seen_out) {
  Reader* r = static_cast<Reader*>(handle);
  auto& c = r->scalar_cols[col];
  std::memcpy(out, c.data(), c.size() * sizeof(double));
  auto& sflags = r->scalar_seen[col];
  if (seen_out) {
    // columns whose field exists carry one flag per record; a column whose
    // field is absent from the schema has no flags => never seen.
    std::memset(seen_out, 0, static_cast<size_t>(r->nrecords));
    std::memcpy(seen_out, sflags.data(), sflags.size());
  }
}

void pml_reader_strings(void* handle, int32_t which, int64_t* offsets,
                        char* bytes) {
  // which: -1 = uids, >=0 = entity pool
  Reader* r = static_cast<Reader*>(handle);
  StringPool& p = which < 0 ? r->uids : r->entities[which];
  std::memcpy(offsets, p.offsets.data(), p.offsets.size() * sizeof(int64_t));
  std::memcpy(bytes, p.bytes.data(), p.bytes.size());
}

void pml_reader_coo(void* handle, int32_t vocab, int32_t* rows, int32_t* cols,
                    double* vals) {
  Reader* r = static_cast<Reader*>(handle);
  auto& cr = r->coo_rows[vocab];
  std::memcpy(rows, cr.data(), cr.size() * sizeof(int32_t));
  std::memcpy(cols, r->coo_cols[vocab].data(),
              cr.size() * sizeof(int32_t));
  std::memcpy(vals, r->coo_vals[vocab].data(), cr.size() * sizeof(double));
}

// distinct-key export (vocabulary building): total byte size then data.
int64_t pml_reader_keys_bytes(void* handle, int64_t* nkeys_out) {
  Reader* r = static_cast<Reader*>(handle);
  int64_t total = 0;
  for (const auto& k : r->keyset) total += static_cast<int64_t>(k.size());
  *nkeys_out = static_cast<int64_t>(r->keyset.size());
  return total;
}

void pml_reader_keys(void* handle, int64_t* offsets, char* bytes) {
  Reader* r = static_cast<Reader*>(handle);
  int64_t off = 0;
  int64_t i = 0;
  offsets[0] = 0;
  for (const auto& k : r->keyset) {
    std::memcpy(bytes + off, k.data(), k.size());
    off += static_cast<int64_t>(k.size());
    offsets[++i] = off;
  }
}

const char* pml_reader_error(void* handle) {
  return static_cast<Reader*>(handle)->error.c_str();
}

void pml_reader_free(void* handle) { delete static_cast<Reader*>(handle); }

}  // extern "C"

// ---------------------------------------------------------------------------
// columnar writer (the scoring driver's output path)
// ---------------------------------------------------------------------------
// Flat-record encoder: Python passes per-field write ops over columnar
// arrays; the container framing (header with schema JSON, deflate blocks,
// sync markers) is produced here. Schemas with arrays/maps of values fall
// back to the Python codec — the hot write path (ScoringResultAvro) is
// flat scalars + optional strings + always-null unions.

namespace {

enum WriteOp : int32_t {
  WOP_DOUBLE = 1,       // non-null double from column `arg`
  WOP_OPT_DOUBLE = 2,   // [null, double] union from column + present flags
  WOP_OPT_STRING = 3,   // [null, string] union from pool `arg`
  WOP_NULL_UNION = 4,   // union whose value is always null (branch 0)
  WOP_FLOAT = 5,        // non-null float (4-byte wire) from column `arg`
  WOP_OPT_FLOAT = 6,    // [null, float] union from column + present flags
};

void put_varlong(std::string& out, int64_t v) {
  uint64_t n = (static_cast<uint64_t>(v) << 1) ^
               static_cast<uint64_t>(v >> 63);
  while (true) {
    uint8_t b = n & 0x7F;
    n >>= 7;
    if (n) {
      out.push_back(static_cast<char>(b | 0x80));
    } else {
      out.push_back(static_cast<char>(b));
      return;
    }
  }
}

bool deflate_raw(const std::string& src, std::string& dst) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  // raw deflate (windowBits -15), default level
  if (deflateInit2(&zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED, -15, 8,
                   Z_DEFAULT_STRATEGY) != Z_OK)
    return false;
  dst.resize(deflateBound(&zs, src.size()));
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(src.data()));
  zs.avail_in = static_cast<uInt>(src.size());
  zs.next_out = reinterpret_cast<Bytef*>(dst.data());
  zs.avail_out = static_cast<uInt>(dst.size());
  int rc = deflate(&zs, Z_FINISH);
  bool ok = rc == Z_STREAM_END;
  dst.resize(dst.size() - zs.avail_out);
  deflateEnd(&zs);
  return ok;
}

}  // namespace

extern "C" {

// Write an Avro container file of flat records from columnar arrays.
// ops: int32 pairs (op, arg). doubles: [ncols][n] row-major as one flat
// array. present: per optional-double column, n flags (may alias). pools:
// per string column, n+1 byte offsets + bytes (empty string == null).
// sync: 16 random bytes from the caller (the spec's per-file marker).
// codec: 0 = null, 1 = deflate. Returns 0 on success, negative on error.
int64_t pml_write_columnar(const char* path, const char* schema_json,
                           int64_t n, const int32_t* ops, int32_t nops,
                           const double* doubles,
                           const uint8_t* present_flags,
                           const int64_t* pool_offsets,
                           const char* pool_bytes, const char* sync,
                           int32_t codec, int64_t block_records) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  // header
  std::string header;
  header.append("Obj\x01", 4);
  put_varlong(header, 2);  // two metadata entries
  auto put_str = [&header](const std::string& s) {
    put_varlong(header, static_cast<int64_t>(s.size()));
    header.append(s);
  };
  put_str("avro.schema");
  put_str(schema_json);
  put_str("avro.codec");
  put_str(codec == 1 ? "deflate" : "null");
  put_varlong(header, 0);
  header.append(sync, 16);
  if (std::fwrite(header.data(), 1, header.size(), f) != header.size()) {
    std::fclose(f);
    return -2;
  }

  if (block_records <= 0) block_records = 4096;
  std::string block, packed, framed;
  // column layout bookkeeping: each op's `arg` indexes the shared arrays
  for (int64_t start = 0; start < n; start += block_records) {
    int64_t count = std::min(block_records, n - start);
    block.clear();
    for (int64_t i = start; i < start + count; ++i) {
      for (int32_t o = 0; o < nops; ++o) {
        int32_t op = ops[2 * o];
        int32_t arg = ops[2 * o + 1];
        switch (op) {
          case WOP_DOUBLE: {
            double v = doubles[static_cast<int64_t>(arg) * n + i];
            char buf[8];
            std::memcpy(buf, &v, 8);
            block.append(buf, 8);
            break;
          }
          case WOP_OPT_DOUBLE: {
            bool present =
                present_flags[static_cast<int64_t>(arg) * n + i] != 0;
            put_varlong(block, present ? 1 : 0);  // [null, double]
            if (present) {
              double v = doubles[static_cast<int64_t>(arg) * n + i];
              char buf[8];
              std::memcpy(buf, &v, 8);
              block.append(buf, 8);
            }
            break;
          }
          case WOP_OPT_STRING: {
            int64_t a = pool_offsets[static_cast<int64_t>(arg) * (n + 1) + i];
            int64_t b =
                pool_offsets[static_cast<int64_t>(arg) * (n + 1) + i + 1];
            if (b > a) {
              put_varlong(block, 1);  // [null, string]
              put_varlong(block, b - a);
              block.append(pool_bytes + a, static_cast<size_t>(b - a));
            } else {
              put_varlong(block, 0);
            }
            break;
          }
          case WOP_NULL_UNION:
            put_varlong(block, 0);
            break;
          case WOP_FLOAT: {
            float v = static_cast<float>(
                doubles[static_cast<int64_t>(arg) * n + i]);
            char buf[4];
            std::memcpy(buf, &v, 4);
            block.append(buf, 4);
            break;
          }
          case WOP_OPT_FLOAT: {
            bool present =
                present_flags[static_cast<int64_t>(arg) * n + i] != 0;
            put_varlong(block, present ? 1 : 0);  // [null, float]
            if (present) {
              float v = static_cast<float>(
                  doubles[static_cast<int64_t>(arg) * n + i]);
              char buf[4];
              std::memcpy(buf, &v, 4);
              block.append(buf, 4);
            }
            break;
          }
          default:
            std::fclose(f);
            return -3;
        }
      }
    }
    const std::string* payload = &block;
    if (codec == 1) {
      if (!deflate_raw(block, packed)) {
        std::fclose(f);
        return -4;
      }
      payload = &packed;
    }
    framed.clear();
    put_varlong(framed, count);
    put_varlong(framed, static_cast<int64_t>(payload->size()));
    framed.append(*payload);
    framed.append(sync, 16);
    if (std::fwrite(framed.data(), 1, framed.size(), f) != framed.size()) {
      std::fclose(f);
      return -2;
    }
  }
  if (std::fclose(f) != 0) return -2;
  return 0;
}

}  // extern "C"
