"""Per-feature statistics: one fused masked pass over the batch.

Rebuild of ``stat/BasicStatistics.scala`` + ``stat/BasicStatisticalSummary.scala:33-127``
(a wrapper over Spark mllib ``Statistics.colStats`` with NaN / negative-variance
sanitization). Here the whole summary is one jitted reduction over the (n, d)
design matrix — under pjit with the batch axis sharded, XLA turns the sums
into psum collectives; pass axis_name explicitly when using shard_map.

The summary feeds normalization (``core/normalization.build_normalization_context``)
and the feature-summary output of the driver (``Driver.scala:212-225``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.core.types import LabeledBatch, _pytree_dataclass
from photon_ml_tpu.ops import sparse as sparse_ops


@_pytree_dataclass
class BasicStatisticalSummary:
    """Per-feature moments; all fields (d,) except count (scalar).

    Mirrors ``BasicStatisticalSummary.scala``: mean, variance, count, min,
    max, normL1, normL2, meanAbs, numNonzeros. Variance is the unbiased
    (n-1) sample variance like Spark's colStats, sanitized to 0 when
    negative/NaN (``BasicStatisticalSummary.scala:60-127``).
    """

    mean: jax.Array
    variance: jax.Array
    count: jax.Array
    min: jax.Array
    max: jax.Array
    norm_l1: jax.Array
    norm_l2: jax.Array
    mean_abs: jax.Array
    num_nonzeros: jax.Array

    @property
    def max_abs(self) -> jax.Array:
        """max(|x|) per feature, for SCALE_WITH_MAX_MAGNITUDE."""
        return jnp.maximum(jnp.abs(self.min), jnp.abs(self.max))


def summarize_features(
    batch: LabeledBatch, axis_name: Optional[str] = None
) -> BasicStatisticalSummary:
    """Single-pass masked column statistics (unweighted rows, like colStats).
    Sparse batches take the scatter-kernel path (implicit zeros included in
    every statistic, matching the dense semantics)."""
    x = batch.features
    if sparse_ops.is_feature_sharded(x):
        import dataclasses as _dc

        if x.is_balanced:
            # virtual rows are per-block packings, not batch rows — the
            # flat-ELL view is a host-side rebuild, so the balanced
            # layout gets its own trace-safe direct summary
            return _summarize_balanced_blocked(batch, axis_name)
        # flatten to one ELL over the blocked column space; statistics come
        # back in blocked layout, matching the solver's coefficient layout
        flat = _dc.replace(
            batch, features=sparse_ops.feature_sharded_as_ell(x)
        )
        return _summarize_sparse(flat, axis_name)
    if sparse_ops.is_hybrid(x):
        return _summarize_hybrid(batch, axis_name)
    if sparse_ops.is_sparse(x):
        return _summarize_sparse(batch, axis_name)
    m = batch.mask[:, None]
    # where (not *): padding rows may legitimately hold NaN/Inf (validators
    # exempt masked rows) and NaN * 0 would poison every sum
    xm = jnp.where(m > 0, x, 0.0)

    def _psum(v):
        return jax.lax.psum(v, axis_name) if axis_name is not None else v

    n = _psum(jnp.sum(batch.mask))
    s1 = _psum(jnp.sum(xm, axis=0))
    s2 = _psum(jnp.sum(xm * xm, axis=0))
    sabs = _psum(jnp.sum(jnp.abs(xm), axis=0))
    nnz = _psum(jnp.sum((xm != 0.0) * m, axis=0))
    # masked rows must not contribute to min/max: substitute +/- inf
    big = jnp.asarray(jnp.inf, x.dtype)
    mn = _psum_min(jnp.min(jnp.where(m > 0, x, big), axis=0), axis_name)
    mx = _psum_max(jnp.max(jnp.where(m > 0, x, -big), axis=0), axis_name)

    safe_n = jnp.maximum(n, 1.0)
    mean = s1 / safe_n
    # unbiased sample variance, sanitized like the reference
    var = (s2 - n * mean * mean) / jnp.maximum(n - 1.0, 1.0)
    var = jnp.where(jnp.isfinite(var) & (var > 0.0), var, 0.0)

    return BasicStatisticalSummary(
        mean=mean,
        variance=var,
        count=n,
        min=mn,
        max=mx,
        norm_l1=sabs,
        norm_l2=jnp.sqrt(s2),
        mean_abs=sabs / safe_n,
        num_nonzeros=nnz,
    )


def _psum_min(v, axis_name):
    return -jax.lax.pmax(-v, axis_name) if axis_name is not None else v


def _psum_max(v, axis_name):
    return jax.lax.pmax(v, axis_name) if axis_name is not None else v


def _summarize_hybrid(
    batch: LabeledBatch, axis_name: Optional[str] = None
) -> BasicStatisticalSummary:
    """Hybrid = disjoint column split, so per-column statistics merge by
    overwrite: hot columns take the dense-slab stats (the cold pass sees
    them as all-zero columns), cold columns keep the ELL-scatter stats."""
    import dataclasses as _dc

    x = batch.features
    cold = summarize_features(
        _dc.replace(batch, features=sparse_ops.cold_as_single_ell(x)),
        axis_name,
    )
    slab = summarize_features(
        _dc.replace(batch, features=x.dense), axis_name
    )
    hot = x.hot_ids

    def merge(cold_v, slab_v):
        return cold_v.at[hot].set(slab_v.astype(cold_v.dtype))

    return BasicStatisticalSummary(
        mean=merge(cold.mean, slab.mean),
        variance=merge(cold.variance, slab.variance),
        count=cold.count,
        min=merge(cold.min, slab.min),
        max=merge(cold.max, slab.max),
        norm_l1=merge(cold.norm_l1, slab.norm_l1),
        norm_l2=merge(cold.norm_l2, slab.norm_l2),
        mean_abs=merge(cold.mean_abs, slab.mean_abs),
        num_nonzeros=merge(cold.num_nonzeros, slab.num_nonzeros),
    )


def _summarize_balanced_blocked(
    batch: LabeledBatch, axis_name: Optional[str] = None
) -> BasicStatisticalSummary:
    """Column statistics over a row-BALANCED blocked container
    (docs/PARALLEL.md) without the flat-ELL view: the moment sums ride
    the container's own colsum (whose back-projection routes per-entry
    masks through the row map), and min/max scatter per entry into the
    blocked column space with the same implicit-zero correction as
    ``_summarize_sparse``. Statistics come back in BLOCKED layout,
    matching the solver's coefficient layout."""
    import dataclasses

    x = batch.features
    m = batch.mask
    dtype = x.values.dtype
    d_block = x.num_blocks * x.d_shard

    def _psum(v):
        return jax.lax.psum(v, axis_name) if axis_name is not None else v

    n = _psum(jnp.sum(m))
    s1 = _psum(sparse_ops.colsum(x, m))
    s2 = _psum(sparse_ops.colsum(x, m, square=True))
    absx = dataclasses.replace(x, values=jnp.abs(x.values))
    sabs = _psum(sparse_ops.colsum(absx, m))
    nzx = dataclasses.replace(x, values=(x.values != 0.0).astype(dtype))
    nnz = _psum(sparse_ops.colsum(nzx, m))
    onesx = dataclasses.replace(x, values=jnp.ones_like(x.values))
    stored = _psum(sparse_ops.colsum(onesx, m))

    # per-entry mask: the stored row of every virtual lane (identity for
    # the aligned head, routed for the overflow tail; sentinel lanes
    # gather-fill 0 = masked out)
    al = x.aligned_rows
    if al:
        head = jnp.broadcast_to(m[:al, None], (al, x.num_blocks))
        tail = m.at[x.row_map[al:]].get(mode="fill", fill_value=0.0)
        m_v = jnp.concatenate([head, tail], axis=0)  # (V, F)
    else:
        m_v = m.at[x.row_map].get(mode="fill", fill_value=0.0)
    big = jnp.asarray(jnp.inf, dtype)
    blk = jnp.arange(x.num_blocks, dtype=x.indices.dtype)[None, :, None]
    glob = jnp.where(
        x.indices < x.d_shard, blk * x.d_shard + x.indices, d_block
    )
    entry_ok = (x.indices < x.d_shard) & (m_v[..., None] > 0)
    flat_idx = jnp.where(entry_ok, glob, d_block).reshape(-1)
    mn_stored = (
        jnp.full((d_block,), big)
        .at[flat_idx]
        .min(jnp.where(entry_ok, x.values, big).reshape(-1), mode="drop")
    )
    mx_stored = (
        jnp.full((d_block,), -big)
        .at[flat_idx]
        .max(jnp.where(entry_ok, x.values, -big).reshape(-1), mode="drop")
    )
    mn_stored = _psum_min(mn_stored, axis_name)
    mx_stored = _psum_max(mx_stored, axis_name)
    has_zero = stored < n  # some unmasked row lacks a stored entry
    mn = jnp.where(has_zero, jnp.minimum(mn_stored, 0.0), mn_stored)
    mx = jnp.where(has_zero, jnp.maximum(mx_stored, 0.0), mx_stored)
    mn = jnp.where(jnp.isfinite(mn), mn, 0.0)
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)

    safe_n = jnp.maximum(n, 1.0)
    mean = s1 / safe_n
    var = (s2 - n * mean * mean) / jnp.maximum(n - 1.0, 1.0)
    var = jnp.where(jnp.isfinite(var) & (var > 0.0), var, 0.0)
    return BasicStatisticalSummary(
        mean=mean,
        variance=var,
        count=n,
        min=mn,
        max=mx,
        norm_l1=sabs,
        norm_l2=jnp.sqrt(s2),
        mean_abs=sabs / safe_n,
        num_nonzeros=nnz,
    )


def _summarize_sparse(
    batch: LabeledBatch, axis_name: Optional[str] = None
) -> BasicStatisticalSummary:
    """Column statistics over a padded-ELL sparse design without
    densifying: sums/moments via scatter-add, min/max via scatter-min/max
    corrected for each column's implicit zeros (a column stored in fewer
    unmasked rows than exist contains zeros, exactly as a dense matrix
    would)."""
    import dataclasses

    x = batch.features
    d = x.d
    m = batch.mask
    dtype = x.values.dtype

    def _psum(v):
        return jax.lax.psum(v, axis_name) if axis_name is not None else v

    n = _psum(jnp.sum(m))
    s1 = _psum(sparse_ops.colsum(x, m))
    s2 = _psum(sparse_ops.colsum(x, m, square=True))
    absx = dataclasses.replace(x, values=jnp.abs(x.values))
    sabs = _psum(sparse_ops.colsum(absx, m))
    nzx = dataclasses.replace(x, values=(x.values != 0.0).astype(dtype))
    nnz = _psum(sparse_ops.colsum(nzx, m))
    # stored-slot count per column (for implicit-zero detection); padding
    # slots carry index d, so the all-ones payload scatter-drops them
    onesx = dataclasses.replace(x, values=jnp.ones_like(x.values))
    stored = _psum(sparse_ops.colsum(onesx, m))

    big = jnp.asarray(jnp.inf, dtype)
    entry_ok = (x.indices < d) & (m[:, None] > 0)
    flat_idx = jnp.where(entry_ok, x.indices, d).reshape(-1)
    mn_stored = (
        jnp.full((d,), big)
        .at[flat_idx]
        .min(jnp.where(entry_ok, x.values, big).reshape(-1), mode="drop")
    )
    mx_stored = (
        jnp.full((d,), -big)
        .at[flat_idx]
        .max(jnp.where(entry_ok, x.values, -big).reshape(-1), mode="drop")
    )
    mn_stored = _psum_min(mn_stored, axis_name)
    mx_stored = _psum_max(mx_stored, axis_name)
    has_zero = stored < n  # some unmasked row lacks a stored entry
    mn = jnp.where(has_zero, jnp.minimum(mn_stored, 0.0), mn_stored)
    mx = jnp.where(has_zero, jnp.maximum(mx_stored, 0.0), mx_stored)
    mn = jnp.where(jnp.isfinite(mn), mn, 0.0)
    mx = jnp.where(jnp.isfinite(mx), mx, 0.0)

    safe_n = jnp.maximum(n, 1.0)
    mean = s1 / safe_n
    var = (s2 - n * mean * mean) / jnp.maximum(n - 1.0, 1.0)
    var = jnp.where(jnp.isfinite(var) & (var > 0.0), var, 0.0)
    return BasicStatisticalSummary(
        mean=mean,
        variance=var,
        count=n,
        min=mn,
        max=mx,
        norm_l1=sabs,
        norm_l2=jnp.sqrt(s2),
        mean_abs=sabs / safe_n,
        num_nonzeros=nnz,
    )
