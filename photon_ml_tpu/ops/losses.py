"""Pointwise GLM losses: l(z, y), dl/dz, d2l/dz2 on the margin z = x.w + offset.

Rebuild of the reference's ``function/PointwiseLossFunction.scala:23-39``
interface and its four concrete losses. Each loss is a triple of vectorized
functions of (margins, labels); everything else (weighting, reduction,
regularization, normalization) lives in ops/objective.py. First derivatives
are also available by autodiff, but the analytic forms below are what the
fused kernels use (one transcendental per element instead of a VJP graph).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """l(z,y), l'(z,y), l''(z,y) — all elementwise over same-shape arrays."""

    name: str
    value: Callable[[jax.Array, jax.Array], jax.Array]
    d1: Callable[[jax.Array, jax.Array], jax.Array]
    d2: Callable[[jax.Array, jax.Array], jax.Array]
    # E[y|z] link inverse for scoring (``GeneralizedLinearModel.computeMean``).
    mean: Callable[[jax.Array], jax.Array] = lambda z: z
    # smoothed hinge is first-order only in the reference (LBFGS-only,
    # ``function/SmoothedHingeLossFunction.scala:24-60``); its d2 is a
    # subgradient-style surrogate and TRON refuses it (models/training.py).
    twice_differentiable: bool = True


def _logistic_value(z, y):
    # l = log(1 + exp(-z)) for y=1, log(1 + exp(z)) for y=0/-1.
    # Stable via softplus, matching util/Utils.log1pExp
    # (``function/LogisticLossFunction.scala:31-88``). Labels are {0,1}.
    s = 2.0 * y - 1.0  # {0,1} -> {-1,+1}
    return jax.nn.softplus(-s * z)


def _logistic_d1(z, y):
    s = 2.0 * y - 1.0
    return -s * jax.nn.sigmoid(-s * z)  # = sigmoid(z) - y for y in {0,1}


def _logistic_d2(z, y):
    p = jax.nn.sigmoid(z)
    return p * (1.0 - p)


LOGISTIC_LOSS = PointwiseLoss(
    name="logistic",
    value=_logistic_value,
    d1=_logistic_d1,
    d2=_logistic_d2,
    mean=jax.nn.sigmoid,
)


SQUARED_LOSS = PointwiseLoss(
    # l = 0.5 (z - y)^2  (``function/SquaredLossFunction.scala:29-64``)
    name="squared",
    value=lambda z, y: 0.5 * (z - y) ** 2,
    d1=lambda z, y: z - y,
    d2=lambda z, y: jnp.ones_like(z),
)


POISSON_LOSS = PointwiseLoss(
    # l = exp(z) - y z  (``function/PoissonLossFunction.scala:29-81``)
    name="poisson",
    value=lambda z, y: jnp.exp(z) - y * z,
    d1=lambda z, y: jnp.exp(z) - y,
    d2=lambda z, y: jnp.exp(z),
    mean=jnp.exp,
)


def _smoothed_hinge_value(z, y):
    # Rennie smoothed hinge on s*z with s in {-1,+1}
    # (``function/SmoothedHingeLossFunction.scala:24-60``): labels {0,1}.
    s = 2.0 * y - 1.0
    m = s * z
    return jnp.where(m >= 1.0, 0.0, jnp.where(m <= 0.0, 0.5 - m, 0.5 * (1.0 - m) ** 2))


def _smoothed_hinge_d1(z, y):
    s = 2.0 * y - 1.0
    m = s * z
    dldm = jnp.where(m >= 1.0, 0.0, jnp.where(m <= 0.0, -1.0, m - 1.0))
    return s * dldm


def _smoothed_hinge_d2(z, y):
    s = 2.0 * y - 1.0
    m = s * z
    return jnp.where((m > 0.0) & (m < 1.0), 1.0, 0.0)


SMOOTHED_HINGE_LOSS = PointwiseLoss(
    name="smoothed_hinge",
    value=_smoothed_hinge_value,
    d1=_smoothed_hinge_d1,
    d2=_smoothed_hinge_d2,
    twice_differentiable=False,
)


_LOSS_BY_TASK = {
    "LOGISTIC_REGRESSION": LOGISTIC_LOSS,
    "LINEAR_REGRESSION": SQUARED_LOSS,
    "POISSON_REGRESSION": POISSON_LOSS,
    "SMOOTHED_HINGE_LOSS_LINEAR_SVM": SMOOTHED_HINGE_LOSS,
}


def loss_for_task(task_type) -> PointwiseLoss:
    """Task → loss dispatch (``ModelTraining.scala:50-93``)."""
    key = getattr(task_type, "name", task_type)
    if key not in _LOSS_BY_TASK:
        raise ValueError(
            f"unknown task type {task_type!r}; expected one of "
            f"{sorted(_LOSS_BY_TASK)}"
        )
    return _LOSS_BY_TASK[key]
