from photon_ml_tpu.ops.losses import (
    PointwiseLoss,
    LOGISTIC_LOSS,
    SQUARED_LOSS,
    POISSON_LOSS,
    SMOOTHED_HINGE_LOSS,
    loss_for_task,
)
from photon_ml_tpu.ops.objective import GLMObjective, RegularizationContext
from photon_ml_tpu.ops.sparse import SparseFeatures
from photon_ml_tpu.ops.stats import BasicStatisticalSummary, summarize_features
from photon_ml_tpu.ops import metrics, sparse

__all__ = [
    "SparseFeatures",
    "sparse",
    "RegularizationContext",
    "BasicStatisticalSummary",
    "summarize_features",
    "metrics",
    "PointwiseLoss",
    "LOGISTIC_LOSS",
    "SQUARED_LOSS",
    "POISSON_LOSS",
    "SMOOTHED_HINGE_LOSS",
    "loss_for_task",
    "GLMObjective",
]
