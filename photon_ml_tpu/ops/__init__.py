from photon_ml_tpu.ops.losses import (
    PointwiseLoss,
    LOGISTIC_LOSS,
    SQUARED_LOSS,
    POISSON_LOSS,
    SMOOTHED_HINGE_LOSS,
    loss_for_task,
)
from photon_ml_tpu.ops.objective import GLMObjective

__all__ = [
    "PointwiseLoss",
    "LOGISTIC_LOSS",
    "SQUARED_LOSS",
    "POISSON_LOSS",
    "SMOOTHED_HINGE_LOSS",
    "loss_for_task",
    "GLMObjective",
]
