from photon_ml_tpu.ops.losses import (
    PointwiseLoss,
    LOGISTIC_LOSS,
    SQUARED_LOSS,
    POISSON_LOSS,
    SMOOTHED_HINGE_LOSS,
    loss_for_task,
)
from photon_ml_tpu.ops.objective import GLMObjective, RegularizationContext
from photon_ml_tpu.ops.sparse import (
    HybridFeatures,
    SparseFeatures,
    to_hybrid,
)
from photon_ml_tpu.ops.stats import BasicStatisticalSummary, summarize_features
from photon_ml_tpu.ops import metrics, sparse

__all__ = [
    "HybridFeatures",
    "SparseFeatures",
    "to_hybrid",
    "sparse",
    "RegularizationContext",
    "BasicStatisticalSummary",
    "summarize_features",
    "metrics",
    "PointwiseLoss",
    "LOGISTIC_LOSS",
    "SQUARED_LOSS",
    "POISSON_LOSS",
    "SMOOTHED_HINGE_LOSS",
    "loss_for_task",
    "GLMObjective",
]
