"""Fused GLM objectives: value / gradient / Hessian-vector / Hessian-diagonal.

This is the TPU replacement for the reference's whole L2 layer:
``function/DiffFunction.scala``, ``function/TwiceDiffFunction.scala``,
``function/ValueAndGradientAggregator.scala``,
``function/HessianVectorAggregator.scala`` and
``function/GeneralizedLinearModelLossFunction.scala``.

Where the reference tree-aggregates a per-datum scalar loop over executors, we
compute the same sums as two matmuls on the MXU:

    margins = X @ (w * factor) + margin_shift(w) + offsets          (n,)
    a       = weight * mask * l'(margins, labels)                   (n,)
    grad    = factor * (X^T @ a) - (shift*factor) * sum(a)          (d,)

The (factor, shift) algebra is exactly the reference aggregators'
effectiveCoefficients / margin-shift trick
(``ValueAndGradientAggregator.scala:87-118``): features are never whitened in
memory; normalization costs one extra rank-1 correction. The Hessian-vector
product uses the analytic second derivative the same way
(``HessianVectorAggregator.scala:57-117``) — no double-backprop graph.

Distribution: every method computes *local* partial sums. Under shard_map with
the batch axis sharded, pass ``axis_name`` and the partials are psum-reduced
over ICI — the one-line equivalent of the reference's
``RDD.treeAggregate(depth)`` (``function/DiffFunction.scala:126-143``). Under
plain jit with sharded inputs, leave ``axis_name=None`` and XLA inserts the
collectives from the sharding annotations.

L2 regularization is folded into the objective (value/grad/HVP/diag), L1 is
*not* — it is exposed as ``l1_weight`` for the OWL-QN optimizer, mirroring
``function/L1RegularizationTerm.scala`` + ``optimization/LBFGS.scala:56-98``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.core.normalization import NormalizationContext, no_normalization
from photon_ml_tpu.core.types import LabeledBatch
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.sparse import (
    colsum,
    is_feature_sharded,
    is_sparse,
    matvec,
    matvec_and_feature_dots,
    rmatvec,
)


def _maybe_psum(x, axis_name):
    return jax.lax.psum(x, axis_name) if axis_name is not None else x


_REG_TYPES = ("NONE", "L1", "L2", "ELASTIC_NET")


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    """Elastic-net split of a single regularization weight
    (``optimization/RegularizationContext.scala:25-47``):
    l1 = alpha * lambda, l2 = (1 - alpha) * lambda."""

    reg_type: str = "NONE"  # NONE | L1 | L2 | ELASTIC_NET
    alpha: float = 0.0  # elastic-net mixing; 1.0 = pure L1

    def __post_init__(self):
        if self.reg_type not in _REG_TYPES:
            raise ValueError(
                f"unknown reg_type {self.reg_type!r}; expected one of {_REG_TYPES}"
            )
        if not 0.0 <= self.alpha <= 1.0:
            raise ValueError(f"elastic-net alpha must be in [0,1], got {self.alpha}")

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type == "L1":
            return reg_weight
        if self.reg_type == "ELASTIC_NET":
            return self.alpha * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type == "L2":
            return reg_weight
        if self.reg_type == "ELASTIC_NET":
            return (1.0 - self.alpha) * reg_weight
        return 0.0


@dataclasses.dataclass(frozen=True)
class GLMObjective:
    """A pointwise loss bound to normalization + regularization.

    All methods are pure functions of (w, batch) and are safe under jit, grad,
    vmap and shard_map — this single implementation serves both of the
    reference's execution regimes (the ``Either[RDD, Iterable]`` duality,
    ``optimization/Optimizer.scala:163-212``): the "global" instantiation runs
    batch-sharded with psum, the "per-entity" instantiation runs vmapped.
    """

    loss: PointwiseLoss
    normalization: NormalizationContext = dataclasses.field(
        default_factory=no_normalization
    )
    l2_weight: float = 0.0
    l1_weight: float = 0.0  # consumed by OWL-QN, NOT added to value/grad here
    axis_name: Optional[str] = None
    # Feature-sharded designs: ride the scalar feature-space dots (L2
    # value term, margin shift) on the margins block-sum so one bucketed
    # all-reduce serves the whole pass (ops.sparse.matvec_and_feature_dots;
    # BENCH_r05's sparse_fs_scaling 2-device regression). False restores
    # the one-collective-per-contraction formulation — kept for the
    # before/after cost-book comparison, not for production use.
    fuse_feature_reductions: bool = True

    @property
    def _has_l2(self) -> bool:
        """Trace-safe L2 gate: reg weights may be traced scalars (the lambda
        path jits ONE solve reused across lambdas), in which case the term is
        always emitted and XLA folds the zero case."""
        if isinstance(self.l2_weight, (int, float)):
            return self.l2_weight != 0.0
        return True

    # -- margins ---------------------------------------------------------

    def margins(self, w: jax.Array, batch: LabeledBatch) -> jax.Array:
        return self._dmargin_dot(w, batch) + batch.offsets

    def _dmargin_dot(self, v: jax.Array, batch: LabeledBatch) -> jax.Array:
        """(d margin / d w) @ v for each row — normalized-feature dot.
        Dispatches dense (MXU matmul) / sparse ELL (gather kernel). On
        feature-sharded designs with whitening shifts, the margin-shift
        dot rides the margins block-sum (one bucketed all-reduce)."""
        norm = self.normalization
        eff = norm.effective_coefficients(v)
        if (
            self.fuse_feature_reductions
            and norm.shifts is not None
            and is_feature_sharded(batch.features)
        ):
            z0, (ms,) = matvec_and_feature_dots(
                batch.features, eff, ((norm.shifts, eff),)
            )
            return z0 - ms
        return matvec(batch.features, eff) + norm.margin_shift(v)

    def _backproject(self, a: jax.Array, batch: LabeledBatch) -> jax.Array:
        """X'^T @ a where X' is the (virtually) normalized design matrix."""
        norm = self.normalization
        g = rmatvec(batch.features, a)
        if norm.factors is not None:
            g = g * norm.factors
        if norm.shifts is not None:
            shift_eff = norm.shifts * (
                norm.factors if norm.factors is not None else 1.0
            )
            g = g - shift_eff * jnp.sum(a)
        return g

    # -- value / gradient ------------------------------------------------

    def value(self, w: jax.Array, batch: LabeledBatch) -> jax.Array:
        return self.value_and_grad(w, batch)[0]

    def value_and_grad(self, w: jax.Array, batch: LabeledBatch):
        """Fused loss+gradient — the reference's hot aggregator
        (``ValueAndGradientAggregator.scala:204-235``) as two matmuls.
        (The unused curvature output is dead-code-eliminated under jit.)"""
        val, grad, _ = self.value_grad_curvature(w, batch)
        return val, grad

    def grad(self, w: jax.Array, batch: LabeledBatch) -> jax.Array:
        return self.value_and_grad(w, batch)[1]

    def value_grad_curvature(self, w: jax.Array, batch: LabeledBatch):
        """(value, gradient, curvature weights) from ONE margins pass.
        The curvature weights c = w_i * l''(z_i) are what
        :meth:`hessian_vector_at` needs — TRON's acceptance evaluation
        already computes z at the trial point, so on acceptance the next
        iteration's CG starts with c for free (no separate
        :meth:`hessian_coefficients` pass).

        Collectives: the value/grad partials reduce in ONE tuple psum
        (one collective per pass, not two); on feature-sharded designs
        the L2 value dot and margin shift additionally ride the margins
        block-sum (``matvec_and_feature_dots``). On Pallas-eligible ELL
        designs the whole pass is the single-design-read fused kernel
        (``kernels.fused_value_grad_curvature``)."""
        if self._use_fused_kernel(batch.features, w.dtype):
            return self._value_grad_curvature_fused(w, batch)
        norm = self.normalization
        wdot = None
        if (
            self.fuse_feature_reductions
            and is_feature_sharded(batch.features)
            and (self._has_l2 or norm.shifts is not None)
        ):
            eff = norm.effective_coefficients(w)
            pairs = []
            if norm.shifts is not None:
                pairs.append((norm.shifts, eff))
            if self._has_l2:
                pairs.append((w, w))
            z0, dots = matvec_and_feature_dots(batch.features, eff, pairs)
            if norm.shifts is not None:
                z0 = z0 - dots[0]
                dots = dots[1:]
            z = z0 + batch.offsets
            if self._has_l2:
                wdot = dots[0]
        else:
            z = self.margins(w, batch)
            if self._has_l2:
                wdot = jnp.vdot(w, w)
        ew = batch.effective_weights()
        val = jnp.sum(ew * self.loss.value(z, batch.labels))
        a = ew * self.loss.d1(z, batch.labels)
        grad = self._backproject(a, batch)
        c = ew * self.loss.d2(z, batch.labels)
        val, grad = _maybe_psum((val, grad), self.axis_name)
        if self._has_l2:
            val = val + 0.5 * self.l2_weight * wdot
            grad = grad + self.l2_weight * w
        return val, grad, c

    # -- fused Pallas passes (one design read per pass) ------------------

    def _use_fused_kernel(self, feats, w_dtype) -> bool:
        """Take the single-read fused Pallas pass? Plain ELL designs
        only (the hybrid/blocked containers keep their per-segment
        dispatch through matvec/rmatvec/colsum), under the same
        mode/backend/VMEM eligibility as the per-op kernels."""
        from photon_ml_tpu.ops.sparse import _use_pallas_for

        return is_sparse(feats) and _use_pallas_for(feats, w_dtype)

    def _fused_inputs(self, w: jax.Array, batch: LabeledBatch):
        """(effective coefficients, shift-folded offsets, weights) for
        the fused kernels: the margin shift is a scalar, so it folds
        into the per-row offsets outside the kernel."""
        norm = self.normalization
        eff = norm.effective_coefficients(w)
        off = batch.offsets + norm.margin_shift(w)
        return eff, off, batch.effective_weights()

    def _correct_backprojection(self, g, total_a):
        """The normalization algebra of :meth:`_backproject` applied to
        a raw X^T a from a fused kernel, with sum(a) already reduced
        in-kernel."""
        norm = self.normalization
        if norm.factors is not None:
            g = g * norm.factors
        if norm.shifts is not None:
            shift_eff = norm.shifts * (
                norm.factors if norm.factors is not None else 1.0
            )
            g = g - shift_eff * total_a
        return g

    def _value_grad_curvature_fused(self, w: jax.Array, batch: LabeledBatch):
        from photon_ml_tpu import kernels

        x = batch.features
        eff, off, ew = self._fused_inputs(w, batch)
        val, g, asum, c = kernels.fused_value_grad_curvature(
            x.indices, x.values, batch.labels, off, ew, eff, x.d, self.loss
        )
        grad = self._correct_backprojection(g, asum)
        val, grad = _maybe_psum((val, grad), self.axis_name)
        if self._has_l2:
            val = val + 0.5 * self.l2_weight * jnp.vdot(w, w)
            grad = grad + self.l2_weight * w
        return val, grad, c

    # -- second-order ----------------------------------------------------

    def hessian_vector(
        self, w: jax.Array, v: jax.Array, batch: LabeledBatch
    ) -> jax.Array:
        """H(w) @ v via analytic d2 (``HessianVectorAggregator.scala:57-117``).
        One CG iteration of TRON = one call here."""
        return self.hessian_vector_at(
            self.hessian_coefficients(w, batch), v, batch
        )

    def hessian_coefficients(
        self, w: jax.Array, batch: LabeledBatch
    ) -> jax.Array:
        """(n,) per-row curvature weights c = w_i * l''(z_i, y_i) — the
        only w-dependent part of H(w) @ v. Loop-INVARIANT across an inner
        CG solve (w is fixed while CG iterates over v), so TRON computes
        this once per outer iteration and each CG step saves the margins
        pass: 2 design reads per HVP instead of 3."""
        z = self.margins(w, batch)
        return batch.effective_weights() * self.loss.d2(z, batch.labels)

    def hessian_vector_at(
        self, c: jax.Array, v: jax.Array, batch: LabeledBatch
    ) -> jax.Array:
        """H @ v with the curvature weights ``c`` precomputed by
        :meth:`hessian_coefficients`. TRON's inner CG loop is almost
        entirely this call, so on Pallas-eligible ELL designs it takes
        the fused single-read sweep (``kernels.fused_hessian_vector``:
        the v-margins gather and the back-projection scatter share one
        walk of the stored design)."""
        if self._use_fused_kernel(batch.features, v.dtype):
            from photon_ml_tpu import kernels

            norm = self.normalization
            x = batch.features
            eff_v = norm.effective_coefficients(v)
            hv0, usum = kernels.fused_hessian_vector(
                x.indices, x.values, c, eff_v, norm.margin_shift(v), x.d
            )
            hv = self._correct_backprojection(hv0, usum)
        else:
            zv = self._dmargin_dot(v, batch)
            hv = self._backproject(c * zv, batch)
        hv = _maybe_psum(hv, self.axis_name)
        if self._has_l2:
            hv = hv + self.l2_weight * v
        return hv

    def hessian_diagonal(self, w: jax.Array, batch: LabeledBatch) -> jax.Array:
        """diag(H) for coefficient variances
        (``TwiceDiffFunction.scala:179-394``, used by
        ``OptimizationProblem.updateCoefficientsVariances``)."""
        norm = self.normalization
        x = batch.features
        if self._use_fused_kernel(x, w.dtype):
            from photon_ml_tpu import kernels

            eff, off, ew = self._fused_inputs(w, batch)
            d_x2, d_x, csum = kernels.fused_hessian_diagonal(
                x.indices, x.values, batch.labels, off, ew, eff, x.d,
                self.loss,
            )
            if norm.shifts is not None:
                s = norm.shifts
                diag = d_x2 - 2.0 * s * d_x + s * s * csum
            else:
                diag = d_x2
        else:
            z = self.margins(w, batch)
            c = batch.effective_weights() * self.loss.d2(z, batch.labels)
            d_x2 = colsum(x, c, square=True)
            if norm.shifts is not None:
                d_x = colsum(x, c)
                s = norm.shifts
                diag = d_x2 - 2.0 * s * d_x + s * s * jnp.sum(c)
            else:
                diag = d_x2
        if norm.factors is not None:
            diag = diag * norm.factors**2
        diag = _maybe_psum(diag, self.axis_name)
        if self._has_l2:
            diag = diag + self.l2_weight
        return diag

    def hessian_full(self, w: jax.Array, batch: LabeledBatch) -> jax.Array:
        """The EXPLICIT (d, d) Hessian X'^T diag(c) X' + l2 I — only
        sensible for small d, where it is one MXU-friendly pass.

        The reference has no analog: on Spark a d^2 treeAggregate is
        prohibitive, which is why its only optimizers are L-BFGS and
        Hessian-VECTOR TRON. On TPU a d<=O(10^3) cross-product is trivial
        (n d^2 matmul FLOPs, d^2 output), enabling exact Newton steps —
        one pass replaces an entire inner CG loop. Dense features with
        scale-only (or no) normalization."""
        norm = self.normalization
        if norm.shifts is not None:
            raise ValueError(
                "hessian_full supports scale-only normalization (whiten "
                "shifts change X densely; use hessian_vector instead)"
            )
        x = batch.features
        from photon_ml_tpu.ops.sparse import is_structured

        if is_structured(x):
            raise ValueError("hessian_full requires dense features")
        z = self.margins(w, batch)
        c = batch.effective_weights() * self.loss.d2(z, batch.labels)
        h = jnp.einsum("ni,n,nj->ij", x, c, x)
        if norm.factors is not None:
            h = h * jnp.outer(norm.factors, norm.factors)
        h = _maybe_psum(h, self.axis_name)
        if self._has_l2:
            h = h + self.l2_weight * jnp.eye(w.shape[-1], dtype=h.dtype)
        return h

    # -- variations ------------------------------------------------------

    def with_l2(self, l2_weight: float) -> "GLMObjective":
        return dataclasses.replace(self, l2_weight=l2_weight)

    def with_axis(self, axis_name: Optional[str]) -> "GLMObjective":
        return dataclasses.replace(self, axis_name=axis_name)

    def with_regularization(
        self, reg: RegularizationContext, reg_weight: float
    ) -> "GLMObjective":
        """``DiffFunction.withRegularization`` (``DiffFunction.scala:198-321``):
        L2 into the objective, L1 as an optimizer flag."""
        return dataclasses.replace(
            self,
            l2_weight=reg.l2_weight(reg_weight),
            l1_weight=reg.l1_weight(reg_weight),
        )
