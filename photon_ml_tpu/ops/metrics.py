"""Evaluation metrics as jitted device kernels.

Rebuild of the reference's two metric stacks:
  - batch metrics ``Evaluation.scala:30-140`` (RMSE/MAE/MSE via Spark
    RegressionMetrics; AUROC/AUPR/peak-F1 via BinaryClassificationMetrics;
    per-datum log-likelihood; AIC),
  - GAME evaluators ``evaluation/*.scala`` (AUC / RMSE / SquaredLoss /
    LogisticLoss over (label, offset, weight) triples) including the exact
    weighted tie-aware AUC of
    ``evaluation/AreaUnderROCCurveLocalEvaluator.scala:33-85``.

Everything is weighted and mask-aware (pass weight=0 for padding rows), pure
jnp, O(n log n) in the sort. On multi-device inputs run under jit with
sharded arrays (sort induces an all-gather for exact global AUC — the same
cost the reference pays collecting score/label pairs to sort).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _weighted(values, weights):
    w = jnp.sum(weights)
    return jnp.sum(values * weights) / jnp.maximum(w, 1e-30)


# -- regression metrics (``Evaluation.scala:75-96``) ------------------------


def mean_squared_error(labels, predictions, weights):
    return _weighted((predictions - labels) ** 2, weights)


def root_mean_squared_error(labels, predictions, weights):
    return jnp.sqrt(mean_squared_error(labels, predictions, weights))


def mean_absolute_error(labels, predictions, weights):
    return _weighted(jnp.abs(predictions - labels), weights)


# -- total-loss evaluators (GAME ``evaluation/{Logistic,Squared}LossEvaluator``)


def total_logistic_loss(labels, margins, weights):
    s = 2.0 * labels - 1.0
    return jnp.sum(weights * jax.nn.softplus(-s * margins))


def total_squared_loss(labels, margins, weights):
    return jnp.sum(weights * 0.5 * (margins - labels) ** 2)


def total_poisson_loss(labels, margins, weights):
    return jnp.sum(weights * (jnp.exp(margins) - labels * margins))


# -- binary classification --------------------------------------------------


def area_under_roc_curve(labels, scores, weights):
    """Exact weighted, tie-aware AUROC.

    AUC = P(score+ > score-) + 0.5 P(score+ = score-), pair-weighted —
    the closed form of the trapezoid rule the reference implements by
    sorted scan (``AreaUnderROCCurveLocalEvaluator.scala:33-85``).
    Rows with weight 0 (padding) are invisible. Returns 0.5 when either
    class is empty (degenerate, matching random-guess).
    """
    order = jnp.argsort(scores)
    s = scores[order]
    y = labels[order]
    w = weights[order]
    pos_w = jnp.where(y > 0.5, w, 0.0)
    neg_w = jnp.where(y > 0.5, 0.0, w)
    cum_neg = jnp.cumsum(neg_w)
    total_neg = cum_neg[-1]
    total_pos = jnp.sum(pos_w)

    # for each row: negative weight at strictly-smaller scores, and at ties
    left = jnp.searchsorted(s, s, side="left")
    right = jnp.searchsorted(s, s, side="right")
    cum0 = jnp.concatenate([jnp.zeros((1,), cum_neg.dtype), cum_neg])
    neg_below = cum0[left]
    neg_equal = cum0[right] - neg_below

    pairs = jnp.sum(pos_w * (neg_below + 0.5 * neg_equal))
    denom = total_pos * total_neg
    return jnp.where(denom > 0.0, pairs / jnp.maximum(denom, 1e-30), 0.5)


def _pr_curve(labels, scores, weights):
    """Sorted-descending cumulative TP/FP weights + tie-group boundary mask."""
    order = jnp.argsort(-scores)
    s = scores[order]
    y = labels[order]
    w = weights[order]
    tp = jnp.cumsum(jnp.where(y > 0.5, w, 0.0))
    fp = jnp.cumsum(jnp.where(y > 0.5, 0.0, w))
    # a row is a valid operating point iff it is the last of its tie group
    # (padding rows add no TP/FP mass, so spurious boundaries are harmless)
    is_boundary = jnp.concatenate([s[1:] != s[:-1], jnp.ones((1,), bool)])
    return tp, fp, is_boundary


def average_precision(labels, scores, weights):
    """AUPR by step interpolation (sklearn's average_precision convention).

    Intentional divergence from the reference: Spark's ``areaUnderPR``
    linearly interpolates between PR points (trapezoids), which is known to
    overestimate the area; the step convention is both the sklearn standard
    and the conservative choice, so values can differ slightly from
    reference output on the same data."""
    tp, fp, boundary = _pr_curve(labels, scores, weights)
    total_pos = tp[-1]
    precision = tp / jnp.maximum(tp + fp, 1e-30)
    recall = tp / jnp.maximum(total_pos, 1e-30)
    # only integrate across tie-group boundaries
    d_recall = jnp.where(boundary, recall - _prev_boundary(recall, boundary), 0.0)
    return jnp.sum(d_recall * precision)


def _prev_boundary(values, boundary):
    """For each boundary row, the value at the previous boundary (0 before
    the first). Non-boundary rows return garbage (masked by caller)."""
    idx = jnp.arange(values.shape[0])
    # index of the most recent boundary strictly before each row
    bidx = jnp.where(boundary, idx, -1)
    prev_idx = jax.lax.cummax(bidx)  # inclusive
    prev_before = jnp.concatenate([jnp.full((1,), -1), prev_idx[:-1]])
    safe = jnp.maximum(prev_before, 0)
    return jnp.where(prev_before >= 0, values[safe], 0.0)


def peak_f1(labels, scores, weights):
    """max_t F1(t) over all thresholds (``Evaluation.scala`` F-measure)."""
    tp, fp, boundary = _pr_curve(labels, scores, weights)
    total_pos = tp[-1]
    precision = tp / jnp.maximum(tp + fp, 1e-30)
    recall = tp / jnp.maximum(total_pos, 1e-30)
    f1 = 2.0 * precision * recall / jnp.maximum(precision + recall, 1e-30)
    return jnp.max(jnp.where(boundary, f1, 0.0))


# -- information criteria (``Evaluation.scala:98-140``) ---------------------


def akaike_information_criterion(total_loss_value, num_effective_params, n=None):
    """AICc = 2k + 2 * negative-log-likelihood, plus the reference's
    small-sample correction 2k(k+1)/(n-k-1) when n is given
    (``Evaluation.scala:103-105``)."""
    k = num_effective_params
    base = 2.0 * k + 2.0 * total_loss_value
    if n is None or n <= k + 1:
        # the correction's denominator n-k-1 is <= 0: AICc is undefined in
        # this regime, fall back to the uncorrected AIC
        return base
    return base + 2.0 * k * (k + 1) / (n - k - 1.0)


def per_datum_log_likelihood(task, labels, margins, weights):
    """(n,) weighted per-example log-likelihood (``Evaluation.scala``'s
    per-datum LL; the negative pointwise loss)."""
    from photon_ml_tpu.ops.losses import loss_for_task

    return -weights * loss_for_task(task).value(margins, labels)


# reference metric names (``Evaluation.scala:30-48``)
ROOT_MEAN_SQUARED_ERROR = "ROOT_MEAN_SQUARED_ERROR"
MEAN_SQUARED_ERROR = "MEAN_SQUARED_ERROR"
MEAN_ABSOLUTE_ERROR = "MEAN_ABSOLUTE_ERROR"
AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS = (
    "AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS"
)
AREA_UNDER_PRECISION_RECALL = "AREA_UNDER_PRECISION_RECALL"
PEAK_F1_SCORE = "PEAK_F1_SCORE"
DATA_LOG_LIKELIHOOD = "DATA_LOG_LIKELIHOOD"
AKAIKE_INFORMATION_CRITERION = "AKAIKE_INFORMATION_CRITERION"


def evaluate(task, labels, margins, weights, num_effective_params=None):
    """Named-metric map for one model on one dataset — the
    ``Evaluation.evaluate`` facade (``Evaluation.scala:50-140``). Inputs are
    raw margins (w.x + offset); mean-link transforms happen here. Returns
    {metric name: float}."""
    from photon_ml_tpu.core.tasks import TaskType
    from photon_ml_tpu.ops.losses import loss_for_task

    loss = loss_for_task(task)
    means = loss.mean(margins)
    out = {}
    if task.is_classifier:
        out[AREA_UNDER_RECEIVER_OPERATOR_CHARACTERISTICS] = float(
            area_under_roc_curve(labels, margins, weights)
        )
        out[AREA_UNDER_PRECISION_RECALL] = float(
            average_precision(labels, margins, weights)
        )
        out[PEAK_F1_SCORE] = float(peak_f1(labels, margins, weights))
    else:
        out[ROOT_MEAN_SQUARED_ERROR] = float(
            root_mean_squared_error(labels, means, weights)
        )
        out[MEAN_SQUARED_ERROR] = float(
            mean_squared_error(labels, means, weights)
        )
        out[MEAN_ABSOLUTE_ERROR] = float(
            mean_absolute_error(labels, means, weights)
        )
    # Reference convention (``Evaluation.scala:91-105``): DATA_LOG_LIKELIHOOD
    # is the UNWEIGHTED per-datum mean (sample weights do not scale it) and
    # AIC is the small-sample-corrected AICc over mean * n. Zero-weight rows
    # are padding, though — they must not enter n or the mean.
    present = weights > 0
    n = float(jnp.sum(present))
    unweighted_ll = per_datum_log_likelihood(
        task, labels, margins, present.astype(margins.dtype)
    )
    mean_ll = float(jnp.sum(unweighted_ll)) / n if n else 0.0
    out[DATA_LOG_LIKELIHOOD] = mean_ll
    if num_effective_params is not None:
        out[AKAIKE_INFORMATION_CRITERION] = float(
            akaike_information_criterion(
                -mean_ll * n, num_effective_params, n=n
            )
        )
    return out
