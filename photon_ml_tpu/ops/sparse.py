"""Sparse (wide) feature batches: padded row-wise (ELL) format + kernels.

The reference reaches >200k-feature spaces with Breeze sparse vectors and an
off-heap feature index (``util/PalDBIndexMap.scala:43``; tree-aggregation
depth bumps above 200k features, ``cli/game/training/Driver.scala:336-341``).
On TPU, CSR's ragged rows are hostile to XLA's static shapes, so the batch
format here is ELL: every row holds up to ``k`` (column, value) pairs, padded
with column id ``d`` (one past the last feature) and value 0 — padding is
algebraically invisible because gathers fill 0 and scatters drop
out-of-bounds ids. The three kernels below are exactly the contractions the
dense objective needs (margins, gradient back-projection, Hessian diagonal),
so ``GLMObjective`` runs unchanged on either representation:

    matvec:   z_i = sum_k v_ik * w[c_ik]              (gather + row reduce)
    rmatvec:  g_j = sum_{ik: c_ik=j} v_ik * a_i       (scatter-add)
    colsum:   s_j = sum_{ik: c_ik=j} f(v_ik) * c_i    (scatter-add)

All are single XLA ops (gather / scatter-add) that shard cleanly over the
'data' mesh axis: indices/values are row-leading, so batch sharding and the
psum-reduced partials work exactly as for dense features.

Since PR 5 the three ELL contractions DISPATCH between that XLA lowering
and the hand-written Pallas suite in ``photon_ml_tpu/kernels/`` (VMEM-
resident table/accumulator, streamed row blocks — docs/KERNELS.md) per
``PHOTON_SPARSE_KERNEL={auto,pallas,xla}``: ``auto`` takes Pallas on TPU
(where XLA's ~90 ms/pass gather/scatter rate was the measured solve
ceiling, BENCH_r05) and stays bit-for-bit on the XLA path off-TPU;
``pallas`` forces the suite (interpret mode on CPU — the tier-1 proof);
``xla`` pins today's lowering. Dispatch happens here so every consumer
— ``GLMObjective``, GAME random-effect batches, serving scorers, the
hybrid container's cold segments — switches with zero call-site changes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.kernels import dispatch as _kdispatch


@dataclasses.dataclass(frozen=True)
class SparseFeatures:
    """(n, k) padded sparse design matrix with static width ``d``.

    indices: (n, k) int32 column ids; padding slots hold ``d`` (out of
             bounds — gather-fills 0.0, scatter-drops).
    values:  (n, k) float payloads; padding slots hold 0.0.
    d:       number of feature columns (static aux data, not a leaf).
    """

    indices: jax.Array
    values: jax.Array
    d: int

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.indices.shape[-2], self.d)

    @property
    def ndim(self) -> int:  # row-leading container, like a (n, d) matrix
        return 2

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nnz_per_row(self) -> int:
        return self.indices.shape[-1]

    def __matmul__(self, w: jax.Array) -> jax.Array:
        return matvec(self, w)


def _flatten(sf: SparseFeatures):
    return (sf.indices, sf.values), sf.d


def _unflatten(d, children):
    return SparseFeatures(indices=children[0], values=children[1], d=d)


jax.tree_util.register_pytree_node(SparseFeatures, _flatten, _unflatten)


@dataclasses.dataclass(frozen=True)
class HybridFeatures:
    """Power-law split of a sparse matrix: dense slab for the hot columns,
    row-bucketed padded-ELL for the cold tail.

    On TPU the ~130 M elem/s XLA gather/scatter bound makes every stored
    ELL SLOT (incl. padding) cost ~8 ns, while a dense slab column costs
    one MXU/HBM pass (~n * 4 bytes at full bandwidth) regardless of
    sparsity — so any column with enough entries is cheaper densified
    (the "feature-hashing into dense-ish blocks" direction of SURVEY §7
    hard-part 3; docs/PERF.md has the measured rates). CTR-style feature
    data is Zipf-distributed, so a small slab absorbs most entries.

    Because the irregular cost scales with padded SLOTS, the cold tail is
    additionally row-bucketed: rows sorted by cold-entry count, split
    into contiguous segments by the same exact-DP padding minimizer the
    GAME random-effect designs use (``game/data.py``), each segment an
    ELL at its own width. Rows therefore live in a PERMUTED order;
    ``row_perm[i]`` is the original index of stored row i. Training is
    row-order-invariant — callers permute the rest of the batch once at
    construction (labels, offsets, weights, mask) and everything else
    follows.

    dense:         (n, H) slab holding the hot columns (stored row order).
    hot_ids:       (H,) int32 original column ids of the slab columns.
    cold_segments: contiguous-row ELL segments over the SAME d covering
                   all n rows in stored order (hot columns never appear).
    row_perm:      (n,) int32 stored-row -> original-row map.
    """

    dense: jax.Array
    hot_ids: jax.Array
    cold_segments: Tuple[SparseFeatures, ...]
    row_perm: jax.Array

    @property
    def d(self) -> int:
        return self.cold_segments[0].d

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.dense.shape[-2], self.d)

    @property
    def ndim(self) -> int:
        return 2

    @property
    def dtype(self):
        return self.dense.dtype

    def segment_bounds(self) -> Tuple[Tuple[int, int], ...]:
        """[(lo, hi)) stored-row ranges, one per cold segment (static)."""
        bounds = []
        lo = 0
        for seg in self.cold_segments:
            hi = lo + seg.indices.shape[-2]
            bounds.append((lo, hi))
            lo = hi
        return tuple(bounds)

    def __matmul__(self, w: jax.Array) -> jax.Array:
        return matvec(self, w)


def _flatten_hybrid(hf: HybridFeatures):
    return (hf.dense, hf.hot_ids, hf.cold_segments, hf.row_perm), None


def _unflatten_hybrid(_, children):
    return HybridFeatures(
        dense=children[0],
        hot_ids=children[1],
        cold_segments=tuple(children[2]),
        row_perm=children[3],
    )


jax.tree_util.register_pytree_node(
    HybridFeatures, _flatten_hybrid, _unflatten_hybrid
)


@dataclasses.dataclass(frozen=True)
class FeatureShardedSparse:
    """Column-blocked padded-ELL for coefficient-sharded (huge-d) solves.

    The regime of the reference's off-heap coefficient index
    (``util/PalDBIndexMap.scala:43-212``, "hundreds of billions of
    coefficients" ``README.md:58``): w no longer fits replicated, so the
    scatter TARGET — coefficients, gradient, CG vectors — must shard over
    the 'feature' mesh axis. A flat ELL cannot express that (each row's
    column ids cross shard boundaries), so entries are grouped by column
    BLOCK: block f holds the original columns ``{c : c % F == f}``
    (round-robin, so frequency-sorted vocabularies balance), stored with
    LOCAL ids ``c // F``.

    indices: (n, F, k) int32 local column ids; padding slots hold
             ``d_shard`` (out of local bounds: gather-fills 0, scatter-drops).
    values:  (n, F, k) float payloads; padding slots hold 0.0.
    d_shard: columns per block (static). Solver-visible width = F * d_shard.
    d_orig:  pre-blocking column count (static; blocked positions >= it in
             no block are real columns — they solve to exactly 0).

    Sharded P('data', 'feature', None) on a ('data', 'feature') mesh, every
    kernel is SPMD with NO communication except one O(n) psum of margin
    partials over 'feature' (matvec's block sum): the gather/scatter run
    against each device's LOCAL (d_shard,) coefficient block — XLA
    partitions the vmapped gather/scatter along the block axis, which is a
    batch dimension on both operand and indices. This is the TPU analog of
    the reference's per-feature-block aggregation
    (``function/ValueAndGradientAggregator.scala:204-220``).

    ROW-BALANCED layout (``shard_columns(..., balance_rows=True)``, the
    ``PHOTON_COLLECTIVE_MODE=overlap`` strategy): the flat layout pads
    every (row, block) lane to the DATASET max per-block entry count, so
    at width F the stored slots — the irregular-access cost driver —
    inflate toward max/mean over rows (measured 3.7x at F=8 on the
    bench workload, THE inverse-scaling term of BENCH_r06's
    ``sparse_fs_scaling``). Balanced blocks instead pack each block's
    entries into width-``k`` VIRTUAL rows: a row with c entries in
    block f occupies ceil(c/k) of them, and ``row_map[v, f]`` records
    the original row virtual row v of block f contributes to (sentinel
    ``num_rows`` = empty pad lane: gathers fill 0, scatters drop).
    Margins then need one extra per-block scatter-add of the virtual-row
    partials into (n,) — O(V) — against an O(slots) padding saving.

    row_map:  (V, F) int32 virtual row -> original row, or None for the
              flat layout.
    num_rows: logical row count n when ``row_map`` is set (the leading
              axis is V, not n).
    aligned_rows: the first ``aligned_rows`` virtual rows are IDENTITY
              mapped (virtual row v holds row v's first <= k entries in
              every block), so their margin partials need no scatter
              routing and their back-projection weights no gather — the
              O(V) routing cost only touches the overflow tail.
    """

    indices: jax.Array
    values: jax.Array
    d_shard: int
    d_orig: int
    row_map: Optional[jax.Array] = None
    num_rows: Optional[int] = None
    aligned_rows: int = 0

    @property
    def num_blocks(self) -> int:
        return self.indices.shape[-2]

    @property
    def is_balanced(self) -> bool:
        return self.row_map is not None

    @property
    def shape(self) -> Tuple[int, int]:
        # solver-visible width: the blocked coefficient vector
        rows = (
            self.num_rows
            if self.num_rows is not None
            else self.indices.shape[-3]
        )
        return (rows, self.num_blocks * self.d_shard)

    @property
    def ndim(self) -> int:
        return 2

    @property
    def dtype(self):
        return self.values.dtype

    def __matmul__(self, w: jax.Array) -> jax.Array:
        return matvec(self, w)


def _flatten_fsharded(fs: FeatureShardedSparse):
    return (
        (fs.indices, fs.values, fs.row_map),
        (fs.d_shard, fs.d_orig, fs.num_rows, fs.aligned_rows),
    )


def _unflatten_fsharded(aux, children):
    return FeatureShardedSparse(
        indices=children[0],
        values=children[1],
        d_shard=aux[0],
        d_orig=aux[1],
        row_map=children[2],
        num_rows=aux[2],
        aligned_rows=aux[3],
    )


jax.tree_util.register_pytree_node(
    FeatureShardedSparse, _flatten_fsharded, _unflatten_fsharded
)


# -- kernels (dispatch on representation) -----------------------------------


def _use_pallas_for(sf: "SparseFeatures", other_dtype) -> bool:
    """Route this ELL contraction to the Pallas suite? Centralizes the
    eligibility call so matvec/rmatvec/colsum cannot drift: mode knob,
    backend/probe, VMEM budget at the contraction's COMPUTE dtype, and
    degenerate/sharded-batch exclusions (kernels.dispatch)."""
    n, k = sf.indices.shape[-2], sf.indices.shape[-1]
    cd = jnp.result_type(sf.values.dtype, other_dtype)
    return _kdispatch.use_pallas(
        d=sf.d, itemsize=jnp.dtype(cd).itemsize, n=n, nnz_per_row=k
    )


def is_sparse(x) -> bool:
    return isinstance(x, SparseFeatures)


def is_hybrid(x) -> bool:
    return isinstance(x, HybridFeatures)


def is_feature_sharded(x) -> bool:
    return isinstance(x, FeatureShardedSparse)


def is_structured(x) -> bool:
    """Any non-plain-array representation this module owns."""
    return is_sparse(x) or is_hybrid(x) or is_feature_sharded(x)


def cast_values(x, dtype):
    """Representation-preserving device cast: plain arrays, ELL values,
    or hybrid slab+cold values to ``dtype``. The one place that knows how
    to move every feature container to the device at a target precision."""
    if is_hybrid(x):
        return dataclasses.replace(
            x,
            dense=jnp.asarray(x.dense, dtype),
            cold_segments=tuple(
                dataclasses.replace(
                    seg, values=jnp.asarray(seg.values, dtype)
                )
                for seg in x.cold_segments
            ),
        )
    if is_feature_sharded(x):
        return dataclasses.replace(
            x,
            indices=jnp.asarray(x.indices),
            values=jnp.asarray(x.values, dtype),
            row_map=(
                None if x.row_map is None else jnp.asarray(x.row_map)
            ),
        )
    if is_sparse(x):
        return dataclasses.replace(
            x,
            indices=jnp.asarray(x.indices),
            values=jnp.asarray(x.values, dtype),
        )
    return jnp.asarray(x, dtype)


def _low_precision_dot(x: jax.Array, w: jax.Array):
    """x @ w keeping the CONTRACTION in the matrix's low precision with
    f32 accumulation (``preferred_element_type``) when the design is
    stored bf16/f16. Without this, jnp's type promotion upcasts the
    matrix to the vector's f32 — and XLA MATERIALIZES the converted
    design as a temp, so every pass pays ~3 extra design-sized HBM
    round trips (measured r5: the dense TRON solve ran 6.0 ms/pass
    where the roofline pass is ~1.2 ms; benchmarks/dense_roofline_lab).
    The vector rounds to bf16 — the same precision class the stored
    design already imposes (docs/PERF.md: coefficients agree with the
    all-f32 solve to ~2e-4). Full-precision designs are untouched."""
    low_x = x.dtype in (jnp.bfloat16, jnp.float16)
    low_w = w.dtype in (jnp.bfloat16, jnp.float16)
    if low_x != low_w:  # mixed precision: round the f32 side DOWN
        if low_x:
            w = w.astype(x.dtype)
        else:
            x = x.astype(w.dtype)
        return jnp.matmul(x, w, preferred_element_type=jnp.float32)
    return x @ w


def _block_margin_partials(x: "FeatureShardedSparse", w: jax.Array):
    """(F, n) per-block margin partials of a blocked container — the
    payload whose block-axis sum is THE feature-space reduction of every
    objective pass (``parallel.overlap.feature_block_sum`` owns the
    fused-vs-overlap schedule). Flat layout: gather + row reduce.
    Balanced layout: gather + virtual-row reduce + one per-block
    scatter-add routing virtual rows to their original rows (sentinel
    lanes drop)."""
    w2 = w.reshape(x.num_blocks, x.d_shard)
    gathered = jax.vmap(  # per-block local gather; block axis = batch dim
        lambda wf, idxf: wf.at[idxf].get(mode="fill", fill_value=0.0),
        in_axes=(0, 1),
        out_axes=1,
    )(w2, x.indices)
    if not x.is_balanced:
        return jnp.einsum("nfk,nfk->fn", x.values, gathered)
    partial_v = jnp.einsum("vfk,vfk->fv", x.values, gathered)  # (F, V)
    n = x.shape[0]
    a = x.aligned_rows
    if a:
        # identity head: virtual row v == row v, no routing; only the
        # overflow tail scatters (its rows route by row_map)
        head = partial_v[:, :a]
        if a < n:
            head = jnp.pad(head, ((0, 0), (0, n - a)))
        if partial_v.shape[1] == a:
            return head

        def route_tail(pv, rm):
            return jnp.zeros((n,), pv.dtype).at[rm].add(pv, mode="drop")

        tail = jax.vmap(route_tail)(
            partial_v[:, a:], x.row_map[a:].T
        )
        return head + tail

    def route(pv, rm):
        return jnp.zeros((n,), pv.dtype).at[rm].add(pv, mode="drop")

    return jax.vmap(route)(partial_v, x.row_map.T)


def matvec(x, w: jax.Array) -> jax.Array:
    """margins contraction: (n, d) @ (d,) -> (n,). Hybrid output is in
    STORED (permuted) row order, matching the permuted batch."""
    if is_feature_sharded(x):
        from photon_ml_tpu.parallel.overlap import feature_block_sum

        return feature_block_sum(_block_margin_partials(x, w))
    if is_hybrid(x):
        # dtype promotion mirrors the dense path (bf16 slab @ f32 w -> f32)
        cold = jnp.concatenate(
            [matvec(seg, w) for seg in x.cold_segments]
        )
        return _low_precision_dot(x.dense, w[x.hot_ids]) + cold
    if not is_sparse(x):
        return _low_precision_dot(x, w)
    if _use_pallas_for(x, w.dtype):
        from photon_ml_tpu import kernels

        return kernels.ell_matvec(x.indices, x.values, w, x.d)
    gathered = w.at[x.indices].get(mode="fill", fill_value=0.0)
    return jnp.sum(x.values * gathered, axis=-1)


def rmatvec(x, a: jax.Array) -> jax.Array:
    """gradient back-projection: (n, d)^T @ (n,) -> (d,). Hybrid `a` is
    in stored row order."""
    if is_feature_sharded(x):
        if x.is_balanced:
            # route each virtual row's weight from its original row; the
            # identity-aligned head broadcasts straight from ``a``
            # (sentinel lanes gather-fill 0, so their slots contribute 0)
            al = x.aligned_rows
            if al:
                head = jnp.broadcast_to(
                    a[:al, None], (al, x.num_blocks)
                )
                tail = a.at[x.row_map[al:]].get(
                    mode="fill", fill_value=0.0
                )
                a_v = jnp.concatenate([head, tail], axis=0)
            else:
                a_v = a.at[x.row_map].get(mode="fill", fill_value=0.0)
            upd = x.values * a_v[..., None]
        else:
            upd = x.values * a[:, None, None]
        g2 = jax.vmap(  # per-block local scatter into the block's coefficients
            lambda idxf, updf: jnp.zeros((x.d_shard,), updf.dtype)
            .at[idxf.reshape(-1)]
            .add(updf.reshape(-1), mode="drop"),
            in_axes=(1, 1),
        )(x.indices, upd)
        return g2.reshape(-1)
    if is_hybrid(x):
        g = jnp.zeros((x.d,), a.dtype)
        for (lo, hi), seg in zip(x.segment_bounds(), x.cold_segments):
            g = g + rmatvec(seg, a[lo:hi])
        return g.at[x.hot_ids].add(_low_precision_dot(a, x.dense))
    if not is_sparse(x):
        return _low_precision_dot(x.T, a)
    if _use_pallas_for(x, a.dtype):
        from photon_ml_tpu import kernels

        return kernels.ell_rmatvec(x.indices, x.values, a, x.d)
    upd = (x.values * a[..., None]).reshape(-1)
    return (
        jnp.zeros((x.d,), upd.dtype)
        .at[x.indices.reshape(-1)]
        .add(upd, mode="drop")
    )


def colsum(x, c: jax.Array, square: bool = False) -> jax.Array:
    """sum_i c_i * x_ij (or x_ij^2) -> (d,): the Hessian-diagonal sums."""
    if is_feature_sharded(x):
        v = x.values * x.values if square else x.values
        return rmatvec(dataclasses.replace(x, values=v), c)
    if is_hybrid(x):
        v = x.dense * x.dense if square else x.dense
        hot = jnp.einsum("n,nh->h", c, v)
        g = jnp.zeros((x.d,), c.dtype)
        for (lo, hi), seg in zip(x.segment_bounds(), x.cold_segments):
            g = g + colsum(seg, c[lo:hi], square=square)
        return g.at[x.hot_ids].add(hot)
    if not is_sparse(x):
        v = x * x if square else x
        return jnp.einsum("n,nd->d", c, v)
    if _use_pallas_for(x, c.dtype):
        from photon_ml_tpu import kernels

        return kernels.ell_colsum(x.indices, x.values, c, x.d, square=square)
    v = x.values * x.values if square else x.values
    upd = (v * c[..., None]).reshape(-1)
    return (
        jnp.zeros((x.d,), upd.dtype)
        .at[x.indices.reshape(-1)]
        .add(upd, mode="drop")
    )


def matvec_and_feature_dots(
    x, w: jax.Array, dot_pairs: Sequence[Tuple[jax.Array, jax.Array]] = ()
):
    """``(matvec(x, w), tuple(vdot(u, v) for u, v in dot_pairs))`` with
    the feature-space dots RIDING THE MARGINS REDUCTION when ``x`` is
    feature-sharded.

    Under a ('data', 'feature') mesh every feature-space contraction —
    the (n,) margin block-sum AND each scalar dot over the sharded
    coefficient space (the L2 value term w.w, the normalization margin
    shift s.w_eff) — costs one all-reduce, and BENCH_r05's
    ``sparse_fs_scaling`` showed those per-pass collectives are what
    broke 2-device scaling (5.32 s @2 vs 3.08 s @1). Here the per-block
    margin partials (n,) and the per-block scalar partials (1,) each
    concatenate into ONE (n + P,) payload whose single sharded-axis sum
    lowers to a single bucketed all-reduce; the XLA partitioner sees one
    reduction instead of 1 + P.

    For every other representation (nothing sharded to coalesce) the
    dots are computed directly — ``jnp.vdot`` — and results are
    bit-identical to the unfused formulation.
    """
    if not is_feature_sharded(x) or not dot_pairs:
        return matvec(x, w), tuple(jnp.vdot(u, v) for u, v in dot_pairs)
    from photon_ml_tpu.parallel.overlap import feature_block_sum

    n = x.shape[0]
    zb = _block_margin_partials(x, w)  # (F, n) partials
    cols = [zb]
    for u, v in dot_pairs:
        ub = u.reshape(x.num_blocks, x.d_shard)
        vb = v.reshape(x.num_blocks, x.d_shard)
        cols.append(jnp.sum(ub * vb, axis=-1, keepdims=True))  # (F, 1)
    payload = jnp.concatenate(cols, axis=-1)  # (F, n + P), sharded on F
    # fused: ONE bucketed all-reduce of (n + P,). overlap: the row axis
    # chunks into reduce-scatters issued as their chunk's partials land,
    # plus one trailing all-gather (parallel.overlap.feature_block_sum —
    # the schedule is the PHOTON_COLLECTIVE_MODE knob, equivalence
    # drilled in tests/test_partition.py).
    total = feature_block_sum(payload)
    # collective profiler (obs.collectives): this function only ever
    # runs under tracing, so the note fires once per COMPILATION —
    # recording the bucketed reduction's payload geometry
    # (collective.traced.matvec_and_feature_dots.w<F>.{count,bytes})
    # with zero cost in the compiled program; callers that know their
    # pass counts (bench.py) scale it
    from photon_ml_tpu.obs import collectives as _obs_coll

    _obs_coll.note_traced_collective(
        "matvec_and_feature_dots",
        mesh_width=x.num_blocks,
        nbytes=(n + len(dot_pairs)) * jnp.dtype(total.dtype).itemsize,
    )
    return total[:n], tuple(total[n + i] for i in range(len(dot_pairs)))


def pad_rows(x, pad: int):
    """Append `pad` all-padding rows (index d, value 0), preserving the
    padding invariant that plain zero-padding would break."""
    if is_feature_sharded(x):
        if x.is_balanced:
            # appended rows hold no entries; only the logical row count
            # (the scatter target / margin length) grows. Virtual-row
            # sentinels already point at num_rows and keep dropping.
            return dataclasses.replace(x, num_rows=x.shape[0] + pad)
        return dataclasses.replace(
            x,
            indices=jnp.pad(
                x.indices, ((0, pad), (0, 0), (0, 0)), constant_values=x.d_shard
            ),
            values=jnp.pad(x.values, ((0, pad), (0, 0), (0, 0))),
        )
    if is_hybrid(x):
        n = x.dense.shape[-2]
        segs = list(x.cold_segments)
        segs[-1] = pad_rows(segs[-1], pad)
        return HybridFeatures(
            dense=jnp.pad(x.dense, ((0, pad), (0, 0))),
            hot_ids=x.hot_ids,
            cold_segments=tuple(segs),
            row_perm=jnp.concatenate(
                [x.row_perm, jnp.arange(n, n + pad, dtype=jnp.int32)]
            ),
        )
    return SparseFeatures(
        indices=jnp.pad(x.indices, ((0, pad), (0, 0)), constant_values=x.d),
        values=jnp.pad(x.values, ((0, pad), (0, 0))),
        d=x.d,
    )


def row_density(x) -> jax.Array:
    """Per-row stored-entry count (diagnostic; hybrid in stored order)."""
    if is_hybrid(x):
        cold = jnp.concatenate(
            [row_density(seg) for seg in x.cold_segments]
        )
        return jnp.sum(x.dense != 0, axis=-1) + cold
    if not is_sparse(x):
        return jnp.sum(x != 0, axis=-1)
    return jnp.sum(x.indices < x.d, axis=-1)


def stored_cold_entries(hf: HybridFeatures) -> int:
    """Total stored (non-padding) entries across the cold segments."""
    return sum(
        int(np.sum(np.asarray(seg.indices) < seg.d))
        for seg in hf.cold_segments
    )


def cold_padded_slots(hf: HybridFeatures) -> int:
    """Total padded ELL slots across the cold segments (the quantity the
    irregular-access cost scales with)."""
    return sum(
        int(np.prod(seg.indices.shape)) for seg in hf.cold_segments
    )


def cold_as_single_ell(hf: HybridFeatures) -> SparseFeatures:
    """Concatenate the cold segments back into one ELL at the max segment
    width (stored row order). Re-inflates padding — for once-per-run
    consumers (statistics), not hot kernels."""
    kmax = max(seg.nnz_per_row for seg in hf.cold_segments)
    ind = []
    val = []
    for seg in hf.cold_segments:
        extra = kmax - seg.nnz_per_row
        ind.append(
            jnp.pad(seg.indices, ((0, 0), (0, extra)), constant_values=seg.d)
        )
        val.append(jnp.pad(seg.values, ((0, 0), (0, extra))))
    return SparseFeatures(
        indices=jnp.concatenate(ind),
        values=jnp.concatenate(val),
        d=hf.d,
    )


def feature_sharded_as_ell(fs: FeatureShardedSparse) -> SparseFeatures:
    """View a blocked container as one flat ELL over the BLOCKED column
    space (width F * d_shard): global id = block * d_shard + local. For
    once-per-run consumers (feature statistics), not hot kernels.
    Balanced containers rebuild host-side through their row map (their
    virtual rows are per-block packings, not batch rows)."""
    d_block = fs.num_blocks * fs.d_shard
    if fs.is_balanced:
        ind = np.asarray(fs.indices)
        val = np.asarray(fs.values)
        rm = np.asarray(fs.row_map)
        v_rows, F, k = ind.shape
        keep = ind < fs.d_shard
        vv, ff, _ = np.nonzero(keep)
        rows = rm[vv, ff]
        cols = ff.astype(np.int64) * fs.d_shard + ind[keep]
        return from_coo(
            rows,
            cols,
            val[keep],
            fs.shape[0],
            d_block,
            dtype=fs.values.dtype,
        )
    n, F, k = fs.indices.shape
    base = (
        jnp.arange(F, dtype=fs.indices.dtype) * fs.d_shard
    )[None, :, None]
    glob = jnp.where(fs.indices < fs.d_shard, fs.indices + base, d_block)
    return SparseFeatures(
        indices=glob.reshape(n, F * k),
        values=fs.values.reshape(n, F * k),
        d=d_block,
    )


def blocked_column_map(d: int, num_blocks: int) -> np.ndarray:
    """(d,) original column -> blocked position, for the round-robin
    blocking ``shard_columns`` applies: column c lives in block c % F at
    local id c // F. Used to block/unblock coefficient, bound, and
    normalization vectors."""
    c = np.arange(d, dtype=np.int64)
    d_shard = -(-d // num_blocks)
    return (c % num_blocks) * d_shard + c // num_blocks


def balanced_virtual_width(counts: np.ndarray) -> int:
    """The virtual-row width k0 minimizing the ALIGNED balanced
    layout's cost proxy ``slots + 2 * routed_virtual_rows`` (a
    scatter/gather routing touch costs ~2 stored-slot touches on the
    measured backends), given the (F, n) per-(block, row) entry counts.
    Every row owns one identity-aligned virtual row (n * k slots per
    block); only entries past k spill into routed overflow rows. Exact
    scan over candidate widths — counts are small ints."""
    kmax = int(counts.max()) if counts.size else 1
    if kmax <= 1:
        return 1
    best_k, best_cost = 1, None
    F, n = counts.shape
    for k in range(1, kmax + 1):
        over = np.maximum(counts - k, 0)
        # overflow rows pad to the max over blocks so the (V, F, k)
        # arrays stay rectangular
        v_ovf = int((-(-over // k)).sum(axis=1).max())
        cost = F * (n + v_ovf) * k + 2 * F * v_ovf
        if best_cost is None or cost < best_cost:
            best_k, best_cost = k, cost
    return best_k


def shard_columns(
    sf: SparseFeatures,
    num_blocks: int,
    dtype=None,
    balance_rows: bool = False,
) -> FeatureShardedSparse:
    """Block an ELL matrix by column for feature-sharded solves
    (host-side, once per dataset). Columns are assigned round-robin
    (block = c % F) so frequency-sorted vocabularies — the common layout
    after ``cli/build_index`` — spread their hot columns evenly across
    blocks. ``blocked_column_map`` gives the induced coefficient layout.

    Flat layout (default): the per-(row, block) width k is the max over
    the dataset; round-robin keeps the MEAN near nnz/F, but the max —
    which every lane pads to — concentrates near the binomial tail, so
    stored slots inflate as F grows (3.7x at F=8 on the bench workload).

    ``balance_rows=True`` (the ``PHOTON_COLLECTIVE_MODE=overlap``
    layout): each block packs its entries into width-k0 VIRTUAL rows
    (``balanced_virtual_width`` picks k0), recorded in ``row_map`` —
    stored slots then track the entry count instead of the max row.
    Same round-robin column map, so coefficients are interchangeable
    between layouts.
    """
    if num_blocks < 1:
        raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
    F = num_blocks
    d_shard = -(-sf.d // F)
    out_dtype = np.dtype(jnp.dtype(dtype or sf.values.dtype))
    ind = np.asarray(sf.indices)
    val = np.asarray(sf.values)
    n, k = ind.shape
    keep = ind < sf.d
    rows = np.broadcast_to(np.arange(n)[:, None], ind.shape)[keep]
    cols = ind[keep].astype(np.int64)
    vals = val[keep]
    blk = cols % F
    loc = cols // F
    key = rows * F + blk
    counts = np.bincount(key, minlength=n * F)
    order = np.argsort(key, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
    slot = np.arange(key.size) - starts[key[order]]
    if balance_rows and F > 1:
        cfr = counts.reshape(n, F).T  # (F, n) per-(block, row) counts
        k0 = balanced_virtual_width(cfr)
        # identity-aligned head: virtual row r == row r holds the first
        # <= k0 entries of every block; entries past k0 spill into
        # routed overflow rows appended after the head
        over = np.maximum(cfr - k0, 0)
        ovf_per = -(-over // k0)  # (F, n) overflow rows per (block, row)
        v_ovf = int(ovf_per.sum(axis=1).max())
        v_total = n + v_ovf if (n + v_ovf) else 1
        base = np.zeros((F, n), np.int64)
        base[:, 1:] = np.cumsum(ovf_per, axis=1)[:, :-1]
        r_o, b_o, s_o = rows[order], blk[order], slot
        in_head = s_o < k0
        vrow = np.where(
            in_head,
            r_o,
            n + base[b_o, r_o] + np.maximum(s_o - k0, 0) // k0,
        )
        pos = np.where(in_head, s_o, np.maximum(s_o - k0, 0) % k0)
        indices = np.full((v_total, F, k0), d_shard, np.int32)
        values = np.zeros((v_total, F, k0), out_dtype)
        row_map = np.full((v_total, F), n, np.int32)
        row_map[:n] = np.arange(n, dtype=np.int32)[:, None]
        indices[vrow, b_o, pos] = loc[order]
        values[vrow, b_o, pos] = vals[order]
        row_map[vrow, b_o] = r_o
        return FeatureShardedSparse(
            indices=jnp.asarray(indices),
            values=jnp.asarray(values),
            d_shard=d_shard,
            d_orig=sf.d,
            row_map=jnp.asarray(row_map),
            num_rows=n,
            aligned_rows=n,
        )
    k_new = int(counts.max()) if counts.size and counts.max() > 0 else 1
    indices = np.full((n, F, k_new), d_shard, np.int32)
    values = np.zeros((n, F, k_new), out_dtype)
    indices[rows[order], blk[order], slot] = loc[order]
    values[rows[order], blk[order], slot] = vals[order]
    return FeatureShardedSparse(
        indices=jnp.asarray(indices),
        values=jnp.asarray(values),
        d_shard=d_shard,
        d_orig=sf.d,
    )


# -- construction ------------------------------------------------------------


def from_coo(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    num_cols: int,
    nnz_per_row: int = 0,
    dtype=jnp.float32,
    as_numpy: bool = False,
) -> SparseFeatures:
    """Build from COO triplets (host-side). Duplicate (row, col) entries are
    summed (the reference's dedup-by-sum, ``DataProcessingUtils.scala:70-76``).
    ``nnz_per_row`` pads/caps the row width; 0 means the max observed.
    ``as_numpy`` keeps the buffers host-side (no device placement) for
    containers that are re-cast per consumer (e.g. GAME shards)."""
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    vals = np.asarray(vals, np.float64)
    # dedup-by-sum on (row, col)
    flat = rows * num_cols + cols
    uniq, inv = np.unique(flat, return_inverse=True)
    summed = np.zeros(uniq.size, np.float64)
    np.add.at(summed, inv, vals)
    r = (uniq // num_cols).astype(np.int64)
    c = (uniq % num_cols).astype(np.int64)
    counts = np.bincount(r, minlength=num_rows)
    k = int(counts.max()) if counts.size and counts.max() > 0 else 1
    if nnz_per_row:
        if k > nnz_per_row:
            raise ValueError(
                f"a row has {k} entries, above nnz_per_row={nnz_per_row}"
            )
        k = nnz_per_row
    indices = np.full((num_rows, k), num_cols, np.int64)
    values = np.zeros((num_rows, k), np.float64)
    # slot of each entry within its row (entries are sorted by flat id)
    starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
    slot = np.arange(uniq.size) - starts[r]
    indices[r, slot] = c
    values[r, slot] = summed
    if as_numpy:
        return SparseFeatures(
            indices=indices.astype(np.int32),
            values=values.astype(np.dtype(jnp.dtype(dtype))),
            d=num_cols,
        )
    return SparseFeatures(
        indices=jnp.asarray(indices, jnp.int32),
        values=jnp.asarray(values, dtype),
        d=num_cols,
    )


def to_hybrid(
    sf: SparseFeatures,
    hot_columns: int = -1,
    dtype=None,
    min_count: int = 64,
    max_slab_bytes: int = 1 << 30,
    num_row_buckets: int = 8,
) -> HybridFeatures:
    """Split an ELL matrix into dense-hot + bucketed sparse-cold
    (host-side, once per dataset).

    ``hot_columns`` = H picks the H highest-count columns; -1 sizes the
    slab automatically: columns whose stored-entry count exceeds
    ``min_count`` (the measured v5e break-even — ~64 irregular accesses
    cost about one dense n-row column pass, docs/PERF.md), hottest
    first, until the slab reaches ``max_slab_bytes`` at the target dtype.
    A slab with zero qualifying columns degrades to H=1 so shapes stay
    static.

    The cold rows are then sorted by remaining-entry count and split
    into at most ``num_row_buckets`` contiguous ELL segments by the
    exact-DP padding minimizer (``game/data.py``) — the irregular-access
    cost scales with padded SLOTS, and a single max-width ELL would hand
    the hottest row's width to every row. The returned ``row_perm``
    records stored-row -> original-row; callers permute the rest of the
    batch to match.

    The input must be dedup-summed — no (row, column) pair stored twice
    (``from_coo``'s invariant, which every ingest path goes through).
    Duplicate slots would sum into one slab cell, changing the SQUARED
    statistics (colsum(square=True) -> Hessian diagonal / variances)
    relative to the ELL, so they are rejected here rather than silently
    diverging.
    """
    from photon_ml_tpu.game.data import _split_minimizing_padding

    out_dtype = np.dtype(jnp.dtype(dtype or sf.values.dtype))
    ind = np.asarray(sf.indices)
    val = np.asarray(sf.values)
    n, k = ind.shape
    sorted_cols = np.sort(np.where(ind < sf.d, ind, -1), axis=-1)
    dup_rows = np.flatnonzero(
        ((sorted_cols[:, 1:] == sorted_cols[:, :-1])
         & (sorted_cols[:, 1:] >= 0)).any(axis=-1)
    )
    if dup_rows.size:
        raise ValueError(
            f"to_hybrid requires dedup-summed input (from_coo's "
            f"invariant); {dup_rows.size} rows store a (row, column) "
            f"pair twice, e.g. row {int(dup_rows[0])}"
        )
    flat = ind.reshape(-1)
    keep = flat < sf.d
    counts = np.bincount(flat[keep], minlength=sf.d)
    if hot_columns < 0:
        hot = np.flatnonzero(counts > min_count)
        hot = hot[np.argsort(-counts[hot], kind="stable")]
        h_cap = max(1, max_slab_bytes // (n * out_dtype.itemsize))
        hot = hot[:h_cap]
        if hot.size == 0:
            hot = np.argsort(-counts, kind="stable")[:1]
    else:
        h = max(1, min(hot_columns, sf.d))
        hot = np.argsort(-counts, kind="stable")[:h]
    H = hot.size
    hot_rank = np.full(sf.d + 1, -1, np.int64)
    hot_rank[hot] = np.arange(H)
    is_hot = hot_rank[ind] >= 0  # (n, k); padding col d is never hot

    # row permutation: ascending cold-entry count (matches the DP input)
    cold_entry = ~is_hot & (ind < sf.d)
    cold_counts = cold_entry.sum(axis=1)
    row_perm = np.argsort(cold_counts, kind="stable").astype(np.int32)
    sorted_counts = cold_counts[row_perm]
    bounds = _split_minimizing_padding(
        sorted_counts, max(1, num_row_buckets)
    ) or [(0, n)]

    # slab built at the target dtype's f32/f64 (never narrower than f32 —
    # bf16 accumulation would lose dedup sums), in STORED row order
    acc_dtype = np.float64 if out_dtype == np.float64 else np.float32
    dense = np.zeros((n, H), acc_dtype)
    rows = np.broadcast_to(np.arange(n)[:, None], ind.shape)
    np.add.at(dense, (rows[is_hot], hot_rank[ind[is_hot]]), val[is_hot])
    inv_perm = np.empty(n, np.int64)
    inv_perm[row_perm] = np.arange(n)
    dense = dense[row_perm]

    # cold segments: contiguous stored-row ranges, each its own ELL width
    stored_rows = inv_perm[rows[cold_entry]]
    cold_cols = ind[cold_entry]
    cold_vals = val[cold_entry]
    order = np.argsort(stored_rows, kind="stable")
    stored_rows = stored_rows[order]
    cold_cols = cold_cols[order]
    cold_vals = cold_vals[order]
    entry_starts = np.searchsorted(stored_rows, [lo for lo, _ in bounds])
    entry_ends = np.searchsorted(stored_rows, [hi for _, hi in bounds])
    segments = []
    for (lo, hi), es, ee in zip(bounds, entry_starts, entry_ends):
        segments.append(
            from_coo(
                stored_rows[es:ee] - lo,
                cold_cols[es:ee],
                cold_vals[es:ee],
                hi - lo,
                sf.d,
                dtype=dtype or sf.values.dtype,
            )
        )
    return HybridFeatures(
        dense=jnp.asarray(dense, dtype or sf.values.dtype),
        hot_ids=jnp.asarray(hot.astype(np.int32)),
        cold_segments=tuple(segments),
        row_perm=jnp.asarray(row_perm),
    )


def from_dense(x: np.ndarray, nnz_per_row: int = 0, dtype=jnp.float32) -> SparseFeatures:
    """Sparsify a dense matrix (testing / oracles)."""
    x = np.asarray(x)
    r, c = np.nonzero(x)
    return from_coo(
        r, c, x[r, c], x.shape[0], x.shape[1], nnz_per_row, dtype
    )


def to_dense(sf) -> np.ndarray:
    """Densify (small problems / tests only). Hybrid matrices come back
    in ORIGINAL row order (row_perm inverted); feature-sharded matrices
    come back in BLOCKED column order (width F * d_shard)."""
    if is_feature_sharded(sf):
        return to_dense(feature_sharded_as_ell(sf))
    if is_hybrid(sf):
        stored = np.concatenate(
            [to_dense(seg) for seg in sf.cold_segments]
        )
        stored[:, np.asarray(sf.hot_ids)] += np.asarray(
            sf.dense, np.float64
        ).astype(stored.dtype)
        out = np.empty_like(stored)
        out[np.asarray(sf.row_perm)] = stored
        return out
    ind = np.asarray(sf.indices)
    val = np.asarray(sf.values)
    n, k = ind.shape
    out = np.zeros((n, sf.d), val.dtype)
    keep = ind < sf.d
    np.add.at(out, (np.repeat(np.arange(n), k)[keep.reshape(-1)], ind[keep]), val[keep])
    return out
