"""Sparse (wide) feature batches: padded row-wise (ELL) format + kernels.

The reference reaches >200k-feature spaces with Breeze sparse vectors and an
off-heap feature index (``util/PalDBIndexMap.scala:43``; tree-aggregation
depth bumps above 200k features, ``cli/game/training/Driver.scala:336-341``).
On TPU, CSR's ragged rows are hostile to XLA's static shapes, so the batch
format here is ELL: every row holds up to ``k`` (column, value) pairs, padded
with column id ``d`` (one past the last feature) and value 0 — padding is
algebraically invisible because gathers fill 0 and scatters drop
out-of-bounds ids. The three kernels below are exactly the contractions the
dense objective needs (margins, gradient back-projection, Hessian diagonal),
so ``GLMObjective`` runs unchanged on either representation:

    matvec:   z_i = sum_k v_ik * w[c_ik]              (gather + row reduce)
    rmatvec:  g_j = sum_{ik: c_ik=j} v_ik * a_i       (scatter-add)
    colsum:   s_j = sum_{ik: c_ik=j} f(v_ik) * c_i    (scatter-add)

All are single XLA ops (gather / scatter-add) that shard cleanly over the
'data' mesh axis: indices/values are row-leading, so batch sharding and the
psum-reduced partials work exactly as for dense features.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SparseFeatures:
    """(n, k) padded sparse design matrix with static width ``d``.

    indices: (n, k) int32 column ids; padding slots hold ``d`` (out of
             bounds — gather-fills 0.0, scatter-drops).
    values:  (n, k) float payloads; padding slots hold 0.0.
    d:       number of feature columns (static aux data, not a leaf).
    """

    indices: jax.Array
    values: jax.Array
    d: int

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.indices.shape[-2], self.d)

    @property
    def ndim(self) -> int:  # row-leading container, like a (n, d) matrix
        return 2

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nnz_per_row(self) -> int:
        return self.indices.shape[-1]

    def __matmul__(self, w: jax.Array) -> jax.Array:
        return matvec(self, w)


def _flatten(sf: SparseFeatures):
    return (sf.indices, sf.values), sf.d


def _unflatten(d, children):
    return SparseFeatures(indices=children[0], values=children[1], d=d)


jax.tree_util.register_pytree_node(SparseFeatures, _flatten, _unflatten)


# -- kernels (dispatch on representation) -----------------------------------


def is_sparse(x) -> bool:
    return isinstance(x, SparseFeatures)


def matvec(x, w: jax.Array) -> jax.Array:
    """margins contraction: (n, d) @ (d,) -> (n,)."""
    if not is_sparse(x):
        return x @ w
    gathered = w.at[x.indices].get(mode="fill", fill_value=0.0)
    return jnp.sum(x.values * gathered, axis=-1)


def rmatvec(x, a: jax.Array) -> jax.Array:
    """gradient back-projection: (n, d)^T @ (n,) -> (d,)."""
    if not is_sparse(x):
        return x.T @ a
    upd = (x.values * a[..., None]).reshape(-1)
    return (
        jnp.zeros((x.d,), upd.dtype)
        .at[x.indices.reshape(-1)]
        .add(upd, mode="drop")
    )


def colsum(x, c: jax.Array, square: bool = False) -> jax.Array:
    """sum_i c_i * x_ij (or x_ij^2) -> (d,): the Hessian-diagonal sums."""
    if not is_sparse(x):
        v = x * x if square else x
        return jnp.einsum("n,nd->d", c, v)
    v = x.values * x.values if square else x.values
    upd = (v * c[..., None]).reshape(-1)
    return (
        jnp.zeros((x.d,), upd.dtype)
        .at[x.indices.reshape(-1)]
        .add(upd, mode="drop")
    )


def pad_rows(sf: SparseFeatures, pad: int) -> SparseFeatures:
    """Append `pad` all-padding rows (index d, value 0), preserving the
    padding invariant that plain zero-padding would break."""
    return SparseFeatures(
        indices=jnp.pad(sf.indices, ((0, pad), (0, 0)), constant_values=sf.d),
        values=jnp.pad(sf.values, ((0, pad), (0, 0))),
        d=sf.d,
    )


def row_density(x) -> jax.Array:
    """Per-row stored-entry count (diagnostic)."""
    if not is_sparse(x):
        return jnp.sum(x != 0, axis=-1)
    return jnp.sum(x.indices < x.d, axis=-1)


# -- construction ------------------------------------------------------------


def from_coo(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    num_rows: int,
    num_cols: int,
    nnz_per_row: int = 0,
    dtype=jnp.float32,
) -> SparseFeatures:
    """Build from COO triplets (host-side). Duplicate (row, col) entries are
    summed (the reference's dedup-by-sum, ``DataProcessingUtils.scala:70-76``).
    ``nnz_per_row`` pads/caps the row width; 0 means the max observed."""
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    vals = np.asarray(vals, np.float64)
    # dedup-by-sum on (row, col)
    flat = rows * num_cols + cols
    uniq, inv = np.unique(flat, return_inverse=True)
    summed = np.zeros(uniq.size, np.float64)
    np.add.at(summed, inv, vals)
    r = (uniq // num_cols).astype(np.int64)
    c = (uniq % num_cols).astype(np.int64)
    counts = np.bincount(r, minlength=num_rows)
    k = int(counts.max()) if counts.size and counts.max() > 0 else 1
    if nnz_per_row:
        if k > nnz_per_row:
            raise ValueError(
                f"a row has {k} entries, above nnz_per_row={nnz_per_row}"
            )
        k = nnz_per_row
    indices = np.full((num_rows, k), num_cols, np.int64)
    values = np.zeros((num_rows, k), np.float64)
    # slot of each entry within its row (entries are sorted by flat id)
    starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
    slot = np.arange(uniq.size) - starts[r]
    indices[r, slot] = c
    values[r, slot] = summed
    return SparseFeatures(
        indices=jnp.asarray(indices, jnp.int32),
        values=jnp.asarray(values, dtype),
        d=num_cols,
    )


def from_dense(x: np.ndarray, nnz_per_row: int = 0, dtype=jnp.float32) -> SparseFeatures:
    """Sparsify a dense matrix (testing / oracles)."""
    x = np.asarray(x)
    r, c = np.nonzero(x)
    return from_coo(
        r, c, x[r, c], x.shape[0], x.shape[1], nnz_per_row, dtype
    )


def to_dense(sf: SparseFeatures) -> np.ndarray:
    """Densify (small problems / tests only)."""
    ind = np.asarray(sf.indices)
    val = np.asarray(sf.values)
    n, k = ind.shape
    out = np.zeros((n, sf.d), val.dtype)
    keep = ind < sf.d
    np.add.at(out, (np.repeat(np.arange(n), k)[keep.reshape(-1)], ind[keep]), val[keep])
    return out
