"""Sparse-kernel dispatch: PHOTON_SPARSE_KERNEL={auto,pallas,xla}.

BENCH_r05 pinned the wide-feature GLM loss on XLA's gather/scatter
lowering of the ELL contractions (``sparse_uniform_vs_sklearn`` 0.39x at
92% ceiling fit — the pass cost IS the wall clock), so ``ops/sparse.py``
now routes its ELL kernels through the hand-written Pallas suite in this
package when that is the better backend. This module is the ONE place
that decides:

- ``kernel_mode()``: the env knob. ``auto`` (default) selects Pallas on
  TPU and the existing XLA lowering everywhere else; ``pallas`` forces
  the Pallas suite (interpret mode off-TPU — how tier-1 proves kernel
  correctness on CPU); ``xla`` pins today's gather/scatter path.
- ``use_pallas(...)``: mode x environment x shape eligibility. Pallas is
  skipped when the coefficient table or accumulator would not fit the
  VMEM budget (``PHOTON_PALLAS_VMEM_CAP``, default 4 MiB per buffer —
  row blocks stream, but w and the scatter accumulator are resident),
  when the batch is degenerate (0 rows/slots), or when a >1-device mesh
  is active — sharded ELL solves stay on XLA, whose partitioner knows
  how to split a gather; a Pallas custom call would be replicated.
- ``pallas_available()``: a cached one-shot probe that builds and runs a
  tiny kernel on the current backend. ``auto`` consults it, so a Mosaic
  toolchain that cannot lower the suite (the round-3 lab saw exactly
  that) degrades to the XLA path instead of failing every solve.
  ``pallas`` skips the probe — forced means forced, and tests want the
  real error.
- ``record_kernel_cost(...)``: every kernel wrapper books its analytic
  cost profile (FLOPs, bytes, ONE-design-read roofline traffic) into the
  shared :mod:`photon_ml_tpu.obs.xla_cost` cost book, once per
  (kernel, shape bucket), so bench MFU/achieved-bytes attribution covers
  the new executables exactly like the XLA ones.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, Optional, Tuple

import jax

__all__ = [
    "ENV_VAR",
    "VMEM_CAP_ENV",
    "KERNEL_MODES",
    "kernel_mode",
    "pallas_available",
    "use_pallas",
    "interpret_mode",
    "accumulator_fits",
    "active_mesh_devices",
    "record_kernel_cost",
    "design_reads",
    "reset_probe_cache",
    "shard_local",
    "in_shard_local",
]

ENV_VAR = "PHOTON_SPARSE_KERNEL"
VMEM_CAP_ENV = "PHOTON_PALLAS_VMEM_CAP"
KERNEL_MODES = ("auto", "pallas", "xla")

# Per-buffer VMEM budget for the resident (non-streamed) buffers: the
# gathered coefficient table and the dense scatter accumulator. 4 MiB
# holds d = 1M f32 columns and leaves the double-buffered row blocks
# plenty of a ~16 MiB core (docs/KERNELS.md "Tiling").
_DEFAULT_VMEM_CAP = 4 << 20

# Design reads per pass, per kernel: the counted-work unit the fused
# passes exist to shrink. The XLA objective sequence reads the design
# once per contraction (matvec + rmatvec [+ colsum]); each fused pass
# reads (indices, values) exactly once.
_DESIGN_READS = {
    "ell_matvec": 1,
    "ell_rmatvec": 1,
    "ell_colsum": 1,
    "fused_vgc": 1,
    "fused_hvp": 1,
    "fused_hdiag": 1,
}

_probe_lock = threading.Lock()
_probe_result: Dict[str, bool] = {}

_record_lock = threading.Lock()
_recorded = set()

# one-shot multidevice-fallback signal (the eligibility rule below used
# to fire SILENTLY: every >1-device run quietly lost the Pallas kernels
# with nothing in any artifact saying so)
_fallback_lock = threading.Lock()
_fallback_logged = False

_shard_local_depth = threading.local()


@contextlib.contextmanager
def shard_local():
    """Mark the dynamic extent as SHARD-LOCAL: the caller guarantees the
    traced code runs per-shard under ``shard_map`` (explicit-collective
    paths like ``parallel.distributed.shard_map_value_and_grad`` /
    ``hierarchical_value_and_grad`` and the entity-sharded GAME update),
    so per-shard arrays are device-local and a Pallas custom call keeps
    its semantics — the >1-device-mesh eligibility exclusion below is
    LIFTED here. Under plain GSPMD jit the exclusion stands: the
    partitioner would replicate the custom call and silently compute on
    whole-array shapes."""
    depth = getattr(_shard_local_depth, "value", 0)
    _shard_local_depth.value = depth + 1
    try:
        yield
    finally:
        _shard_local_depth.value = depth


def in_shard_local() -> bool:
    return getattr(_shard_local_depth, "value", 0) > 0


def _note_multidevice_fallback(devices: int) -> None:
    """One-shot log + always-counted metric when the >1-device-mesh rule
    routes an eligible contraction to XLA (ISSUE 14 bugfix: the silent
    loss of the Pallas kernels on every multi-device run)."""
    global _fallback_logged
    try:
        from photon_ml_tpu import obs

        obs.registry().inc("kernels.dispatch.multidevice_fallback")
        with _fallback_lock:
            if _fallback_logged:
                return
            _fallback_logged = True
        obs.emit_event(
            "kernels.dispatch.multidevice_fallback",
            cat="kernels",
            devices=devices,
            hint=(
                "GSPMD meshes route ELL contractions to XLA; shard_map "
                "paths keep Pallas via kernels.dispatch.shard_local()"
            ),
        )
        from photon_ml_tpu.utils.logging import PhotonLogger

        PhotonLogger(None).warn(
            f"sparse ELL contractions falling back to the XLA lowering "
            f"under a {devices}-device mesh (Pallas custom calls are "
            "not GSPMD-partitionable); explicit shard_map paths can "
            "keep the Pallas suite via kernels.dispatch.shard_local()"
        )
    except Exception:
        pass  # dispatch must never fail on observability


def kernel_mode() -> str:
    """The validated ``PHOTON_SPARSE_KERNEL`` value (default ``auto``)."""
    mode = os.environ.get(ENV_VAR, "auto").strip().lower() or "auto"
    if mode not in KERNEL_MODES:
        raise ValueError(
            f"{ENV_VAR}={mode!r}: expected one of {KERNEL_MODES}"
        )
    return mode


def interpret_mode() -> bool:
    """Pallas interpret mode everywhere but real TPU hardware — the
    tier-1 CPU gate proves kernel semantics through the interpreter."""
    return jax.default_backend() != "tpu"


def _vmem_cap() -> int:
    try:
        return int(os.environ.get(VMEM_CAP_ENV, _DEFAULT_VMEM_CAP))
    except ValueError:
        return _DEFAULT_VMEM_CAP


def accumulator_fits(d: int, itemsize: int) -> bool:
    """Would a (d,)-dense resident buffer (coefficients in, accumulator
    out) fit the per-buffer VMEM budget? Lane-pads d the way the kernels
    do before checking."""
    d_pad = -(-(d + 1) // 128) * 128
    return d_pad * itemsize <= _vmem_cap()


def active_mesh_devices() -> int:
    """Device count of the active mesh context (1 when none). Sharded
    solves enter through ``parallel.mesh.set_mesh``; both the 0.4.x
    ``with mesh:`` form and newer ``jax.set_mesh`` land in thread-local
    state this reads back, best-effort."""
    try:
        from jax._src import mesh as mesh_lib

        env = mesh_lib.thread_resources.env
        size = getattr(env.physical_mesh, "size", 0)
        if size and size > 1:
            return int(size)
    except Exception:
        pass
    try:  # newer jax: abstract mesh context
        from jax._src import mesh as mesh_lib

        am = mesh_lib.get_abstract_mesh()
        if am is not None and getattr(am, "size", 0) > 1:
            return int(am.size)
    except Exception:
        pass
    return 1


def _probe() -> bool:
    """Build + run a tiny representative kernel once per backend; any
    failure marks Pallas unavailable for ``auto`` until process exit
    (``reset_probe_cache`` for tests)."""
    backend = jax.default_backend()
    with _probe_lock:
        if backend in _probe_result:
            return _probe_result[backend]
    ok = True
    try:
        import numpy as np

        from photon_ml_tpu.kernels import ell

        idx = np.array([[0, 2], [1, 3]], np.int32)
        val = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        w = np.arange(3, dtype=np.float32)
        out = jax.jit(
            lambda i, v, ww: ell.ell_matvec(i, v, ww, 3)
        )(idx, val, w)
        # row0 = 1*w[0] + 2*w[2] = 4; row1 = 3*w[1] + 4*w[3->pad] = 3
        np.testing.assert_allclose(
            np.asarray(out), [4.0, 3.0], rtol=1e-5
        )
    except Exception:
        ok = False
    with _probe_lock:
        _probe_result[backend] = ok
    return ok


def pallas_available() -> bool:
    return _probe()


def reset_probe_cache() -> None:
    """Forget probe results (tests that flip backends/envs)."""
    with _probe_lock:
        _probe_result.clear()


def use_pallas(
    d: Optional[int] = None,
    itemsize: int = 4,
    n: Optional[int] = None,
    nnz_per_row: Optional[int] = None,
) -> bool:
    """Should the current op take the Pallas path? Trace-time static:
    mode, backend, probe, mesh context, and shape eligibility."""
    mode = kernel_mode()
    if mode == "xla":
        return False
    if n is not None and n == 0:
        return False  # nothing to tile; XLA returns the empty/zero result
    if nnz_per_row is not None and nnz_per_row == 0:
        return False
    if d is not None and not accumulator_fits(d, itemsize):
        return False
    devices = active_mesh_devices()
    if devices > 1 and not in_shard_local():
        # GSPMD would replicate a Pallas custom call (wrong results at
        # whole-array shapes), so sharded solves stay on XLA — but no
        # longer silently (one-shot log + counter), and shard_map'd
        # paths that declared themselves shard-local keep the kernels.
        _note_multidevice_fallback(devices)
        return False
    if mode == "pallas":
        return True
    return jax.default_backend() == "tpu" and pallas_available()


def design_reads(kernel: str) -> int:
    """Design reads per pass of a kernel in this suite — the counted
    unit behind the fused passes' >=2-reads-per-iteration saving."""
    return _DESIGN_READS[kernel]


def record_kernel_cost(
    kernel: str,
    n: int,
    k: int,
    d: int,
    itemsize: int,
    flops_per_slot: float = 2.0,
    extra_bytes: float = 0.0,
) -> None:
    """Book one (kernel, shape) cost record into the shared cost book,
    once per key per process. Called from the kernel wrappers at trace
    time — host-side and cheap, so it is safe inside jit tracing.

    ``roofline_bytes`` is pinned to ``design_reads(kernel)`` times the
    stored design bytes (indices + values): the minimal HBM traffic of
    the pass, which is exactly what the fused kernels reduce and what
    span-level achieved-bytes/s should be measured against.
    """
    key = (kernel, n, k, d, itemsize)
    with _record_lock:
        if key in _recorded:
            return
        _recorded.add(key)
    try:
        from photon_ml_tpu.obs.xla_cost import cost_book

        slots = float(n) * float(k)
        design_bytes = slots * (4 + itemsize)  # int32 ids + payload
        reads = design_reads(kernel)
        cost_book().record(
            f"kernels.{kernel}",
            None,
            bucket=f"{n}x{k}x{d}",
            analytic_flops=flops_per_slot * slots,
            analytic_bytes=reads * design_bytes + extra_bytes,
            roofline_bytes=reads * design_bytes,
        )
    except Exception:
        # observability must never fail the kernel it observes
        with _record_lock:
            _recorded.discard(key)
