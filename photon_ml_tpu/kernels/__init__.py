"""Hand-written Pallas kernel suite for the wide-feature sparse path.

``ops/sparse.py`` routes its three ELL contractions here (per
``PHOTON_SPARSE_KERNEL`` — see :mod:`photon_ml_tpu.kernels.dispatch`),
and ``GLMObjective`` additionally swaps whole objective passes for the
fused single-read sweeps in :mod:`photon_ml_tpu.kernels.fused`. Every
consumer of the sparse containers — ``GLMObjective`` solves, GAME
random-effect batches, serving, ``HybridFeatures``' cold segments —
benefits with zero call-site changes. docs/KERNELS.md is the field
guide.
"""

from photon_ml_tpu.kernels.dispatch import (
    ENV_VAR,
    KERNEL_MODES,
    design_reads,
    interpret_mode,
    kernel_mode,
    pallas_available,
    record_kernel_cost,
    reset_probe_cache,
    use_pallas,
)
from photon_ml_tpu.kernels.ell import (
    ell_colsum,
    ell_matvec,
    ell_rmatvec,
    ell_scatter_add,
)
from photon_ml_tpu.kernels.fused import (
    fused_hessian_diagonal,
    fused_hessian_vector,
    fused_value_grad_curvature,
)

__all__ = [
    "ENV_VAR",
    "KERNEL_MODES",
    "kernel_mode",
    "use_pallas",
    "pallas_available",
    "interpret_mode",
    "design_reads",
    "record_kernel_cost",
    "reset_probe_cache",
    "ell_matvec",
    "ell_rmatvec",
    "ell_colsum",
    "ell_scatter_add",
    "fused_value_grad_curvature",
    "fused_hessian_vector",
    "fused_hessian_diagonal",
]
