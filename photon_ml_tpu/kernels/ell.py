"""Pallas TPU kernels for the padded-ELL contractions.

These replace XLA's gather/scatter lowering of the three hot ops in
``ops/sparse.py`` — the ~90 ms/pass frontier BENCH_r05 measured at 92%
of the sparse solve's wall clock:

    matvec:   z_i = sum_k v_ik * w[c_ik]      (gather + row reduce)
    rmatvec:  g_j = sum_{ik: c_ik=j} v_ik a_i (scatter-add)
    colsum:   s_j = sum_{ik: c_ik=j} f(v_ik) c_i (scatter-add)

Tiling scheme (docs/KERNELS.md):

- The grid runs over ROW BLOCKS of ``_ROW_BLOCK`` rows; Pallas's grid
  pipeline double-buffers each block's (indices, values) DMA against the
  previous block's compute, so the design streams HBM->VMEM at line
  rate.
- The coefficient table (matvec) and the output accumulator (scatter)
  are RESIDENT in VMEM for the whole grid, lane-padded to a multiple of
  128: every scatter-add is a dense accumulation into on-chip memory
  instead of XLA's serialized HBM scatter, and every gather hits VMEM.
  ``dispatch.accumulator_fits`` caps eligibility at the VMEM budget;
  wider problems keep the XLA path (the feature-sharded container
  already splits d per device, so its per-block width is small).
- Padding is algebraically invisible by the same convention as the XLA
  path: padding slots carry column id ``d`` and value 0; the table/
  accumulator is padded past ``d`` with an always-zero tail, so padded
  gathers read 0 and padded scatter lanes add 0 to the tail.
- The scatter kernels are DUPLICATE-SAFE without a scatter primitive:
  within each row, every slot is first replaced by its column GROUP
  TOTAL (a k x k same-column mask contraction), so the unordered vector
  store writes the same value from every duplicate lane; rows then
  accumulate sequentially. Duplicate (row, column) pairs — which
  ``from_coo``'s dedup-sum normally removes — therefore still sum
  exactly like XLA's scatter-add.

Off TPU the kernels run in Pallas interpret mode (tier-1 proves their
semantics on CPU); ``ops/sparse.py`` only routes here per
``kernels.dispatch``.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

try:  # pragma: no cover - exercised via dispatch.pallas_available
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    pl = None
    HAVE_PALLAS = False

from photon_ml_tpu.kernels import dispatch

__all__ = ["ell_matvec", "ell_rmatvec", "ell_colsum", "ell_scatter_add"]

# Rows per grid step. 256 rows x k<=64 slots keeps a block's
# (indices, values) tiles well under 256 KiB while amortizing the grid
# step overhead; override for experiments via PHOTON_PALLAS_ROW_BLOCK.
_DEFAULT_ROW_BLOCK = 256


def _row_block(n: int) -> int:
    try:
        br = int(os.environ.get("PHOTON_PALLAS_ROW_BLOCK", _DEFAULT_ROW_BLOCK))
    except ValueError:
        br = _DEFAULT_ROW_BLOCK
    br = max(8, br)
    # shrink for small batches: one short block beats many empty ones
    return min(br, _round_up(max(n, 1), 8))


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _lane_pad(d: int) -> int:
    """Table/accumulator width: one past-d zero column for padding ids,
    rounded to full 128-lane tiles."""
    return _round_up(d + 1, 128)


def _pad_rows(indices, values, n_pad: int, d: int):
    n = indices.shape[0]
    if n_pad == n:
        return indices, values
    return (
        jnp.pad(indices, ((0, n_pad - n), (0, 0)), constant_values=d),
        jnp.pad(values, ((0, n_pad - n), (0, 0))),
    )


def _group_totals(ix, upd):
    """(rows, k) updates -> same shape with every slot carrying its
    row-local same-column group total. Makes the unordered vector store
    deterministic under duplicate columns (module docstring)."""
    eq = (ix[:, :, None] == ix[:, None, :]).astype(upd.dtype)
    return jnp.einsum("rjk,rk->rj", eq, upd)


# -- matvec ------------------------------------------------------------------


def _matvec_kernel(idx_ref, val_ref, w_ref, out_ref, *, compute_dtype):
    ix = idx_ref[...]
    v = val_ref[...].astype(compute_dtype)
    gathered = w_ref[0, :][ix]  # VMEM-resident table gather
    out_ref[...] = jnp.sum(v * gathered, axis=-1)


def ell_matvec(indices, values, w, d: int):
    """z = ELL(indices, values) @ w — (n,) in the XLA path's promoted
    dtype (bf16 values x f32 w accumulate in f32)."""
    n, k = indices.shape
    cd = jnp.result_type(values.dtype, w.dtype)
    br = _row_block(n)
    n_pad = _round_up(max(n, 1), br)
    d_pad = _lane_pad(d)
    idx_p, val_p = _pad_rows(indices, values, n_pad, d)
    w_p = jnp.pad(w.astype(cd), (0, d_pad - d)).reshape(1, d_pad)
    dispatch.record_kernel_cost(
        "ell_matvec", n, k, d, jnp.dtype(values.dtype).itemsize,
        extra_bytes=d_pad * jnp.dtype(cd).itemsize,
    )
    out = pl.pallas_call(
        functools.partial(_matvec_kernel, compute_dtype=cd),
        grid=(n_pad // br,),
        in_specs=[
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((n_pad,), cd),
        interpret=dispatch.interpret_mode(),
    )(idx_p, val_p, w_p)
    return out[:n]


# -- scatter-add (rmatvec / colsum) ------------------------------------------


def _scatter_kernel(idx_ref, upd_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    ix = idx_ref[...]
    comb = _group_totals(ix, upd_ref[...])

    def body(r, carry):
        row_ix = ix[r, :]
        cur = out_ref[0, :][row_ix]
        out_ref[0, row_ix] = cur + comb[r, :]
        return carry

    jax.lax.fori_loop(0, ix.shape[0], body, 0)


def ell_scatter_add(indices, upd, d: int):
    """g_j = sum over slots with column j of ``upd`` — the shared core
    of rmatvec and colsum. The (1, d_pad) accumulator stays in VMEM
    across the whole row-block grid (kernel='fused'-style dense
    accumulation); output dtype is ``upd``'s."""
    n, k = indices.shape
    cd = upd.dtype
    br = _row_block(n)
    n_pad = _round_up(max(n, 1), br)
    d_pad = _lane_pad(d)
    idx_p, upd_p = _pad_rows(indices, upd, n_pad, d)
    out = pl.pallas_call(
        _scatter_kernel,
        grid=(n_pad // br,),
        in_specs=[
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((br, k), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, d_pad), cd),
        interpret=dispatch.interpret_mode(),
    )(idx_p, upd_p)
    return out[0, :d]


def ell_rmatvec(indices, values, a, d: int):
    """g = ELL^T @ a. The per-slot update v_ik * a_i is formed outside
    the kernel (elementwise, fused by XLA into the DMA feed); the
    scatter itself is the Pallas dense accumulation."""
    n, k = indices.shape
    upd = values * a[..., None]
    dispatch.record_kernel_cost(
        "ell_rmatvec", n, k, d, jnp.dtype(values.dtype).itemsize,
        extra_bytes=_lane_pad(d) * jnp.dtype(upd.dtype).itemsize,
    )
    return ell_scatter_add(indices, upd, d)


def ell_colsum(indices, values, c, d: int, square: bool = False):
    """s_j = sum_i c_i * v_ij (or v_ij^2) — the Hessian-diagonal sums."""
    n, k = indices.shape
    v = values * values if square else values
    upd = v * c[..., None]
    dispatch.record_kernel_cost(
        "ell_colsum", n, k, d, jnp.dtype(values.dtype).itemsize,
        extra_bytes=_lane_pad(d) * jnp.dtype(upd.dtype).itemsize,
    )
    return ell_scatter_add(indices, upd, d)
