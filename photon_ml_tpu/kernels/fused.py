"""Fused ELL objective passes: one design read per solver iteration.

The XLA objective walks the stored design up to three times per
iteration — margins (matvec), gradient back-projection (rmatvec), and
the Hessian-diagonal column sums (colsum) — and BENCH_r05 showed that
walk IS the sparse solve's wall clock (92% ceiling fit at ~90 ms/pass).
Because the pointwise losses are ROW-LOCAL (``ops/losses.py``: l, l',
l'' are elementwise in the margin), the whole forward+backward of one
iteration folds into a single row-block sweep that reads
``(indices, values)`` once:

- :func:`fused_value_grad_curvature` — margins on the forward, the
  weighted loss sum, the scatter-add gradient on the backward, sum(a)
  for the normalization rank-1 correction, and the curvature weights
  c_i = ew_i * l''(z_i) that TRON's next CG loop wants. Replaces the
  matvec + rmatvec pair (and the colsum-bearing sequence below): 3
  design reads -> 1.
- :func:`fused_hessian_vector` — one CG step's H@v: the v-margins
  gather-dot and the back-projection scatter in one sweep (2 reads ->
  1). TRON's inner loop is almost entirely these.
- :func:`fused_hessian_diagonal` — margins plus BOTH column sums
  (value and squared) plus sum(c) for the variance pass (3 reads -> 1).

Loss derivatives are traced straight into the kernel body (VPU
transcendentals); tiling, padding, duplicate-safety, and the VMEM
residency rules are exactly :mod:`photon_ml_tpu.kernels.ell`'s.
``GLMObjective`` applies normalization algebra, L2, and the psum OUTSIDE
— those touch (d,)/(n,) vectors, not the design.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pragma: no cover - exercised via dispatch.pallas_available
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    pl = None
    HAVE_PALLAS = False

from photon_ml_tpu.kernels import dispatch
from photon_ml_tpu.kernels.ell import (
    _group_totals,
    _lane_pad,
    _pad_rows,
    _round_up,
    _row_block,
)

__all__ = [
    "fused_value_grad_curvature",
    "fused_hessian_vector",
    "fused_hessian_diagonal",
]


def _scatter_rows(ix, comb, acc_ref):
    """Sequential per-row accumulate of group-totaled updates into the
    VMEM-resident (1, d_pad) accumulator (see ell.py on duplicate
    safety)."""

    def body(r, carry):
        row_ix = ix[r, :]
        cur = acc_ref[0, :][row_ix]
        acc_ref[0, row_ix] = cur + comb[r, :]
        return carry

    jax.lax.fori_loop(0, ix.shape[0], body, 0)


def _prep(indices, values, row_vecs, w_vec, d):
    """Shared row/lane padding + compute dtype for the fused passes."""
    n, k = indices.shape
    cd = jnp.result_type(values.dtype, w_vec.dtype, *[
        rv.dtype for rv in row_vecs
    ])
    br = _row_block(n)
    n_pad = _round_up(max(n, 1), br)
    d_pad = _lane_pad(d)
    idx_p, val_p = _pad_rows(indices, values, n_pad, d)
    rows_p = tuple(
        jnp.pad(rv.astype(cd), (0, n_pad - n)) for rv in row_vecs
    )
    w_p = jnp.pad(w_vec.astype(cd), (0, d_pad - d)).reshape(1, d_pad)
    return n, k, cd, br, n_pad, d_pad, idx_p, val_p, rows_p, w_p


# -- value / grad / curvature ------------------------------------------------


def _vgc_kernel(
    idx_ref, val_ref, y_ref, off_ref, ew_ref, w_ref,
    val_acc, asum_acc, grad_acc, c_ref, *, loss, compute_dtype,
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        val_acc[...] = jnp.zeros_like(val_acc)
        asum_acc[...] = jnp.zeros_like(asum_acc)
        grad_acc[...] = jnp.zeros_like(grad_acc)

    ix = idx_ref[...]
    v = val_ref[...].astype(compute_dtype)
    z = jnp.sum(v * w_ref[0, :][ix], axis=-1) + off_ref[...]
    y = y_ref[...]
    ew = ew_ref[...]
    val_acc[0, 0] += jnp.sum(ew * loss.value(z, y))
    a = ew * loss.d1(z, y)
    asum_acc[0, 0] += jnp.sum(a)
    c_ref[...] = ew * loss.d2(z, y)
    _scatter_rows(ix, _group_totals(ix, v * a[:, None]), grad_acc)


def fused_value_grad_curvature(
    indices, values, labels, offsets, ew, w_eff, d: int, loss
):
    """One design read -> (loss sum, raw gradient X^T a, sum(a),
    curvature weights c). ``offsets`` must already carry the margin
    shift; ``w_eff`` is the normalization-effective coefficient vector.
    The caller applies factors/shifts corrections, L2 and psum."""
    n, k, cd, br, n_pad, d_pad, idx_p, val_p, rows, w_p = _prep(
        indices, values, (labels, offsets, ew), w_eff, d
    )
    dispatch.record_kernel_cost(
        "fused_vgc", n, k, d, jnp.dtype(values.dtype).itemsize,
        flops_per_slot=4.0,
        extra_bytes=2 * d_pad * jnp.dtype(cd).itemsize,
    )
    val, asum, grad, c = pl.pallas_call(
        functools.partial(_vgc_kernel, loss=loss, compute_dtype=cd),
        grid=(n_pad // br,),
        in_specs=[
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((br,), lambda i: (i,)),
            pl.BlockSpec((br,), lambda i: (i,)),
            pl.BlockSpec((br,), lambda i: (i,)),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((br,), lambda i: (i,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), cd),
            jax.ShapeDtypeStruct((1, 1), cd),
            jax.ShapeDtypeStruct((1, d_pad), cd),
            jax.ShapeDtypeStruct((n_pad,), cd),
        ],
        interpret=dispatch.interpret_mode(),
    )(idx_p, val_p, *rows, w_p)
    return val[0, 0], grad[0, :d], asum[0, 0], c[:n]


# -- Hessian-vector ----------------------------------------------------------


def _hvp_kernel(
    idx_ref, val_ref, c_ref, shift_ref, v_ref,
    hv_acc, usum_acc, *, compute_dtype,
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        hv_acc[...] = jnp.zeros_like(hv_acc)
        usum_acc[...] = jnp.zeros_like(usum_acc)

    ix = idx_ref[...]
    v = val_ref[...].astype(compute_dtype)
    zv = jnp.sum(v * v_ref[0, :][ix], axis=-1) + shift_ref[0, 0]
    u = c_ref[...] * zv
    usum_acc[0, 0] += jnp.sum(u)
    _scatter_rows(ix, _group_totals(ix, v * u[:, None]), hv_acc)


def fused_hessian_vector(indices, values, c, v_eff, shift_v, d: int):
    """One design read -> (raw H@v back-projection X^T (c * (X@v_eff +
    shift_v)), sum(u)). ``c`` are the precomputed curvature weights;
    ``shift_v`` is the scalar margin shift of the CG direction."""
    n, k, cd, br, n_pad, d_pad, idx_p, val_p, rows, v_p = _prep(
        indices, values, (c,), v_eff, d
    )
    dispatch.record_kernel_cost(
        "fused_hvp", n, k, d, jnp.dtype(values.dtype).itemsize,
        flops_per_slot=4.0,
        extra_bytes=2 * d_pad * jnp.dtype(cd).itemsize,
    )
    shift_p = jnp.asarray(shift_v, cd).reshape(1, 1)
    hv, usum = pl.pallas_call(
        functools.partial(_hvp_kernel, compute_dtype=cd),
        grid=(n_pad // br,),
        in_specs=[
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((br,), lambda i: (i,)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, d_pad), cd),
            jax.ShapeDtypeStruct((1, 1), cd),
        ],
        interpret=dispatch.interpret_mode(),
    )(idx_p, val_p, rows[0], shift_p, v_p)
    return hv[0, :d], usum[0, 0]


# -- Hessian diagonal --------------------------------------------------------


def _hdiag_kernel(
    idx_ref, val_ref, y_ref, off_ref, ew_ref, w_ref,
    dx2_acc, dx_acc, csum_acc, *, loss, compute_dtype,
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dx2_acc[...] = jnp.zeros_like(dx2_acc)
        dx_acc[...] = jnp.zeros_like(dx_acc)
        csum_acc[...] = jnp.zeros_like(csum_acc)

    ix = idx_ref[...]
    v = val_ref[...].astype(compute_dtype)
    z = jnp.sum(v * w_ref[0, :][ix], axis=-1) + off_ref[...]
    c = ew_ref[...] * loss.d2(z, y_ref[...])
    csum_acc[0, 0] += jnp.sum(c)
    _scatter_rows(ix, _group_totals(ix, v * v * c[:, None]), dx2_acc)
    _scatter_rows(ix, _group_totals(ix, v * c[:, None]), dx_acc)


def fused_hessian_diagonal(
    indices, values, labels, offsets, ew, w_eff, d: int, loss
):
    """One design read -> (colsum(x^2, c), colsum(x, c), sum(c)) with
    c = ew * l''(z) computed from in-sweep margins — the whole variance
    pass, which the XLA path spends matvec + 2 colsums (3 reads) on."""
    n, k, cd, br, n_pad, d_pad, idx_p, val_p, rows, w_p = _prep(
        indices, values, (labels, offsets, ew), w_eff, d
    )
    dispatch.record_kernel_cost(
        "fused_hdiag", n, k, d, jnp.dtype(values.dtype).itemsize,
        flops_per_slot=5.0,
        extra_bytes=3 * d_pad * jnp.dtype(cd).itemsize,
    )
    dx2, dx, csum = pl.pallas_call(
        functools.partial(_hdiag_kernel, loss=loss, compute_dtype=cd),
        grid=(n_pad // br,),
        in_specs=[
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((br, k), lambda i: (i, 0)),
            pl.BlockSpec((br,), lambda i: (i,)),
            pl.BlockSpec((br,), lambda i: (i,)),
            pl.BlockSpec((br,), lambda i: (i,)),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, d_pad), cd),
            jax.ShapeDtypeStruct((1, d_pad), cd),
            jax.ShapeDtypeStruct((1, 1), cd),
        ],
        interpret=dispatch.interpret_mode(),
    )(idx_p, val_p, *rows, w_p)
    return dx2[0, :d], dx[0, :d], csum[0, 0]
