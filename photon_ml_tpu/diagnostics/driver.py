"""Driver-side diagnostic orchestration.

Rebuild of ``Driver.diagnose()`` (``Driver.scala:424-474``) + the
``writeDiagnostics`` HTML emission (``Driver.scala:549-569``): per trained
model, run prediction-error independence, both feature importances, and
(for logistic models) Hosmer–Lemeshow on the VALIDATION data; when
training diagnostics are enabled, add the learning-curve fitting
diagnostic and bootstrap confidence intervals over the TRAINING data.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.core.tasks import TaskType
from photon_ml_tpu.diagnostics.bootstrap_diag import bootstrap_diagnostic
from photon_ml_tpu.diagnostics.fitting import fitting_diagnostic
from photon_ml_tpu.diagnostics.hl import hosmer_lemeshow
from photon_ml_tpu.diagnostics.importance import feature_importance
from photon_ml_tpu.diagnostics.independence import (
    prediction_error_independence,
)
from photon_ml_tpu.diagnostics.reports import (
    DiagnosticReport,
    ModelDiagnosticReport,
    SystemReport,
)

# beyond this many features the per-feature summary table is omitted from
# the report (the numbers still live in feature-summary.tsv)
MAX_SUMMARY_FEATURES = 200


def build_diagnostic_report(
    params_dict: Dict[str, object],
    models,  # Sequence[TrainedModel]
    validation_metrics: List[Dict[str, float]],
    train_batch,
    validation_batch,
    vocab,
    summary,
    training_config,
    training_diagnostics: bool = False,
    seed: int = 0,
) -> DiagnosticReport:
    """Assemble the full DiagnosticReport for a completed training run."""
    task: TaskType = training_config.task

    summary_table = None
    feature_names = None
    if summary is not None and len(vocab) <= MAX_SUMMARY_FEATURES:
        cols = ("mean", "variance", "min", "max", "mean_abs", "num_nonzeros")
        summary_table = {
            c: [float(v) for v in np.asarray(getattr(summary, c))]
            for c in cols
        }
        feature_names = [
            "{} / {}".format(*vocab.name_term(i))
            for i in range(len(vocab))
        ]

    system = SystemReport(
        params=params_dict,
        num_features=len(vocab),
        summary_table=summary_table,
        feature_names=feature_names,
    )
    report = DiagnosticReport(system=system)

    fit_by_lambda = {}
    if training_diagnostics:
        fit_by_lambda = fitting_diagnostic(
            train_batch, training_config, seed=seed
        )

    vweights = np.asarray(validation_batch.effective_weights())
    vlabels = np.asarray(validation_batch.labels)
    for i, tm in enumerate(models):
        means = np.asarray(
            tm.model.compute_mean(
                validation_batch.features, validation_batch.offsets
            )
        )
        coef = np.asarray(tm.model.coefficients.means)
        hl = None
        if task == TaskType.LOGISTIC_REGRESSION:
            hl = hosmer_lemeshow(
                vlabels, means, num_dimensions=len(vocab), weights=vweights
            )
        bootstrap = None
        if training_diagnostics:
            single = dataclasses.replace(
                training_config, reg_weights=(tm.reg_weight,)
            )
            bootstrap = bootstrap_diagnostic(
                train_batch,
                single,
                coef,
                vocab,
                summary=summary,
                evaluation_batch=validation_batch,
                seed=seed,
            )
        report.models.append(
            ModelDiagnosticReport(
                model_description=(
                    f"{task.name} @ lambda = {tm.reg_weight:g}"
                ),
                reg_weight=tm.reg_weight,
                metrics=(
                    validation_metrics[i]
                    if i < len(validation_metrics)
                    else {}
                ),
                prediction_error_independence=prediction_error_independence(
                    vlabels, means, weights=vweights, seed=seed
                ),
                hosmer_lemeshow=hl,
                mean_impact_importance=feature_importance(
                    coef, vocab, summary, kind="EXPECTED_MAGNITUDE"
                ),
                variance_impact_importance=feature_importance(
                    coef, vocab, summary, kind="VARIANCE"
                ),
                fit_report=fit_by_lambda.get(tm.reg_weight),
                bootstrap_report=bootstrap,
            )
        )
    return report
