"""Learning-curve (fitting) diagnostic.

Rebuild of ``diagnostics/fitting/FittingDiagnostic.scala:33-131``: rows are
tagged uniformly into NUM_TRAINING_PARTITIONS buckets, the last bucket is
held out, and models are refit on cumulative portions (10%, 20%, ... 90%),
warm-starting each portion from the previous one; train + holdout metrics
per lambda per portion form the learning curves.

TPU-first restructuring: the reference materializes filtered RDDs per
portion; here every "subset" is the SAME static-shape batch with the mask
(and weights) zeroed outside the portion — so all 9 refits reuse one jitted
solver compilation, and the holdout evaluation is a margin slice of one
device matmul.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

NUM_TRAINING_PARTITIONS = 10
MIN_SAMPLES_PER_PARTITION_PER_DIMENSION = 10


@dataclasses.dataclass(frozen=True)
class FittingReport:
    """``fitting/FittingReport.scala``: per metric, aligned arrays of
    (portion %, train value, holdout value)."""

    metrics: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]]
    message: str = ""


def fitting_diagnostic(
    batch,
    config,
    seed: int = 0,
) -> Dict[float, FittingReport]:
    """Learning curves for every reg weight in ``config``.

    Returns {lambda: FittingReport}; empty when there is not enough data
    (``FittingDiagnostic.scala:62-64``: need more than
    d * MIN_SAMPLES_PER_PARTITION_PER_DIMENSION real rows).
    """
    import jax.numpy as jnp

    from photon_ml_tpu.models.training import train_glm
    from photon_ml_tpu.ops import metrics as metrics_mod

    mask = np.asarray(batch.mask)
    real = mask > 0
    n_real = int(real.sum())
    d = batch.num_features
    if n_real <= d * MIN_SAMPLES_PER_PARTITION_PER_DIMENSION:
        return {}

    rng = np.random.default_rng(seed)
    tags = np.where(
        real, rng.integers(0, NUM_TRAINING_PARTITIONS, size=mask.shape), -1
    )
    holdout = tags == NUM_TRAINING_PARTITIONS - 1
    holdout_w = np.asarray(batch.effective_weights()) * holdout

    # (lambda, portion) -> {metric: value}; built portion by portion
    curves_train: Dict[float, Dict[float, Dict[str, float]]] = {
        lam: {} for lam in config.reg_weights
    }
    curves_test: Dict[float, Dict[float, Dict[str, float]]] = {
        lam: {} for lam in config.reg_weights
    }

    warm = None
    for max_tag in range(NUM_TRAINING_PARTITIONS - 1):
        in_portion = (tags >= 0) & (tags <= max_tag)
        portion_pct = 100.0 * in_portion.sum() / n_real
        sub = dataclasses.replace(
            batch, mask=jnp.asarray(in_portion, batch.mask.dtype) * batch.mask
        )
        models = train_glm(sub, config, initial_coefficients=warm)
        warm = models[0].model.coefficients  # chain to the next portion
        portion_w = np.asarray(batch.weights) * in_portion
        for tm in models:
            margins = np.asarray(
                tm.model.compute_margin(batch.features, batch.offsets)
            )
            curves_train[tm.reg_weight][portion_pct] = metrics_mod.evaluate(
                config.task, batch.labels, margins, jnp.asarray(portion_w)
            )
            curves_test[tm.reg_weight][portion_pct] = metrics_mod.evaluate(
                config.task, batch.labels, margins, jnp.asarray(holdout_w)
            )

    out: Dict[float, FittingReport] = {}
    for lam in config.reg_weights:
        portions = sorted(curves_test[lam])
        metric_names = sorted(
            {m for p in portions for m in curves_test[lam][p]}
        )
        out[lam] = FittingReport(
            metrics={
                name: (
                    np.asarray(portions),
                    np.asarray(
                        [curves_train[lam][p].get(name, np.nan) for p in portions]
                    ),
                    np.asarray(
                        [curves_test[lam][p].get(name, np.nan) for p in portions]
                    ),
                )
                for name in metric_names
            },
        )
    return out
