"""Diagnostics subsystem (reference L10): model goodness-of-fit, error
independence, feature importance, learning curves, bootstrap confidence
intervals, and the HTML diagnostic report.

Rebuild of ``diagnostics/**`` (~4,200 reference LoC): the statistical
content is preserved — Hosmer–Lemeshow chi-square calibration, Kendall-tau
prediction/error independence, expected-magnitude + variance feature
importances, cumulative-portion learning curves with warm starts, and
bootstrap aggregation — while the Spark RDD choreography is replaced by
vectorized array passes (binning via bincount, the tau pair scan as one
O(m^2) broadcast) and the Scala renderer class hierarchy by a small
logical-report -> HTML pass.
"""

from photon_ml_tpu.diagnostics.hl import (
    HosmerLemeshowReport,
    hosmer_lemeshow,
)
from photon_ml_tpu.diagnostics.importance import (
    FeatureImportanceReport,
    feature_importance,
)
from photon_ml_tpu.diagnostics.independence import (
    KendallTauReport,
    PredictionErrorIndependenceReport,
    kendall_tau,
    prediction_error_independence,
)
from photon_ml_tpu.diagnostics.fitting import FittingReport, fitting_diagnostic
from photon_ml_tpu.diagnostics.bootstrap_diag import (
    BootstrapDiagnosticReport,
    bootstrap_diagnostic,
)
from photon_ml_tpu.diagnostics.reports import (
    DiagnosticReport,
    ModelDiagnosticReport,
    SystemReport,
)
from photon_ml_tpu.diagnostics.html import render_html

__all__ = [
    "HosmerLemeshowReport",
    "hosmer_lemeshow",
    "FeatureImportanceReport",
    "feature_importance",
    "KendallTauReport",
    "PredictionErrorIndependenceReport",
    "kendall_tau",
    "prediction_error_independence",
    "FittingReport",
    "fitting_diagnostic",
    "BootstrapDiagnosticReport",
    "bootstrap_diagnostic",
    "DiagnosticReport",
    "ModelDiagnosticReport",
    "SystemReport",
    "render_html",
]
