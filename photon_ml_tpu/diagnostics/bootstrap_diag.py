"""Bootstrap training diagnostic: coefficient + metric confidence intervals.

Rebuild of ``diagnostics/bootstrap/BootstrapTrainingDiagnostic.scala:26-150``
on top of :func:`photon_ml_tpu.models.bootstrap.bootstrap_train_glm` — the
reference fits 15 bootstrap samples of 70% of the data sequentially on the
cluster; here all replicas run as ONE vmapped device solve. Aggregations
match the reference: per-metric five-number summaries, the
importance-ranked feature list with per-coefficient quartiles, and the
"straddling zero" list (features whose [q1, q3] crosses 0 — candidates for
pruning).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

NUM_IMPORTANT_FEATURES = 15
DEFAULT_BOOTSTRAP_SAMPLES = 15
DEFAULT_BOOTSTRAP_PORTION = 0.7
# report-size cap for the straddling-zero list (wide models can have
# thousands of noise features; keep the artifact bounded like the driver's
# MAX_SUMMARY_FEATURES cap)
MAX_STRADDLING_REPORTED = 100


@dataclasses.dataclass(frozen=True)
class CoefficientInterval:
    """Five-number summary of one coefficient across replicas."""

    name: str
    term: str
    index: int
    importance: float
    min: float
    q1: float
    median: float
    q3: float
    max: float

    @property
    def straddles_zero(self) -> bool:
        return self.q1 < 0.0 < self.q3


@dataclasses.dataclass(frozen=True)
class BootstrapDiagnosticReport:
    """``bootstrap/BootstrapReport.scala``: metric distributions plus the
    important / zero-straddling coefficient intervals."""

    # metric -> (min, q1, median, q3, max) across replicas
    metric_distributions: Dict[str, Tuple[float, float, float, float, float]]
    important_features: Tuple[CoefficientInterval, ...]
    straddling_zero: Tuple[CoefficientInterval, ...]
    num_replicas: int
    portion: float


def bootstrap_diagnostic(
    batch,
    config,
    model_coefficients,
    vocab,
    summary=None,
    evaluation_batch=None,
    num_replicas: int = DEFAULT_BOOTSTRAP_SAMPLES,
    portion: float = DEFAULT_BOOTSTRAP_PORTION,
    seed: int = 0,
) -> BootstrapDiagnosticReport:
    """Bootstrap CIs for ONE (task, lambda) configuration.

    model_coefficients: the full-data fit's raw-space means — used for the
    importance ranking exactly like the reference (|coef| * meanAbs,
    falling back to |coef|; ``BootstrapTrainingDiagnostic.scala:36-60``).
    """
    from photon_ml_tpu.models.bootstrap import bootstrap_train_glm

    result = bootstrap_train_glm(
        batch,
        config,
        num_replicas=num_replicas,
        seed=seed,
        evaluation_batch=(
            evaluation_batch if evaluation_batch is not None else batch
        ),
        portion=portion,
    )

    coef = np.asarray(model_coefficients, np.float64)
    scale = (
        np.asarray(summary.mean_abs, np.float64)
        if summary is not None
        else np.ones_like(coef)
    )
    importance = np.abs(coef) * scale

    w = result.coefficients  # (R, d)
    q1, med, q3 = (
        np.quantile(w, 0.25, axis=0),
        np.quantile(w, 0.5, axis=0),
        np.quantile(w, 0.75, axis=0),
    )
    lo, hi = w.min(axis=0), w.max(axis=0)

    def interval(idx: int) -> CoefficientInterval:
        name, term = vocab.name_term(idx)
        return CoefficientInterval(
            name=name,
            term=term,
            index=idx,
            importance=float(importance[idx]),
            min=float(lo[idx]),
            q1=float(q1[idx]),
            median=float(med[idx]),
            q3=float(q3[idx]),
            max=float(hi[idx]),
        )

    order = np.argsort(-importance, kind="stable")
    important = tuple(
        interval(int(i)) for i in order[:NUM_IMPORTANT_FEATURES]
    )
    straddles = (q1 < 0.0) & (q3 > 0.0)  # vectorized filter before any
    straddling = tuple(  # per-feature object construction
        interval(int(i))
        for i in order[straddles[order]][:MAX_STRADDLING_REPORTED]
    )

    metric_distributions = {
        name: (
            float(np.min(vals)),
            float(np.quantile(vals, 0.25)),
            float(np.quantile(vals, 0.5)),
            float(np.quantile(vals, 0.75)),
            float(np.max(vals)),
        )
        for name, vals in result.metric_distributions.items()
    }

    return BootstrapDiagnosticReport(
        metric_distributions=metric_distributions,
        important_features=important,
        straddling_zero=straddling,
        num_replicas=num_replicas,
        portion=portion,
    )
