"""Diagnostic report -> self-contained HTML.

Rebuild of ``diagnostics/reporting/html/*.scala`` (render strategies per
physical-report node) collapsed into one pass: chapters per model, sections
per diagnostic, tables for numbers, and dependency-free inline SVG line
charts for the learning curves (the reference shells out to a JS plotting
library; a report artifact should not need a network).
"""

from __future__ import annotations

import html as html_mod
from typing import Iterable, List, Sequence

from photon_ml_tpu.diagnostics.reports import (
    DiagnosticReport,
    ModelDiagnosticReport,
)


def _esc(x) -> str:
    return html_mod.escape(str(x))


def _table(headers: Sequence[str], rows: Iterable[Sequence[object]]) -> str:
    out = ["<table><thead><tr>"]
    out += [f"<th>{_esc(h)}</th>" for h in headers]
    out.append("</tr></thead><tbody>")
    for row in rows:
        out.append(
            "<tr>" + "".join(f"<td>{_esc(c)}</td>" for c in row) + "</tr>"
        )
    out.append("</tbody></table>")
    return "".join(out)


def _fmt(v: float) -> str:
    return f"{v:.6g}"


def _svg_lines(
    series: Sequence[tuple],  # (label, xs, ys, color)
    width: int = 560,
    height: int = 280,
    x_label: str = "",
    y_label: str = "",
) -> str:
    """Minimal inline SVG multi-line chart with axis labels."""
    pad = 48
    xs_all = [x for _, xs, _, _ in series for x in xs]
    ys_all = [y for _, _, ys, _ in series for y in ys if y == y]  # drop NaN
    if not xs_all or not ys_all:
        return "<p>(no data)</p>"
    x0, x1 = min(xs_all), max(xs_all)
    y0, y1 = min(ys_all), max(ys_all)
    if x1 == x0:
        x1 = x0 + 1.0
    if y1 == y0:
        y1 = y0 + 1.0

    def sx(x):
        return pad + (x - x0) / (x1 - x0) * (width - 2 * pad)

    def sy(y):
        return height - pad - (y - y0) / (y1 - y0) * (height - 2 * pad)

    parts = [
        f'<svg viewBox="0 0 {width} {height}" width="{width}" '
        f'height="{height}" xmlns="http://www.w3.org/2000/svg">',
        f'<rect width="{width}" height="{height}" fill="white"/>',
        f'<line x1="{pad}" y1="{height - pad}" x2="{width - pad}" '
        f'y2="{height - pad}" stroke="#333"/>',
        f'<line x1="{pad}" y1="{pad}" x2="{pad}" y2="{height - pad}" '
        'stroke="#333"/>',
        f'<text x="{width / 2}" y="{height - 8}" text-anchor="middle" '
        f'font-size="12">{_esc(x_label)}</text>',
        f'<text x="12" y="{height / 2}" text-anchor="middle" font-size="12" '
        f'transform="rotate(-90 12 {height / 2})">{_esc(y_label)}</text>',
        f'<text x="{pad}" y="{height - pad + 16}" font-size="10" '
        f'text-anchor="middle">{_fmt(x0)}</text>',
        f'<text x="{width - pad}" y="{height - pad + 16}" font-size="10" '
        f'text-anchor="middle">{_fmt(x1)}</text>',
        f'<text x="{pad - 4}" y="{height - pad}" font-size="10" '
        f'text-anchor="end">{_fmt(y0)}</text>',
        f'<text x="{pad - 4}" y="{pad}" font-size="10" '
        f'text-anchor="end">{_fmt(y1)}</text>',
    ]
    legend_y = pad
    for label, xs, ys, color in series:
        pts = " ".join(
            f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys) if y == y
        )
        parts.append(
            f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
            f'points="{pts}"/>'
        )
        parts.append(
            f'<rect x="{width - pad - 110}" y="{legend_y - 8}" width="10" '
            f'height="10" fill="{color}"/>'
            f'<text x="{width - pad - 96}" y="{legend_y}" font-size="11">'
            f"{_esc(label)}</text>"
        )
        legend_y += 16
    parts.append("</svg>")
    return "".join(parts)


_CSS = """
body { font-family: sans-serif; margin: 2em; color: #222; }
h1 { border-bottom: 2px solid #444; }
h2 { border-bottom: 1px solid #999; margin-top: 2em; }
h3 { margin-top: 1.5em; }
table { border-collapse: collapse; margin: 0.5em 0; }
th, td { border: 1px solid #bbb; padding: 3px 8px; font-size: 13px; }
th { background: #eee; }
pre { background: #f6f6f6; padding: 8px; font-size: 12px; }
.warn { color: #a40; }
"""


def _render_model(m: ModelDiagnosticReport) -> List[str]:
    out = [f"<h2>{_esc(m.model_description)}</h2>"]
    if m.metrics:
        out.append("<h3>Validation metrics</h3>")
        out.append(
            _table(
                ("Metric", "Value"),
                [(k, _fmt(v)) for k, v in sorted(m.metrics.items())],
            )
        )
    if m.hosmer_lemeshow is not None:
        hl = m.hosmer_lemeshow
        out.append("<h3>Hosmer&ndash;Lemeshow goodness-of-fit</h3>")
        out.append(f"<pre>{_esc(hl.binning_msg)}</pre>")
        out.append(
            _table(
                ("Chi^2", "DoF", "P(X^2 <= observed)", "p-value"),
                [
                    (
                        _fmt(hl.chi_square),
                        hl.degrees_of_freedom,
                        _fmt(hl.chi_square_probability),
                        _fmt(hl.p_value),
                    )
                ],
            )
        )
        out.append(
            _table(
                (
                    "Bin", "Observed +", "Expected +",
                    "Observed -", "Expected -",
                ),
                [
                    (
                        f"[{b.lower:.3f}, {b.upper:.3f})",
                        b.observed_pos,
                        b.expected_pos,
                        b.observed_neg,
                        b.expected_neg,
                    )
                    for b in hl.bins
                ],
            )
        )
        out.append("<h4>Chi^2 cutoffs by confidence level</h4>")
        out.append(
            _table(
                ("Confidence", "Cutoff"),
                [(_fmt(c), _fmt(x)) for c, x in hl.cutoffs],
            )
        )
        if hl.chi_square_msg:
            out.append(
                f'<pre class="warn">{_esc(hl.chi_square_msg)}</pre>'
            )
    if m.prediction_error_independence is not None:
        kt = m.prediction_error_independence.kendall_tau
        out.append("<h3>Prediction / error independence (Kendall tau)</h3>")
        out.append(
            _table(
                (
                    "Concordant", "Discordant", "Items", "Pairs",
                    "tau-alpha", "tau-beta", "z", "p",
                ),
                [
                    (
                        kt.num_concordant,
                        kt.num_discordant,
                        kt.num_items,
                        kt.num_pairs,
                        _fmt(kt.tau_alpha),
                        _fmt(kt.tau_beta),
                        _fmt(kt.z_alpha),
                        _fmt(kt.p_value),
                    )
                ],
            )
        )
        if kt.message:
            out.append(f'<pre class="warn">{_esc(kt.message)}</pre>')
    for title, rep in (
        ("Feature importance (inner-product expectation)",
         m.mean_impact_importance),
        ("Feature importance (inner-product variance)",
         m.variance_impact_importance),
    ):
        if rep is None:
            continue
        out.append(f"<h3>{_esc(title)}</h3>")
        out.append(f"<p>{_esc(rep.importance_description)}</p>")
        out.append(
            _table(
                ("Rank", "Name", "Term", "Importance", "Coefficient"),
                [
                    (i + 1, f.name, f.term, _fmt(f.importance),
                     _fmt(f.coefficient))
                    for i, f in enumerate(rep.features)
                ],
            )
        )
    if m.fit_report is not None and m.fit_report.metrics:
        out.append("<h3>Learning curves (fitting diagnostic)</h3>")
        for name, (portions, train, test) in sorted(
            m.fit_report.metrics.items()
        ):
            out.append(f"<h4>{_esc(name)}</h4>")
            out.append(
                _svg_lines(
                    [
                        ("train", list(portions), list(train), "#1f77b4"),
                        ("holdout", list(portions), list(test), "#d62728"),
                    ],
                    x_label="% of training data",
                    y_label=name,
                )
            )
    if m.bootstrap_report is not None:
        br = m.bootstrap_report
        out.append(
            f"<h3>Bootstrap ({br.num_replicas} replicas, "
            f"{br.portion:.0%} samples)</h3>"
        )
        if br.metric_distributions:
            out.append(
                _table(
                    ("Metric", "Min", "Q1", "Median", "Q3", "Max"),
                    [
                        (k, *(_fmt(v) for v in vals))
                        for k, vals in sorted(
                            br.metric_distributions.items()
                        )
                    ],
                )
            )
        out.append("<h4>Important features (coefficient intervals)</h4>")
        out.append(
            _table(
                ("Name", "Term", "Importance", "Min", "Q1", "Median",
                 "Q3", "Max"),
                [
                    (f.name, f.term, _fmt(f.importance), _fmt(f.min),
                     _fmt(f.q1), _fmt(f.median), _fmt(f.q3), _fmt(f.max))
                    for f in br.important_features
                ],
            )
        )
        if br.straddling_zero:
            out.append(
                "<h4>Features whose [Q1, Q3] straddles zero</h4>"
            )
            out.append(
                _table(
                    ("Name", "Term", "Importance", "Q1", "Median", "Q3"),
                    [
                        (f.name, f.term, _fmt(f.importance), _fmt(f.q1),
                         _fmt(f.median), _fmt(f.q3))
                        for f in br.straddling_zero
                    ],
                )
            )
    return out


def render_html(report: DiagnosticReport, title: str = "Model diagnostics") -> str:
    """DiagnosticReport -> one self-contained HTML document
    (``Driver.writeDiagnostics`` / ``HTMLRenderStrategy.scala``)."""
    out = [
        "<!DOCTYPE html><html><head><meta charset='utf-8'>",
        f"<title>{_esc(title)}</title><style>{_CSS}</style></head><body>",
        f"<h1>{_esc(title)}</h1>",
        "<h2>System</h2>",
        f"<p>Feature space: {report.system.num_features} columns</p>",
        "<h3>Driver parameters</h3>",
        _table(
            ("Parameter", "Value"),
            sorted(report.system.params.items()),
        ),
    ]
    if report.system.summary_table:
        out.append("<h3>Feature summary</h3>")
        cols = list(report.system.summary_table)
        names = report.system.feature_names or []
        rows = [
            [names[i] if i < len(names) else i]
            + [_fmt(report.system.summary_table[c][i]) for c in cols]
            for i in range(
                len(next(iter(report.system.summary_table.values())))
            )
        ]
        out.append(_table(["Feature"] + cols, rows))
    for m in report.models:
        out.extend(_render_model(m))
    out.append("</body></html>")
    return "".join(out)
