"""Prediction/error independence via Kendall's tau.

Rebuild of ``diagnostics/independence/KendallTauAnalysis.scala:26-128`` +
``PredictionErrorIndependenceDiagnostic.scala:26-54``. The reference
samples up to 5000 (prediction, error) pairs and classifies every ordered
pair via a cartesian RDD / nested loop; here the pair classification is a
single vectorized O(m^2) broadcast (25M sign comparisons — one fused device
or numpy pass), with identical tie semantics: a tie in the FIRST variable
is TIES_IN_A regardless of the second (``KendallTauAnalysis.scala:101-127``).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

MAXIMUM_SAMPLE_SIZE = 5000  # ``PredictionErrorIndependenceDiagnostic.scala:52``


@dataclasses.dataclass(frozen=True)
class KendallTauReport:
    """``independence/KendallTauReport.scala``."""

    num_concordant: int
    num_discordant: int
    num_items: int
    num_pairs: int
    num_effective_pairs: int  # concordant + discordant
    tau_alpha: float
    tau_beta: float
    z_alpha: float
    p_value: float
    message: str


@dataclasses.dataclass(frozen=True)
class PredictionErrorIndependenceReport:
    """``independence/PredictionErrorIndependenceReport.scala``: the
    sampled (prediction, error) arrays plus the tau analysis."""

    predictions: np.ndarray
    errors: np.ndarray
    kendall_tau: KendallTauReport


def _normal_cdf(x: float) -> float:
    return 0.5 * (1.0 + math.erf(x / math.sqrt(2.0)))


def kendall_tau(a, b) -> KendallTauReport:
    """Tau-alpha / tau-beta / z / p over all i<j pairs of (a, b) draws.

    Matches ``KendallTauAnalysis.analyze``: tau_alpha = (C-D)/(C+D),
    tau_beta = (C-D)/sqrt((P-Ta)(P-Tb)) with P = m(m-1)/2, z from the
    standard tau variance approximation, and the two-sided-mass "p value"
    convention the reference uses (cdf(|z|) - cdf(-|z|): LARGE means
    dependence detected).

    Note: the reference classifies each pair into exactly one category
    with ties in the FIRST variable taking precedence, so pairs tied in
    BOTH variables count toward Ta but never Tb. Its tau_beta therefore
    differs slightly from the textbook/scipy tau-b whenever double ties
    exist; we reproduce the reference's arithmetic.
    """
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    m = a.shape[0]
    iu = np.triu_indices(m, k=1)
    # index the pair vectors directly — no (m, m) temporaries
    dx = np.sign(a[iu[0]] - a[iu[1]])
    dy = np.sign(b[iu[0]] - b[iu[1]])
    ties_a = int(np.sum(dx == 0))
    ties_b = int(np.sum((dx != 0) & (dy == 0)))
    concordant = int(np.sum(dx * dy > 0))
    discordant = int(np.sum(dx * dy < 0))

    num_pairs = m * (m - 1) // 2
    no_ties_a = num_pairs - ties_a
    no_ties_b = num_pairs - ties_b
    effective = concordant + discordant
    tau_alpha = (concordant - discordant) / effective if effective else 0.0
    denom = math.sqrt(float(no_ties_a) * float(no_ties_b))
    tau_beta = (concordant - discordant) / denom if denom else 0.0
    va = 2.0 * (2.0 * m + 5.0)
    vb = 9.0 * m * (m - 1.0)
    d = math.sqrt(va / vb) if vb > 0 else 1.0
    z_alpha = tau_alpha / d
    p_value = _normal_cdf(abs(z_alpha)) - _normal_cdf(-abs(z_alpha))

    message = (
        f"Note: detected ties (ties in first variable: {ties_a}, ties in "
        f"second variable: {ties_b}). This means that the computed z score "
        "/ p value for tau-alpha over-estimates the degree of independence "
        "between A and B."
        if ties_a + ties_b > 0
        else ""
    )
    return KendallTauReport(
        num_concordant=concordant,
        num_discordant=discordant,
        num_items=m,
        num_pairs=num_pairs,
        num_effective_pairs=effective,
        tau_alpha=tau_alpha,
        tau_beta=tau_beta,
        z_alpha=z_alpha,
        p_value=p_value,
        message=message,
    )


def prediction_error_independence(
    labels,
    predicted_means,
    weights=None,
    seed: int = 0,
    max_sample: int = MAXIMUM_SAMPLE_SIZE,
) -> PredictionErrorIndependenceReport:
    """error = label - predicted mean; tau analysis on a <=5000-row sample
    (``PredictionErrorIndependenceDiagnostic.scala:31-49``)."""
    y = np.asarray(labels, np.float64)
    p = np.asarray(predicted_means, np.float64)
    if weights is not None:
        keep = np.asarray(weights, np.float64) > 0
        y, p = y[keep], p[keep]
    err = y - p
    n = y.shape[0]
    if n > max_sample:
        idx = np.random.default_rng(seed).choice(n, max_sample, replace=False)
        p, err = p[idx], err[idx]
    return PredictionErrorIndependenceReport(
        predictions=p, errors=err, kendall_tau=kendall_tau(p, err)
    )
