"""Logical diagnostic report structures.

Rebuild of ``diagnostics/reporting/reports/{combined,model,system}/*.scala``:
the reference separates logical reports (what was measured) from physical
reports (sections/tables/plots) from rendering (HTML/text). Python needs no
three-layer class hierarchy — the logical layer is these dataclasses and
the physical+render layers collapse into :mod:`photon_ml_tpu.diagnostics.html`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from photon_ml_tpu.diagnostics.bootstrap_diag import BootstrapDiagnosticReport
from photon_ml_tpu.diagnostics.fitting import FittingReport
from photon_ml_tpu.diagnostics.hl import HosmerLemeshowReport
from photon_ml_tpu.diagnostics.importance import FeatureImportanceReport
from photon_ml_tpu.diagnostics.independence import (
    PredictionErrorIndependenceReport,
)


@dataclasses.dataclass
class SystemReport:
    """``reports/system/SystemReport.scala``: driver params + the feature
    summary, common to every model in the run."""

    params: Dict[str, object]
    num_features: int
    summary_table: Optional[Dict[str, List[float]]] = None
    feature_names: Optional[List[str]] = None


@dataclasses.dataclass
class ModelDiagnosticReport:
    """``reports/model/ModelDiagnosticReport.scala``: everything measured
    about one (lambda, model)."""

    model_description: str
    reg_weight: float
    metrics: Dict[str, float]
    prediction_error_independence: Optional[
        PredictionErrorIndependenceReport
    ] = None
    hosmer_lemeshow: Optional[HosmerLemeshowReport] = None
    mean_impact_importance: Optional[FeatureImportanceReport] = None
    variance_impact_importance: Optional[FeatureImportanceReport] = None
    fit_report: Optional[FittingReport] = None
    bootstrap_report: Optional[BootstrapDiagnosticReport] = None


@dataclasses.dataclass
class DiagnosticReport:
    """``reports/combined/DiagnosticReport.scala``."""

    system: SystemReport
    models: List[ModelDiagnosticReport] = dataclasses.field(
        default_factory=list
    )
