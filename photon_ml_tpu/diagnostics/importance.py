"""Feature importance rankings.

Rebuild of ``diagnostics/featureimportance/*.scala``: two notions of
per-feature importance over a fitted GLM —

  EXPECTED_MAGNITUDE  |coef_j| * meanAbs_j   (inner-product expectation,
                      ``ExpectedMagnitudeFeatureImportanceDiagnostic.scala:29-62``)
  VARIANCE            |coef_j| * variance_j  (inner-product variance,
                      ``VarianceFeatureImportanceDiagnostic.scala:29-60``)

both falling back to |coef_j| when no feature summary is available, with
the reference's top-50 detail list and 101-point importance-by-fractile
curve (``AbstractFeatureImportanceDiagnostic.scala:39-127``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

MAX_RANKED_FEATURES = 50
NUM_IMPORTANCE_FRACTILES = 100

IMPORTANCE_KINDS = ("EXPECTED_MAGNITUDE", "VARIANCE")


@dataclasses.dataclass(frozen=True)
class RankedFeature:
    name: str
    term: str
    index: int
    importance: float
    coefficient: float
    description: str


@dataclasses.dataclass(frozen=True)
class FeatureImportanceReport:
    """``featureimportance/FeatureImportanceReport.scala``."""

    importance_type: str
    importance_description: str
    features: Tuple[RankedFeature, ...]  # top MAX_RANKED_FEATURES, desc
    rank_to_importance: Dict[float, float]  # fractile (%) -> importance


def feature_importance(
    coefficients,
    vocab,
    summary=None,
    kind: str = "EXPECTED_MAGNITUDE",
) -> FeatureImportanceReport:
    """Rank every feature by the chosen importance measure.

    coefficients: (d,) raw-space means; vocab: FeatureVocabulary;
    summary: BasicStatisticalSummary or None.
    """
    if kind not in IMPORTANCE_KINDS:
        raise ValueError(f"kind must be one of {IMPORTANCE_KINDS}: {kind}")
    coef = np.asarray(coefficients, np.float64)
    d = coef.shape[0]
    if summary is not None:
        scale = np.asarray(
            summary.mean_abs if kind == "EXPECTED_MAGNITUDE"
            else summary.variance,
            np.float64,
        )
        description = (
            "Expected magnitude of inner product contribution"
            if kind == "EXPECTED_MAGNITUDE"
            else "Expected inner product variance contribution"
        )
    else:
        scale = np.ones(d)
        description = "Magnitude of feature coefficient"
    importance = np.abs(coef * scale)

    order = np.argsort(-importance, kind="stable")
    top = order[:MAX_RANKED_FEATURES]
    features = []
    for idx in top:
        name, term = vocab.name_term(int(idx))
        desc = (
            f"Feature (name=[{name}], term=[{term}]) importance = "
            f"[{importance[idx]:.3f}], coefficient = [{coef[idx]:.6g}]"
        )
        if summary is not None:
            desc += (
                f" min=[{float(np.asarray(summary.min)[idx])}]"
                f", mean=[{float(np.asarray(summary.mean)[idx])}]"
                f", max=[{float(np.asarray(summary.max)[idx])}]"
                f", variance=[{float(np.asarray(summary.variance)[idx])}]"
            )
        features.append(
            RankedFeature(
                name=name,
                term=term,
                index=int(idx),
                importance=float(importance[idx]),
                coefficient=float(coef[idx]),
                description=desc,
            )
        )

    # importance at evenly spaced ranks, reported by fractile percent.
    # Intentional divergence: ``AbstractFeatureImportanceDiagnostic.scala:94-97``
    # divides the rank by MAX_RANKED_FEATURES (50) while iterating 0..100
    # fractiles, so its curve saturates at the minimum importance beyond the
    # 50% fractile — an apparent bug; we use the fractile count so the curve
    # spans the whole ranking.
    sorted_imp = importance[order]
    rank_to_importance = {}
    for f in range(NUM_IMPORTANCE_FRACTILES + 1):
        pos = min(d - 1, f * d // NUM_IMPORTANCE_FRACTILES)
        rank_to_importance[100.0 * f / NUM_IMPORTANCE_FRACTILES] = float(
            sorted_imp[pos]
        )

    return FeatureImportanceReport(
        importance_type=(
            "Inner product expectation"
            if kind == "EXPECTED_MAGNITUDE"
            else "Inner product variance"
        ),
        importance_description=description,
        features=tuple(features),
        rank_to_importance=rank_to_importance,
    )
