"""Hosmer–Lemeshow goodness-of-fit for logistic models.

Rebuild of ``diagnostics/hl/HosmerLemeshowDiagnostic.scala:28-97`` +
``DefaultPredictedProbabilityVersusObservedFrequencyBinner.scala:28-62`` +
``PredictedProbabilityVersusObservedFrequencyHistogramBin.scala:30-79``.
The reference walks the RDD once per partition updating mutable bins via
binary search; here the whole binning is two ``bincount`` calls on the bin
index vector (one device pass), after which the chi-square arithmetic is
host-side scalar work on the B-bin table.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Tuple

import numpy as np

# ``HosmerLemeshowDiagnostic.scala:92-96``
STANDARD_CONFIDENCE_LEVELS = (
    0.000001, 0.01, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5,
    0.6, 0.7, 0.8, 0.9, 0.95, 0.99, 0.999999,
)
MINIMUM_EXPECTED_IN_BUCKET = 5
# ``DefaultPredictedProbabilityVersusObservedFrequencyBinner`` — the
# reference applies FACTOR_A to both the sqrt and log1p terms (its
# FACTOR_B constant is defined but unused); replicated as-written.
DATA_HEURISTIC_FACTOR_A = 0.9


@dataclasses.dataclass(frozen=True)
class HistogramBin:
    """One [lower, upper) probability bin with observed +/- counts and the
    midpoint-based expected counts (``...HistogramBin.scala:30-79``)."""

    lower: float
    upper: float
    observed_pos: int
    observed_neg: int

    @property
    def total(self) -> int:
        return self.observed_pos + self.observed_neg

    @property
    def expected_pos(self) -> int:
        # ceil(total * bin midpoint), like the reference's Long ceil
        return int(math.ceil(self.total * (self.lower + self.upper) / 2.0))

    @property
    def expected_neg(self) -> int:
        return self.total - self.expected_pos


@dataclasses.dataclass(frozen=True)
class HosmerLemeshowReport:
    """``hl/HosmerLemeshowReport.scala``: the binned table plus the
    chi-square score, degrees of freedom, and confidence cutoffs."""

    binning_msg: str
    chi_square_msg: str
    chi_square: float
    degrees_of_freedom: int
    chi_square_probability: float  # P(X^2 <= observed) under H0
    cutoffs: Tuple[Tuple[float, float], ...]  # (confidence level, cutoff)
    bins: Tuple[HistogramBin, ...]

    @property
    def p_value(self) -> float:
        """P(X^2 >= observed): small means the model is poorly calibrated."""
        return 1.0 - self.chi_square_probability


def _bin_count(num_items: int, num_dimensions: int) -> Tuple[str, int]:
    """``DefaultPredictedProbabilityVersusObservedFrequencyBinner``: the
    min of a dimension-driven and a data-volume-driven bin target."""
    by_dim = num_dimensions + 2
    by_data = int(
        DATA_HEURISTIC_FACTOR_A * math.sqrt(num_items)
        + DATA_HEURISTIC_FACTOR_A * math.log1p(num_items)
    )
    actual = max(1, min(by_data, by_dim))
    ok = (
        "Sufficient bins for a discriminative test"
        if actual >= by_dim
        else "Not enough bins for a discriminative test; please be careful "
        "when interpreting these results or rerun with more data"
    )
    msg = (
        f"Number of test set samples: {num_items}\n"
        f"Sample dimensionality: {num_dimensions}\n"
        f"Target number of bins based on dimensionality alone: {by_dim}\n"
        f"Target number of bins based on data alone: {by_data}\n"
        f"{ok}"
    )
    return msg, actual


def hosmer_lemeshow(
    labels,
    predicted_probabilities,
    num_dimensions: int,
    weights=None,
) -> HosmerLemeshowReport:
    """HL test on (observed label, predicted probability) pairs.

    ``labels`` in {0, 1}; probabilities in [0, 1]. Rows with weight 0
    (padding) are dropped. Binning + counting is vectorized; the chi-square
    over the B-bin table follows ``HosmerLemeshowDiagnostic.scala:46-90``
    exactly, including the zero-expected guards and the small-expected-count
    warnings.
    """
    y = np.asarray(labels, np.float64)
    p = np.asarray(predicted_probabilities, np.float64)
    if weights is not None:
        keep = np.asarray(weights, np.float64) > 0
        y, p = y[keep], p[keep]
    n = y.shape[0]
    bin_msg, num_bins = _bin_count(n, num_dimensions)

    idx = np.clip((p * num_bins).astype(np.int64), 0, num_bins - 1)
    pos = np.bincount(idx, weights=(y > 0.5), minlength=num_bins)
    tot = np.bincount(idx, minlength=num_bins)
    neg = tot - pos

    bins: List[HistogramBin] = [
        HistogramBin(
            lower=b / num_bins,
            upper=(b + 1) / num_bins,
            observed_pos=int(pos[b]),
            observed_neg=int(neg[b]),
        )
        for b in range(num_bins)
    ]

    chi_sq = 0.0
    msgs: List[str] = []
    for b in bins:
        ep, en = b.expected_pos, b.expected_neg
        if ep > 0:
            chi_sq += (b.observed_pos - ep) ** 2 / float(ep)
        if ep < MINIMUM_EXPECTED_IN_BUCKET:
            msgs.append(
                f"For bin [{b.lower:.4f}, {b.upper:.4f}), expected positive "
                "count is too small to soundly use in a Chi^2 estimate"
            )
        if en > 0:
            chi_sq += (b.observed_neg - en) ** 2 / float(en)
        if en < MINIMUM_EXPECTED_IN_BUCKET:
            msgs.append(
                f"For bin [{b.lower:.4f}, {b.upper:.4f}), expected negative "
                "count is too small to soundly use in a Chi^2 estimate"
            )

    from scipy.stats import chi2 as chi2_dist

    dof = max(num_bins - 2, 1)
    cutoffs = tuple(
        (level, float(chi2_dist.ppf(level, dof)))
        for level in STANDARD_CONFIDENCE_LEVELS
    )
    prob = float(chi2_dist.cdf(chi_sq, dof))

    return HosmerLemeshowReport(
        binning_msg=bin_msg,
        chi_square_msg="\n".join(msgs),
        chi_square=chi_sq,
        degrees_of_freedom=dof,
        chi_square_probability=prob,
        cutoffs=cutoffs,
        bins=tuple(bins),
    )
