"""Self-healing model lifecycle: drift-triggered continual retrain.

PR 13 built the trigger (DriftMonitor PSI/JS alarms, train-time baseline
fingerprints, ``photon-obs drift`` exit codes) and PR 10/11 built the
safety nets (reload breaker, chaos drills, sharded checkpoints). This
package closes the loop: a retrain orchestrator that consumes the drift
signal, runs an incremental warm-started retrain, re-exports through the
manifest gate, and hot-reloads under live traffic with the breaker as
the last line of defense. docs/LIFECYCLE.md is the walkthrough;
``photon-retrain`` (cli/retrain.py) is the operational surface.
"""

from photon_ml_tpu.lifecycle.orchestrator import (
    CycleResult,
    LifecycleError,
    RetrainOrchestrator,
    RetrainPlan,
    StageResult,
    WarmStartError,
    export_retrained_model,
    fingerprint_drift_trigger,
    latest_version_dir,
    load_admission_candidates,
    load_warm_start,
    next_version_dir,
    registry_drift_trigger,
    select_retrain_targets,
)

__all__ = [
    "CycleResult",
    "LifecycleError",
    "RetrainOrchestrator",
    "RetrainPlan",
    "StageResult",
    "WarmStartError",
    "export_retrained_model",
    "fingerprint_drift_trigger",
    "latest_version_dir",
    "load_admission_candidates",
    "load_warm_start",
    "next_version_dir",
    "registry_drift_trigger",
    "select_retrain_targets",
]
