"""The retrain orchestrator: drift alarm -> warm-started retrain ->
manifest-gated export -> hot-reload, with every stage a named fault
site and a defined degraded outcome.

The loop (docs/LIFECYCLE.md has the full walkthrough)::

    trigger --> plan --> retrain --> export gate --> reload --> verify
      |          |          |            |              |         |
      |     admission   warm-start   manifest       breaker    drift
      |     log + conv  entity-     verification   guarded    re-check
      |     health      KEYED       (partial        swap      (alarm
      |                 (PR-4/11    export never               clears)
      |                 bug class)  serves)
      +--- no alarm: nothing to do (the cheap steady-state path)

Degraded outcomes are the design center, not the error path: a failed
stage (after its in-cycle retries) fails the CYCLE — the old model
keeps serving, the alarm stays latched, and the next cycle retries
after an exponential backoff. Nothing in this module ever touches the
scoring path directly; the serving registry's reload breaker remains
the last line of defense against a bad retrain that makes it all the
way to an export.

Stage fault sites (resilience/faults.py):

- ``retrain.warm_start`` — the prior-export load. raise = unreadable
  export; corrupt = torn/poisoned warm start that
  :func:`load_warm_start`'s finiteness gate must catch.
- ``retrain.export``     — the re-export. raise = export dies mid-write
  (no manifest lands, the registry never sees the partial dir);
  corrupt = a torn payload written AFTER the manifest, which the
  integrity gate + reload breaker must quarantine.
- ``serving.reload`` / ``cache.admission_log`` participate from their
  own layers.

Warm starts are ENTITY-KEYED end to end: the default GAME path rides
``initial_model_dir`` (``load_game_model`` re-keys rows by raw entity
id into the new run's vocabulary) and checkpoint-based paths ride
:func:`~photon_ml_tpu.io.checkpoint.reindex_entity_params`. Positional
warm starts are the known PR-4/PR-11 bug class; nothing here indexes a
prior table by row number.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from photon_ml_tpu import obs
from photon_ml_tpu.resilience import faults as _faults

__all__ = [
    "CycleResult",
    "LifecycleError",
    "RetrainOrchestrator",
    "RetrainPlan",
    "StageResult",
    "WarmStartError",
    "export_retrained_model",
    "fingerprint_drift_trigger",
    "latest_version_dir",
    "load_admission_candidates",
    "load_warm_start",
    "next_version_dir",
    "registry_drift_trigger",
    "select_retrain_targets",
]


class LifecycleError(Exception):
    """A lifecycle stage failed in a way retries cannot mask."""


class WarmStartError(LifecycleError):
    """The prior export loaded but its parameters are unusable (non-
    finite values — a torn write or an injected corruption). The cycle
    must fail rather than retrain from poison: a NaN warm start
    converges to a NaN model that the export gate cannot catch."""


# ---------------------------------------------------------------------------
# stage helpers (the named fault seams)
# ---------------------------------------------------------------------------


def load_warm_start(export_dir: str):
    """Load the previous export as the retrain's warm start — entity-
    keyed by construction (``load_game_model_auto`` returns per-RE-type
    ``{raw_id: row}`` vocabularies; consumers re-key by id, never by
    position). Probes the ``retrain.warm_start`` fault site and gates
    the result on finiteness, so a corrupt prior export fails the
    cycle instead of seeding a poisoned retrain.

    Returns ``(params, shards, random_effects, shard_vocabs,
    re_vocabs)`` exactly like ``load_game_model_auto``."""
    from photon_ml_tpu.io.models import load_game_model_auto

    action = _faults.fire("retrain.warm_start", key=export_dir)
    loaded = load_game_model_auto(export_dir)
    params = dict(loaded[0])
    if action is not None and action.corrupt:
        # chaos seam payload: poison one table the way a torn read
        # would — the finiteness gate below must refuse it
        name = sorted(params)[0]
        table = params[name]
        if hasattr(table, "gamma"):
            table = np.asarray(table.gamma)
        poisoned = np.array(table, dtype=float)
        poisoned.reshape(-1)[0] = np.nan
        params[name] = poisoned
    for name, table in params.items():
        leaves = (
            (np.asarray(table.gamma), np.asarray(table.projection))
            if hasattr(table, "gamma")
            else (np.asarray(table),)
        )
        for leaf in leaves:
            if leaf.dtype.kind == "f" and not np.all(np.isfinite(leaf)):
                raise WarmStartError(
                    f"{export_dir}: warm-start coordinate {name!r} has "
                    "non-finite values"
                )
    return (params,) + tuple(loaded[1:])


def export_retrained_model(
    root: str,
    params: Dict[str, object],
    shards: Dict[str, str],
    vocabs: Dict[str, object],
    entity_vocabs: Dict[str, dict],
    random_effects: Dict[str, Optional[str]],
    task=None,
    fingerprint=None,
) -> str:
    """Export a retrained model through the existing manifest gate,
    probing the ``retrain.export`` fault site at the mid-export seam:

    - raise-mode fires AFTER the payload but BEFORE the manifest — the
      partial directory carries no ``model-manifest.json``, so registry
      ``poll()`` never even considers it (the cheapest degraded
      outcome).
    - corrupt-mode tears a manifest-covered file AFTER the manifest is
      sealed — the export looks complete, and the serving integrity
      gate + reload breaker must quarantine it.

    Feature vocabularies save as ``feature-index-<shard>.txt`` at the
    export root (the layout ``load_game_model_auto`` resolves).
    Returns ``root``."""
    from photon_ml_tpu.io.models import save_game_model, write_model_manifest

    save_game_model(
        root,
        params=params,
        shards=shards,
        vocabs=vocabs,
        entity_vocabs=entity_vocabs,
        random_effects=random_effects,
        task=task,
    )
    for shard in sorted({s for s in shards.values()}):
        for name, vocab in vocabs.items():
            if shards[name] == shard:
                vocab.save(os.path.join(root, f"feature-index-{shard}.txt"))
                break
    if fingerprint is not None:
        fingerprint.save(root)
    # chaos seam: the mid-export fault. Everything above is payload;
    # everything below is the integrity seal.
    action = _faults.fire("retrain.export", key=root)
    manifest = write_model_manifest(root)
    if action is not None and action.corrupt:
        import json

        with open(manifest) as f:
            covered = sorted(json.load(f)["digests"])
        _faults.corrupt_file(os.path.join(root, covered[0]))
    return root


def next_version_dir(watch_root: str, prefix: str = "v") -> str:
    """The next lexically-newest version directory name under a serving
    watch root (``v0001``, ``v0002``, ...). Registry ``poll()`` loads
    the lexically newest manifest-bearing subdirectory, so zero-padded
    monotone names ARE the publish ordering."""
    highest = 0
    if os.path.isdir(watch_root):
        for name in os.listdir(watch_root):
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                highest = max(highest, int(name[len(prefix):]))
    return os.path.join(watch_root, f"{prefix}{highest + 1:04d}")


def latest_version_dir(
    watch_root: str, *, verified: bool = False
) -> Optional[str]:
    """The lexically-newest subdirectory carrying a model manifest —
    the warm-start source (same selection rule as registry polling).

    With ``verified=True``, exports whose manifest fails content
    verification are skipped (newest-first): a torn export — sealed
    but corrupted after sealing — must never become a warm-start
    source, mirroring the serving-side breaker quarantine."""
    from photon_ml_tpu.io.models import MODEL_MANIFEST

    if not os.path.isdir(watch_root):
        return None
    candidates = sorted(
        name
        for name in os.listdir(watch_root)
        if os.path.exists(os.path.join(watch_root, name, MODEL_MANIFEST))
    )
    if not verified:
        if not candidates:
            return None
        return os.path.join(watch_root, candidates[-1])
    from photon_ml_tpu.io.models import verify_model_manifest

    for name in reversed(candidates):
        path = os.path.join(watch_root, name)
        try:
            verify_model_manifest(path)
        except Exception:
            continue
        return path
    return None


# ---------------------------------------------------------------------------
# plan inputs: admission log + convergence health
# ---------------------------------------------------------------------------


def load_admission_candidates(
    path: Optional[str],
    min_misses: int = 2,
    max_per_key: Optional[int] = None,
) -> Dict[str, List[str]]:
    """Repeat-missed entity keys from a persisted admission log
    (serving/cache.py's atomic-swap file), most-missed first per RE
    key. A missing/torn log reads as empty — admission is an
    optimization, never a cycle blocker."""
    if not path:
        return {}
    from photon_ml_tpu.serving.cache import AdmissionLog

    out: Dict[str, List[str]] = {}
    for rk, ents in AdmissionLog.load(path).items():
        keys = [
            k for k, v in ents.items() if v["misses"] >= int(min_misses)
        ]
        keys.sort(key=lambda k: (-ents[k]["misses"], k))
        if max_per_key is not None:
            keys = keys[: int(max_per_key)]
        if keys:
            out[rk] = keys
    return out


def select_retrain_targets(
    report: Optional[dict],
    nonconverged_threshold: float = 0.05,
    worst_k: int = 8,
) -> dict:
    """Which coordinates need the retrain, from a PR-7 convergence
    report (``convergence-report.json``): a coordinate whose
    ``nonconverged_frac`` is at/above the threshold retrains; healthy
    coordinates FREEZE (warm-started and carried bit-identical, not
    re-fit — the paper's incremental per-entity refit made cheap).
    ``worst_entities`` carries each retrained coordinate's worst-k
    table ids for logging/targeting. No report (first cycle, or
    reports disabled) retrains everything and freezes nothing."""
    if not report or not report.get("coordinates"):
        return {"retrain": None, "freeze": [], "worst_entities": {}}
    retrain: List[str] = []
    freeze: List[str] = []
    worst: Dict[str, list] = {}
    for name, stats in sorted(report["coordinates"].items()):
        frac = float(stats.get("nonconverged_frac", 0.0))
        if frac >= nonconverged_threshold:
            retrain.append(name)
            worst[name] = list(stats.get("worst_entities", []))[:worst_k]
        else:
            freeze.append(name)
    if not retrain:
        # the alarm fired but every coordinate converged cleanly last
        # run: the drift is in the DATA, so everything refits
        return {"retrain": None, "freeze": [], "worst_entities": {}}
    return {"retrain": retrain, "freeze": freeze, "worst_entities": worst}


# ---------------------------------------------------------------------------
# triggers
# ---------------------------------------------------------------------------


def registry_drift_trigger(
    registry, psi_alarm: Optional[float] = None
) -> Callable[[], Optional[dict]]:
    """Trigger from a live serving registry's health surface: fires on
    any DriftMonitor alarm (or on ``psi_max >= psi_alarm`` when given a
    threshold of its own)."""

    def check() -> Optional[dict]:
        drift = (registry.health() or {}).get("drift")
        if not drift:
            return None
        psi = drift.get("psi_max")
        if drift.get("alarms", 0) > 0 or (
            psi_alarm is not None and psi is not None and psi >= psi_alarm
        ):
            return {"source": "registry", **drift}
        return None

    return check


def fingerprint_drift_trigger(
    baseline_dir: str, current_dir: str, psi_alarm: float = 0.25
) -> Callable[[], Optional[dict]]:
    """Trigger with ``photon-obs drift`` semantics, in-process: compare
    two quality-fingerprint exports; fire when the report alarms. An
    unreadable fingerprint does NOT trigger (same degraded stance as
    serving without drift monitoring: you cannot retrain your way out
    of missing observability)."""

    def check() -> Optional[dict]:
        from photon_ml_tpu.obs.quality import (
            compare_fingerprints,
            try_load_fingerprint,
        )

        base = try_load_fingerprint(baseline_dir)
        cur = try_load_fingerprint(current_dir)
        if base is None or cur is None:
            return None
        report = compare_fingerprints(base, cur, psi_alarm=psi_alarm)
        if report.get("alarm"):
            return {"source": "fingerprint", **report}
        return None

    return check


# ---------------------------------------------------------------------------
# the orchestrator
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StageResult:
    name: str
    ok: bool
    attempts: int
    seconds: float
    error: Optional[str] = None


@dataclasses.dataclass
class RetrainPlan:
    """What one cycle intends to do — the ``photon-retrain plan``
    surface and the argument every injected ``retrain_fn`` receives."""

    reason: dict
    # RE key -> promoted entity keys (repeat-missed in serving)
    admitted: Dict[str, List[str]]
    # None = retrain every coordinate (no convergence report)
    retrain_coordinates: Optional[List[str]]
    freeze_coordinates: List[str]
    worst_entities: Dict[str, list]
    warm_start_dir: Optional[str]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CycleResult:
    ok: bool
    triggered: bool
    skipped: bool = False
    stage: Optional[str] = None  # the failed stage, None when ok
    stages: List[StageResult] = dataclasses.field(default_factory=list)
    plan: Optional[RetrainPlan] = None
    export_dir: Optional[str] = None
    version: Optional[str] = None
    cycle_s: float = 0.0
    next_retry_s: Optional[float] = None


class _StageFailed(Exception):
    def __init__(self, result: StageResult):
        super().__init__(result.error)
        self.result = result


class RetrainOrchestrator:
    """Drives alarm -> retrain -> reload cycles with per-stage retry
    and cycle-level exponential backoff.

    The train/reload legs are injected callables so the same
    orchestration (and the same fault sites and degraded outcomes)
    serves the real CLI wiring, the chaos drill, and the tests:

    - ``trigger()`` -> truthy reason dict when drift demands a retrain
      (see :func:`registry_drift_trigger` /
      :func:`fingerprint_drift_trigger`).
    - ``retrain_fn(plan)`` -> path of the new export directory. It is
      expected to warm-start entity-keyed from ``plan.warm_start_dir``
      (:func:`load_warm_start`) and to publish through
      :func:`export_retrained_model` (or the GAME driver's own
      manifest-gated export).
    - ``reload_fn(export_dir)`` -> served version id (falsy = the swap
      did not happen). Typically ``registry.poll(watch_root)`` so the
      reload breaker stays in the loop.
    - ``verify_fn()`` -> post-reload drift report (``{"alarm": ...,
      "psi_max": ...}``) or None to skip verification.

    Failure semantics (the contract the chaos drill proves): any stage
    failing after ``max_stage_attempts`` fails the cycle; the old model
    keeps serving, the alarm stays LATCHED, and :meth:`run_cycle`
    refuses to start again until the backoff expires (``force=True``
    overrides). A clean verify clears the latch and resets the
    backoff."""

    def __init__(
        self,
        trigger: Callable[[], Optional[dict]],
        retrain_fn: Callable[[RetrainPlan], str],
        reload_fn: Callable[[str], Optional[str]],
        verify_fn: Optional[Callable[[], Optional[dict]]] = None,
        *,
        watch_root: Optional[str] = None,
        admission_log_path: Optional[str] = None,
        admission_min_misses: int = 2,
        admission_max_per_key: Optional[int] = None,
        convergence_report_path: Optional[str] = None,
        nonconverged_threshold: float = 0.05,
        max_stage_attempts: int = 2,
        stage_backoff_s: float = 0.05,
        cycle_backoff_s: float = 1.0,
        cycle_backoff_mult: float = 2.0,
        max_cycle_backoff_s: float = 600.0,
        stats=None,
        sleep: Callable[[float], None] = time.sleep,
        logger=None,
    ):
        self.trigger = trigger
        self.retrain_fn = retrain_fn
        self.reload_fn = reload_fn
        self.verify_fn = verify_fn
        self.watch_root = watch_root
        self.admission_log_path = admission_log_path
        self.admission_min_misses = admission_min_misses
        self.admission_max_per_key = admission_max_per_key
        self.convergence_report_path = convergence_report_path
        self.nonconverged_threshold = nonconverged_threshold
        self.max_stage_attempts = max(1, int(max_stage_attempts))
        self.stage_backoff_s = stage_backoff_s
        self.cycle_backoff_s = cycle_backoff_s
        self.cycle_backoff_mult = cycle_backoff_mult
        self.max_cycle_backoff_s = max_cycle_backoff_s
        self.stats = stats
        self._sleep = sleep
        self._logger = logger
        self.alarm_latched = False
        self.consecutive_failures = 0
        self._not_before = 0.0
        self.last_result: Optional[CycleResult] = None

    # -- stage machinery ---------------------------------------------------

    def _run_stage(self, name: str, fn: Callable[[], object], out: list):
        """One named stage with bounded in-cycle retries. Returns the
        stage's value; raises :class:`_StageFailed` when attempts are
        exhausted (the cycle's failure path)."""
        t0 = time.perf_counter()
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_stage_attempts + 1):
            try:
                value = fn()
                out.append(
                    StageResult(
                        name, True, attempt, time.perf_counter() - t0
                    )
                )
                return value
            except Exception as e:  # noqa: BLE001 — every stage error
                # has the same degraded outcome: the old model serves
                last = e
                obs.registry().inc("lifecycle.stage_failures")
                if attempt < self.max_stage_attempts:
                    obs.emit_event(
                        "lifecycle.stage_retry",
                        cat="lifecycle",
                        stage=name,
                        attempt=attempt,
                        error=repr(e),
                    )
                    self._sleep(self.stage_backoff_s * (2 ** (attempt - 1)))
        result = StageResult(
            name,
            False,
            self.max_stage_attempts,
            time.perf_counter() - t0,
            error=repr(last),
        )
        out.append(result)
        raise _StageFailed(result)

    def _plan(self, reason: dict) -> RetrainPlan:
        admitted = load_admission_candidates(
            self.admission_log_path,
            min_misses=self.admission_min_misses,
            max_per_key=self.admission_max_per_key,
        )
        n_admitted = sum(len(v) for v in admitted.values())
        if n_admitted:
            # the promotion counter pairs with the cache's
            # serving.cache.admission_logged
            if self.stats is not None:
                self.stats.record_admission_promoted(n_admitted)
            else:
                obs.registry().inc(
                    "serving.cache.admission_promoted", n_admitted
                )
            obs.registry().inc("lifecycle.admitted_entities", n_admitted)
        report = None
        if self.convergence_report_path and os.path.exists(
            self.convergence_report_path
        ):
            import json

            try:
                with open(self.convergence_report_path) as f:
                    report = json.load(f)
            except (OSError, ValueError):
                report = None  # health input lost: retrain everything
        targets = select_retrain_targets(
            report, nonconverged_threshold=self.nonconverged_threshold
        )
        warm = (
            latest_version_dir(self.watch_root, verified=True)
            if self.watch_root
            else None
        )
        return RetrainPlan(
            reason=reason,
            admitted=admitted,
            retrain_coordinates=targets["retrain"],
            freeze_coordinates=targets["freeze"],
            worst_entities=targets["worst_entities"],
            warm_start_dir=warm,
        )

    # -- the cycle ---------------------------------------------------------

    def run_cycle(self, force: bool = False) -> CycleResult:
        """One full cycle. Steady state (no alarm) is one trigger probe;
        a latched alarm inside its backoff window is a no-op skip."""
        now = time.monotonic()
        if not force and now < self._not_before:
            result = CycleResult(
                ok=False,
                triggered=True,
                skipped=True,
                next_retry_s=round(self._not_before - now, 3),
            )
            self.last_result = result
            return result
        t0 = time.perf_counter()
        stages: List[StageResult] = []
        obs.registry().inc("lifecycle.cycles")
        try:
            with obs.span("lifecycle.cycle"):
                reason = self._run_stage(
                    "trigger", self.trigger, stages
                )
                if not reason and not self.alarm_latched:
                    result = CycleResult(
                        ok=True,
                        triggered=False,
                        stages=stages,
                        cycle_s=time.perf_counter() - t0,
                    )
                    self.last_result = result
                    return result
                if not self.alarm_latched:
                    self.alarm_latched = True
                    obs.registry().set_gauge("lifecycle.alarm_latched", 1)
                    obs.emit_event(
                        "lifecycle.alarm_latched",
                        cat="lifecycle",
                        reason=reason,
                    )
                reason = dict(reason or {"source": "latched"})
                plan = self._run_stage(
                    "plan", lambda: self._plan(reason), stages
                )
                export_dir = self._run_stage(
                    "retrain", lambda: self.retrain_fn(plan), stages
                )
                # defense in depth BEFORE asking the registry: a partial
                # export must fail here, not burn a breaker probe
                self._run_stage(
                    "export_gate",
                    lambda: self._verify_export(export_dir),
                    stages,
                )
                version = self._run_stage(
                    "reload",
                    lambda: self._reload(export_dir),
                    stages,
                )
                self._run_stage("verify", self._verify_recovery, stages)
        except _StageFailed as e:
            return self._fail(e.result.name, stages, t0)
        # success: clear the latch, reset the backoff
        self.alarm_latched = False
        self.consecutive_failures = 0
        self._not_before = 0.0
        cycle_s = time.perf_counter() - t0
        obs.registry().inc("lifecycle.retrains")
        obs.registry().set_gauge("lifecycle.retrain_cycle_s", cycle_s)
        obs.registry().set_gauge("lifecycle.alarm_latched", 0)
        obs.emit_event(
            "lifecycle.cycle_completed",
            cat="lifecycle",
            export_dir=export_dir,
            version=version,
            cycle_s=round(cycle_s, 3),
            admitted=sum(len(v) for v in plan.admitted.values()),
        )
        if self._logger is not None:
            self._logger.info(
                f"lifecycle cycle complete: serving {version} "
                f"from {export_dir} ({cycle_s:.2f}s)"
            )
        result = CycleResult(
            ok=True,
            triggered=True,
            stages=stages,
            plan=plan,
            export_dir=export_dir,
            version=version,
            cycle_s=cycle_s,
        )
        self.last_result = result
        return result

    def _verify_export(self, export_dir: str) -> bool:
        from photon_ml_tpu.io.models import verify_model_manifest

        verify_model_manifest(export_dir)
        return True

    def _reload(self, export_dir: str) -> str:
        version = self.reload_fn(export_dir)
        if not version:
            raise LifecycleError(
                f"reload did not swap to {export_dir!r} (breaker open "
                "or candidate rejected)"
            )
        return version

    def _verify_recovery(self) -> Optional[dict]:
        if self.verify_fn is None:
            return None
        report = self.verify_fn()
        if report and report.get("alarm"):
            raise LifecycleError(
                "post-retrain drift still alarming "
                f"(psi_max={report.get('psi_max')})"
            )
        return report

    def _fail(
        self, stage: str, stages: List[StageResult], t0: float
    ) -> CycleResult:
        """The defined degraded outcome: old model keeps serving, alarm
        stays latched, next cycle backs off exponentially."""
        self.consecutive_failures += 1
        backoff = min(
            self.cycle_backoff_s
            * (self.cycle_backoff_mult ** (self.consecutive_failures - 1)),
            self.max_cycle_backoff_s,
        )
        self._not_before = time.monotonic() + backoff
        obs.registry().inc("lifecycle.cycle_failures")
        obs.emit_event(
            "lifecycle.cycle_failed",
            cat="lifecycle",
            stage=stage,
            failures=self.consecutive_failures,
            backoff_s=round(backoff, 3),
        )
        if self._logger is not None:
            self._logger.warn(
                f"lifecycle cycle failed at stage {stage!r} "
                f"(failure #{self.consecutive_failures}); old model "
                f"keeps serving, retry in {backoff:.1f}s"
            )
        result = CycleResult(
            ok=False,
            triggered=True,
            stage=stage,
            stages=stages,
            cycle_s=time.perf_counter() - t0,
            next_retry_s=backoff,
        )
        self.last_result = result
        return result

    # -- watch mode --------------------------------------------------------

    def watch(
        self,
        poll_s: float = 30.0,
        max_cycles: Optional[int] = None,
        shutdown=None,
    ) -> int:
        """Cron-less mode: poll the trigger forever (or ``max_cycles``
        probes), honoring a GracefulShutdown. Returns the number of
        SUCCESSFUL retrains."""
        retrains = 0
        cycles = 0
        while max_cycles is None or cycles < max_cycles:
            if shutdown is not None and getattr(
                shutdown, "requested", False
            ):
                break
            result = self.run_cycle()
            cycles += 1
            if result.ok and result.triggered:
                retrains += 1
            if max_cycles is not None and cycles >= max_cycles:
                break
            self._sleep(poll_s)
        return retrains
