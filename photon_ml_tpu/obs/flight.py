"""Crash flight recorder: a bounded ring of the last N observations.

The tracer batches span records 64 deep and the trace document only
exports on clean teardown, so the moments that matter most — the spans
and metric movements immediately BEFORE a divergence rollback, a
preemption, or an unhandled crash — are exactly the ones most likely to
be lost. This module keeps them in memory: a :class:`FlightRecorder` is
a fixed-capacity ring fed by the active tracer (every span, instant
event, and HBM counter sample lands in it the instant it is recorded,
flushed or not) plus periodic metric-delta samples, and
:func:`flight_dump` serializes the ring as ``flight-<reason>.json`` the
moment something goes wrong:

- ``resilience.shutdown.GracefulShutdown`` dumps on SIGTERM/SIGINT/
  preemption (reason ``preemption``; programmatic -> ``shutdown``),
- the GAME divergence guard dumps on a non-finite rollback
  (``divergence``),
- an installed ``sys.excepthook`` chain dumps on any unhandled crash
  (``crash``) before the previous hook runs.

The dump is self-contained: reason, pod identity (``obs.dist``), the
ring (oldest first, with a dropped-record count), and a full metrics
registry snapshot — a post-mortem no longer depends on whatever happened
to be flushed. Recording is O(1) deque appends under the tracer's
existing lock discipline; ``benchmarks/obs_overhead.py`` gates the
enabled-mode cost inside the same <5% budget as the tracer itself.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

from photon_ml_tpu.obs import dist as _dist
from photon_ml_tpu.obs.metrics import MetricsRegistry
from photon_ml_tpu.obs.metrics import registry as _registry
from photon_ml_tpu.obs.trace import get_tracer

__all__ = [
    "FlightRecorder",
    "install_flight_recorder",
    "uninstall_flight_recorder",
    "flight_recorder",
    "flight_dump",
]

DEFAULT_CAPACITY = 2048


class FlightRecorder:
    """Fixed-capacity ring of recent observation records.

    ``note(record)`` is the tracer-side hook (called for every span /
    instant / counter JSONL-style record); ``sample_metrics()`` appends a
    counter-delta record (what moved since the last sample);
    ``dump(reason)`` writes the ring + a registry snapshot to
    ``flight-<reason>.json`` and never raises — it runs on the failure
    paths it exists to document.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        flight_dir: Optional[str] = None,
        registry: Optional[MetricsRegistry] = None,
    ):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.flight_dir = flight_dir
        self._registry = registry
        self._lock = threading.Lock()
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=capacity
        )
        self._seq = 0
        self._dropped = 0
        self._last_counters: Dict[str, float] = {}

    # -- recording ----------------------------------------------------------

    def note(self, record: Dict[str, Any]) -> None:
        """Append one observation record (already JSON-safe)."""
        with self._lock:
            self._seq += 1
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append({"seq": self._seq, **record})

    def sample_metrics(self) -> None:
        """Append a ``metrics_delta`` record: every counter that moved
        since the previous sample. Gauge/histogram state rides the full
        snapshot in :meth:`dump`; counters are the ones whose *movement*
        tells the crash story (retries fired, rollbacks, rejected
        requests)."""
        reg = self._registry if self._registry is not None else _registry()
        counters = reg.snapshot()["counters"]
        with self._lock:
            changed = {
                name: round(value - self._last_counters.get(name, 0.0), 6)
                for name, value in counters.items()
                if value != self._last_counters.get(name, 0.0)
            }
            self._last_counters = dict(counters)
        if changed:
            self.note(
                {
                    "kind": "metrics_delta",
                    "time_unix": round(time.time(), 6),
                    "changed": changed,
                }
            )

    # -- readout ------------------------------------------------------------

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def dump(
        self, reason: str, flight_dir: Optional[str] = None
    ) -> Optional[str]:
        """Write ``flight-<reason>.json`` (suffixing ``-2``, ``-3``… when
        the name exists: repeated rollbacks in one run must not clobber
        the first post-mortem). Returns the path, or None when there is
        nowhere to write or the write failed — the failure path being
        documented must not gain a second failure."""
        directory = flight_dir or self.flight_dir or "."
        reason = "".join(
            c if (c.isalnum() or c in "-_") else "-" for c in str(reason)
        ) or "unknown"
        try:
            self.sample_metrics()
        except Exception:
            pass
        with self._lock:
            records = list(self._ring)
            dropped = self._dropped
        reg = self._registry if self._registry is not None else _registry()
        try:
            metrics = reg.snapshot()
        except Exception:
            metrics = {}
        idx, count = _dist.process_identity()
        payload = {
            "reason": reason,
            "time_unix": round(time.time(), 6),
            "process_index": idx,
            "process_count": count,
            "pid": os.getpid(),
            "capacity": self.capacity,
            "records_dropped": dropped,
            "records": records,
            "metrics": metrics,
        }
        try:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, f"flight-{reason}.json")
            n = 2
            while os.path.exists(path):
                path = os.path.join(directory, f"flight-{reason}-{n}.json")
                n += 1
            with open(path, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            return path
        except Exception:
            return None


# ---------------------------------------------------------------------------
# Process-global recorder + crash hook
# ---------------------------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_prev_excepthook = None


def _crash_excepthook(exc_type, exc, tb) -> None:
    rec = _recorder
    if rec is not None:
        try:
            rec.note(
                {
                    "kind": "event",
                    "name": "crash",
                    "time_unix": round(time.time(), 6),
                    "exception": f"{exc_type.__name__}: {exc}",
                }
            )
            rec.dump("crash")
        except Exception:
            pass
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def install_flight_recorder(
    capacity: int = DEFAULT_CAPACITY,
    flight_dir: Optional[str] = None,
    registry: Optional[MetricsRegistry] = None,
    crash_hook: bool = True,
) -> FlightRecorder:
    """Install a process-global flight recorder: attach it to the active
    tracer (spans/events/counters start landing in the ring), and chain
    a crash ``sys.excepthook`` that dumps ``flight-crash.json`` before
    the previous hook runs. Returns the recorder. Re-installing replaces
    the previous recorder (its ring is abandoned)."""
    global _recorder, _prev_excepthook
    rec = FlightRecorder(
        capacity=capacity, flight_dir=flight_dir, registry=registry
    )
    _recorder = rec
    tracer = get_tracer()
    if tracer is not None:
        tracer.recorder = rec
    if crash_hook and sys.excepthook is not _crash_excepthook:
        _prev_excepthook = sys.excepthook
        sys.excepthook = _crash_excepthook
    return rec


def uninstall_flight_recorder() -> None:
    """Detach the global recorder and restore the previous excepthook."""
    global _recorder, _prev_excepthook
    tracer = get_tracer()
    if tracer is not None and tracer.recorder is _recorder:
        tracer.recorder = None
    _recorder = None
    if sys.excepthook is _crash_excepthook:
        sys.excepthook = _prev_excepthook or sys.__excepthook__
        _prev_excepthook = None


def flight_recorder() -> Optional[FlightRecorder]:
    """The installed process-global recorder, or None."""
    return _recorder


def flight_dump(
    reason: str, flight_dir: Optional[str] = None
) -> Optional[str]:
    """Dump the global recorder's ring as ``flight-<reason>.json``.
    No-op (returns None) when no recorder is installed — failure paths
    call this unconditionally."""
    rec = _recorder
    if rec is None:
        return None
    return rec.dump(reason, flight_dir=flight_dir)
