"""Tail-based exemplar sampling: latency buckets -> live trace ids.

A p99 number tells you the tail exists; an *exemplar* hands you an
actual request from it. This module keeps bounded rings of recent trace
ids, keyed two ways:

- **by outcome class** — ``error`` / ``expired`` / ``shed`` /
  ``degraded`` / ``failover`` requests are kept at **100%** (the
  requests you will be asked about are precisely the ones something
  went wrong for), the rolling slow tail likewise, and the healthy fast
  path is sampled at a small deterministic fraction (it is only needed
  as a baseline to diff the tail against);
- **by latency bucket** — the SAME log-spaced buckets as the
  ``serving.request_latency_ms`` histogram
  (:meth:`~photon_ml_tpu.obs.metrics.LatencyHistogram.bucket_index`),
  so a spike in a histogram bucket resolves directly to recent trace
  ids from that bucket, served by the ``{"cmd": "exemplars"}`` admin
  command (cli/serve.py) and fed to ``photon-obs request``.

The *rolling* slow tail needs no configuration: the store keeps a small
window of recent latencies and refreshes its slow threshold (the
window's ``1 - tail_frac`` quantile) every ``_REFRESH`` records — a
fleet whose baseline drifts from 2ms to 20ms keeps sampling its
relative tail instead of flooding the rings.

Everything is bounded (``deque(maxlen=...)``) and the record path is a
few comparisons plus at most two ring appends under one lock —
``benchmarks/obs_overhead.py`` gates the serving leg with this enabled.
Like :mod:`obs.trace`, pure stdlib: importable before backend selection
and from CPU-only subprocesses.

Process-global store: :func:`install_store` / :func:`store` /
:func:`set_store`, mirroring ``obs.metrics.registry``. With no store
installed the batcher's record call is one global read — serving pays
nothing until an operator opts in.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

from photon_ml_tpu.obs.metrics import LatencyHistogram

__all__ = [
    "ExemplarStore",
    "install_store",
    "set_store",
    "store",
]

# outcome classes kept at 100% (docs/OBSERVABILITY.md sampling table)
KEEP_CLASSES = ("error", "expired", "shed", "degraded", "failover")
_REFRESH = 64


class ExemplarStore:
    """Bounded exemplar rings with tail-based admission.

    ``fast_fraction`` — deterministic sampling rate for healthy
    fast-path requests (counter-crossing, not RNG: reproducible in
    tests and immune to seeding). ``tail_frac`` — the rolling slow-tail
    width (0.05 = the slowest ~5% of the recent window always kept).
    ``ring_size`` — per-bucket / per-class ring capacity. ``lo_ms`` /
    ``hi_ms`` / ``bins`` must match the latency histogram the exemplars
    annotate (defaults match ``LatencyHistogram``'s).
    """

    def __init__(
        self,
        *,
        fast_fraction: float = 0.01,
        tail_frac: float = 0.05,
        ring_size: int = 8,
        window: int = 256,
        lo_ms: float = 1e-3,
        hi_ms: float = 6e4,
        bins: int = 64,
    ):
        if not 0.0 <= fast_fraction <= 1.0:
            raise ValueError(
                f"fast_fraction must be in [0, 1]: {fast_fraction}"
            )
        self.fast_fraction = float(fast_fraction)
        self.tail_frac = float(tail_frac)
        self.ring_size = int(ring_size)
        # counts-free histogram reused purely for the bucket math, so
        # bucket<->le mapping cannot drift from the real latency metric
        self._hist = LatencyHistogram(lo_ms=lo_ms, hi_ms=hi_ms, bins=bins)
        self._buckets: Dict[int, deque] = {}
        self._classes: Dict[str, deque] = {}
        self._window = deque(maxlen=int(window))
        self._slow_ms = float("inf")
        self._lock = threading.Lock()
        self.recorded = 0
        self.kept = 0
        self.kept_by = {
            "class": 0,
            "slow": 0,
            "sampled": 0,
        }
        self._sampled_quota = 0.0

    # -- admission ----------------------------------------------------------

    def _refresh_slow_threshold(self) -> None:
        if not self._window or self.tail_frac <= 0.0:
            self._slow_ms = float("inf")
            return
        ordered = sorted(self._window)
        k = min(
            len(ordered) - 1,
            max(0, int(len(ordered) * (1.0 - self.tail_frac))),
        )
        self._slow_ms = ordered[k]

    def record(
        self,
        trace: Optional[str],
        latency_ms: float,
        *,
        outcome: str = "ok",
        degraded: bool = False,
        failover: bool = False,
    ) -> bool:
        """Offer one finished request; returns True when kept. ``trace``
        may be None (untraced submitter) — the decision still counts so
        sampling statistics stay honest, but nothing enters a ring."""
        classes = []
        if outcome != "ok":
            classes.append(outcome if outcome in KEEP_CLASSES else "error")
        if degraded:
            classes.append("degraded")
        if failover:
            classes.append("failover")
        with self._lock:
            self.recorded += 1
            self._window.append(latency_ms)
            if self.recorded % _REFRESH == 1:
                self._refresh_slow_threshold()
            if classes:
                why = "class"
            elif latency_ms >= self._slow_ms:
                why = "slow"
                classes.append("slow")
            else:
                # deterministic fraction: keep when the accumulated
                # quota crosses an integer boundary
                self._sampled_quota += self.fast_fraction
                if self._sampled_quota < 1.0:
                    return False
                self._sampled_quota -= 1.0
                why = "sampled"
                classes.append("sampled")
            self.kept += 1
            self.kept_by[why] += 1
            if trace is None:
                return True
            entry = {
                "trace": trace,
                "latency_ms": round(float(latency_ms), 4),
                "outcome": outcome,
            }
            b = self._hist.bucket_index(latency_ms)
            ring = self._buckets.get(b)
            if ring is None:
                ring = self._buckets[b] = deque(maxlen=self.ring_size)
            ring.append(entry)
            for cls in classes:
                cring = self._classes.get(cls)
                if cring is None:
                    cring = self._classes[cls] = deque(
                        maxlen=self.ring_size
                    )
                cring.append(entry)
        return True

    # -- readout ------------------------------------------------------------

    def lookup(
        self,
        *,
        ge_ms: Optional[float] = None,
        cls: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """Recent exemplars, newest last. ``ge_ms`` keeps only buckets
        whose upper edge is >= the floor (the "hand me the p99 bucket's
        traces" query); ``cls`` reads one outcome-class ring."""
        with self._lock:
            if cls is not None:
                return list(self._classes.get(cls, ()))
            out = []
            for b in sorted(self._buckets):
                if ge_ms is not None and self._hist.bucket_le(b) < ge_ms:
                    continue
                out.extend(self._buckets[b])
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The ``{"cmd": "exemplars"}`` payload: config, admission
        counts, and every non-empty ring with its bucket edge."""
        with self._lock:
            buckets = [
                {
                    "bucket": b,
                    "le_ms": (
                        self._hist.bucket_le(b)
                        if self._hist.bucket_le(b) != float("inf")
                        else None
                    ),
                    "exemplars": list(ring),
                }
                for b, ring in sorted(self._buckets.items())
                if ring
            ]
            classes = {
                cls: list(ring)
                for cls, ring in sorted(self._classes.items())
                if ring
            }
            return {
                "config": {
                    "fast_fraction": self.fast_fraction,
                    "tail_frac": self.tail_frac,
                    "ring_size": self.ring_size,
                },
                "recorded": int(self.recorded),
                "kept": int(self.kept),
                "kept_by": dict(self.kept_by),
                "slow_threshold_ms": (
                    None if self._slow_ms == float("inf")
                    else round(self._slow_ms, 4)
                ),
                "buckets": buckets,
                "classes": classes,
            }


# ---------------------------------------------------------------------------
# Process-global store (the registry()/set_registry shape)
# ---------------------------------------------------------------------------

_store: Optional[ExemplarStore] = None
_store_lock = threading.Lock()


def store() -> Optional[ExemplarStore]:
    """The installed store, or None (sampling disabled — the batcher's
    record path is then one global read)."""
    return _store


def set_store(st: Optional[ExemplarStore]) -> Optional[ExemplarStore]:
    """Install ``st`` process-wide (None uninstalls); returns the
    previous store so callers can restore it."""
    global _store
    with _store_lock:
        prev = _store
        _store = st
    return prev


def install_store(**kwargs) -> ExemplarStore:
    """Construct-and-install convenience for drivers (cli/serve.py)."""
    st = ExemplarStore(**kwargs)
    set_store(st)
    return st
