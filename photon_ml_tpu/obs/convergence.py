"""Convergence-health layer: decode device-side solver tapes post-solve.

The reference ships OptimizationStatesTracker + ModelTracker
(``optimization/OptimizationStatesTracker.scala``,
``supervised/model/ModelTracker.scala``) because GLM debugging is
convergence debugging. Our solvers already record per-iteration tapes
INSIDE their ``lax.while_loop`` carries (``solvers/common.SolverResult``:
values, grad norms, trust-region radius + CG steps for TRON, step size +
line-search evals for L-BFGS/OWL-QN/Newton) — telemetry that keeps
working when the whole solve is one device program (ROADMAP item 1 kills
every host-side per-iteration seam; the tapes are what survives). This
module is the layer that reads them:

- :func:`decode_result` — one completed :class:`SolverResult` (tapes
  masked past ``iterations``) -> a :class:`ConvergenceReport`:
  ConvergenceReason, linear/superlinear rate estimate, plateau / stall /
  oscillation detection, the masked tapes themselves.
- :func:`fleet_summary` — the vmapped GAME regime: thousands of
  per-entity solves per coordinate update collapse to an
  iterations-to-converge histogram, non-converged entity count/fraction,
  and the worst-k entities by final gradient norm — an earlier precursor
  signal than the divergence guard's non-finite objective check
  (``convergence.precursor`` events fire on a high non-converged
  fraction or any non-finite per-entity gradient).
- :func:`note_solve` / :func:`note_update` — route reports into the
  existing instruments: ``convergence.*`` registry metrics (reason
  taxonomy counters, per-coordinate gauges), structured
  ``convergence.solve`` / ``convergence.fleet`` events (which also ride
  the tracer's hook into the crash flight recorder — the last-N solve
  tapes are in every flight dump), Chrome counter tracks replaying a
  solve's value/grad curves under its span, and an installed
  :class:`ConvergenceTracker` (the ``--convergence-report`` surface).

Everything here is host-side numpy over already-fetched arrays; the
recording paths are gated by the callers (active tracer OR installed
tracker), so pipelined solves pay nothing by default —
``benchmarks/obs_overhead.py`` runs a tapes-on leg under the same <5%
budget.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "ConvergenceReport",
    "FleetSummary",
    "ConvergenceTracker",
    "analyze_history",
    "decode_result",
    "fleet_summary",
    "note_solve",
    "note_update",
    "emit_tape_counters",
    "install_convergence_tracker",
    "uninstall_convergence_tracker",
    "convergence_tracker",
    "tracking_enabled",
]

# non-converged fraction above which a coordinate update emits a
# `convergence.precursor` event — the fleet-level early-warning the
# divergence guard (non-finite objective, one update later) lacks
PRECURSOR_NONCONVERGED_FRAC = 0.5
# how many trailing grad-norm ratios the rate estimate uses
_RATE_WINDOW = 6


# lazy taxonomy caches: resolving the enum through the solvers package
# per decoded update would put import machinery on the materialize()
# drain path (the decode runs once per coordinate per pass)
_REASON_NAMES: Dict[int, str] = {}
_NONCONVERGED: Optional[Tuple[int, int]] = None


def _reason_name(code) -> str:
    code = int(code)
    if not _REASON_NAMES:
        from photon_ml_tpu.solvers.common import ConvergenceReason

        _REASON_NAMES.update({int(r): r.name for r in ConvergenceReason})
    return _REASON_NAMES.get(code, f"UNKNOWN_{code}")


def _nonconverged_codes() -> Tuple[int, int]:
    global _NONCONVERGED
    if _NONCONVERGED is None:
        from photon_ml_tpu.solvers.common import ConvergenceReason

        _NONCONVERGED = (
            int(ConvergenceReason.NOT_CONVERGED),
            int(ConvergenceReason.MAX_ITERATIONS),
        )
    return _NONCONVERGED


# ---------------------------------------------------------------------------
# Per-solve decode
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ConvergenceReport:
    """Post-solve decode of one scalar :class:`SolverResult`."""

    optimizer: str
    iterations: int
    reason: str
    final_value: float
    final_grad_norm: float
    # estimated asymptotic contraction ratio g_{k+1}/g_k over the last
    # few iterations (None when the tape is too short)
    rate: Optional[float]
    # "superlinear" | "linear" | "sublinear" | "stalled" | "unknown"
    order: str
    # trailing iterations whose relative objective change stayed below
    # tolerance while the gradient had NOT converged (a plateau/stall)
    plateau_iters: int
    # iterations where the tracked objective went UP (trust-region
    # rejections, line-search overshoot — oscillation)
    oscillations: int
    values: List[float]
    grad_norms: List[float]
    # solver-specific tapes: {"radius": [...], "cg": [...]} (TRON) or
    # {"step": [...], "evals": [...]} (L-BFGS / OWL-QN / Newton)
    tapes: Dict[str, List[float]] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def analyze_history(
    values: Sequence[float],
    grad_norms: Sequence[float],
    tolerance: float = 1e-9,
) -> Dict[str, Any]:
    """Rate/order estimate + plateau + oscillation counts from masked
    (values, grad_norms) tapes. Pure numpy; NaN/inf entries (batched
    masking, untracked buffers) are ignored."""
    v = np.asarray(values, dtype=float)
    g = np.asarray(grad_norms, dtype=float)
    v = v[np.isfinite(v)]
    g = g[np.isfinite(g)]
    out: Dict[str, Any] = {
        "rate": None,
        "order": "unknown",
        "plateau_iters": 0,
        "oscillations": 0,
    }
    if v.size >= 2:
        dv = np.diff(v)
        scale = max(abs(float(v[0])), 1e-30)
        out["oscillations"] = int(np.sum(dv > tolerance * scale))
        # trailing run of ~no objective movement
        flat = np.abs(dv) <= tolerance * scale
        n_flat = 0
        for moved in flat[::-1]:
            if not moved:
                break
            n_flat += 1
        out["plateau_iters"] = n_flat
    gp = g[g > 0.0]
    if gp.size >= 3:
        ratios = gp[1:] / gp[:-1]
        window = ratios[-_RATE_WINDOW:]
        # geometric mean of the trailing contraction ratios
        rate = float(np.exp(np.mean(np.log(np.maximum(window, 1e-300)))))
        out["rate"] = rate
        if window.size >= 2 and window[-1] <= 0.5 * window[0] and rate < 0.3:
            # ratios themselves shrinking: faster than any geometric
            # series — Newton/TRON's terminal behaviour
            out["order"] = "superlinear"
        elif rate < 0.95:
            out["order"] = "linear"
        elif rate < 1.0:
            out["order"] = "sublinear"
        else:
            out["order"] = "stalled"
    return out


def decode_result(result, optimizer: str = "solver") -> ConvergenceReport:
    """One scalar SolverResult -> ConvergenceReport. Materializes the
    result's tapes (device->host); callers gate on observability being
    enabled, like ``record_solver_metrics``."""
    from photon_ml_tpu.solvers.common import mask_tape

    values, grad_norms = result.masked_history()[:2]
    analysis = analyze_history(values, grad_norms)
    tapes: Dict[str, List[float]] = {}
    if result.radius_tape is not None:
        tapes["radius"] = np.asarray(
            mask_tape(result.radius_tape, result.iterations), float
        ).tolist()
    if result.cg_tape is not None:
        tapes["cg"] = np.asarray(
            mask_tape(result.cg_tape, result.iterations), float
        ).tolist()
    if result.step_tape is not None:
        tapes["step"] = np.asarray(
            mask_tape(result.step_tape, result.iterations), float
        ).tolist()
    if result.eval_tape is not None:
        tapes["evals"] = np.asarray(
            mask_tape(result.eval_tape, result.iterations), float
        ).tolist()
    return ConvergenceReport(
        optimizer=optimizer,
        iterations=int(np.asarray(result.iterations)),
        reason=_reason_name(np.asarray(result.reason)),
        final_value=float(np.asarray(values)[-1]),
        final_grad_norm=float(np.asarray(grad_norms)[-1]),
        rate=analysis["rate"],
        order=analysis["order"],
        plateau_iters=analysis["plateau_iters"],
        oscillations=analysis["oscillations"],
        values=np.asarray(values, float).tolist(),
        grad_norms=np.asarray(grad_norms, float).tolist(),
        tapes=tapes,
    )


# ---------------------------------------------------------------------------
# Fleet-level decode (the vmapped GAME regime)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FleetSummary:
    """One coordinate update's per-entity convergence, aggregated —
    the analog of ``RandomEffectOptimizationTracker.scala:33-110``."""

    coordinate: str
    iteration: int
    entities: int
    # iterations-to-converge histogram: iterations -> entity count
    iters_histogram: Dict[int, int]
    median_iters: float
    reason_counts: Dict[str, int]
    nonconverged: int
    nonconverged_frac: float
    # worst-k (entity table row, final grad norm), worst first
    worst: List[Tuple[int, float]]
    nonfinite_grad_norms: int

    def to_dict(self) -> dict:
        # hand-rolled (dataclasses.asdict deep-copies recursively —
        # measurable on the per-update decode path)
        return {
            "coordinate": self.coordinate,
            "iteration": self.iteration,
            "entities": self.entities,
            "iters_histogram": self.iters_histogram,
            "median_iters": self.median_iters,
            "reason_counts": self.reason_counts,
            "nonconverged": self.nonconverged,
            "nonconverged_frac": self.nonconverged_frac,
            "worst": [[int(e), float(g)] for e, g in self.worst],
            "nonfinite_grad_norms": self.nonfinite_grad_norms,
        }


def fleet_summary(
    reasons,
    iterations,
    grad_norms=None,
    entity_ids=None,
    coordinate: str = "",
    iteration: int = 0,
    worst_k: int = 5,
) -> FleetSummary:
    """Aggregate one update's per-entity (reason, iterations[, final
    grad norm[, entity id]]) arrays. Host-side numpy on already-fetched
    data."""
    reasons = np.atleast_1d(np.asarray(reasons)).astype(np.int64)
    iters = np.atleast_1d(np.asarray(iterations)).astype(np.int64)
    n = int(reasons.size)
    bad_a, bad_b = _nonconverged_codes()
    nonconverged = int(((reasons == bad_a) | (reasons == bad_b)).sum())
    # iterations and reason codes are small non-negative ints: bincount
    # beats np.unique on the per-pass decode path
    it_counts = np.bincount(np.maximum(iters, 0))
    hist = {int(k): int(c) for k, c in enumerate(it_counts) if c}
    r_counts = np.bincount(np.maximum(reasons, 0))
    reason_counts = {
        _reason_name(r): int(c) for r, c in enumerate(r_counts) if c
    }
    worst: List[Tuple[int, float]] = []
    nonfinite = 0
    if grad_norms is not None:
        gn = np.atleast_1d(np.asarray(grad_norms, dtype=float))
        nonfinite = int((~np.isfinite(gn)).sum())
        ids = (
            np.atleast_1d(np.asarray(entity_ids)).astype(np.int64)
            if entity_ids is not None
            else np.arange(gn.size, dtype=np.int64)
        )
        # non-finite sorts worst of all: substitute +inf-like rank
        rank = np.where(np.isfinite(gn), gn, np.inf)
        k = min(worst_k, gn.size)
        top = (
            np.argpartition(-rank, k - 1)[:k] if k < gn.size
            else np.arange(gn.size)
        )
        top = top[np.argsort(-rank[top], kind="stable")]
        worst = [(int(ids[i]), float(gn[i])) for i in top]
    return FleetSummary(
        coordinate=coordinate,
        iteration=int(iteration),
        entities=n,
        iters_histogram=hist,
        median_iters=float(np.median(iters)) if n else 0.0,
        reason_counts=reason_counts,
        nonconverged=nonconverged,
        nonconverged_frac=nonconverged / n if n else 0.0,
        worst=worst,
        nonfinite_grad_norms=nonfinite,
    )


# ---------------------------------------------------------------------------
# Recording: registry metrics + events + tracker
# ---------------------------------------------------------------------------


def _registry(registry=None):
    if registry is not None:
        return registry
    from photon_ml_tpu.obs.metrics import registry as _default

    return _default()


def note_solve(
    report: ConvergenceReport,
    label: str = "",
    registry=None,
    emit: bool = True,
) -> None:
    """Record one per-solve report: ``convergence.*`` metrics, a
    ``convergence.solve`` event (tapes included — via the tracer hook it
    also lands in the flight recorder ring), and the installed tracker."""
    reg = _registry(registry)
    reg.inc("convergence.solves")
    reg.inc(f"convergence.reason.{report.reason}")
    reg.observe("convergence.iters", float(report.iterations))
    if report.reason in ("NOT_CONVERGED", "MAX_ITERATIONS"):
        reg.inc("convergence.nonconverged")
    if report.rate is not None:
        reg.set_gauge("convergence.rate", report.rate)
    if emit:
        from photon_ml_tpu.obs.trace import emit_event

        emit_event(
            "convergence.solve",
            cat="convergence",
            label=label,
            **report.to_dict(),
        )
    tracker = _tracker
    if tracker is not None:
        tracker.note_solve(report, label=label)


def note_update(
    coordinate: str,
    iteration: int,
    reasons,
    iterations,
    grad_norms=None,
    entity_ids=None,
    registry=None,
    worst_k: int = 5,
    emit: bool = True,
) -> Optional[FleetSummary]:
    """Record one coordinate update's per-entity convergence: fleet
    summary -> metrics + ``convergence.fleet`` event + precursor check +
    tracker. Returns the summary (None for empty input)."""
    summary = fleet_summary(
        reasons,
        iterations,
        grad_norms,
        entity_ids,
        coordinate=coordinate,
        iteration=iteration,
        worst_k=worst_k,
    )
    if summary.entities == 0:
        return None
    reg = _registry(registry)
    reg.inc("convergence.solves", float(summary.entities))
    reg.inc("convergence.nonconverged", float(summary.nonconverged))
    for name, count in summary.reason_counts.items():
        reg.inc(f"convergence.reason.{name}", float(count))
    reg.set_gauge(
        f"convergence.{coordinate}.median_iters", summary.median_iters
    )
    reg.set_gauge(
        f"convergence.{coordinate}.nonconverged_frac",
        summary.nonconverged_frac,
    )
    if summary.worst:
        reg.set_gauge(
            f"convergence.{coordinate}.worst_grad_norm",
            summary.worst[0][1]
            if math.isfinite(summary.worst[0][1])
            else -1.0,
        )
    precursor = (
        summary.nonconverged_frac > PRECURSOR_NONCONVERGED_FRAC
        or summary.nonfinite_grad_norms > 0
    )
    summary_dict = summary.to_dict()  # built once, shared by all sinks
    if emit:
        from photon_ml_tpu.obs.trace import get_tracer

        tracer = get_tracer()
        if tracer is not None:
            # periodic telemetry, not a crash instant: ride the batched
            # span flush (the precursor below DOES flush immediately)
            tracer.add_instant(
                "convergence.fleet",
                cat="convergence",
                args=summary_dict,
                flush=False,
            )
        if precursor:
            from photon_ml_tpu.obs.trace import emit_event

            emit_event(
                "convergence.precursor",
                cat="convergence",
                coordinate=coordinate,
                iteration=iteration,
                nonconverged_frac=round(summary.nonconverged_frac, 4),
                nonfinite_grad_norms=summary.nonfinite_grad_norms,
            )
    if precursor:
        reg.inc("convergence.precursors")
    tracker = _tracker
    if tracker is not None:
        tracker.note_fleet(summary, summary_dict)
    return summary


def emit_tape_counters(
    report: ConvergenceReport,
    tracer,
    ts_us: float,
    dur_us: float,
    name: str = "convergence.solve",
) -> None:
    """Replay a solve's (value, grad_norm) tape as a Chrome counter
    track spread evenly across the solve's span window — in Perfetto the
    convergence curve renders directly under the ``glm.solve`` span that
    produced it. The tape has no per-iteration timestamps (the whole
    solve is one dispatch), so even spacing is the honest rendering."""
    if tracer is None:
        return
    n = len(report.values)
    if n == 0:
        return
    step = dur_us / max(n - 1, 1)
    for i in range(n):
        vals = {"value": float(report.values[i])}
        if i < len(report.grad_norms):
            g = float(report.grad_norms[i])
            # log scale: grad norms span many decades per solve
            vals["log10_grad_norm"] = (
                math.log10(g) if g > 0 and math.isfinite(g) else -12.0
            )
        tracer.add_counter(name, vals, ts_us=ts_us + i * step)


# ---------------------------------------------------------------------------
# ConvergenceTracker: the --convergence-report collector
# ---------------------------------------------------------------------------


class ConvergenceTracker:
    """Bounded collector of per-solve reports and fleet summaries,
    aggregated into one run-level convergence report
    (``convergence-report.json`` under the driver's output dir).
    Thread-safe; keeps the last ``last_n`` solve tapes whole (the
    flight-recorder-style bound) plus running aggregates for everything.
    """

    def __init__(self, last_n: int = 64, worst_k: int = 5):
        self.last_n = last_n
        self.worst_k = worst_k
        self._lock = threading.Lock()
        self._solves: List[dict] = []
        self._fleet: List[dict] = []
        self._n_solves = 0
        self._n_updates = 0

    def note_solve(self, report: ConvergenceReport, label: str = "") -> None:
        with self._lock:
            self._n_solves += 1
            self._solves.append({"label": label, **report.to_dict()})
            del self._solves[: -self.last_n]

    def note_fleet(
        self, summary: FleetSummary, summary_dict: Optional[dict] = None
    ) -> None:
        with self._lock:
            self._n_updates += 1
            self._fleet.append(
                summary_dict if summary_dict is not None
                else summary.to_dict()
            )
            del self._fleet[: -max(self.last_n, 256)]

    def report(self) -> dict:
        """Aggregate across everything noted: per-coordinate medians,
        reason taxonomy totals, overall non-converged fraction, the
        retained last-N solve reports and fleet summaries."""
        with self._lock:
            solves = list(self._solves)
            fleet = list(self._fleet)
            n_solves = self._n_solves
            n_updates = self._n_updates
        coords: Dict[str, dict] = {}
        reason_totals: Dict[str, int] = {}
        total_entities = 0
        total_nonconverged = 0
        all_medians: List[float] = []
        for f in fleet:
            c = coords.setdefault(
                f["coordinate"],
                {
                    "updates": 0,
                    "entities": 0,
                    "nonconverged": 0,
                    "median_iters": [],
                    "worst": [],
                },
            )
            c["updates"] += 1
            c["entities"] += f["entities"]
            c["nonconverged"] += f["nonconverged"]
            c["median_iters"].append(f["median_iters"])
            c["worst"].extend(f["worst"])
            total_entities += f["entities"]
            total_nonconverged += f["nonconverged"]
            all_medians.append(f["median_iters"])
            for name, count in f["reason_counts"].items():
                reason_totals[name] = reason_totals.get(name, 0) + count
        for r in solves:
            reason_totals[r["reason"]] = reason_totals.get(r["reason"], 0) + 1
            total_entities += 1
            if r["reason"] in ("NOT_CONVERGED", "MAX_ITERATIONS"):
                total_nonconverged += 1
            all_medians.append(float(r["iterations"]))
        per_coord = {}
        for name, c in coords.items():
            worst = sorted(
                c["worst"],
                key=lambda eg: -(
                    eg[1] if math.isfinite(eg[1]) else float("inf")
                ),
            )[: self.worst_k]
            per_coord[name] = {
                "updates": c["updates"],
                "entities": c["entities"],
                "nonconverged": c["nonconverged"],
                "nonconverged_frac": (
                    c["nonconverged"] / c["entities"] if c["entities"] else 0.0
                ),
                "median_iters": (
                    float(np.median(c["median_iters"]))
                    if c["median_iters"]
                    else 0.0
                ),
                "worst_entities": worst,
            }
        return {
            "solves": n_solves,
            "updates": n_updates,
            "median_iters": (
                float(np.median(all_medians)) if all_medians else 0.0
            ),
            "nonconverged": total_nonconverged,
            "nonconverged_frac": (
                total_nonconverged / total_entities if total_entities else 0.0
            ),
            "reason_counts": reason_totals,
            "coordinates": per_coord,
            "last_solves": solves,
            "last_fleet": fleet,
        }

    def dump(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.report(), f, indent=2)
        return path


_tracker: Optional[ConvergenceTracker] = None


def install_convergence_tracker(
    last_n: int = 64, worst_k: int = 5
) -> ConvergenceTracker:
    """Install the process-global tracker (replacing any previous one).
    While installed, solver call sites decode tapes even without an
    active tracer — the ``--convergence-report`` opt-in."""
    global _tracker
    _tracker = ConvergenceTracker(last_n=last_n, worst_k=worst_k)
    return _tracker


def uninstall_convergence_tracker() -> None:
    global _tracker
    _tracker = None


def convergence_tracker() -> Optional[ConvergenceTracker]:
    return _tracker


def tracking_enabled() -> bool:
    """True when a ConvergenceTracker is installed — the gate the solve
    paths OR with ``obs.get_tracer() is not None`` before paying the
    decode (both synchronize; pipelined solves must stay sync-free by
    default)."""
    return _tracker is not None
