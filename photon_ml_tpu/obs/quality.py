"""Model/data-quality observability: baselines, drift, online quality.

Three layers close the gap between "the system is healthy" (spans,
SLOs, breakers) and "the MODEL is healthy":

1. **Train-time baseline fingerprint** (:class:`BaselineFingerprint`) —
   per-feature, label, and margin sketches (:mod:`.sketches`)
   accumulated over ingest chunks by the io paths through an installed
   process-global collector (:func:`install_fingerprint_collector`,
   mirroring the convergence-tracker pattern), exported as
   ``quality-fingerprint.json`` next to ``model-manifest.json`` by the
   train CLIs. Sketch ``merge()`` is exact, so per-chunk / per-host
   fingerprints fold into the single-pass fingerprint bit-for-bit
   (``photon-obs merge`` folds shard fingerprints the same way).

2. **Serving-side drift detection** (:class:`DriftMonitor`) — hung off
   the :class:`~photon_ml_tpu.serving.engine.ScoringEngine`, sampling
   request features and score distributions into live sketches and
   comparing tumbling windows against the loaded model's baseline:
   per-feature PSI / JS-divergence as ``drift.*`` gauges, a
   ``drift.alarm`` instant event (which rides the tracer hook into the
   crash flight recorder) when any PSI crosses the alarm threshold.
   Hot-reload swaps the monitor WITH the engine, so baselines change
   atomically with the model. ``photon-obs drift`` compares two
   fingerprints offline and exits nonzero on alarm (cron use).

3. **Online quality** (:class:`OnlineQuality`) — the delayed-label
   feedback loop: ``{"cmd": "feedback"}`` on ``cli/serve.py`` records
   (label, score, weight) into a bounded rolling window whose exact
   weighted tie-aware AUC (:func:`exact_auc` — the numpy mirror of
   ``ops.metrics.area_under_roc_curve``, equal to ≤1e-6 on any stream)
   and calibration error export as ``quality.*`` gauges.

A missing or corrupt fingerprint must never take down serving: loads go
through :func:`try_load_fingerprint`, which probes the
``quality.baseline`` fault site, counts
``quality.baseline_missing`` / ``quality.baseline_errors``, and returns
None — the engine serves without drift monitoring and says so.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.obs.metrics import MetricsRegistry
from photon_ml_tpu.obs.metrics import registry as _default_registry
from photon_ml_tpu.obs.sketches import (
    HistogramSketch,
    MomentSketch,
    TopKSketch,
    histogram_add_matrix,
    js_divergence,
    moments_add_matrix,
    psi,
    psi_and_js,
)
from photon_ml_tpu.obs.trace import emit_event
from photon_ml_tpu.resilience import faults as _faults

__all__ = [
    "QUALITY_FINGERPRINT",
    "FeatureSketch",
    "BaselineFingerprint",
    "try_load_fingerprint",
    "install_fingerprint_collector",
    "uninstall_fingerprint_collector",
    "fingerprint_collector",
    "DriftMonitor",
    "OnlineQuality",
    "exact_auc",
    "calibration_error",
    "compare_fingerprints",
]

QUALITY_FINGERPRINT = "quality-fingerprint.json"

DEFAULT_MAX_FEATURES = 64
DEFAULT_PSI_ALARM = 0.25
# drift monitors sample 1-in-N scored batches by default: covariate
# shift persists across batches, so sampling trades alarm latency (x N)
# for per-batch overhead (/ N) — drills that need tight latency pass
# sample_every=1 explicitly
DEFAULT_SAMPLE_EVERY = 4


class FeatureSketch:
    """One tracked quantity: moments + fixed-bin histogram (+ label)."""

    __slots__ = ("name", "moments", "histogram")

    def __init__(
        self,
        name: Optional[str] = None,
        histogram: Optional[HistogramSketch] = None,
    ):
        self.name = name
        self.moments = MomentSketch()
        self.histogram = (
            histogram
            if histogram is not None
            else HistogramSketch.for_features()
        )

    def add(self, values, weights=None) -> "FeatureSketch":
        self.moments.add(values, weights)
        self.histogram.add(values, weights)
        return self

    def merge(self, other: "FeatureSketch") -> "FeatureSketch":
        if self.name is None:
            self.name = other.name
        self.moments.merge(other.moments)
        self.histogram.merge(other.histogram)
        return self

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "moments": self.moments.to_dict(),
            "histogram": self.histogram.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FeatureSketch":
        out = cls(name=d.get("name"))
        out.moments = MomentSketch.from_dict(d["moments"])
        out.histogram = HistogramSketch.from_dict(d["histogram"])
        return out


class BaselineFingerprint:
    """What the training data looked like, as mergeable sketches.

    Per-shard, per-column feature sketches (capped at ``max_features``
    leading columns per shard — deterministic, so chunked and
    single-pass fingerprints track the same set), a label sketch, a
    margin sketch (score space — what the serving DriftMonitor compares
    live score distributions against), and optional categorical top-k
    sketches (entity types). Thread-safe: the ingest pipeline's decode
    pool and the in-core paths both feed it.
    """

    VERSION = 1

    def __init__(self, max_features: int = DEFAULT_MAX_FEATURES):
        self.max_features = int(max_features)
        self.shards: Dict[str, Dict[int, FeatureSketch]] = {}
        self.label = FeatureSketch("label")
        self.margin = FeatureSketch(
            "margin", HistogramSketch.for_scores()
        )
        self.categoricals: Dict[str, TopKSketch] = {}
        self.rows = 0
        self._lock = threading.Lock()

    # -- accumulation -------------------------------------------------------

    def observe_rows(
        self,
        shard: str,
        matrix,
        weights=None,
        names: Optional[Sequence[str]] = None,
    ) -> None:
        """One dense (n, d) host chunk of shard ``shard``. Only the
        leading ``max_features`` columns are sketched (bounded cost and
        file size; the cap is part of the fingerprint so both sides of
        a comparison track the same columns)."""
        m = np.asarray(matrix)
        if m.ndim != 2 or m.shape[0] == 0:
            return
        ncols = min(m.shape[1], self.max_features)
        with self._lock:
            cols = self.shards.setdefault(shard, {})
            for j in range(ncols):
                if j not in cols:
                    name = (
                        str(names[j])
                        if names is not None and j < len(names)
                        else None
                    )
                    cols[j] = FeatureSketch(name)
            sks = [cols[j] for j in range(ncols)]
            sub = m[:, :ncols]
            histogram_add_matrix(
                [sk.histogram for sk in sks], sub, weights
            )
            moments_add_matrix([sk.moments for sk in sks], sub, weights)

    def observe_labels(self, labels, weights=None) -> None:
        lab = np.asarray(labels)
        if lab.size == 0:
            return
        with self._lock:
            self.label.add(lab, weights)
            self.rows += int(lab.size)

    def observe_margins(self, margins, weights=None) -> None:
        with self._lock:
            self.margin.add(np.asarray(margins), weights)

    def observe_categorical(self, kind: str, keys, weights=None) -> None:
        with self._lock:
            sk = self.categoricals.get(kind)
            if sk is None:
                sk = self.categoricals[kind] = TopKSketch()
            sk.add_many(keys, weights)

    def observe_batch(
        self,
        features=None,
        labels=None,
        weights=None,
        shard: str = "features",
        names: Optional[Sequence[str]] = None,
    ) -> None:
        """One ingest chunk: dense features (None / non-2D — e.g. a
        sparse container — contribute nothing), labels, weights."""
        if features is not None and getattr(features, "ndim", 0) == 2:
            self.observe_rows(shard, features, weights, names=names)
        if labels is not None:
            self.observe_labels(labels, weights)

    # -- merge / io ---------------------------------------------------------

    def merge(self, other: "BaselineFingerprint") -> "BaselineFingerprint":
        """Exact fold: self ∪ other equals the single-pass fingerprint
        over the concatenated rows (the pod-merge contract)."""
        with self._lock:
            for shard, cols in other.shards.items():
                mine = self.shards.setdefault(shard, {})
                for j, sk in cols.items():
                    if j in mine:
                        mine[j].merge(sk)
                    else:
                        mine[j] = sk
            self.label.merge(other.label)
            self.margin.merge(other.margin)
            for kind, sk in other.categoricals.items():
                if kind in self.categoricals:
                    self.categoricals[kind].merge(sk)
                else:
                    self.categoricals[kind] = sk
            self.rows += other.rows
        return self

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "version": self.VERSION,
                "max_features": self.max_features,
                "rows": self.rows,
                "shards": {
                    shard: {
                        str(j): sk.to_dict()
                        for j, sk in sorted(cols.items())
                    }
                    for shard, cols in sorted(self.shards.items())
                },
                "label": self.label.to_dict(),
                "margin": self.margin.to_dict(),
                "categoricals": {
                    k: sk.to_dict()
                    for k, sk in sorted(self.categoricals.items())
                },
            }

    @classmethod
    def from_dict(cls, d: dict) -> "BaselineFingerprint":
        out = cls(max_features=int(d.get("max_features", DEFAULT_MAX_FEATURES)))
        out.rows = int(d["rows"])
        for shard, cols in d.get("shards", {}).items():
            out.shards[shard] = {
                int(j): FeatureSketch.from_dict(sk)
                for j, sk in cols.items()
            }
        out.label = FeatureSketch.from_dict(d["label"])
        out.margin = FeatureSketch.from_dict(d["margin"])
        out.categoricals = {
            k: TopKSketch.from_dict(sk)
            for k, sk in d.get("categoricals", {}).items()
        }
        return out

    def save(self, path: str) -> str:
        """Write the fingerprint JSON (``path`` may be the export dir).
        Write-then-rename so a reader never sees a torn fingerprint."""
        if os.path.isdir(path):
            path = os.path.join(path, QUALITY_FINGERPRINT)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "BaselineFingerprint":
        if os.path.isdir(path):
            path = os.path.join(path, QUALITY_FINGERPRINT)
        with open(path, encoding="utf-8") as f:
            return cls.from_dict(json.load(f))


def try_load_fingerprint(
    root: str, registry: Optional[MetricsRegistry] = None
) -> Optional[BaselineFingerprint]:
    """Load the export's fingerprint, or None — NEVER raises. A model
    without a (readable) baseline must still serve; the degraded state
    is counted (``quality.baseline_missing`` / ``.baseline_errors``)
    and evented so the silent-no-drift-monitoring mode is visible.
    Probes the ``quality.baseline`` fault site (raise = unreadable,
    corrupt = torn/garbage fingerprint)."""
    reg = registry if registry is not None else _default_registry()
    path = (
        os.path.join(root, QUALITY_FINGERPRINT)
        if os.path.isdir(root)
        else root
    )
    try:
        action = _faults.fire(
            "quality.baseline", key=os.path.basename(os.path.dirname(path))
        )
        if not os.path.exists(path):
            reg.inc("quality.baseline_missing")
            emit_event(
                "quality.baseline_missing", cat="quality", path=path
            )
            return None
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if action.corrupt:
            raise ValueError("injected fingerprint corruption")
        return BaselineFingerprint.from_dict(doc)
    except OSError as e:
        reg.inc("quality.baseline_missing")
        emit_event(
            "quality.baseline_missing", cat="quality", path=path,
            error=repr(e),
        )
        return None
    except (ValueError, KeyError, TypeError) as e:
        reg.inc("quality.baseline_errors")
        emit_event(
            "quality.baseline_error", cat="quality", path=path,
            error=repr(e),
        )
        return None


# ---------------------------------------------------------------------------
# process-global fingerprint collector (the ingest-side hook)
# ---------------------------------------------------------------------------

_collector: Optional[BaselineFingerprint] = None


def install_fingerprint_collector(
    fingerprint: Optional[BaselineFingerprint] = None,
    max_features: int = DEFAULT_MAX_FEATURES,
) -> BaselineFingerprint:
    """Install a process-global fingerprint the io paths feed
    (``io/ingest.py`` in-core assembly, ``io/pipeline.py`` staged
    chunks). Mirrors the convergence-tracker install pattern: drivers
    install around ingest, export, then uninstall. Re-installing
    replaces the previous collector."""
    global _collector
    fp = (
        fingerprint
        if fingerprint is not None
        else BaselineFingerprint(max_features=max_features)
    )
    _collector = fp
    return fp


def uninstall_fingerprint_collector() -> None:
    global _collector
    _collector = None


def fingerprint_collector() -> Optional[BaselineFingerprint]:
    """The installed collector, or None (the common, zero-cost case)."""
    return _collector


# ---------------------------------------------------------------------------
# serving-side drift monitor
# ---------------------------------------------------------------------------


class DriftMonitor:
    """Compare sampled serving traffic against a training baseline.

    Feeds per-feature live sketches (same fixed-bin configs as the
    baseline's, so PSI is well-defined) from every ``sample_every``-th
    scored batch; every ``check_every_rows`` sampled rows it computes
    per-feature PSI / JS against the baseline over the tumbling window,
    exports ``drift.*`` gauges, and — when any PSI (features or score
    distribution) reaches ``psi_alarm`` — counts ``drift.alarms`` and
    emits a ``drift.alarm`` instant event carrying the worst offenders
    (the flight recorder sees it through the tracer hook). The window
    then resets; a persistent shift re-alarms every window.
    """

    def __init__(
        self,
        baseline: BaselineFingerprint,
        registry: Optional[MetricsRegistry] = None,
        psi_alarm: float = DEFAULT_PSI_ALARM,
        check_every_rows: int = 512,
        min_rows: int = 128,
        sample_every: int = DEFAULT_SAMPLE_EVERY,
        max_rows_per_batch: int = 128,
    ):
        if sample_every < 1:
            raise ValueError(
                f"sample_every must be >= 1, got {sample_every}"
            )
        if max_rows_per_batch < 1:
            raise ValueError(
                f"max_rows_per_batch must be >= 1, got {max_rows_per_batch}"
            )
        self.baseline = baseline
        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        self.psi_alarm = float(psi_alarm)
        self.check_every_rows = int(check_every_rows)
        self.min_rows = int(min_rows)
        self.sample_every = int(sample_every)
        # rows within a batch are exchangeable, so a huge coalesced
        # batch contributes a capped prefix — bounds per-batch overhead
        # independent of the engine's bucket ladder
        self.max_rows_per_batch = int(max_rows_per_batch)
        self._lock = threading.Lock()
        self._batches = 0
        self._rows_in_window = 0
        self.alarms = 0
        self.checks = 0
        self.last_report: Optional[dict] = None
        self._reset_window_locked()

    def _reset_window_locked(self) -> None:
        self._live: Dict[str, Dict[int, HistogramSketch]] = {
            shard: {
                j: HistogramSketch(
                    scale=sk.histogram.scale,
                    lo=sk.histogram.lo,
                    hi=sk.histogram.hi,
                    bins=sk.histogram.bins,
                    x0=sk.histogram.x0,
                )
                for j, sk in cols.items()
            }
            for shard, cols in self.baseline.shards.items()
        }
        self._live_score = HistogramSketch(
            scale=self.baseline.margin.histogram.scale,
            lo=self.baseline.margin.histogram.lo,
            hi=self.baseline.margin.histogram.hi,
            bins=self.baseline.margin.histogram.bins,
            x0=self.baseline.margin.histogram.x0,
        )
        # hot-path cache: contiguous-leading-column histogram lists per
        # shard (the baseline's column set is fixed for the monitor's
        # lifetime, so this never changes shape across window resets)
        self._live_fast: Dict[str, Tuple[int, List[HistogramSketch]]] = {}
        for shard, cols in self._live.items():
            ncols = (max(cols) + 1) if cols else 0
            if ncols and all(j in cols for j in range(ncols)):
                self._live_fast[shard] = (
                    ncols,
                    [cols[j] for j in range(ncols)],
                )
        self._rows_in_window = 0

    # -- recording ----------------------------------------------------------

    def observe(
        self,
        features: Mapping[str, np.ndarray],
        scores: Optional[np.ndarray] = None,
    ) -> Optional[dict]:
        """One scored batch (unpadded rows). Returns the drift report
        when this observation completed a window check, else None."""
        with self._lock:
            self._batches += 1
            if (self._batches - 1) % self.sample_every != 0:
                return None
            rows = 0
            cap = self.max_rows_per_batch
            for shard, cols in self._live.items():
                m = features.get(shard)
                if m is None:
                    continue
                m = np.asarray(m)[:cap]
                if m.ndim != 2 or m.shape[0] == 0:
                    continue
                rows = max(rows, m.shape[0])
                fast = self._live_fast.get(shard)
                if fast is not None and m.shape[1] >= fast[0]:
                    # contiguous leading columns — the common case —
                    # one bincount for the whole matrix, checks skipped
                    # (this window owns every sketch, one config)
                    histogram_add_matrix(
                        fast[1], m[:, : fast[0]], check_configs=False
                    )
                else:
                    for j, hist in cols.items():
                        if j < m.shape[1]:
                            hist.add(m[:, j])
            if scores is not None:
                s = np.asarray(scores)[:cap]
                rows = max(rows, s.size)
                self._live_score.add(s)
            self._rows_in_window += rows
            if (
                self._rows_in_window < self.check_every_rows
                or self._rows_in_window < self.min_rows
            ):
                return None
            return self._check_locked()

    def check(self) -> Optional[dict]:
        """Force a window check now (tests, shutdown) — None when the
        window holds fewer than ``min_rows`` sampled rows."""
        with self._lock:
            if self._rows_in_window < self.min_rows:
                return None
            return self._check_locked()

    def _check_locked(self) -> dict:
        reg = self.registry
        per_feature: Dict[str, dict] = {}
        psi_max = 0.0
        js_max = 0.0
        worst: List[Tuple[float, str]] = []
        for shard, cols in self._live.items():
            base_cols = self.baseline.shards.get(shard, {})
            for j, hist in cols.items():
                base = base_cols.get(j)
                if base is None or hist.weight <= 0.0:
                    continue
                p, jsd = psi_and_js(base.histogram, hist)
                key = f"{shard}.{j}"
                per_feature[key] = {
                    "psi": round(p, 6),
                    "js": round(jsd, 6),
                    "name": base.name,
                }
                reg.set_gauge(f"drift.psi.{shard}.{j}", p)
                psi_max = max(psi_max, p)
                js_max = max(js_max, jsd)
                worst.append((p, key))
        score_psi = None
        if (
            self.baseline.margin.histogram.weight > 0.0
            and self._live_score.weight > 0.0
        ):
            score_psi = psi(
                self.baseline.margin.histogram, self._live_score
            )
            reg.set_gauge("drift.score_psi", score_psi)
        flagged = sorted(
            (k for p, k in worst if p >= self.psi_alarm),
        )
        alarm = bool(flagged) or (
            score_psi is not None and score_psi >= self.psi_alarm
        )
        self.checks += 1
        reg.inc("drift.checks")
        reg.set_gauge("drift.psi_max", psi_max)
        reg.set_gauge("drift.js_max", js_max)
        reg.set_gauge("drift.features_flagged", len(flagged))
        report = {
            "rows": self._rows_in_window,
            "psi_max": round(psi_max, 6),
            "js_max": round(js_max, 6),
            "score_psi": (
                round(score_psi, 6) if score_psi is not None else None
            ),
            "flagged": flagged,
            "alarm": alarm,
            "features": per_feature,
        }
        if alarm:
            self.alarms += 1
            reg.inc("drift.alarms")
            top = sorted(worst, reverse=True)[:5]
            emit_event(
                "drift.alarm",
                cat="quality",
                psi_max=round(psi_max, 6),
                score_psi=report["score_psi"],
                threshold=self.psi_alarm,
                rows=self._rows_in_window,
                worst={k: round(p, 4) for p, k in top},
            )
        self.last_report = report
        self._reset_window_locked()
        return report

    # -- readout ------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "psi_alarm": self.psi_alarm,
                "check_every_rows": self.check_every_rows,
                "sample_every": self.sample_every,
                "baseline_rows": self.baseline.rows,
                "window_rows": self._rows_in_window,
                "checks": self.checks,
                "alarms": self.alarms,
                "last_report": self.last_report,
            }


# ---------------------------------------------------------------------------
# online quality: the delayed-label feedback loop
# ---------------------------------------------------------------------------


def exact_auc(labels, scores, weights=None) -> float:
    """Exact weighted, tie-aware AUROC — the numpy mirror of
    ``ops.metrics.area_under_roc_curve`` (same closed form:
    P(s⁺ > s⁻) + ½·P(s⁺ = s⁻), pair-weighted; 0.5 when a class is
    empty; zero-weight rows invisible). The streaming/online quality
    path computes THIS, and tests assert ≤1e-6 agreement with the
    jitted device kernel on the same stream."""
    s = np.asarray(scores, np.float64).ravel()
    y = np.asarray(labels, np.float64).ravel()
    w = (
        np.ones_like(s)
        if weights is None
        else np.asarray(weights, np.float64).ravel()
    )
    if s.size == 0:
        return 0.5
    order = np.argsort(s, kind="stable")
    s, y, w = s[order], y[order], w[order]
    pos_w = np.where(y > 0.5, w, 0.0)
    neg_w = np.where(y > 0.5, 0.0, w)
    cum_neg = np.cumsum(neg_w)
    total_neg = cum_neg[-1]
    total_pos = pos_w.sum()
    left = np.searchsorted(s, s, side="left")
    right = np.searchsorted(s, s, side="right")
    cum0 = np.concatenate([np.zeros(1), cum_neg])
    neg_below = cum0[left]
    neg_equal = cum0[right] - neg_below
    pairs = float(np.sum(pos_w * (neg_below + 0.5 * neg_equal)))
    denom = total_pos * total_neg
    return pairs / denom if denom > 0.0 else 0.5


def calibration_error(labels, scores, weights=None) -> float:
    """Calibration-in-the-large: |E_w[σ(score)] − E_w[label]| — the
    one-number answer to "are the served probabilities drifting from
    observed rates". Scores are margins (logits); labels {0, 1}."""
    s = np.asarray(scores, np.float64).ravel()
    y = np.asarray(labels, np.float64).ravel()
    w = (
        np.ones_like(s)
        if weights is None
        else np.asarray(weights, np.float64).ravel()
    )
    total = w.sum()
    if total <= 0.0:
        return 0.0
    # numerically-stable sigmoid
    p = np.where(s >= 0, 1.0 / (1.0 + np.exp(-s)), 0.0)
    ex = np.exp(s[s < 0])
    p[s < 0] = ex / (1.0 + ex)
    return abs(float(((p - y) * w).sum()) / float(total))


class OnlineQuality:
    """Rolling-window model quality from delayed labels.

    ``record(label, score, weight)`` appends to a bounded window (at
    most ``max_samples`` newest feedbacks — like the SLO tracker's
    window, full-precision samples, not a sketch, because the AUC
    contract is EXACT agreement with the offline replay). Every
    ``refresh_every`` records the gauges refresh:
    ``quality.auc`` / ``quality.calibration_error`` /
    ``quality.window_n``; ``quality.feedback_total`` counts for life.
    """

    _REFRESH_EVERY = 64

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        max_samples: int = 8192,
        refresh_every: int = _REFRESH_EVERY,
    ):
        self.registry = (
            registry if registry is not None else MetricsRegistry()
        )
        self._lock = threading.Lock()
        self._window = collections.deque(maxlen=max_samples)
        self._since_refresh = 0
        self.refresh_every = max(int(refresh_every), 1)
        self.total = 0

    def record(
        self, label: float, score: float, weight: float = 1.0
    ) -> None:
        label = float(label)
        score = float(score)
        weight = float(weight)
        if not math.isfinite(score) or not math.isfinite(label):
            raise ValueError(
                f"feedback must be finite (label={label}, score={score})"
            )
        with self._lock:
            self._window.append((label, score, weight))
            self.total += 1
            self.registry.inc("quality.feedback_total")
            self._since_refresh += 1
            refresh = self._since_refresh >= self.refresh_every
            if refresh:
                self._since_refresh = 0
        if refresh:
            self.snapshot()

    @property
    def window_n(self) -> int:
        with self._lock:
            return len(self._window)

    def window_arrays(self):
        """(labels, scores, weights) of the current window — what the
        exact-replay equivalence drills feed to ``ops.metrics``."""
        with self._lock:
            items = list(self._window)
        if not items:
            z = np.zeros(0)
            return z, z, z
        a = np.asarray(items, np.float64)
        return a[:, 0], a[:, 1], a[:, 2]

    def snapshot(self) -> dict:
        labels, scores, weights = self.window_arrays()
        auc = exact_auc(labels, scores, weights)
        cal = calibration_error(labels, scores, weights)
        out = {
            "window_n": int(labels.size),
            "total": self.total,
            "auc": round(auc, 6),
            "calibration_error": round(cal, 6),
            "positive_weight": float(
                weights[labels > 0.5].sum() if labels.size else 0.0
            ),
            "negative_weight": float(
                weights[labels <= 0.5].sum() if labels.size else 0.0
            ),
        }
        self.registry.set_gauge("quality.auc", auc)
        self.registry.set_gauge("quality.calibration_error", cal)
        self.registry.set_gauge("quality.window_n", labels.size)
        return out


# ---------------------------------------------------------------------------
# offline fingerprint comparison (photon-obs drift)
# ---------------------------------------------------------------------------


def compare_fingerprints(
    baseline: BaselineFingerprint,
    current: BaselineFingerprint,
    psi_alarm: float = DEFAULT_PSI_ALARM,
) -> dict:
    """Per-feature PSI/JS between two fingerprints (features present in
    both), plus label and margin distribution distances — the report
    behind ``photon-obs drift``."""
    features: Dict[str, dict] = {}
    psi_max = 0.0
    js_max = 0.0
    for shard, cols in sorted(baseline.shards.items()):
        cur_cols = current.shards.get(shard, {})
        for j, base in sorted(cols.items()):
            cur = cur_cols.get(j)
            if cur is None or cur.histogram.weight <= 0.0:
                continue
            if base.histogram.weight <= 0.0:
                continue
            p, jsd = psi_and_js(base.histogram, cur.histogram)
            features[f"{shard}.{j}"] = {
                "psi": round(p, 6),
                "js": round(jsd, 6),
                "name": base.name or cur.name,
                "baseline_mean": round(base.moments.mean, 6),
                "current_mean": round(cur.moments.mean, 6),
            }
            psi_max = max(psi_max, p)
            js_max = max(js_max, jsd)
    label_psi = None
    if (
        baseline.label.histogram.weight > 0.0
        and current.label.histogram.weight > 0.0
    ):
        label_psi = round(
            psi(baseline.label.histogram, current.label.histogram), 6
        )
    margin_psi = None
    if (
        baseline.margin.histogram.weight > 0.0
        and current.margin.histogram.weight > 0.0
    ):
        margin_psi = round(
            psi(baseline.margin.histogram, current.margin.histogram), 6
        )
    flagged = sorted(
        k for k, v in features.items() if v["psi"] >= psi_alarm
    )
    alarm = bool(flagged) or (
        margin_psi is not None and margin_psi >= psi_alarm
    )
    return {
        "psi_alarm": psi_alarm,
        "psi_max": round(psi_max, 6),
        "js_max": round(js_max, 6),
        "label_psi": label_psi,
        "margin_psi": margin_psi,
        "flagged": flagged,
        "alarm": alarm,
        "baseline_rows": baseline.rows,
        "current_rows": current.rows,
        "features": features,
    }
