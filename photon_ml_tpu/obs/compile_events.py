"""XLA backend-compile counting via ``jax.monitoring``.

Promoted out of ``serving/stats.py`` (which re-exports it) so TRAINING can
assert its own steady-state zero-recompile invariants the same way serving
proved PR 2's zero-recompile guarantee: the cached-solve path in
``models/training.py`` and the per-pass jit cache in ``game/descent.py``
are only provably recompile-free because something counts actual XLA
backend compiles — wall-clock regressions alone can't distinguish "slow"
from "recompiling".

Every observed compile also increments the default metrics registry's
``xla.compiles`` counter and emits a ``xla.compile`` instant event on the
active tracer, so recompiles land in ``metrics.json`` and in the Perfetto
timeline without any caller wiring.
"""

from __future__ import annotations

import threading

from photon_ml_tpu.obs import metrics as _metrics
from photon_ml_tpu.obs import trace as _trace

__all__ = ["install_compile_listener", "xla_compile_events"]

# every backend compile fires this duration event exactly once (jax 0.4.x);
# tracing-only events are deliberately excluded — a cache-hit retrace that
# does not reach XLA costs microseconds, a backend compile costs seconds
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_compile_lock = threading.Lock()
_compile_events = 0
_listener_installed = False


def _on_event_duration(name: str, secs: float, **_kw) -> None:
    global _compile_events
    if name == _COMPILE_EVENT:
        with _compile_lock:
            _compile_events += 1
        _metrics.registry().inc("xla.compiles")
        _trace.emit_event(
            "xla.compile", cat="xla", duration_ms=round(secs * 1e3, 3)
        )


def install_compile_listener() -> None:
    """Idempotently register the jax.monitoring listener that feeds
    :func:`xla_compile_events`. Listener registration is global and
    permanent in jax, so this installs exactly once per process."""
    global _listener_installed
    with _compile_lock:
        if _listener_installed:
            return
        _listener_installed = True
    import jax.monitoring

    jax.monitoring.register_event_duration_secs_listener(_on_event_duration)


def xla_compile_events() -> int:
    """Process-wide count of XLA backend compiles observed since
    :func:`install_compile_listener` — the ground truth any per-instance
    ``compile_count`` is cross-checked against in tests."""
    with _compile_lock:
        return _compile_events
