"""Live device (HBM) memory telemetry.

The only HBM numbers the repo had were static: compiled-footprint
``memory_analysis()`` sizes recorded by the bench and the ingest path's
*designed* peak ("dataset + one chunk"). Nothing ever observed the live
allocator — a 2x assembly peak or a leaked device buffer was invisible
until an OOM. This module samples ``device.memory_stats()`` (PJRT's
allocator counters: ``bytes_in_use``, ``peak_bytes_in_use``, ...) into
the obs layer:

- :func:`sample_hbm` — one sample per local device: ``hbm.d<i>.*``
  registry gauges plus Chrome **counter-track** events on the active
  tracer (Perfetto renders them as a memory graph under the timeline).
- :class:`HbmSampler` — background thread sampling on an interval for
  the life of an ``obs.observe`` envelope.
- :func:`hbm_watermark` — context manager bracketing a phase (ingest
  assembly, a descent pass, serving warmup): records before/after/peak
  bytes, exposes ``delta_bytes``/``peak_bytes`` to the caller, and emits
  a ``hbm.watermark`` event + ``hbm.<label>.*`` gauges.

Support is platform-dependent: CPU (and some backends) return ``None``
from ``memory_stats()``. Everything here degrades to a graceful no-op —
zero threads, zero events, zero cost — so CPU test/bench runs and the
<5% overhead gate are untouched. Tests monkeypatch :func:`read_memory_stats`
to drive the machinery without real HBM.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional

# symbol imports: the package rebinds its `trace` attribute to the
# context-manager function once __init__ runs (see xla_cost.py)
from photon_ml_tpu.obs.metrics import registry as _registry
from photon_ml_tpu.obs.trace import emit_event as _emit_event
from photon_ml_tpu.obs.trace import get_tracer as _get_tracer

__all__ = [
    "read_memory_stats",
    "hbm_supported",
    "sample_hbm",
    "HbmSampler",
    "HbmWatermark",
    "hbm_watermark",
]

# The allocator counters worth exporting (when present); memory_stats()
# key names follow PJRT's TF-derived allocator stats.
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "largest_alloc_size")


def read_memory_stats(device=None) -> Optional[Dict[str, int]]:
    """``device.memory_stats()`` with every failure mode collapsed to
    ``None`` (unsupported platform, uninitialized backend, tunnel
    hiccup). The ONE seam the rest of the module reads through — tests
    monkeypatch this to simulate an HBM-bearing device."""
    try:
        if device is None:
            import jax

            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return {k: int(v) for k, v in stats.items()}


def _local_devices() -> List:
    try:
        import jax

        return list(jax.local_devices())
    except Exception:
        return []


def hbm_supported() -> bool:
    """True when at least the first local device reports memory stats."""
    return read_memory_stats() is not None


def sample_hbm(registry=None, tracer=None) -> Dict[str, Dict[str, int]]:
    """Sample every local device once. Returns ``{device_label: stats}``
    (empty when unsupported); side effects: ``hbm.d<i>.*`` gauges and a
    counter-track event per device on the active tracer."""
    reg = registry if registry is not None else _registry()
    tr = tracer if tracer is not None else _get_tracer()
    out: Dict[str, Dict[str, int]] = {}
    for i, dev in enumerate(_local_devices()):
        stats = read_memory_stats(dev)
        if stats is None:
            # device 0 unsupported => the platform is; don't probe 8x
            if i == 0:
                break
            continue
        label = f"d{i}"
        out[label] = stats
        track = {}
        for k in _STAT_KEYS:
            if k in stats:
                reg.set_gauge(f"hbm.{label}.{k}", stats[k])
                track[k] = stats[k]
        if tr is not None and track:
            tr.add_counter(f"hbm.{label}", track)
    return out


class HbmSampler:
    """Background HBM sampler for the life of an observe() envelope.

    ``start()`` is a no-op when the platform reports no memory stats, so
    installing it unconditionally costs one probe. Event-driven stop
    (like MetricsDumper): teardown returns promptly, and a final sample
    on stop means the trace's counter track covers the full window.
    """

    def __init__(self, every_s: float = 0.5, registry=None):
        self.every_s = every_s
        self._registry = registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _run(self) -> None:
        while not self._stop.wait(self.every_s):
            sample_hbm(registry=self._registry)

    def start(self) -> "HbmSampler":
        if (
            self.every_s > 0
            and self._thread is None
            and read_memory_stats() is not None
        ):
            self._thread = threading.Thread(
                target=self._run, name="obs-hbm-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
            sample_hbm(registry=self._registry)


class HbmWatermark:
    """Result object of :func:`hbm_watermark`. ``supported`` is False on
    platforms without memory stats; every byte field is then None."""

    __slots__ = (
        "label", "supported", "before_bytes", "after_bytes",
        "peak_bytes", "delta_bytes",
    )

    def __init__(self, label: str):
        self.label = label
        self.supported = False
        self.before_bytes: Optional[int] = None
        self.after_bytes: Optional[int] = None
        self.peak_bytes: Optional[int] = None
        self.delta_bytes: Optional[int] = None


@contextlib.contextmanager
def hbm_watermark(label: str, registry=None):
    """Bracket a phase with HBM readings on the first local device.

    Yields an :class:`HbmWatermark`; on exit (supported platforms) fills
    ``before/after/peak/delta`` bytes, sets ``hbm.<label>.peak_bytes`` /
    ``hbm.<label>.delta_bytes`` gauges, and emits an ``hbm.watermark``
    instant event. ``peak_bytes`` is the allocator's high-water mark *as
    of phase end* — monotone per process, so compare watermarks of the
    same phase across configurations, not across phases of one run.
    Unsupported platforms run the body with zero overhead beyond two
    ``None`` probes.
    """
    wm = HbmWatermark(label)
    before = read_memory_stats()
    try:
        yield wm
    finally:
        if before is not None:
            after = read_memory_stats()
            if after is not None:
                wm.supported = True
                wm.before_bytes = before.get("bytes_in_use")
                wm.after_bytes = after.get("bytes_in_use")
                wm.peak_bytes = after.get("peak_bytes_in_use")
                if (
                    wm.before_bytes is not None
                    and wm.after_bytes is not None
                ):
                    wm.delta_bytes = wm.after_bytes - wm.before_bytes
                reg = (
                    registry if registry is not None else _registry()
                )
                if wm.peak_bytes is not None:
                    reg.set_gauge(f"hbm.{label}.peak_bytes", wm.peak_bytes)
                if wm.delta_bytes is not None:
                    reg.set_gauge(f"hbm.{label}.delta_bytes", wm.delta_bytes)
                _emit_event(
                    "hbm.watermark",
                    cat="hbm",
                    label=label,
                    before_bytes=wm.before_bytes,
                    after_bytes=wm.after_bytes,
                    peak_bytes=wm.peak_bytes,
                    delta_bytes=wm.delta_bytes,
                )
