"""Streaming data sketches: small, mergeable, O(1)-update summaries.

The obs stack measures *how* the system runs (spans, cost book, SLOs);
nothing records *what the data looked like* — which is the signal the
ROADMAP-4 retrain loop needs to notice that serving traffic has walked
away from the training distribution. These sketches are the primitive:

- :class:`MomentSketch` — weighted Welford mean/variance plus min/max,
  combined across chunks with Chan's parallel update.
- :class:`HistogramSketch` — FIXED-bin quantile histograms; bin edges
  are a function of the configuration alone (never the data), so two
  sketches built over different chunkings of the same rows hold
  *identical* counts. ``linear`` scale for bounded values (scores,
  probabilities); ``symlog`` (signed log-modulus) for features of
  unknown magnitude — ±1e6 at default config, ~12% relative resolution.
- :class:`TopKSketch` — weighted counters for categorical keys with a
  bounded heavy-hitters readout.

The contract every consumer (baseline fingerprints, the serving
DriftMonitor, ``photon-obs merge``) relies on: **merge() is exact** —
folding per-chunk / per-host sketches in any grouping or order yields
bit-identical state to one single-pass sketch over the concatenated
rows (floating-point summation order aside, drilled to 1e-12 in
tests/test_quality.py). TopK exactness holds while distinct-key
cardinality stays within ``max_keys`` (feature vocabularies and entity
types are bounded, so in practice always); past it the readout truncates
deterministically and the spilled mass stays accounted in ``other``.

Distribution distances (:func:`psi`, :func:`js_divergence`) operate on
two same-config histogram sketches — the drift math of
:mod:`photon_ml_tpu.obs.quality`.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "MomentSketch",
    "HistogramSketch",
    "TopKSketch",
    "coarsen_counts",
    "histogram_add_matrix",
    "moments_add_matrix",
    "psi",
    "js_divergence",
    "psi_and_js",
]


class MomentSketch:
    """Weighted running mean/variance/min/max (Welford, mergeable).

    ``add`` consumes a vector of values (+ optional weights) in one
    numpy pass; ``merge`` combines two sketches exactly (Chan's
    parallel variance update), so per-chunk accumulation commutes with
    single-pass accumulation.
    """

    __slots__ = ("count", "weight", "mean", "m2", "min", "max")

    def __init__(self):
        self.count = 0  # rows observed (unweighted)
        self.weight = 0.0  # total weight
        self.mean = 0.0  # weighted mean
        self.m2 = 0.0  # weighted sum of squared deviations
        self.min = math.inf
        self.max = -math.inf

    def add(self, values, weights=None) -> "MomentSketch":
        v = np.asarray(values, np.float64).ravel()
        if v.size == 0:
            return self
        if weights is None:
            w_sum = float(v.size)
            mean_b = float(v.mean())
            m2_b = float(((v - mean_b) ** 2).sum())
            vmin, vmax = float(v.min()), float(v.max())
            count_b = int(v.size)
        else:
            w = np.asarray(weights, np.float64).ravel()
            live = w > 0.0
            if not live.all():
                # zero-weight rows are padding: invisible to every
                # statistic, min/max included
                v = v[live]
                w = w[live]
            if v.size == 0:
                return self
            w_sum = float(w.sum())
            mean_b = float((v * w).sum() / w_sum)
            m2_b = float((w * (v - mean_b) ** 2).sum())
            vmin, vmax = float(v.min()), float(v.max())
            count_b = int(v.size)
        delta = mean_b - self.mean
        total = self.weight + w_sum
        self.m2 += m2_b + delta * delta * self.weight * w_sum / total
        self.mean += delta * w_sum / total
        self.weight = total
        self.count += count_b
        self.min = min(self.min, vmin)
        self.max = max(self.max, vmax)
        return self

    def merge(self, other: "MomentSketch") -> "MomentSketch":
        if other.weight == 0.0:
            self.count += other.count
            return self
        if self.weight == 0.0:
            self.count += other.count
            self.weight = other.weight
            self.mean = other.mean
            self.m2 = other.m2
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)
            return self
        delta = other.mean - self.mean
        total = self.weight + other.weight
        self.m2 += other.m2 + delta * delta * self.weight * other.weight / total
        self.mean += delta * other.weight / total
        self.weight = total
        self.count += other.count
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    @property
    def variance(self) -> float:
        return self.m2 / self.weight if self.weight > 0 else 0.0

    @property
    def std(self) -> float:
        return math.sqrt(max(self.variance, 0.0))

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "weight": self.weight,
            "mean": self.mean,
            "m2": self.m2,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MomentSketch":
        out = cls()
        out.count = int(d["count"])
        out.weight = float(d["weight"])
        out.mean = float(d["mean"])
        out.m2 = float(d["m2"])
        out.min = math.inf if d.get("min") is None else float(d["min"])
        out.max = -math.inf if d.get("max") is None else float(d["max"])
        return out


# histogram scale configurations; edges depend ONLY on these constants,
# which is what makes per-chunk sketches exactly mergeable
SCALES = ("linear", "symlog")

# symlog default: sign(v) * log10(1 + |v|/x0) covers |v| in
# [0, ~x0*10^span] — x0=1e-3 and span=9 reach ±1e6 with sub-decade bins
DEFAULT_SYMLOG_X0 = 1e-3
DEFAULT_SYMLOG_SPAN = 9.0
DEFAULT_BINS = 64


class HistogramSketch:
    """Fixed-bin weighted histogram with quantile readout.

    ``scale="linear"`` bins ``[lo, hi]`` uniformly; ``scale="symlog"``
    bins the signed log-modulus transform ``sign(v)*log10(1+|v|/x0)``
    over ``[-span, span]`` — symmetric about zero, near-linear below
    ``x0``, logarithmic above, so one configuration covers raw features
    of any sign and magnitude. Two extra bins catch under/overflow.
    Configurations must match to merge (checked).
    """

    __slots__ = ("scale", "lo", "hi", "bins", "x0", "counts", "weight")

    def __init__(
        self,
        scale: str = "symlog",
        lo: float = -DEFAULT_SYMLOG_SPAN,
        hi: float = DEFAULT_SYMLOG_SPAN,
        bins: int = DEFAULT_BINS,
        x0: float = DEFAULT_SYMLOG_X0,
    ):
        if scale not in SCALES:
            raise ValueError(f"scale must be one of {SCALES}: {scale!r}")
        if not (hi > lo) or bins < 1:
            raise ValueError(
                f"need hi > lo and bins >= 1 (got lo={lo}, hi={hi}, "
                f"bins={bins})"
            )
        self.scale = scale
        self.lo = float(lo)
        self.hi = float(hi)
        self.bins = int(bins)
        self.x0 = float(x0)
        self.counts = np.zeros(self.bins + 2, np.float64)
        self.weight = 0.0

    def config(self) -> Tuple:
        return (self.scale, self.lo, self.hi, self.bins, self.x0)

    def _transform(self, v: np.ndarray) -> np.ndarray:
        if self.scale == "linear":
            return v
        return np.sign(v) * np.log10(1.0 + np.abs(v) / self.x0)

    def add(self, values, weights=None) -> "HistogramSketch":
        v = np.asarray(values).ravel()
        if v.dtype.kind != "f":
            v = v.astype(np.float64)
        if v.size == 0:
            return self
        w = (
            np.ones_like(v)
            if weights is None
            else np.asarray(weights, np.float64).ravel()
        )
        t = self._transform(v)
        # bin 0 = underflow, 1..bins = body, bins+1 = overflow; NaN
        # lands in overflow (it is "not where the baseline was") —
        # substituted BEFORE the int cast (NaN->int is undefined)
        t = np.where(np.isnan(t), np.inf, t)
        frac = (t - self.lo) / (self.hi - self.lo)
        idx = np.clip(frac * self.bins, -1.0, float(self.bins))
        idx = np.floor(idx).astype(np.int64) + 1
        np.add.at(self.counts, idx, w)
        self.weight += float(w.sum())
        return self

    def merge(self, other: "HistogramSketch") -> "HistogramSketch":
        if self.config() != other.config():
            raise ValueError(
                f"histogram configs differ: {self.config()} vs "
                f"{other.config()}"
            )
        self.counts += other.counts
        self.weight += other.weight
        return self

    def _edge(self, i: int) -> float:
        """Left edge of body bin ``i`` (0-based) in VALUE space."""
        t = self.lo + (self.hi - self.lo) * i / self.bins
        if self.scale == "linear":
            return t
        return math.copysign(self.x0 * (10.0 ** abs(t) - 1.0), t)

    def quantile(self, q: float) -> float:
        """Weighted quantile by linear interpolation within the winning
        bin (0.0 when empty; under/overflow clamp to the body edges)."""
        if self.weight <= 0.0:
            return 0.0
        target = q * self.weight
        seen = 0.0
        for b, c in enumerate(self.counts):
            if c <= 0.0:
                continue
            if seen + c >= target:
                if b == 0:
                    return self._edge(0)
                if b == self.bins + 1:
                    return self._edge(self.bins)
                f = (target - seen) / c
                left, right = self._edge(b - 1), self._edge(b)
                return left + f * (right - left)
            seen += c
        return self._edge(self.bins)

    def pdf(self) -> np.ndarray:
        """Normalized bin probabilities (uniform when empty — a distance
        against an empty sketch should read as 'no evidence', not inf)."""
        if self.weight <= 0.0:
            return np.full(self.counts.size, 1.0 / self.counts.size)
        return self.counts / self.weight

    def summary(self) -> dict:
        """Compact JSON-safe readout (the serving snapshot shape)."""
        return {
            "count": round(self.weight, 6),
            "p01": round(self.quantile(0.01), 6),
            "p50": round(self.quantile(0.50), 6),
            "p99": round(self.quantile(0.99), 6),
        }

    def to_dict(self) -> dict:
        return {
            "scale": self.scale,
            "lo": self.lo,
            "hi": self.hi,
            "bins": self.bins,
            "x0": self.x0,
            "weight": self.weight,
            "counts": [round(c, 9) for c in self.counts.tolist()],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "HistogramSketch":
        out = cls(
            scale=d["scale"],
            lo=d["lo"],
            hi=d["hi"],
            bins=d["bins"],
            x0=d.get("x0", DEFAULT_SYMLOG_X0),
        )
        counts = np.asarray(d["counts"], np.float64)
        if counts.size != out.counts.size:
            raise ValueError(
                f"histogram counts length {counts.size} != "
                f"{out.counts.size} for bins={out.bins}"
            )
        out.counts = counts
        out.weight = float(d["weight"])
        return out

    # convenience constructors — the two configurations the quality
    # layer standardizes on, so baselines and live sketches always match
    @classmethod
    def for_features(cls) -> "HistogramSketch":
        return cls(scale="symlog")

    @classmethod
    def for_scores(cls) -> "HistogramSketch":
        """Margins/scores live in logit space: linear bins over ±20
        (sigmoid saturates well inside)."""
        return cls(scale="linear", lo=-20.0, hi=20.0)


class TopKSketch:
    """Weighted counters over categorical keys with a bounded readout.

    Counts are exact while distinct-key cardinality stays within
    ``max_keys`` (merges included). Past the cap, the LIGHTEST keys
    spill into an aggregate ``other`` mass deterministically (smallest
    weight first, ties by key), so the readout stays bounded and total
    mass stays conserved.
    """

    __slots__ = ("max_keys", "counts", "other", "weight")

    def __init__(self, max_keys: int = 512):
        if max_keys < 1:
            raise ValueError(f"max_keys must be >= 1, got {max_keys}")
        self.max_keys = int(max_keys)
        self.counts: Dict[str, float] = {}
        self.other = 0.0
        self.weight = 0.0

    def add(self, key, weight: float = 1.0) -> "TopKSketch":
        k = str(key)
        self.counts[k] = self.counts.get(k, 0.0) + float(weight)
        self.weight += float(weight)
        if len(self.counts) > 2 * self.max_keys:
            self._compact()
        return self

    def add_many(self, keys: Sequence, weights=None) -> "TopKSketch":
        if weights is None:
            for k in keys:
                self.add(k)
        else:
            for k, w in zip(keys, weights):
                self.add(k, w)
        return self

    def _compact(self) -> None:
        if len(self.counts) <= self.max_keys:
            return
        ranked = sorted(self.counts.items(), key=lambda kv: (-kv[1], kv[0]))
        for k, w in ranked[self.max_keys :]:
            self.other += w
            del self.counts[k]

    def merge(self, other: "TopKSketch") -> "TopKSketch":
        for k, w in other.counts.items():
            self.counts[k] = self.counts.get(k, 0.0) + w
        self.other += other.other
        self.weight += other.weight
        if len(self.counts) > 2 * self.max_keys:
            self._compact()
        return self

    def top(self, k: int = 10) -> List[Tuple[str, float]]:
        return sorted(
            self.counts.items(), key=lambda kv: (-kv[1], kv[0])
        )[:k]

    def to_dict(self) -> dict:
        self._compact()
        return {
            "max_keys": self.max_keys,
            "weight": self.weight,
            "other": self.other,
            "counts": {
                k: v
                for k, v in sorted(
                    self.counts.items(), key=lambda kv: (-kv[1], kv[0])
                )
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TopKSketch":
        out = cls(max_keys=int(d.get("max_keys", 512)))
        out.counts = {str(k): float(v) for k, v in d["counts"].items()}
        out.other = float(d.get("other", 0.0))
        out.weight = float(d["weight"])
        return out


# ---------------------------------------------------------------------------
# distribution distances over same-config histogram sketches
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# vectorized whole-matrix accumulation (the ingest/serving hot path)
# ---------------------------------------------------------------------------


def histogram_add_matrix(
    hists: Sequence[HistogramSketch],
    matrix,
    weights=None,
    check_configs: bool = True,
) -> None:
    """Fold column ``j`` of a dense (n, d) matrix into ``hists[j]`` —
    ONE transform + ONE bincount for the whole matrix instead of d
    per-column passes (~d× cheaper; the fingerprint/drift hot path).
    Every sketch must share one configuration (checked); the resulting
    counts are bit-identical to d independent :meth:`HistogramSketch.
    add` calls (same floats summed by the same np machinery)."""
    m = np.asarray(matrix)
    if m.dtype.kind != "f":
        m = m.astype(np.float64)
    if m.ndim != 2 or m.shape[0] == 0 or not hists:
        return
    n, d = m.shape
    if d != len(hists):
        raise ValueError(f"{len(hists)} sketches for {d} columns")
    h0 = hists[0]
    if check_configs:
        # callers owning all the sketches (the DriftMonitor's window,
        # built from one config) skip this per-call tuple churn
        cfg = h0.config()
        for h in hists[1:]:
            if h.config() != cfg:
                raise ValueError(
                    f"histogram configs differ: {cfg} vs {h.config()}"
                )
    # transform in the input precision: bin RESOLUTION is ~12% — float32
    # bin assignment is identical except for values within float eps of
    # an edge, and the transform over a large matrix is the hot path
    t = h0._transform(m)
    t = np.where(np.isnan(t), np.inf, t)
    frac = (t - h0.lo) / (h0.hi - h0.lo)
    idx = np.clip(frac * h0.bins, -1.0, float(h0.bins))
    idx = np.floor(idx).astype(np.int64) + 1
    nb = h0.bins + 2
    flat = (idx + np.arange(d, dtype=np.int64)[None, :] * nb).ravel()
    if weights is None:
        counts = np.bincount(flat, minlength=d * nb).astype(np.float64)
        w_sum = float(n)
    else:
        w = np.asarray(weights, np.float64).ravel()
        counts = np.bincount(
            flat, weights=np.repeat(w, d), minlength=d * nb
        )
        w_sum = float(w.sum())
    per_col = counts.reshape(d, nb)
    for j, h in enumerate(hists):
        h.counts += per_col[j]
        h.weight += w_sum


def moments_add_matrix(
    moms: Sequence[MomentSketch], matrix, weights=None
) -> None:
    """Fold column ``j`` of a dense (n, d) matrix into ``moms[j]`` with
    axis-0 vectorized statistics plus a scalar Welford merge per column
    — same math as per-column :meth:`MomentSketch.add`."""
    m = np.asarray(matrix, np.float64)
    if m.ndim != 2 or m.shape[0] == 0 or not moms:
        return
    n, d = m.shape
    if d != len(moms):
        raise ValueError(f"{len(moms)} sketches for {d} columns")
    if weights is None:
        w_sum = float(n)
        means = m.mean(axis=0)
        m2s = ((m - means) ** 2).sum(axis=0)
        mins = m.min(axis=0)
        maxs = m.max(axis=0)
        count = n
    else:
        w = np.asarray(weights, np.float64).ravel()
        live = w > 0.0
        if not live.all():
            m = m[live]
            w = w[live]
        if m.shape[0] == 0:
            return
        w_sum = float(w.sum())
        means = (m * w[:, None]).sum(axis=0) / w_sum
        m2s = (w[:, None] * (m - means) ** 2).sum(axis=0)
        mins = m.min(axis=0)
        maxs = m.max(axis=0)
        count = m.shape[0]
    for j, mo in enumerate(moms):
        mean_b = float(means[j])
        m2_b = float(m2s[j])
        delta = mean_b - mo.mean
        total = mo.weight + w_sum
        mo.m2 += m2_b + delta * delta * mo.weight * w_sum / total
        mo.mean += delta * w_sum / total
        mo.weight = total
        mo.count += count
        mo.min = min(mo.min, float(mins[j]))
        mo.max = max(mo.max, float(maxs[j]))


# PSI/JS computed on the sketches' full bin resolution are dominated by
# sampling noise for small comparison windows (E[PSI] ≈ bins/N between
# two samples of the SAME distribution), so distances coarsen to this
# many body bins by default — the conventional 10–20-bin PSI regime,
# while the stored sketches keep full resolution for quantiles.
DEFAULT_DISTANCE_BINS = 16


def coarsen_counts(h: HistogramSketch, bins: int) -> np.ndarray:
    """The sketch's counts folded to ``bins`` body bins (+ the two
    under/overflow bins) by summing adjacent fine bins. ``bins`` must
    divide the sketch's body resolution — exact, no interpolation."""
    if bins >= h.bins:
        return h.counts
    if h.bins % bins:
        raise ValueError(
            f"cannot coarsen {h.bins} body bins to {bins} (must divide)"
        )
    factor = h.bins // bins
    body = h.counts[1:-1].reshape(bins, factor).sum(axis=1)
    return np.concatenate([h.counts[:1], body, h.counts[-1:]])


def _smoothed_pdfs(
    p: HistogramSketch,
    q: HistogramSketch,
    eps: float,
    bins: Optional[int],
) -> Tuple[np.ndarray, np.ndarray]:
    if p.config() != q.config():
        raise ValueError(
            f"histogram configs differ: {p.config()} vs {q.config()}"
        )
    bins = bins if bins is not None else DEFAULT_DISTANCE_BINS
    ca = coarsen_counts(p, bins)
    cb = coarsen_counts(q, bins)
    a = ca / p.weight if p.weight > 0 else np.full(ca.size, 1.0 / ca.size)
    b = cb / q.weight if q.weight > 0 else np.full(cb.size, 1.0 / cb.size)
    a = np.maximum(a, eps)
    b = np.maximum(b, eps)
    return a / a.sum(), b / b.sum()


def psi(
    p: HistogramSketch,
    q: HistogramSketch,
    eps: float = 1e-4,
    bins: Optional[int] = None,
) -> float:
    """Population stability index Σ (pᵢ−qᵢ)·ln(pᵢ/qᵢ) over ε-clamped,
    coarsened bins (an empty bin on one side must not read as infinite
    drift; fine-bin PSI on a small window is pure sampling noise).
    Conventional reading: <0.1 stable, 0.1–0.25 moderate shift, >0.25
    action-worthy — the default alarm threshold of the DriftMonitor."""
    a, b = _smoothed_pdfs(p, q, eps, bins)
    return float(np.sum((a - b) * np.log(a / b)))


def js_divergence(
    p: HistogramSketch,
    q: HistogramSketch,
    eps: float = 1e-4,
    bins: Optional[int] = None,
) -> float:
    """Jensen–Shannon divergence (base-2; bounded [0, 1]) — the
    symmetric, bounded companion to PSI for dashboards."""
    a, b = _smoothed_pdfs(p, q, eps, bins)
    m = 0.5 * (a + b)
    kl_am = float(np.sum(a * np.log2(a / m)))
    kl_bm = float(np.sum(b * np.log2(b / m)))
    return 0.5 * (kl_am + kl_bm)


def psi_and_js(
    p: HistogramSketch,
    q: HistogramSketch,
    eps: float = 1e-4,
    bins: Optional[int] = None,
) -> Tuple[float, float]:
    """(PSI, JS) in one pass over shared smoothed pdfs — the drift
    check computes both per feature, and the coarsen/normalize work is
    the expensive half."""
    a, b = _smoothed_pdfs(p, q, eps, bins)
    ratio = a / b
    psi_v = float(np.sum((a - b) * np.log(ratio)))
    m = 0.5 * (a + b)
    js_v = 0.5 * (
        float(np.sum(a * np.log2(a / m)))
        + float(np.sum(b * np.log2(b / m)))
    )
    return psi_v, js_v
