"""Bench regression sentinel: machine-checked perf baselines.

Five BENCH records exist (``BENCH_r01..r05``) with order-of-magnitude
swings between rounds (GAME CD 1.19 -> 10.1 iters/s), yet nothing
machine-checks that the NEXT change doesn't silently give those wins
back — the records were write-only history. This module turns them into
a gate:

- :func:`flatten_record` maps one parsed BENCH record (the
  ``{"metric", "value", ..., "extra": {...}}`` JSON line) to flat dotted
  numeric metrics.
- :func:`metric_direction` classifies each metric as higher-is-better
  (throughput, speedup ratios, MFU, AUC), lower-is-better (wall clocks,
  per-device footprints, collective counts), or untracked (environment
  noise: tunnel RTT, phase walls, registry dumps — regressions there are
  not code regressions).
- :func:`fit_baselines` fits a noise-tolerant baseline per metric over
  the history: median plus a tolerance band widened by the metric's own
  historical dispersion (MAD-scaled), so a metric that legitimately
  swings across rounds gets a wide band instead of a false alarm, while
  a historically-stable metric is held tight.
- :func:`check_record` compares a current record against the baselines
  and returns the regressions (direction-aware). New metrics and missing
  metrics are tolerated — growth must not be penalized.

``benchmarks/regression_sentinel.py`` is the CLI (standalone / CI);
``bench.py --sentinel`` runs the same check on the record it just
produced.
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform as _platform
import re
import statistics
from typing import Dict, List, Optional, Sequence

__all__ = [
    "flatten_record",
    "metric_direction",
    "metric_floor",
    "host_fingerprint",
    "env_change_note",
    "Baseline",
    "Regression",
    "fit_baselines",
    "check_record",
    "load_bench_record",
    "run_sentinel",
]

# Defaults tuned on the real BENCH_r01..r05 history: every metric of r05
# passes against the r01..r04 baseline, while a uniform 30% degradation
# of r05's tracked throughput/wall metrics is flagged.
DEFAULT_TOLERANCE = 0.25
DEFAULT_MAD_K = 4.0
DEFAULT_MIN_SAMPLES = 2

HIGHER_IS_BETTER = 1
LOWER_IS_BETTER = -1
UNTRACKED = 0

# First match wins; order: untracked overrides, then higher, then lower.
_DIRECTION_RULES = (
    # environment / identity noise, not code performance. host.* is the
    # environment FINGERPRINT (host_fingerprint below): identity, never
    # a metric — but run_sentinel uses it to annotate regressions that
    # coincide with an environment change vs the history
    (re.compile(r"(^|\.)host\."), UNTRACKED),
    (re.compile(r"(^|\.)rtt_ms"), UNTRACKED),
    (re.compile(r"dense_wall_incl_rtt_s$"), UNTRACKED),
    (re.compile(r"max_dw"), UNTRACKED),
    (re.compile(r"transfer_gb$"), UNTRACKED),
    (re.compile(r"(^|\.)phase_s\."), UNTRACKED),
    (re.compile(r"(^|\.)metrics\."), UNTRACKED),
    (re.compile(r"(^|\.)cost_book\."), UNTRACKED),
    (re.compile(r"predicted_over_observed$"), UNTRACKED),
    # bigger is better
    (
        re.compile(
            r"(vs_baseline|vs_cpu|vs_sklearn|vs_python_codec|vs_ell"
            r"|speedup)$"
        ),
        HIGHER_IS_BETTER,
    ),
    (re.compile(r"scaling_efficiency$"), HIGHER_IS_BETTER),
    # overlap-scaled collectives (docs/PARALLEL.md, bench_overlap): the
    # share of a sharded objective pass's wall spent on (or exposed by)
    # the feature-space reduction — the DIRECT overlap gate
    # (scaling_efficiency only infers it). Lower = more of the
    # collective hidden under compute / less partition overhead.
    (re.compile(r"collective_wall_frac"), LOWER_IS_BETTER),
    (re.compile(r"\.wall_frac$"), LOWER_IS_BETTER),
    (re.compile(r"(iters_per_s|rec_per_s|per_s)$"), HIGHER_IS_BETTER),
    # ingest pipeline (docs/INGEST.md): host->device bandwidth and the
    # counted-stage overlap fraction rise as the feed improves; the
    # epoch stall fraction (consumer time NOT covered by device math)
    # falls. These gate the decode/transfer/solve overlap directly —
    # wall clocks on a timeshared bench host cannot. The _gbps rule also
    # tracks ckpt_shard_write_gbps (bench_multihost_resilience): the
    # per-process sharded checkpoint write path must not slow down.
    (re.compile(r"_gbps$"), HIGHER_IS_BETTER),
    # elastic multi-host resilience (docs/MULTIHOST.md): the wall from a
    # stalled collective to a clean retried exchange (watchdog deadline
    # + backoff + redo) — explicit rather than via the generic _s rule
    # so the recovery contract stays gated even if the generic ever
    # narrows
    (re.compile(r"recovery_s$"), LOWER_IS_BETTER),
    (re.compile(r"overlap_frac$"), HIGHER_IS_BETTER),
    (re.compile(r"stall_frac$"), LOWER_IS_BETTER),
    # chaos-hardened serving (docs/ROBUSTNESS.md, bench_overload): the
    # fraction of a FIXED offered overload turned away (expired + shed +
    # rejected) falls as the serving path gets faster/smarter; the
    # companion p99_under_overload_ms / breaker_recovery_s gate through
    # the generic _ms/_s lower-is-better rules below
    (re.compile(r"shed_frac$"), LOWER_IS_BETTER),
    # entity-sharded serving + tiered entity cache (docs/SERVING.md,
    # bench_serving_sharded): sustained throughput of the sharded and
    # cache-tier hit paths, the cache hit fraction under the Zipf load
    # the tier exists for, and the per-process resident RE-table
    # footprint (the ~P x drop mesh partitioning buys — creep here is
    # the capacity regression wall clocks cannot see)
    (re.compile(r"_qps$"), HIGHER_IS_BETTER),
    (re.compile(r"hit_frac$"), HIGHER_IS_BETTER),
    (re.compile(r"resident.*bytes"), LOWER_IS_BETTER),
    # model-quality observability (docs/OBSERVABILITY.md "Quality &
    # drift", bench_quality): the serving path's wall with the
    # DriftMonitor sampling vs without (creep here is the quality
    # layer's tax growing), and how many offered requests / how much
    # wall a real covariate shift needs before drift.alarm fires — the
    # retrain-loop trigger must not get slower to notice. The
    # companion sketch_rows_per_s gates through the generic per_s rule.
    (re.compile(r"overhead_ratio$"), LOWER_IS_BETTER),
    (re.compile(r"drift_alarm_latency"), LOWER_IS_BETTER),
    # self-healing loop (docs/LIFECYCLE.md): alarm-to-reload wall for a
    # full retrain cycle — the mean-time-to-recover of the serving
    # fleet after a confirmed drift; auc_recovered gates through the
    # generic auc rule below
    (re.compile(r"retrain_cycle_s$"), LOWER_IS_BETTER),
    # serving fabric (docs/FRONTEND.md, bench_frontend): wall from a
    # whole-replica loss to the router's first successful failover —
    # the fleet's blast-radius clock; explicit (like recovery_s) so
    # the failover contract stays gated independent of the generic
    # _s rule
    (re.compile(r"failover_s$"), LOWER_IS_BETTER),
    # photon-lint self-hosting gate (docs/ANALYSIS.md): total findings
    # over the tree — NEW findings already fail the lint itself, so
    # what this tracks is ratchet debt (baselined + suppressed) creep;
    # the companion lint_wall_s gates through the generic _s rule
    (re.compile(r"lint_findings_total$"), LOWER_IS_BETTER),
    (re.compile(r"(^|\.)mfu$"), HIGHER_IS_BETTER),
    (re.compile(r"hbm_util$"), HIGHER_IS_BETTER),
    (re.compile(r"achieved_tflops$"), HIGHER_IS_BETTER),
    (re.compile(r"auc"), HIGHER_IS_BETTER),
    # smaller is better
    # convergence health (bench_game's decoded fleet summaries): more
    # iterations to converge or a larger non-converged fraction is a
    # solver-quality regression even when wall clocks hold steady
    (re.compile(r"(^|\.)convergence\.median_iters$"), LOWER_IS_BETTER),
    (
        re.compile(r"(^|\.)convergence\.nonconverged_frac$"),
        LOWER_IS_BETTER,
    ),
    # dispatch economy (ROADMAP item 1, device-resident loops): host
    # round trips per training unit — a creeping dispatch count is the
    # latency regression wall clocks on a timeshared bench host cannot
    # see, so it gates directly and tunnel-invariantly
    (
        re.compile(r"(^|\.)dispatches_per_(path|run|solve)$"),
        LOWER_IS_BETTER,
    ),
    (re.compile(r"(^|\.)game_dispatches_per_run$"), LOWER_IS_BETTER),
    (re.compile(r"(^|\.)dispatches$"), LOWER_IS_BETTER),
    (re.compile(r"(_s|_ms|_mb|_kb|_m)$"), LOWER_IS_BETTER),
    (re.compile(r"(^|\.)passes$"), LOWER_IS_BETTER),
    (re.compile(r"^value$"), LOWER_IS_BETTER),
    (re.compile(r"collectives\."), LOWER_IS_BETTER),
    (re.compile(r"bytes"), LOWER_IS_BETTER),
)


def metric_direction(name: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 untracked."""
    for pattern, direction in _DIRECTION_RULES:
        if pattern.search(name):
            return direction
    return UNTRACKED


# Absolute floors: metrics whose minimum acceptable value is known a
# priori, gated on the CURRENT record alone — no history needed, so the
# gate binds from the very first record that carries the metric (the
# MAD band needs >= min_samples history records first). The multi-device
# scaling efficiency wall_1dev/(N*wall_Ndev) has an honest ceiling of
# ~1/N on the timeshared-CPU bench host (virtual devices share one
# core, wall cannot drop). Through BENCH_r06 the floor was the
# bind-with-zero-history 0.25/N rule — a quarter of the ceiling, i.e.
# "the 2-device regression is back" alarm. With the overlap-scaled path
# landed (PHOTON_COLLECTIVE_MODE=overlap: row-balanced blocking +
# chunked reduce-scatter pipeline, docs/PARALLEL.md) the floors are
# ABSOLUTE per-width targets ~2x higher, set from the measured r07 tree
# (0.32-0.38 / 0.15-0.17 / 0.07-0.09 across bench-box load levels) with
# ~25% headroom for the box's timeshare noise; on ICI hardware (where
# the async collectives actually overlap compute) widths should clear
# these with a wide margin, and the floors should be raised again from
# pod measurements.
_SCALING_FLOORS = {2: 0.25, 4: 0.12, 8: 0.055}
_FLOOR_RULES = (
    (
        re.compile(r"sparse_fs_scaling\.(\d+)\.scaling_efficiency$"),
        lambda m: _SCALING_FLOORS.get(
            int(m.group(1)), 0.25 / int(m.group(1))
        ),
    ),
)


def metric_floor(name: str) -> Optional[float]:
    """The absolute floor for ``name``, or None when only the relative
    history band applies."""
    for pattern, fn in _FLOOR_RULES:
        m = pattern.search(name)
        if m:
            return fn(m)
    return None


def host_fingerprint() -> Dict[str, object]:
    """The environment identity stamped into every BENCH record's
    ``extra.host``: enough to tell "the code regressed" apart from "the
    bench box changed under us". Never initializes a jax backend — the
    version string is importable without one."""
    try:
        import jax

        jax_version = jax.__version__
    except Exception:  # noqa: BLE001 — fingerprints must never fail
        jax_version = None
    return {
        "cpu_count": os.cpu_count(),
        "platform": _platform.platform(),
        "machine": _platform.machine(),
        "python": _platform.python_version(),
        "jax": jax_version,
    }


def env_change_note(history: Sequence[dict], current: dict) -> str:
    """Human-readable diff of ``current``'s host fingerprint vs the most
    recent history record that carries one; ``""`` when nothing changed
    or no fingerprinted history exists. ``history``/``current`` are RAW
    parsed BENCH records (not flattened — flattening drops the strings
    the fingerprint mostly consists of)."""
    cur_fp = (current.get("extra") or {}).get("host")
    if not isinstance(cur_fp, dict):
        return ""
    prev_fp = None
    for rec in reversed(list(history)):
        fp = (rec.get("extra") or {}).get("host")
        if isinstance(fp, dict):
            prev_fp = fp
            break
    if prev_fp is None:
        return ""
    changes = []
    for key in sorted(set(prev_fp) | set(cur_fp)):
        if prev_fp.get(key) != cur_fp.get(key):
            changes.append(
                f"{key} {prev_fp.get(key)!r}->{cur_fp.get(key)!r}"
            )
    return ", ".join(changes)


def flatten_record(parsed: dict) -> Dict[str, float]:
    """Parsed BENCH record -> flat ``{dotted.metric: float}``. ``value``
    keeps its name; ``extra`` flattens recursively; non-numeric leaves
    (metric name, unit, strings) are dropped. Booleans are excluded —
    ``True`` is not a measurement."""
    out: Dict[str, float] = {}

    def walk(prefix: str, obj) -> None:
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
            out[prefix] = float(obj)

    if "value" in parsed:
        walk("value", parsed["value"])
    walk("extra", parsed.get("extra") or {})
    return out


@dataclasses.dataclass(frozen=True)
class Baseline:
    """Per-metric fitted baseline: history median plus a relative
    tolerance band (``tol``), direction-aware."""

    metric: str
    median: float
    tol: float
    direction: int
    n_samples: int

    def bound(self) -> float:
        """The worst still-acceptable value."""
        if self.direction == HIGHER_IS_BETTER:
            return self.median * (1.0 - self.tol)
        return self.median * (1.0 + self.tol)


@dataclasses.dataclass(frozen=True)
class Regression:
    metric: str
    current: float
    baseline: Baseline
    # non-empty when the record's host fingerprint differs from the
    # history's — the regression may be the box, not the code
    env_note: str = ""

    def describe(self) -> str:
        arrow = (
            "below" if self.baseline.direction == HIGHER_IS_BETTER else "above"
        )
        out = (
            f"{self.metric}: {self.current:g} is {arrow} the tolerated "
            f"bound {self.baseline.bound():g} (median {self.baseline.median:g}"
            f" over {self.baseline.n_samples} records, band "
            f"±{self.baseline.tol:.0%})"
        )
        if self.env_note:
            out += f" [environment changed vs history: {self.env_note}]"
        return out


def fit_baselines(
    history: Sequence[Dict[str, float]],
    min_samples: int = DEFAULT_MIN_SAMPLES,
    tolerance: float = DEFAULT_TOLERANCE,
    mad_k: float = DEFAULT_MAD_K,
) -> Dict[str, Baseline]:
    """Fit per-metric baselines over flattened history records.

    The band is ``max(tolerance, mad_k * MAD/|median|)``: the floor
    absorbs run-to-run noise every metric has; the MAD term widens the
    band for metrics whose own history swings (a metric that moved 10x
    between rounds cannot honestly gate a 30% change). Metrics seen in
    fewer than ``min_samples`` records, with a ~zero median, or
    classified untracked get no baseline.
    """
    samples: Dict[str, List[float]] = {}
    for rec in history:
        for name, value in rec.items():
            samples.setdefault(name, []).append(value)
    out: Dict[str, Baseline] = {}
    for name, vals in samples.items():
        direction = metric_direction(name)
        if direction == UNTRACKED or len(vals) < min_samples:
            continue
        med = statistics.median(vals)
        if abs(med) < 1e-12:
            continue  # relative bands are meaningless at zero
        mad = statistics.median(abs(v - med) for v in vals)
        tol = max(tolerance, mad_k * mad / abs(med))
        out[name] = Baseline(
            metric=name,
            median=med,
            tol=tol,
            direction=direction,
            n_samples=len(vals),
        )
    return out


def check_record(
    current: Dict[str, float], baselines: Dict[str, Baseline]
) -> List[Regression]:
    """Regressions of ``current`` vs fitted baselines, worst first.
    Metrics absent from either side are tolerated (renames and new
    instrumentation must not fail the gate). Metrics with an absolute
    floor (:func:`metric_floor`) are additionally gated against it —
    history or not."""
    regs: List[Regression] = []
    flagged = set()
    for name, base in baselines.items():
        cur = current.get(name)
        if cur is None:
            continue
        if base.direction == HIGHER_IS_BETTER:
            bad = cur < base.bound()
        else:
            bad = cur > base.bound()
        if bad:
            regs.append(Regression(metric=name, current=cur, baseline=base))
            flagged.add(name)
    for name, cur in current.items():
        floor = metric_floor(name)
        if floor is None or name in flagged or cur >= floor:
            continue
        regs.append(
            Regression(
                metric=name,
                current=cur,
                baseline=Baseline(
                    metric=name,
                    median=floor,
                    tol=0.0,
                    direction=HIGHER_IS_BETTER,
                    n_samples=0,
                ),
            )
        )
    regs.sort(
        key=lambda r: -(
            abs(r.current - r.baseline.median) / abs(r.baseline.median)
        )
    )
    return regs


def load_bench_record(path: str) -> Optional[dict]:
    """The ``parsed`` record of one BENCH_*.json file (None when the
    round failed or the file predates the parsed field)."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        if doc.get("rc", 0) == 0:
            return doc["parsed"]
        return None
    # a bare record (bench.py's own output) is accepted as-is
    if isinstance(doc, dict) and "extra" in doc:
        return doc
    return None


def run_sentinel(
    history_paths: Sequence[str],
    current: dict,
    min_samples: int = DEFAULT_MIN_SAMPLES,
    tolerance: float = DEFAULT_TOLERANCE,
    mad_k: float = DEFAULT_MAD_K,
):
    """History files + a current parsed record -> (regressions,
    fitted baselines, n_history_records). Regressions carry an
    ``env_note`` when the current host fingerprint (``extra.host``)
    differs from the history's — a flag that may be the box, not the
    code."""
    raw_history = []
    for p in history_paths:
        rec = load_bench_record(p)
        if rec is not None:
            raw_history.append(rec)
    baselines = fit_baselines(
        [flatten_record(r) for r in raw_history],
        min_samples=min_samples,
        tolerance=tolerance,
        mad_k=mad_k,
    )
    regs = check_record(flatten_record(current), baselines)
    note = env_change_note(raw_history, current)
    if note:
        regs = [dataclasses.replace(r, env_note=note) for r in regs]
    return regs, baselines, len(raw_history)
