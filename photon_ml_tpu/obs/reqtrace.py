"""Request-causality layer: trace ids, hop notes, timeline rebuild.

The serving fabric (docs/FRONTEND.md) answers every request through five
independently-instrumented layers — wire frame, tenant admission, the
micro-batcher's coalescing window, a replica hop (possibly several, when
a breaker forces failover), and the sharded engine's device call. Each
layer already records spans/events, but nothing tied them together: a
slow request was five disconnected log lines. This module is the Dapper
seam:

- **Trace ids.** :func:`ensure_trace_id` accepts a client-supplied
  ``trace`` field from the wire envelope (validated — the id crosses
  process boundaries and lands in filenames/JSONL, so the alphabet is
  closed) or issues a fresh one. The id rides the tenant envelope and
  the batcher item; the batcher stamps it on the per-request
  ``serving.request`` retro-span, whose ``batch_id`` is the join key the
  batch-scoped spans below it (``serving.score``, ``replica.hop``,
  ``serving.cache.miss``) already carry via the ambient span context.
- **Hop notes.** :func:`collect_notes` opens a thread-local channel for
  the duration of one batch score call; :func:`note` (called by
  :class:`~photon_ml_tpu.frontend.replicas.ReplicaRouter` per attempt)
  reports replica hops upward without the router needing a reference to
  the batcher — that is how a request's retro-span learns it was
  failover-touched, which in turn drives 100%-keep exemplar sampling
  (obs/exemplars.py).
- **Timeline rebuild.** :func:`reconstruct_timeline` inverts the join
  offline: given ``events.jsonl`` records (one shard, or several merged
  by ``photon-obs merge`` / :func:`obs.dist.merge_events_shards`) and a
  trace id, it returns the causal timeline — wire-read, queue wait,
  batch assembly, replica hop(s), device call, merge, reply-write — with
  failover/degraded/truncation flags. ``photon-obs request`` renders it;
  the ``trace_loss`` chaos drill asserts over it.

Pure stdlib on purpose: importable from CPU-only subprocesses and before
backend selection, like :mod:`obs.trace`.
"""

from __future__ import annotations

import itertools
import os
import re
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "new_trace_id",
    "valid_trace_id",
    "ensure_trace_id",
    "collect_notes",
    "note",
    "trace_ids",
    "reconstruct_timeline",
    "find_orphans",
]

# Client-supplied ids: bounded length, closed alphabet. Anything else is
# replaced (never errored — a malformed trace id must not fail scoring).
_TRACE_RE = re.compile(r"^[A-Za-z0-9._:-]{1,64}$")

# Issued ids: 8 random bytes + a process-wide sequence. The random half
# keeps ids unique across processes/restarts without coordination; the
# sequence half keeps them unique within a process even if the entropy
# source ever repeats.
_SEQ = itertools.count(1)


def new_trace_id() -> str:
    return f"{os.urandom(8).hex()}-{next(_SEQ):x}"


def valid_trace_id(candidate: Any) -> bool:
    return isinstance(candidate, str) and bool(_TRACE_RE.match(candidate))


def ensure_trace_id(candidate: Any) -> Tuple[str, bool]:
    """``(trace_id, issued)``: the validated client id, or a fresh one
    (``issued=True``) when the client sent none — or sent garbage."""
    if valid_trace_id(candidate):
        return candidate, False
    return new_trace_id(), True


# ---------------------------------------------------------------------------
# Hop notes: router -> batcher, per batch, without a reference cycle
# ---------------------------------------------------------------------------

_notes = threading.local()


class collect_notes:
    """Context manager opening a per-thread note channel for one batch
    score call. The list it yields accumulates every :func:`note` made
    on this thread inside the block — the replica router's hop reports.
    Nesting replaces the channel for the inner block (batchers never
    nest, but a drill's direct ``score`` call inside a traced flush must
    not corrupt the outer channel)."""

    __slots__ = ("_prev", "notes")

    def __enter__(self) -> List[Dict[str, Any]]:
        self._prev = getattr(_notes, "active", None)
        self.notes = _notes.active = []
        return self.notes

    def __exit__(self, *exc) -> bool:
        _notes.active = self._prev
        return False


def note(**fields) -> None:
    """Report one hop/annotation to the collecting batcher, if any is
    listening on this thread. No-op otherwise — callers never check."""
    active = getattr(_notes, "active", None)
    if active is not None:
        active.append(fields)


# ---------------------------------------------------------------------------
# Offline timeline reconstruction
# ---------------------------------------------------------------------------

# Records joined into a timeline via the batch id (batch-scoped work the
# per-request span shares with its batchmates).
_BATCH_SCOPED = (
    "serving.score",
    "serving.route.merge",
    "serving.route.group",
    "serving.route.pad",
    "replica.hop",
    "serving.cache.miss",
    "serving.cache.promotion",
    "replica.down",
)


def trace_ids(records: Iterable[Dict[str, Any]]) -> List[str]:
    """Every distinct trace id present, in first-seen order."""
    seen: Dict[str, None] = {}
    for rec in records:
        tid = rec.get("trace")
        if isinstance(tid, str) and tid not in seen:
            seen[tid] = None
    return list(seen)


def _segments(request_span: Dict[str, Any],
              events: Sequence[Dict[str, Any]]) -> Dict[str, float]:
    """The no-unattributed-gap decomposition: wire read and queue/
    assembly/device from the retro-span's args, reply write from the
    frontend's own retro-span."""
    seg: Dict[str, float] = {}
    for key in ("wire_read_ms", "queue_wait_ms", "assembly_ms",
                "device_ms"):
        val = request_span.get(key)
        if isinstance(val, (int, float)):
            seg[key] = float(val)
    for rec in events:
        if rec.get("name") == "frontend.reply_write":
            seg["reply_write_ms"] = float(rec.get("duration_ms", 0.0))
    return seg


def reconstruct_timeline(
    records: Sequence[Dict[str, Any]], trace_id: str
) -> Optional[Dict[str, Any]]:
    """One trace id -> its causal timeline, or None when the id appears
    nowhere.

    Two-phase join: (1) records explicitly stamped with the trace id
    (frontend wire spans, the per-request ``serving.request`` span);
    (2) batch-scoped records sharing a ``batch_id`` with phase 1 — but
    never a record stamped with a DIFFERENT trace (a batchmate's
    ``serving.request`` is its own timeline's, not ours). Events come
    back sorted by ``time_unix``.
    """
    own = [r for r in records if r.get("trace") == trace_id]
    if not own:
        return None
    batch_ids = {
        r["batch_id"] for r in own
        if isinstance(r.get("batch_id"), int)
    }
    shared = [
        r for r in records
        if r.get("trace") is None
        and r.get("batch_id") in batch_ids
        and r.get("name") in _BATCH_SCOPED
    ]
    events = sorted(
        own + shared, key=lambda r: r.get("time_unix", 0.0)
    )
    request_spans = [
        r for r in own if r.get("name") == "serving.request"
    ]
    # an error-marked retro-span (the batcher's failed-batch path) links
    # the batch for the join but does NOT complete the timeline
    ok_spans = [r for r in request_spans if not r.get("error")]
    hops = [
        {
            "replica": r.get("replica"),
            "attempt": r.get("attempt"),
            "error": bool(r.get("error")),
            "host": r.get("host"),
        }
        for r in events
        if r.get("name") == "replica.hop"
    ]
    cache_misses = sum(
        int(r.get("misses", 0)) for r in events
        if r.get("name") == "serving.cache.miss"
    )
    complete = bool(ok_spans)
    first = ok_spans[0] if ok_spans else (
        request_spans[0] if request_spans else {}
    )
    timeline = {
        "trace": trace_id,
        "complete": complete,
        # seen at the frontend / in-flight but never scored: a replica
        # kill (or shed/expiry) truncated it — the photo of a request
        # the fabric lost, explicitly marked instead of orphaned
        "truncated": not complete,
        "failover": any(h["error"] for h in hops) or bool(
            first.get("failover")
        ),
        "degraded": bool(first.get("degraded")),
        "error": first.get("error"),
        "request_id": first.get("request_id"),
        "batch_ids": sorted(batch_ids),
        "hops": hops,
        "cache_misses": cache_misses,
        "segments": _segments(first, events),
        "hosts": sorted({
            r["host"] for r in events if isinstance(r.get("host"), int)
        }),
        "events": events,
    }
    return timeline


def find_orphans(
    records: Sequence[Dict[str, Any]],
    timelines: Sequence[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Request-scoped records claimed by NO reconstructed timeline —
    the ``trace_loss`` drill's zero-orphan assertion. A record is
    request-scoped when it carries a ``trace`` or names batch-scoped
    work with a ``batch_id``."""
    claimed_traces = {t["trace"] for t in timelines}
    claimed_batches = set()
    for t in timelines:
        claimed_batches.update(t.get("batch_ids", ()))
    orphans = []
    for rec in records:
        tid = rec.get("trace")
        if tid is not None:
            if tid not in claimed_traces:
                orphans.append(rec)
            continue
        if (
            rec.get("name") in _BATCH_SCOPED
            and isinstance(rec.get("batch_id"), int)
            and rec["batch_id"] not in claimed_batches
        ):
            orphans.append(rec)
    return orphans
