"""Pod-level (multi-process) observability: identity, clock sync, merging.

Everything the obs layer built so far is strictly per-process: each host
of a pod writes its own ``trace.json`` / ``events.jsonl`` / ``metrics.json``
with its own monotonic epoch, and nothing relates host 3's coordinate
pass to the all-reduce host 0 was blocked in at the same instant. The
GAME workload only makes sense at multi-host scale ("hundreds of
billions of coefficients" sharded across a cluster), so this module adds
the three missing pieces:

- **Process identity.** :func:`process_identity` resolves this process's
  ``(index, count)`` — explicitly set by ``parallel.multihost`` after
  ``jax.distributed.initialize``, or from the ``PHOTON_PROCESS_INDEX`` /
  ``PHOTON_PROCESS_COUNT`` (and ``JAX_PROCESS_ID`` / ``JAX_NUM_PROCESSES``)
  environment variables. The tracer stamps it on every artifact: the
  Chrome ``pid`` becomes the process index (distinct Perfetto tracks),
  the process-name metadata gains a ``host.<i>`` label, JSONL records
  carry a ``host`` field, and :func:`host_metric_prefix` gives merged
  metrics their ``host.<i>.`` namespace. Deliberately env-and-explicit
  only — resolving identity must never initialize a jax backend (the
  tracer is importable from CPU-only subprocesses).

- **Clock sync.** Per-process trace timestamps are microseconds since
  each tracer's OWN ``perf_counter`` epoch; two shards cannot be laid on
  one timeline without a common instant. :func:`emit_clock_sync` records
  a ``clock.sync`` instant event — optionally behind a caller-supplied
  barrier (``multihost.initialize_multihost`` passes
  ``sync_global_devices``), so every process's sync event marks the SAME
  wall instant regardless of host clock skew.

- **Shard merging.** :func:`merge_trace_shards` folds per-process
  ``trace.json`` documents into ONE Perfetto-loadable pod trace:
  per-shard clocks are aligned at the shared ``clock.sync`` event
  (fallback: the ``epoch_unix`` metadata when a shard predates sync
  events or crashed before emitting one), pids are rewritten to process
  indices with fresh ``process_name``/``process_sort_index`` metadata,
  exact-duplicate events (re-read shards, duplicated span ids) are
  dropped, and the result is ts-sorted and normalized to a non-negative
  origin. Truncated or missing shards are SKIPPED with a warning, never
  fatal — a post-mortem merge must work with whatever survived.
  ``cli/obs_tools.py`` (``photon-obs merge``) is the operator surface.

Pure stdlib, like the tracer: mergeable on any host, no jax required.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from photon_ml_tpu.obs.trace import get_tracer

__all__ = [
    "SYNC_EVENT_NAME",
    "process_identity",
    "set_process_identity",
    "host_metric_prefix",
    "emit_clock_sync",
    "load_trace_shard",
    "merge_trace_shards",
    "merge_events_shards",
    "merge_metrics_shards",
]

SYNC_EVENT_NAME = "clock.sync"

# explicit identity (set by parallel.multihost after the distributed
# runtime joins, or by tests); None = fall back to the environment
_identity: Optional[Tuple[int, int]] = None


def set_process_identity(index: int, count: int) -> None:
    """Pin this process's pod identity for every obs artifact. Tracers
    constructed AFTER this call stamp it; ``parallel.multihost`` calls it
    the moment the distributed runtime joins."""
    global _identity
    if count <= 0:
        raise ValueError(f"process_count must be positive, got {count}")
    if not (0 <= index < count):
        raise ValueError(f"process_index {index} outside [0, {count})")
    _identity = (int(index), int(count))


def process_identity() -> Tuple[int, int]:
    """``(process_index, process_count)`` — explicit identity if set,
    else the PHOTON_PROCESS_* / JAX_* environment, else ``(0, 1)``.
    Never touches a jax backend."""
    if _identity is not None:
        return _identity
    env = os.environ
    idx = env.get("PHOTON_PROCESS_INDEX", env.get("JAX_PROCESS_ID"))
    cnt = env.get("PHOTON_PROCESS_COUNT", env.get("JAX_NUM_PROCESSES"))
    try:
        if cnt is not None and int(cnt) > 1:
            return (int(idx or 0), int(cnt))
    except ValueError:
        pass
    return (0, 1)


def host_metric_prefix(index: Optional[int] = None) -> str:
    """``"host.<i>."`` in a multi-process run, ``""`` single-process —
    the namespace merged pod metrics live under."""
    idx, count = process_identity()
    if index is not None:
        return f"host.{index}."
    return f"host.{idx}." if count > 1 else ""


def emit_clock_sync(sync_id: str = "startup", barrier=None) -> None:
    """Record a ``clock.sync`` instant event on the active tracer.

    With ``barrier`` (a callable; ``multihost`` passes
    ``sync_global_devices``) every process blocks until all peers arrive,
    so the events mark one shared wall instant — the anchor
    :func:`merge_trace_shards` aligns per-shard clocks on. Instant events
    flush immediately, so the sync marker survives a later crash. No-op
    untraced."""
    tracer = get_tracer()
    if tracer is None:
        return
    if barrier is not None:
        barrier()
    idx, count = process_identity()
    tracer.add_instant(
        SYNC_EVENT_NAME,
        cat="dist",
        args={
            "sync_id": sync_id,
            "unix_time": time.time(),
            "process_index": idx,
            "process_count": count,
        },
    )


# ---------------------------------------------------------------------------
# Shard merging
# ---------------------------------------------------------------------------


def load_trace_shard(path: str) -> Tuple[Optional[dict], Optional[str]]:
    """Read one shard's ``trace.json``. Returns ``(doc, warning)`` —
    exactly one is None. A directory resolves to ``<dir>/trace.json``.
    Missing, unreadable, truncated, or shape-invalid files are a warning,
    not an exception: merges run during post-mortems."""
    if os.path.isdir(path):
        path = os.path.join(path, "trace.json")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        return None, f"{path}: unreadable ({e})"
    except json.JSONDecodeError as e:
        return None, f"{path}: truncated/corrupt trace JSON ({e})"
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        return None, f"{path}: not a Chrome trace-event document"
    return doc, None


def _shard_sync_events(doc: dict) -> Dict[str, dict]:
    """sync_id -> first matching ``clock.sync`` event of one shard."""
    out: Dict[str, dict] = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("name") == SYNC_EVENT_NAME and ev.get("ph") == "i":
            sid = str((ev.get("args") or {}).get("sync_id", ""))
            out.setdefault(sid, ev)
    return out


def _pick_sync_id(per_shard: Sequence[Dict[str, dict]]) -> Optional[str]:
    """The sync_id to align on: present in the most shards, ties broken
    toward ``"startup"`` (the barrier-backed one)."""
    counts: Dict[str, int] = {}
    for syncs in per_shard:
        for sid in syncs:
            counts[sid] = counts.get(sid, 0) + 1
    if not counts:
        return None
    best = max(counts.values())
    candidates = sorted(s for s, c in counts.items() if c == best)
    if "startup" in candidates:
        return "startup"
    return candidates[0]


def _dedupe_key(ev: dict) -> tuple:
    """Identity of one event for duplicate dropping: phase, name, track,
    window, (for async/flow phases) the explicit id, and the request id
    / trace id when the span carries one in args. Re-read shards and
    duplicated span ids collapse; distinct same-name spans at different
    instants survive — and two replicas' ``serving.request`` spans that
    happen to share a (pid, tid, ts, dur) window are kept apart by their
    instance-namespaced request ids (or the frontend's per-request trace
    ids, which wire_read/reply_write spans carry instead) rather than
    being wrongly collapsed."""
    args = ev.get("args") or {}
    return (
        ev.get("ph"),
        ev.get("name"),
        ev.get("pid"),
        ev.get("tid"),
        round(float(ev.get("ts", 0.0)), 3),
        round(float(ev.get("dur", 0.0)), 3),
        ev.get("id"),
        args.get("request_id"),
        args.get("trace"),
    )


def merge_trace_shards(
    shards: Sequence[Tuple[dict, str]],
) -> Tuple[dict, dict]:
    """Per-process trace documents -> one pod trace document.

    ``shards`` is ``[(doc, label), ...]`` (label = source path, used in
    warnings). Returns ``(merged_doc, info)`` where ``info`` carries
    ``{"shards", "events", "duplicates_dropped", "aligned_by",
    "warnings"}``. See the module docstring for the algorithm.
    """
    warnings: List[str] = []
    metas = []
    for pos, (doc, label) in enumerate(shards):
        meta = doc.get("metadata") or {}
        idx = meta.get("process_index")
        metas.append(
            {
                "doc": doc,
                "label": label,
                "index": int(idx) if isinstance(idx, int) else pos,
                "epoch_unix": meta.get("epoch_unix"),
                "syncs": _shard_sync_events(doc),
            }
        )
    if not metas:
        return (
            {"traceEvents": [], "displayTimeUnit": "ms", "metadata": {}},
            {
                "shards": 0,
                "events": 0,
                "duplicates_dropped": 0,
                "aligned_by": "none",
                "warnings": ["no shards to merge"],
            },
        )
    # positional fallback above may collide with explicit indices
    # (e.g. one shard lost its metadata); disambiguate deterministically
    used: Dict[int, int] = {}
    for m in metas:
        while m["index"] in used:
            m["index"] += 1
        used[m["index"]] = 1

    ref = min(metas, key=lambda m: m["index"])
    sync_id = _pick_sync_id([m["syncs"] for m in metas])
    aligned_by = "sync" if sync_id is not None else "epoch_unix"
    ref_sync = ref["syncs"].get(sync_id) if sync_id is not None else None

    merged: List[dict] = []
    seen: set = set()
    dupes = 0
    for m in metas:
        offset = 0.0
        shard_sync = (
            m["syncs"].get(sync_id) if sync_id is not None else None
        )
        if ref_sync is not None and shard_sync is not None:
            # the two sync events mark ONE barrier instant: aligning
            # them corrects both epoch offsets and host clock skew
            offset = float(ref_sync["ts"]) - float(shard_sync["ts"])
        elif (
            m["epoch_unix"] is not None
            and ref["epoch_unix"] is not None
        ):
            offset = (
                float(m["epoch_unix"]) - float(ref["epoch_unix"])
            ) * 1e6
            if m is not ref and aligned_by == "sync":
                warnings.append(
                    f"{m['label']}: no {sync_id!r} sync event; aligned "
                    "by wall-clock epoch (skew not corrected)"
                )
        pid = m["index"]
        name = "photon_ml_tpu"
        for ev in m["doc"].get("traceEvents", ()):
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    raw = (ev.get("args") or {}).get("name", name)
                    # one host.<i> label regardless of whether the
                    # shard already carried one
                    name = str(raw).split(" host.")[0]
                continue  # fresh metadata is emitted per shard below
            out = dict(ev)
            out["pid"] = pid
            if ev.get("ph") != "M":
                out["ts"] = round(float(ev.get("ts", 0.0)) + offset, 3)
            key = _dedupe_key(out)
            if key in seen:
                dupes += 1
                continue
            seen.add(key)
            merged.append(out)
        merged.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "ts": 0,
                "args": {"name": f"host.{pid} {name}"},
            }
        )
        merged.append(
            {
                "ph": "M",
                "name": "process_sort_index",
                "pid": pid,
                "tid": 0,
                "ts": 0,
                "args": {"sort_index": pid},
            }
        )
    # normalize: Perfetto handles negative ts poorly; shift the merged
    # timeline so the earliest non-metadata event lands at 0
    non_meta = [e for e in merged if e["ph"] != "M"]
    if non_meta:
        t_min = min(float(e["ts"]) for e in non_meta)
        if t_min != 0.0:
            for e in non_meta:
                e["ts"] = round(float(e["ts"]) - t_min, 3)
    merged.sort(key=lambda e: (e.get("ph") != "M", float(e.get("ts", 0.0))))
    doc = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "metadata": {
            "merged_shards": len(metas),
            "aligned_by": aligned_by,
            "sync_id": sync_id,
            "process_count": max(
                [len(metas)]
                + [
                    int((m["doc"].get("metadata") or {}).get(
                        "process_count", 0
                    ) or 0)
                    for m in metas
                ]
            ),
        },
    }
    info = {
        "shards": len(metas),
        "events": len(non_meta),
        "duplicates_dropped": dupes,
        "aligned_by": aligned_by,
        "warnings": warnings,
    }
    return doc, info


def merge_events_shards(
    paths: Sequence[Tuple[str, int]],
) -> Tuple[List[dict], List[str]]:
    """Per-process ``events.jsonl`` files -> one host-tagged record list
    sorted by ``time_unix``. ``paths`` is ``[(path, process_index),...]``.
    Unparseable lines (a record torn mid-write by the crash the merge is
    investigating) are skipped and counted, never fatal."""
    records: List[dict] = []
    warnings: List[str] = []
    for path, idx in paths:
        if os.path.isdir(path):
            path = os.path.join(path, "events.jsonl")
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
        except OSError as e:
            warnings.append(f"{path}: unreadable ({e})")
            continue
        bad = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(rec, dict):
                rec.setdefault("host", idx)
                records.append(rec)
        if bad:
            warnings.append(f"{path}: skipped {bad} torn record(s)")
    records.sort(key=lambda r: r.get("time_unix", 0.0))
    return records, warnings


def merge_metrics_shards(
    snapshots: Sequence[Tuple[dict, int]],
) -> dict:
    """Per-process ``metrics.json`` snapshots -> one pod snapshot with
    every instrument under its ``host.<i>.`` prefix, plus ``pod.*``
    counter sums (the cross-host aggregate a dashboard wants first)."""
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    pod: Dict[str, float] = {}
    for snap, idx in snapshots:
        prefix = host_metric_prefix(index=idx)
        for kind in ("counters", "gauges", "histograms"):
            for name, value in (snap.get(kind) or {}).items():
                out[kind][prefix + name] = value
                if kind == "counters":
                    pod[name] = pod.get(name, 0.0) + float(value)
    for name, total in pod.items():
        out["counters"][f"pod.{name}"] = total
    return out


def _reset_identity_for_tests() -> None:
    global _identity
    _identity = None
