"""Executable-dispatch counting: how many XLA programs did a block run?

The device-resident-loop work (ROADMAP item 1) is judged in DISPATCHES:
a regularization path that used to pay one host round trip per lambda
must execute as ONE program, and a K-pass GAME superpass as
ceil(passes/K). Wall clocks cannot prove that on a timeshared CPU bench
host — the dispatch count can, and it is tunnel-invariant.

``count_dispatches()`` counts per-executable-name executions by
wrapping ``pxla.ExecuteReplicated.__call__`` — the Python layer every
pjit execution funnels through *when the C++ jit fast path is off*. The
fast path caches (executable, fastpath-data) pairs in C++ and re-calls
them without touching Python, so inside the context the installer (a)
patches ``_get_fastpath_data`` to return None — no NEW fast-path
entries — and (b) clears the C++ pjit caches — no PRE-EXISTING entries.
Compiled executables live in the Python-level caches, which are NOT
cleared: counting never forces a recompile (the zero-recompile
invariants stay provable under a counter; asserted in the tests).

Counting therefore slows the host path a little (every call goes
through Python). It is a measurement harness for tests and bench
probes, not something to leave installed around production traffic.

Counts are keyed by the executable's name — the jitted function's name
(``solve_path``, ``superpass``, ``one_pass``, ...) — so assertions can
target the program under test and ignore incidental eager-op dispatches
(slicing a stacked result, building an input array) that are asynchronous
decode work, not host->device round trips of the training loop.
"""

from __future__ import annotations

import contextlib
import fnmatch
import threading
from typing import Dict, Iterator

__all__ = ["DispatchCounts", "count_dispatches"]

_LOCK = threading.Lock()
_DEPTH = 0
_SAVED = {}


class DispatchCounts:
    """Per-executable-name dispatch counts observed inside one
    ``count_dispatches()`` window, plus assertion helpers."""

    def __init__(self) -> None:
        self.by_name: Dict[str, int] = {}

    def note(self, name: str) -> None:
        with _LOCK:
            self.by_name[name] = self.by_name.get(name, 0) + 1

    def total(self) -> int:
        return sum(self.by_name.values())

    def for_program(self, pattern: str) -> int:
        """Total dispatches of executables whose name matches ``pattern``
        (fnmatch; a bare name matches itself and, via ``*name*``, its
        jit-mangled variants)."""
        return sum(
            c
            for n, c in self.by_name.items()
            if fnmatch.fnmatch(n, pattern) or pattern in n
        )

    def assert_program(self, pattern: str, expected: int) -> None:
        """Assert the program matching ``pattern`` dispatched exactly
        ``expected`` times — the test-suite surface for the one-dispatch
        guarantees (N-lambda path = 1, K-pass superpass = ceil(P/K))."""
        got = self.for_program(pattern)
        if got != expected:
            raise AssertionError(
                f"expected {expected} dispatch(es) of {pattern!r}, "
                f"counted {got}; all programs: {self.snapshot()}"
            )

    def snapshot(self) -> Dict[str, int]:
        with _LOCK:
            return dict(self.by_name)


@contextlib.contextmanager
def count_dispatches() -> Iterator[DispatchCounts]:
    """Count every XLA executable dispatch inside the block, per program
    name. Reentrant (nested counters each see the block they wrap);
    never forces a recompile. CPU/TPU alike — the seam is backend-
    independent."""
    global _DEPTH
    from jax._src import pjit as _pjit
    from jax._src.interpreters import pxla as _pxla
    from jax._src.lib import xla_client as _xc

    counts = DispatchCounts()
    with _LOCK:
        _DEPTH += 1
        if _DEPTH == 1:
            _SAVED["call"] = _pxla.ExecuteReplicated.__call__
            _SAVED["fastpath"] = _pjit._get_fastpath_data
            _SAVED["listeners"] = []
        _SAVED["listeners"].append(counts)
        if _DEPTH == 1:
            orig_call = _SAVED["call"]

            def counted_call(self, *args):
                name = getattr(self, "name", "") or "<unnamed>"
                for c in list(_SAVED.get("listeners", ())):
                    c.note(name)
                return orig_call(self, *args)

            _pxla.ExecuteReplicated.__call__ = counted_call
            # no NEW C++ fast-path entries while counting...
            _pjit._get_fastpath_data = lambda *a, **k: None
    # ...and no PRE-EXISTING ones: clear the C++ pjit caches only — the
    # Python-level compiled-executable caches survive, so nothing
    # recompiles (outside the lock: cache eviction may run destructors)
    _xc._xla.PjitFunctionCache.clear_all()
    try:
        yield counts
    finally:
        with _LOCK:
            _DEPTH -= 1
            try:
                _SAVED["listeners"].remove(counts)
            except ValueError:
                pass
            if _DEPTH == 0:
                _pxla.ExecuteReplicated.__call__ = _SAVED.pop("call")
                _pjit._get_fastpath_data = _SAVED.pop("fastpath")
                _SAVED.pop("listeners", None)
