"""The observability NAME TAXONOMY as a machine-readable registry.

docs/OBSERVABILITY.md documents the dotted-path naming scheme
(``<subsystem>.<thing>``) that every span, instant event, and metric in
the tree follows — it is what makes ``photon-obs merge`` output, the
Prometheus exposition, and the BENCH sentinel's direction rules
navigable. Until now that taxonomy lived only in prose: a typo'd
subsystem (``sevring.request_ms``) still recorded happily and silently
orphaned its dashboard panel.

This module is the single source of truth the prose now points at.
Consumers:

- ``photon-lint`` rule **PL006 obs-taxonomy** validates every literal
  name passed to ``obs.span`` / ``obs.emit_event`` / registry
  ``inc``/``set_gauge``/``observe``/``counter``/``gauge``/``histogram``
  against :func:`matches` at build time (f-strings validate their
  static prefix via :func:`valid_prefix`).
- docs/OBSERVABILITY.md's taxonomy section references :data:`TAXONOMY`
  so the doc table and the lint gate cannot drift.

Growing a NEW subsystem is one tuple here (plus its doc blurb) — the
lint failure for an unknown prefix is the reminder.
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

__all__ = [
    "TAXONOMY",
    "subsystems",
    "matches",
    "subsystem_of",
    "valid_prefix",
]

# (subsystem, name regex, one-line description). The regex is anchored
# at the start; a name is documented when ANY entry matches. Kept in the
# same order as the docs/OBSERVABILITY.md taxonomy table.
TAXONOMY: Tuple[Tuple[str, str, str], ...] = (
    (
        "game",
        r"game\.[a-z_]+(\.[a-z0-9_.]+)?",
        "GAME descent spans/counters (game.pass, game.updates, "
        "game.checkpoint.submit_ms, ...)",
    ),
    (
        "solver",
        r"solver\.[a-z0-9_]+(\.[a-z_]+)?",
        "per-optimizer counters recorded at the train_glm host boundary",
    ),
    (
        "glm",
        r"glm\.[a-z_]+",
        "GLM driver/solve spans (glm.solve, glm.solve_path)",
    ),
    (
        "xla",
        r"xla\.[a-z_]+(\..+)?",
        "compile listener + cost book (xla.compiles, xla.cost.*)",
    ),
    (
        "hbm",
        r"hbm\..+",
        "live HBM telemetry gauges/counter tracks + watermark labels",
    ),
    (
        "io",
        r"io\.(ingest|checkpoint|pipeline)\.[a-z0-9_.]+",
        "durability I/O: ingest reads, checkpoint saves/loads, pipeline "
        "lifecycle events",
    ),
    (
        "ingest",
        r"ingest\.[a-z_]+(\.[a-z0-9_.{}<>]+)?",
        "streaming ingest->device pipeline spans/metrics (docs/INGEST.md)",
    ),
    (
        "resilience",
        r"resilience\.[a-z_]+(\..+)?",
        "retry/fault/rollback/preemption/host-loss events + counters",
    ),
    (
        "serving.cache",
        r"serving\.cache\.[a-z_]+",
        "tiered HBM/host entity cache: hit/miss/promotion/demotion "
        "counters, tier-error counter, per-batch miss/promotion "
        "instants carrying batch_id for trace joins (serving/cache.py)",
    ),
    (
        "serving.shard",
        r"serving\.shard\.[a-z0-9_.]+",
        "entity-sharded serving: per-shard occupancy gauges + device "
        "latency histograms, shard-degraded counters, the per-process "
        "resident RE-table footprint gauge (serving/sharding.py)",
    ),
    (
        "serving",
        r"serving\.[a-z_]+(\..+)?",
        "ServingStats registry metrics, request spans, SLO gauges",
    ),
    (
        "convergence",
        r"convergence\.[a-z_]+(\..+)?",
        "solver-tape convergence-health layer (reports, precursors)",
    ),
    (
        "collective",
        r"collective\.[a-z_]+(\..+)?",
        "collective profiler metrics/spans + stall/abandon events, incl. "
        "the collective.overlap.* chunked-pipeline series and per-width "
        "wall_frac overlap gauges (docs/PARALLEL.md)",
    ),
    (
        "partition",
        r"partition\.[a-z_]+(\..+)?",
        "multi-device partition layer: entity-shard layout spans, "
        "balanced-blocking stats, shard-skew drill events "
        "(docs/PARALLEL.md)",
    ),
    (
        "heartbeat",
        r"heartbeat\.[a-z_]+",
        "pod heartbeat monitor events (heartbeat.peer_lost)",
    ),
    (
        "pod",
        r"pod\.[a-z_]+(\..+)?",
        "pod-level aggregates: merged counter sums, heartbeat gauges",
    ),
    (
        "host",
        r"host\.\d+\..+",
        "per-process instruments after a pod merge (photon-obs merge)",
    ),
    (
        "clock",
        r"clock\.sync",
        "barrier-backed clock-sync anchors for trace-shard merging",
    ),
    (
        "kernels",
        r"kernels\.[a-z_]+(\..+)?",
        "Pallas sparse-kernel cost records (docs/KERNELS.md)",
    ),
    (
        "lint",
        r"lint\.[a-z_]+(\..+)?",
        "photon-lint analyzer metrics (docs/ANALYSIS.md)",
    ),
    (
        "drift",
        r"drift\.[a-z_]+(\..+)?",
        "serving-vs-baseline drift detection: per-feature PSI/JS "
        "gauges, drift.alarm events (obs.quality.DriftMonitor)",
    ),
    (
        "quality",
        r"quality\.[a-z_]+(\..+)?",
        "model-quality layer: online AUC/calibration gauges from the "
        "feedback loop, baseline-fingerprint health counters",
    ),
    (
        "lifecycle",
        r"lifecycle\.[a-z_]+(\..+)?",
        "self-healing retrain orchestrator: cycle spans/counters, "
        "per-stage retry events, retrain_cycle_s gauge, admission "
        "promotions (lifecycle/orchestrator.py, docs/LIFECYCLE.md)",
    ),
    (
        "frontend",
        r"frontend\.[a-z_]+(\..+)?",
        "async front end: connection/frame/reply counters, rejected "
        "(RESOURCE_EXHAUSTED answers), bytes in/out, per-request "
        "wire_read/reply_write spans + traces_issued counter "
        "(frontend/server.py, docs/FRONTEND.md)",
    ),
    (
        "tenant",
        r"tenant\.[a-z_]+(\..+)?",
        "multi-tenant engine layer: per-tenant rejected/registered "
        "counters keyed by tenant name (frontend/tenants.py)",
    ),
    (
        "replica",
        r"replica\.[a-z_]+(\..+)?",
        "replica router: per-replica batch/failure counters, "
        "replica.down events, per-attempt replica.hop spans (trace "
        "failover joins), failover_ms histogram, exhausted "
        "counter (frontend/replicas.py)",
    ),
)

_COMPILED = tuple(
    (sub, re.compile(pattern + r"$"), desc) for sub, pattern, desc in TAXONOMY
)
# prefixes that legitimately start a dynamic (f-string) name: every
# subsystem root, plus the documented two-level families whose leaf is
# computed (xla.cost.<key>, convergence.reason.<NAME>, ...)
_PREFIXES = tuple(sorted({sub + "." for sub, _, _ in TAXONOMY}))


def subsystems() -> Tuple[str, ...]:
    """The documented subsystem roots, sorted."""
    return tuple(sorted({sub for sub, _, _ in TAXONOMY}))


def matches(name: str) -> bool:
    """True when ``name`` is a documented span/event/metric name."""
    return any(rx.fullmatch(name) for _, rx, _ in _COMPILED)


def subsystem_of(name: str) -> Optional[str]:
    """The subsystem whose pattern matches ``name`` (None = orphan)."""
    for sub, rx, _ in _COMPILED:
        if rx.fullmatch(name):
            return sub
    return None


def valid_prefix(prefix: str) -> bool:
    """True when a STATIC name prefix (the constant head of an f-string
    name like ``f"resilience.faults_injected.{site}"``) can only produce
    documented names: it must start with ``<subsystem>.``."""
    return any(prefix.startswith(p) or p.startswith(prefix) for p in _PREFIXES)
