"""Structured span tracing: Chrome trace events + JSONL event log.

The reference's only timing instrument is ``Driver.scala:124-149`` — ad-hoc
elapsed-millis log lines per phase. That tells you *that* a GAME pass took
9 seconds, never *where* they went (solver iterations vs recompiles vs
host<->device transfer). This module is the process-wide replacement: a
thread-safe span tracer whose output loads directly into Perfetto /
``chrome://tracing`` (trace-event JSON) and doubles as a structured JSONL
event log written next to the run's ``log-message.txt``.

Design constraints, in priority order:

1. **Near-zero disabled overhead.** Training hot loops call
   :func:`span` unconditionally; with no tracer installed the call is one
   module-global read plus returning a shared no-op singleton — no
   allocation, no lock, no branch in the caller. ``benchmarks/obs_overhead.py``
   gates this (<5% on a smoke GAME run, enabled vs disabled).
2. **Thread-safe.** The serving micro-batcher and stats flushers span from
   worker threads; events append under one lock and carry the recording
   thread id so Perfetto lays them out per-track.
3. **No jax dependency.** Pure stdlib — the tracer must be importable from
   CPU-only subprocesses (bench baselines) and before backend selection.

Usage::

    from photon_ml_tpu import obs

    with obs.trace("out/trace"):            # install for the block
        with obs.span("train", combo=0):    # nestable, thread-safe
            ...
        obs.emit_event("retry", label="read part-0.avro", attempt=2)
    # -> out/trace/trace.json (Perfetto) + out/trace/events.jsonl

Device-time attribution: wall-clock spans lie on an async runtime — the
dispatch returns before the device finishes. ``span(...).sync(arrays)``
calls ``jax.block_until_ready`` on the value and annotates the span with
the blocked time, splitting host dispatch from device completion.
"""

from __future__ import annotations

import atexit
import contextlib
import io
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "Span",
    "Tracer",
    "trace",
    "span",
    "span_context",
    "current_span_context",
    "emit_event",
    "get_tracer",
    "set_tracer",
]

EVENTS_FILENAME = "events.jsonl"
TRACE_FILENAME = "trace.json"


class Tracer:
    """Collects trace events and streams them to a JSONL log.

    ``_FLUSH_EVERY`` bounds the unflushed-span window (see
    :meth:`_log_jsonl`).

    Timestamps are microseconds since the tracer's epoch
    (``perf_counter_ns`` based — monotonic, immune to wall-clock steps),
    which is what the Chrome trace-event format's ``ts`` field wants.
    ``export()`` writes the accumulated events, sorted by ``ts``, as a
    ``{"traceEvents": [...]}`` document loadable in Perfetto.
    """

    _FLUSH_EVERY = 64

    def __init__(
        self,
        trace_dir: Optional[str] = None,
        process_name: str = "photon_ml_tpu",
        keep_events: bool = True,
    ):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        # ring-only mode (keep_events=False): route spans/events to the
        # flight recorder and JSONL without accumulating the in-memory
        # trace — the long-lived-process shape (obs.observe's
        # flight-without-trace envelope) where an unbounded event list
        # would be a leak
        self._keep_events = keep_events
        self._epoch_ns = time.perf_counter_ns()
        self._epoch_unix = time.time()
        # flight-recorder hook: a FlightRecorder (obs.flight) notes every
        # span/instant/counter record into its bounded ring
        self.recorder = None
        # pod identity (obs.dist): in a multi-process run the Chrome pid
        # IS the process index — per-host events land on distinct
        # Perfetto pid tracks and merge without rewriting
        from photon_ml_tpu.obs import dist as _dist

        self.process_index, self.process_count = _dist.process_identity()
        if self.process_count > 1:
            self._pid = self.process_index
            process_name = f"{process_name} host.{self.process_index}"
        else:
            self._pid = os.getpid()
        self.trace_dir = trace_dir
        self._jsonl: Optional[io.TextIOBase] = None
        self._jsonl_pending = 0
        self._atexit_registered = False
        if trace_dir is not None:
            os.makedirs(trace_dir, exist_ok=True)
            self._jsonl = open(
                os.path.join(trace_dir, EVENTS_FILENAME),
                "a",
                encoding="utf-8",
            )
            # clean-exit guard: a tracer installed WITHOUT the trace()
            # context manager (drivers that set_tracer directly, or a
            # process that exits mid-envelope) still flushes its
            # buffered span records and exports the trace — the
            # up-to-63-spans flush loss-window otherwise
            atexit.register(self._atexit_close)
            self._atexit_registered = True
        # process metadata events (name + stable ordering in Perfetto)
        self._events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": self._pid,
                "tid": 0,
                "ts": 0,
                "args": {"name": process_name},
            }
        )
        if self.process_count > 1:
            self._events.append(
                {
                    "ph": "M",
                    "name": "process_sort_index",
                    "pid": self._pid,
                    "tid": 0,
                    "ts": 0,
                    "args": {"sort_index": self.process_index},
                }
            )

    # -- clock --------------------------------------------------------------

    def now_us(self) -> float:
        """Microseconds since this tracer's epoch (monotonic)."""
        return (time.perf_counter_ns() - self._epoch_ns) / 1e3

    def _wall(self, ts_us: float) -> float:
        """Unix seconds for a tracer timestamp (JSONL human anchor)."""
        return self._epoch_unix + ts_us / 1e6

    # -- recording ----------------------------------------------------------

    def _log_jsonl(self, record: Dict[str, Any], flush: bool = False) -> None:
        """Append one JSONL record. Span records are flushed every
        ``_FLUSH_EVERY`` writes (a crash loses at most a handful of
        timing lines — the flight recorder's ring covers that window);
        instant events — faults, retries, preemptions — flush
        immediately, since they exist to survive the crash that
        follows them."""
        rec = self.recorder
        if rec is not None:
            rec.note(record)
        if self._jsonl is None or self._jsonl.closed:
            return
        if self.process_count > 1:
            record = {"host": self.process_index, **record}
        self._jsonl.write(json.dumps(record, sort_keys=True) + "\n")
        self._jsonl_pending += 1
        if flush or self._jsonl_pending >= self._FLUSH_EVERY:
            self._jsonl.flush()
            self._jsonl_pending = 0

    def add_span(
        self,
        name: str,
        ts_us: float,
        dur_us: float,
        cat: str = "app",
        tid: Optional[int] = None,
        args: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Record a complete ('X') event with an explicit window — the
        retro-emission hook for work whose per-piece timing is only known
        after a fused dispatch returns."""
        ev = {
            "ph": "X",
            "name": name,
            "cat": cat,
            "pid": self._pid,
            "tid": tid if tid is not None else threading.get_ident(),
            "ts": round(ts_us, 3),
            "dur": round(max(dur_us, 0.0), 3),
            "args": args or {},
        }
        with self._lock:
            if self._keep_events:
                self._events.append(ev)
            self._log_jsonl(
                {
                    "kind": "span",
                    "name": name,
                    "cat": cat,
                    "time_unix": round(self._wall(ts_us), 6),
                    "duration_ms": round(max(dur_us, 0.0) / 1e3, 6),
                    **(args or {}),
                }
            )

    def add_instant(
        self,
        name: str,
        cat: str = "event",
        args: Optional[Dict[str, Any]] = None,
        flush: bool = True,
    ) -> None:
        """Instant events flush the JSONL immediately by default — they
        exist to survive the crash that follows them. Periodic telemetry
        instants (per-pass convergence summaries) pass ``flush=False``
        and ride the batched span flush instead."""
        ts = self.now_us()
        ev = {
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "name": name,
            "cat": cat,
            "pid": self._pid,
            "tid": threading.get_ident(),
            "ts": round(ts, 3),
            "args": args or {},
        }
        with self._lock:
            if self._keep_events:
                self._events.append(ev)
            self._log_jsonl(
                {
                    "kind": "event",
                    "name": name,
                    "cat": cat,
                    "time_unix": round(self._wall(ts), 6),
                    **(args or {}),
                },
                flush=flush,
            )

    def add_counter(
        self,
        name: str,
        values: Dict[str, float],
        ts_us: Optional[float] = None,
    ) -> None:
        """Record a Chrome counter-track sample ('C' event): Perfetto
        renders successive samples of the same ``name`` as a stacked
        area graph under the timeline — the HBM telemetry surface
        (``obs.device``). ``ts_us`` retro-stamps the sample (the
        convergence layer replays a solve's tape across the solve's
        span window; the iterations happened inside one dispatch, so
        their timestamps are only known after it returns). Samples are
        periodic and bulky, so the JSONL mirror rides the batched span
        flush, not the instant-event immediate flush."""
        ev = {
            "ph": "C",
            "name": name,
            "cat": "counter",
            "pid": self._pid,
            "tid": 0,
            "ts": round(self.now_us() if ts_us is None else ts_us, 3),
            "args": dict(values),
        }
        with self._lock:
            if self._keep_events:
                self._events.append(ev)
            self._log_jsonl(
                {
                    "kind": "counter",
                    "name": name,
                    "time_unix": round(self._wall(ev["ts"]), 6),
                    **values,
                }
            )

    # -- readout ------------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def export(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Chrome trace-event JSON (sorted by ``ts`` so readers
        that assume emission order see monotone timestamps). Returns the
        path written, or None when there is nowhere to write."""
        if path is None:
            if self.trace_dir is None:
                return None
            path = os.path.join(self.trace_dir, TRACE_FILENAME)
        with self._lock:
            events = sorted(self._events, key=lambda e: (e["ts"], -e.get("dur", 0)))
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "epoch_unix": self._epoch_unix,
                "process_index": self.process_index,
                "process_count": self.process_count,
            },
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        return path

    def flush(self) -> None:
        """Force the buffered JSONL span records to disk. Called from
        shutdown paths (``GracefulShutdown``) so a graceful exit never
        loses the up-to-``_FLUSH_EVERY - 1`` buffered records."""
        with self._lock:
            if self._jsonl is not None and not self._jsonl.closed:
                self._jsonl.flush()
                self._jsonl_pending = 0

    def close(self) -> None:
        if self._atexit_registered:
            self._atexit_registered = False
            try:
                atexit.unregister(self._atexit_close)
            except Exception:
                pass
        if self._jsonl is not None and not self._jsonl.closed:
            self._jsonl.close()  # implicit flush of any buffered records

    def _atexit_close(self) -> None:
        """Clean-exit fallback for tracers never close()d: export the
        trace document (the context manager normally does this) and
        flush/close the JSONL log."""
        try:
            self.export()
        except Exception:
            pass
        self.close()


# ---------------------------------------------------------------------------
# Active-tracer plumbing
# ---------------------------------------------------------------------------

# ONE process-global active tracer (like logging's root logger): training,
# serving, and resilience all emit into the same timeline, which is the
# point of a *unified* instrument. Deliberately not thread-local — worker
# threads must land on the main timeline.
_active: Optional[Tracer] = None
_install_lock = threading.Lock()


def get_tracer() -> Optional[Tracer]:
    return _active


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install ``tracer`` as the process-wide destination (None disables).
    Returns the previous tracer so callers can restore it."""
    global _active
    with _install_lock:
        prev = _active
        _active = tracer
    return prev


@contextlib.contextmanager
def trace(trace_dir: Optional[str], process_name: str = "photon_ml_tpu"):
    """Install a :class:`Tracer` writing under ``trace_dir`` for the
    block; export ``trace.json`` and close the JSONL log on exit. With
    ``trace_dir=None`` the block runs untraced (flag-plumbing
    convenience: ``with trace(args.trace_dir): ...``)."""
    if trace_dir is None:
        yield None
        return
    tracer = Tracer(trace_dir, process_name=process_name)
    prev = set_tracer(tracer)
    try:
        yield tracer
    finally:
        set_tracer(prev)
        tracer.export()
        tracer.close()


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


class _NullSpan:
    """The disabled-mode singleton: every method is a no-op. Shared and
    stateless so ``span()`` allocates nothing when tracing is off."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> None:
        pass

    def sync(self, value):
        return value


_NULL_SPAN = _NullSpan()


class Span:
    """A live span: records a complete event on ``__exit__``.

    ``set(**attrs)`` attaches arguments (visible in Perfetto's args pane
    and in the JSONL record). ``sync(value)`` blocks until the device
    work producing ``value`` is done and annotates the span with the
    blocked milliseconds — wall time alone cannot split an async
    dispatch from device completion. A span that exits via an exception
    is recorded with ``error=True``; where the time went is most valuable
    exactly when the phase died (same contract as ``timed()``).
    """

    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: Tracer, name: str, cat: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = tracer.now_us()

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        if exc_type is not None:
            self.args["error"] = True
        t1 = self._tracer.now_us()
        self._tracer.add_span(
            self.name, self._t0, t1 - self._t0, cat=self.cat, args=self.args
        )
        return False

    def set(self, **attrs) -> None:
        self.args.update(attrs)

    def sync(self, value):
        """``jax.block_until_ready(value)``, annotating the span with the
        blocked time (``device_wait_ms``) — the device-time attribution
        seam. Imports jax lazily so the tracer stays stdlib-only."""
        import jax

        t0 = self._tracer.now_us()
        out = jax.block_until_ready(value)
        self.args["device_wait_ms"] = round(
            (self._tracer.now_us() - t0) / 1e3, 4
        )
        return out


# Ambient span context: request-scoped attributes (trace/request ids)
# that cross API seams without threading kwargs through them — the
# serving micro-batcher opens a context around its score_fn call and the
# engine's `serving.score` span inherits the batch/request identity.
# Thread-local so concurrent micro-batchers don't cross-tag. Read ONLY
# when a tracer is active, so disabled-mode span() cost is unchanged.
_span_ctx = threading.local()


def current_span_context() -> Optional[Dict[str, Any]]:
    """The innermost ambient span-context dict, or None."""
    stack = getattr(_span_ctx, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def span_context(**fields):
    """Attach ``fields`` to every span opened in this thread inside the
    block (explicit span attrs win on key collision). Nestable: inner
    contexts layer over outer ones."""
    stack = getattr(_span_ctx, "stack", None)
    if stack is None:
        stack = _span_ctx.stack = []
    merged = {**stack[-1], **fields} if stack else dict(fields)
    stack.append(merged)
    try:
        yield
    finally:
        stack.pop()


def span(name: str, cat: str = "app", **attrs):
    """Open a span on the active tracer (context manager). Disabled mode
    returns a shared no-op singleton — the unconditional-call contract
    every hot loop relies on."""
    tracer = _active
    if tracer is None:
        return _NULL_SPAN
    ctx = current_span_context()
    if ctx:
        attrs = {**ctx, **attrs}
    return Span(tracer, name, cat, attrs)


def emit_event(name: str, cat: str = "event", **fields) -> None:
    """Record an instantaneous structured event (retry fired, fault
    injected, rollback, preemption…) on the active tracer; no-op when
    tracing is off. Fields must be JSON-serializable."""
    tracer = _active
    if tracer is not None:
        tracer.add_instant(name, cat=cat, args=fields)
