"""XLA cost book: device-level performance accounting as observability.

Before this module the only hardware-efficiency numbers in the repo (MFU,
HBM utilization, per-device footprints, collective counts — the BENCH
record's ``mfu``/``hbm_util``/``sparse_fs_scaling`` fields) were computed
by hand inside ``bench.py``: analytic FLOP arithmetic, a one-off regex
over HLO text, local peak constants. Training, serving, and the PR-3 obs
layer could not see them, and nothing guaranteed the bench's accounting
matched what actually compiled. The cost book promotes that accounting to
a first-class instrument:

- :func:`CostBook.record` wraps any **lowered or compiled** executable
  and extracts XLA's own numbers — ``cost_analysis()`` FLOPs and bytes
  accessed, ``memory_analysis()`` argument/temp/output sizes (compiled
  only), and collective-op counts parsed from the optimized HLO
  (:func:`count_collectives`, the generalization of the regex formerly
  inlined in ``bench.py``). Records key by executable name + shape
  bucket, land in the metrics registry as ``xla.cost.*`` gauges, and
  emit a ``xla.cost_record`` instant event on the active tracer.
- :func:`annotate_span` turns a record + a measured window into live
  hardware attribution on a span: ``flops``, ``achieved_tflops``,
  ``mfu``, ``bytes_per_s`` — how TRON/L-BFGS solves, GAME coordinate
  passes, and serving score buckets surface live MFU in the trace.

Every analysis is best-effort: backends without a cost/memory analysis
(or exotic executables) degrade to the caller-supplied analytic
fallbacks, never to an exception — observability must not fail the work
it observes.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Any, Dict, Optional, Tuple

# symbol imports (not `from obs import trace`): the package rebinds its
# `trace` attribute to the context-manager function, so module-attribute
# imports resolve to the function once __init__ has run
from photon_ml_tpu.obs.metrics import MetricsRegistry
from photon_ml_tpu.obs.metrics import registry as _registry
from photon_ml_tpu.obs.trace import emit_event as _emit_event

__all__ = [
    "PEAK_FLOPS",
    "PEAK_HBM_BPS",
    "COLLECTIVE_RE",
    "count_collectives",
    "CostRecord",
    "CostBook",
    "cost_book",
    "set_cost_book",
    "annotate_span",
]

# TPU v5e roofline constants (bench.py's former module constants, now the
# ONE copy every consumer shares): peak dense bf16 matmul FLOP/s and HBM
# bandwidth. GLM objective passes stream the design matrix at ~2
# FLOP/byte — far below the ~240 FLOP/byte compute-bound knee — so the
# HBM line is the relevant ceiling for the solvers in this repo.
PEAK_FLOPS = 197e12
PEAK_HBM_BPS = 819e9

# The collective ops that matter for the scaling story (each -start
# variant collapses onto its base op — async collectives lower as
# start/done pairs and must not double-count). Formerly inlined at the
# bench's sparse-scaling measurement; now the one shared definition.
# Counting matches INSTRUCTIONS (the opcode followed by its operand
# list), not every textual occurrence: the regex that used to be inlined
# in bench.py also matched `%all-reduce` OPERAND references in fusion
# consumers, double-or-more counting each real collective (BENCH_r05's
# "4 all-reduces" in the F>=2 objective pass were 2 instructions plus
# their uses). Collective COUNTS therefore drop across the board
# relative to the r01-r05 history — a counting fix, not a perf change
# (the sentinel's direction for `collectives.` is lower-is-better, so
# the fix cannot trip it).
COLLECTIVE_RE = re.compile(
    r"\b(all-reduce(?:-start)?|all-gather(?:-start)?|"
    r"all-to-all|reduce-scatter|collective-permute)\("
)


def count_collectives(hlo_text: str) -> Dict[str, int]:
    """Collective-INSTRUCTION counts in an (optimized) HLO dump,
    ``{op_base_name: count}`` with ``-start`` variants folded into the
    base op. Empty dict = no collectives (the single-device case)."""
    counts: Dict[str, int] = {}
    for m in COLLECTIVE_RE.findall(hlo_text):
        base = m.split("-start")[0]
        counts[base] = counts.get(base, 0) + 1
    return counts


def _sig(x: float, digits: int = 4) -> float:
    """Round to significant digits: fixed-decimal rounding flattens
    tiny-but-real utilizations (a 600-row drill's MFU) to 0.0, and a
    zero in a trace reads as 'no work', not 'small work'."""
    return float(f"{x:.{digits}g}")


def _first_dict(obj) -> Optional[dict]:
    """cost_analysis() returns a dict on some jax versions and a
    one-element list of dicts on others; normalize."""
    if obj is None:
        return None
    if isinstance(obj, dict):
        return obj
    if isinstance(obj, (list, tuple)) and obj and isinstance(obj[0], dict):
        return obj[0]
    return None


@dataclasses.dataclass(frozen=True)
class CostRecord:
    """One executable's static cost profile.

    ``flops``/``bytes_accessed`` come from XLA's cost analysis of ONE
    execution (loop bodies with dynamic trip counts are counted once —
    callers scale by their own pass counts, exactly like bench.py's
    counted-work methodology). ``argument_bytes``/``output_bytes``/
    ``temp_bytes`` are the compiled per-device memory analysis (None for
    lowered-only records). ``source`` says which analyses ran:
    ``"compiled"``, ``"lowered"``, or ``"analytic"`` (every XLA analysis
    unavailable; the caller's fallbacks carried the numbers).
    """

    name: str
    bucket: str
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    collectives: Dict[str, int] = dataclasses.field(default_factory=dict)
    source: str = "analytic"
    # caller-pinned bytes for bandwidth-roofline arithmetic. XLA's
    # static bytes-accessed counts every materialization in the
    # unoptimized module — including dtype-convert round trips a fused
    # backend never pays (a bf16 design upcast to f32 counts ~2x its
    # true HBM traffic) — so callers measuring a bandwidth ceiling may
    # pin the minimal traffic here; ``achieved()`` prefers it.
    roofline_bytes: Optional[float] = None

    def achieved(
        self,
        seconds: float,
        passes: float = 1.0,
        peak_flops: float = PEAK_FLOPS,
        peak_hbm_bps: float = PEAK_HBM_BPS,
    ) -> Dict[str, float]:
        """Hardware attribution for ``passes`` executions of this record
        over a measured ``seconds`` window — the span-annotation payload
        (flops / achieved_tflops / mfu / bytes_per_s / hbm_util)."""
        out: Dict[str, float] = {}
        if seconds <= 0:
            return out
        if self.flops is not None:
            fl = self.flops * passes
            out["flops"] = fl
            out["achieved_tflops"] = _sig(fl / seconds / 1e12)
            out["mfu"] = _sig(fl / seconds / peak_flops)
        hbm_bytes = (
            self.roofline_bytes
            if self.roofline_bytes is not None
            else self.bytes_accessed
        )
        if hbm_bytes is not None:
            bps = hbm_bytes * passes / seconds
            out["bytes_per_s"] = _sig(bps)
            out["hbm_util"] = _sig(bps / peak_hbm_bps)
        return out


class CostBook:
    """Thread-safe (name, shape bucket) -> :class:`CostRecord` map.

    One book per process (:func:`cost_book`) is the common case — bench,
    training, and serving record into the same table, which is the point:
    the BENCH record's MFU and a traced solve's ``mfu`` span arg are then
    the same arithmetic over the same XLA numbers.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._records: Dict[Tuple[str, str], CostRecord] = {}

    def record(
        self,
        name: str,
        executable: Any = None,
        bucket: str = "",
        analytic_flops: Optional[float] = None,
        analytic_bytes: Optional[float] = None,
        roofline_bytes: Optional[float] = None,
        registry: Optional[MetricsRegistry] = None,
    ) -> CostRecord:
        """Analyze ``executable`` (a ``jax.stages.Lowered`` or
        ``.compile()``-d executable; None = analytic-only) and store the
        record under ``(name, bucket)``. Re-recording the same key
        replaces the record (shapes are in the bucket; a same-key
        re-record is a re-analysis of the same program).

        ``analytic_flops``/``analytic_bytes`` are fallbacks used when the
        backend exposes no cost analysis — the record is still usable for
        MFU attribution, marked ``source="analytic"``.
        ``roofline_bytes`` pins the bandwidth-roofline traffic when the
        caller knows XLA's static count overstates it (see
        :class:`CostRecord`).
        """
        flops = bytes_accessed = None
        arg_b = out_b = tmp_b = None
        colls: Dict[str, int] = {}
        source = "analytic"
        if executable is not None:
            ca = None
            try:
                ca = _first_dict(executable.cost_analysis())
            except Exception:
                ca = None
            if ca is not None:
                flops = float(ca.get("flops", 0.0)) or None
                bytes_accessed = (
                    float(ca.get("bytes accessed", 0.0)) or None
                )
                source = "lowered"
            try:
                ma = executable.memory_analysis()
                if ma is not None:
                    arg_b = int(ma.argument_size_in_bytes)
                    out_b = int(ma.output_size_in_bytes)
                    tmp_b = int(ma.temp_size_in_bytes)
                    source = "compiled"
            except Exception:
                pass
            try:
                # optimized HLO exists on compiled executables only; a
                # Lowered's as_text() is the pre-optimization module whose
                # collectives are not yet final — skip unless compiled
                if arg_b is not None:
                    colls = count_collectives(executable.as_text())
            except Exception:
                colls = {}
        if flops is None:
            flops = analytic_flops
        if bytes_accessed is None:
            bytes_accessed = analytic_bytes
        rec = CostRecord(
            name=name,
            bucket=str(bucket),
            flops=flops,
            bytes_accessed=bytes_accessed,
            argument_bytes=arg_b,
            output_bytes=out_b,
            temp_bytes=tmp_b,
            collectives=colls,
            source=source,
            roofline_bytes=roofline_bytes,
        )
        with self._lock:
            self._records[(name, rec.bucket)] = rec
        self._export(rec, registry)
        return rec

    def _export(self, rec: CostRecord, registry=None) -> None:
        """Registry gauges + a trace instant event for one record, so
        cost profiles land in ``metrics.json`` and in the Perfetto
        timeline without caller wiring."""
        reg = registry if registry is not None else _registry()
        key = rec.name + (f".{rec.bucket}" if rec.bucket else "")
        if rec.flops is not None:
            reg.set_gauge(f"xla.cost.{key}.flops", rec.flops)
        if rec.bytes_accessed is not None:
            reg.set_gauge(f"xla.cost.{key}.bytes_accessed", rec.bytes_accessed)
        if rec.temp_bytes is not None:
            reg.set_gauge(f"xla.cost.{key}.temp_bytes", rec.temp_bytes)
        if rec.collectives:
            reg.set_gauge(
                f"xla.cost.{key}.collectives", sum(rec.collectives.values())
            )
        _emit_event(
            "xla.cost_record",
            cat="xla",
            executable=rec.name,
            bucket=rec.bucket,
            flops=rec.flops,
            bytes_accessed=rec.bytes_accessed,
            argument_bytes=rec.argument_bytes,
            temp_bytes=rec.temp_bytes,
            collectives=dict(rec.collectives),
            source=rec.source,
        )

    def lookup(self, name: str, bucket: str = "") -> Optional[CostRecord]:
        with self._lock:
            return self._records.get((name, str(bucket)))

    def names(self) -> list:
        with self._lock:
            return sorted(self._records)

    def reset(self) -> None:
        with self._lock:
            self._records.clear()

    def snapshot(self) -> dict:
        """Plain-JSON view keyed ``name[.bucket]`` — lands in the BENCH
        record's ``extra.cost_book`` and in trace metadata."""
        with self._lock:
            items = list(self._records.items())
        out = {}
        for (name, bucket), rec in sorted(items):
            key = name + (f".{bucket}" if bucket else "")
            out[key] = {
                "flops": rec.flops,
                "bytes_accessed": rec.bytes_accessed,
                "argument_bytes": rec.argument_bytes,
                "output_bytes": rec.output_bytes,
                "temp_bytes": rec.temp_bytes,
                "collectives": dict(rec.collectives),
                "source": rec.source,
            }
            if rec.roofline_bytes is not None:
                out[key]["roofline_bytes"] = rec.roofline_bytes
        return out


# ONE process-global default book, mirroring the default metrics registry.
_default = CostBook()


def cost_book() -> CostBook:
    """The process-global default cost book."""
    return _default


def set_cost_book(book: CostBook) -> CostBook:
    """Swap the process default (tests). Returns the previous one."""
    global _default
    prev = _default
    _default = book
    return prev


def annotate_span(
    sp,
    record: Optional[CostRecord],
    seconds: float,
    passes: float = 1.0,
    peak_flops: float = PEAK_FLOPS,
    peak_hbm_bps: float = PEAK_HBM_BPS,
) -> None:
    """Attach hardware attribution (``flops``/``achieved_tflops``/
    ``mfu``/``bytes_per_s``) to a live span from a cost-book record and a
    measured window. No-ops on a missing record, a non-positive window,
    or the disabled-mode null span — callers never need to guard."""
    if record is None or seconds is None or seconds <= 0:
        return
    attrs = record.achieved(
        seconds, passes=passes,
        peak_flops=peak_flops, peak_hbm_bps=peak_hbm_bps,
    )
    if attrs:
        sp.set(**attrs)
