"""Process-wide metrics registry: named counters, gauges, histograms.

Before this module, measurement was split three ways: serving carried its
own private ``ServingStats``, training logged free-text phase timings, and
resilience events vanished into log lines. The registry is the one
instrument they all feed: solver iteration counts, XLA recompiles,
ingest/checkpoint bytes, retry/fault/rollback counters, serving latency
histograms — snapshot-able as JSON (``metrics.json`` next to the run's
models) and exposable in Prometheus text format from ``cli/serve.py``.

Three instrument kinds, all lock-guarded and cheap to record:

- :class:`Counter` — monotonically increasing float (``inc``).
- :class:`Gauge`   — last-write-wins float (``set``).
- :class:`LatencyHistogram` — log-spaced histogram with quantile readout
  (promoted here from ``serving/stats.py``; the serving module re-exports
  it so existing imports keep working).

A process-global default registry (:func:`registry`) serves the common
case; subsystems that need isolation (one ``ServingStats`` per engine)
construct their own :class:`MetricsRegistry`.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from typing import Dict

__all__ = [
    "Counter",
    "Gauge",
    "LatencyHistogram",
    "MetricsRegistry",
    "registry",
    "set_registry",
]


class Counter:
    """Monotonic float counter. ``inc`` accepts fractional increments —
    per-entity solver iteration counts aggregate as means, and forcing
    them to ints would silently floor the signal."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increments must be >= 0: {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins float."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class LatencyHistogram:
    """Log-spaced latency histogram (milliseconds) with quantile readout.

    Fixed geometric bucket edges keep recording O(1) and lock-cheap; the
    quantile interpolates within the winning bucket, so resolution is the
    edge ratio (~12% at the default 64 bins over 1e-3..6e4 ms) — plenty
    for p99 dashboards, and bounded memory regardless of request count.
    NOT thread-safe on its own; :class:`MetricsRegistry` (and
    ``ServingStats``) hold the lock.
    """

    def __init__(
        self, lo_ms: float = 1e-3, hi_ms: float = 6e4, bins: int = 64
    ):
        self._lo = math.log(lo_ms)
        self._span = math.log(hi_ms) - self._lo
        self._bins = bins
        self.counts = [0] * (bins + 2)  # + underflow/overflow
        self.count = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def _edge(self, i: int) -> float:
        return math.exp(self._lo + self._span * i / self._bins)

    def bucket_index(self, ms: float) -> int:
        """The bucket a latency lands in (0 = underflow, bins+1 =
        overflow). Exposed so exemplar rings (obs/exemplars.py) attach
        trace ids to exactly the bucket this histogram counted."""
        if ms <= 0:
            return 0
        f = (math.log(ms) - self._lo) / self._span
        return min(max(int(f * self._bins) + 1, 0), self._bins + 1)

    def bucket_le(self, i: int) -> float:
        """Inclusive upper edge of bucket ``i`` (``inf`` for overflow) —
        the Prometheus-style ``le`` label exemplar lookups key on."""
        if i <= 0:
            return self._edge(0)
        if i >= self._bins + 1:
            return math.inf
        return self._edge(i)

    def record(self, ms: float) -> None:
        self.count += 1
        self.sum_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms
        self.counts[self.bucket_index(ms)] += 1

    def quantile(self, q: float) -> float:
        """q in [0, 1] -> latency in ms (0.0 when empty)."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for b, c in enumerate(self.counts):
            seen += c
            if seen >= target and c > 0:
                if b == 0:
                    return self._edge(0)
                if b == self._bins + 1:
                    return self.max_ms
                # geometric midpoint of the winning bucket
                return math.sqrt(self._edge(b - 1) * self._edge(b))
        return self.max_ms

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "mean_ms": self.sum_ms / self.count if self.count else 0.0,
            "p50_ms": round(self.quantile(0.50), 4),
            "p95_ms": round(self.quantile(0.95), 4),
            "p99_ms": round(self.quantile(0.99), 4),
            "max_ms": round(self.max_ms, 4),
        }


class MetricsRegistry:
    """Thread-safe name -> instrument map.

    Names are dotted paths (``game.solver_iterations``,
    ``io.checkpoint.bytes_written``) — see docs/OBSERVABILITY.md for the
    taxonomy. Re-requesting a name returns the SAME instrument;
    re-requesting it as a different kind raises (a silent kind change
    would split one metric across two series).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}

    def _get(self, name: str, kind, factory):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = factory()
                self._instruments[name] = inst
            elif not isinstance(inst, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested {kind.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge, Gauge)

    def histogram(self, name: str, **kwargs) -> LatencyHistogram:
        return self._get(
            name, LatencyHistogram, lambda: LatencyHistogram(**kwargs)
        )

    # -- one-line recording helpers (the common call shape) ----------------

    def inc(self, name: str, amount: float = 1.0) -> None:
        self.counter(name).inc(amount)

    def set_gauge(self, name: str, value: float) -> None:
        self.gauge(name).set(value)

    def observe(self, name: str, ms: float) -> None:
        """Record ``ms`` into histogram ``name`` (created on first use).
        Histogram recording shares the registry lock — one histogram's
        record is not thread-safe on its own."""
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = LatencyHistogram()
                self._instruments[name] = inst
            elif not isinstance(inst, LatencyHistogram):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested LatencyHistogram"
                )
            inst.record(ms)

    def names(self, prefix: str = "") -> list:
        with self._lock:
            return sorted(
                n for n in self._instruments if n.startswith(prefix)
            )

    def reset(self) -> None:
        """Drop every instrument (tests; a long-lived process keeps its
        counters for life, like Prometheus clients)."""
        with self._lock:
            self._instruments.clear()

    # -- readout ------------------------------------------------------------

    def snapshot(self) -> dict:
        """Plain-JSON view: ``{"counters": {...}, "gauges": {...},
        "histograms": {name: {count, mean_ms, p50_ms, ...}}}``."""
        with self._lock:
            items = list(self._instruments.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, inst in sorted(items):
            if isinstance(inst, Counter):
                out["counters"][name] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][name] = inst.value
            else:
                with self._lock:
                    out["histograms"][name] = inst.snapshot()
        return out

    def dump(self, path: str) -> str:
        """Atomic-enough snapshot write (write + rename would be overkill
        for an advisory artifact; a torn ``metrics.json`` is re-written by
        the next periodic dump)."""
        with open(path, "w", encoding="utf-8") as f:
            json.dump(
                {"time_unix": time.time(), **self.snapshot()}, f, indent=2
            )
        return path

    def to_prometheus(self) -> str:
        """Prometheus text exposition (v0.0.4). Dotted names sanitize to
        underscores with a ``photon_`` namespace prefix; histograms export
        summary-style quantile series plus ``_sum``/``_count``."""
        lines = []
        snap = self.snapshot()
        for name, value in snap["counters"].items():
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn} {_prom_value(value)}")
        for name, value in snap["gauges"].items():
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {_prom_value(value)}")
        for name, h in snap["histograms"].items():
            pn = _prom_name(name)
            lines.append(f"# TYPE {pn} summary")
            for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
                lines.append(
                    f'{pn}{{quantile="{q}"}} {_prom_value(h[key])}'
                )
            lines.append(f"{pn}_sum {_prom_value(h['mean_ms'] * h['count'])}")
            lines.append(f"{pn}_count {_prom_value(h['count'])}")
        return "\n".join(lines) + "\n"


_PROM_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "photon_" + _PROM_SANITIZE.sub("_", name)


def _prom_value(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


# ONE process-global default registry: training, serving, and resilience
# all record into the same namespace unless handed an explicit registry.
_default = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-global default registry."""
    return _default


def set_registry(reg: MetricsRegistry) -> MetricsRegistry:
    """Swap the process default (tests). Returns the previous one."""
    global _default
    prev = _default
    _default = reg
    return prev
