"""Unified observability: structured tracing, metrics registry, profiling.

ONE instrument threaded through every layer (docs/OBSERVABILITY.md):

- :mod:`.trace`   — nestable thread-safe spans; Chrome trace-event JSON
  (Perfetto-loadable) + structured JSONL event log; near-zero cost when
  disabled (``benchmarks/obs_overhead.py`` gates <5%).
- :mod:`.metrics` — named counters/gauges/histograms; JSON snapshots
  (``metrics.json``) and Prometheus text exposition (``cli/serve.py``).
- :mod:`.compile_events` — ``jax.monitoring`` backend-compile counter
  (promoted from ``serving/stats.py``), feeding ``xla.compiles``.

Drivers enable all of it in one place::

    with obs.observe(trace_dir=..., metrics_path=..., metrics_every=30,
                     profile_dir=...):
        ...

which installs the tracer, starts a periodic registry dumper, and opens a
``jax.profiler`` capture window; everything tears down (final metrics
dump, trace export) on exit. Hot paths call ``obs.span(...)`` /
``obs.emit_event(...)`` / ``obs.registry()`` unconditionally — disabled
mode costs one global read.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

from photon_ml_tpu.obs import collectives
from photon_ml_tpu.obs import convergence
from photon_ml_tpu.obs import dist
from photon_ml_tpu.obs import exemplars
from photon_ml_tpu.obs import quality
from photon_ml_tpu.obs import reqtrace
from photon_ml_tpu.obs import sketches
from photon_ml_tpu.obs import taxonomy
from photon_ml_tpu.obs.exemplars import (
    ExemplarStore,
    install_store as install_exemplar_store,
    set_store as set_exemplar_store,
    store as exemplar_store,
)
from photon_ml_tpu.obs.reqtrace import (
    ensure_trace_id,
    new_trace_id,
    reconstruct_timeline,
    valid_trace_id,
)
from photon_ml_tpu.obs.convergence import (
    ConvergenceReport,
    ConvergenceTracker,
    FleetSummary,
    convergence_tracker,
    decode_result,
    fleet_summary,
    install_convergence_tracker,
    uninstall_convergence_tracker,
)
from photon_ml_tpu.obs.collectives import (
    collective_span,
    note_traced_collective,
    record_collective,
)
from photon_ml_tpu.obs.compile_events import (
    install_compile_listener,
    xla_compile_events,
)
from photon_ml_tpu.obs.dispatch_count import (
    DispatchCounts,
    count_dispatches,
)
from photon_ml_tpu.obs.device import (
    HbmSampler,
    HbmWatermark,
    hbm_supported,
    hbm_watermark,
    read_memory_stats,
    sample_hbm,
)
from photon_ml_tpu.obs.metrics import (
    Counter,
    Gauge,
    LatencyHistogram,
    MetricsRegistry,
    registry,
    set_registry,
)
from photon_ml_tpu.obs.dist import (
    emit_clock_sync,
    host_metric_prefix,
    merge_trace_shards,
    process_identity,
    set_process_identity,
)
from photon_ml_tpu.obs.flight import (
    FlightRecorder,
    flight_dump,
    flight_recorder,
    install_flight_recorder,
    uninstall_flight_recorder,
)
from photon_ml_tpu.obs.quality import (
    BaselineFingerprint,
    DriftMonitor,
    OnlineQuality,
    fingerprint_collector,
    install_fingerprint_collector,
    try_load_fingerprint,
    uninstall_fingerprint_collector,
)
from photon_ml_tpu.obs.sketches import (
    HistogramSketch,
    MomentSketch,
    TopKSketch,
)
from photon_ml_tpu.obs.trace import (
    Span,
    Tracer,
    current_span_context,
    emit_event,
    get_tracer,
    set_tracer,
    span,
    span_context,
    trace,
)
from photon_ml_tpu.obs.xla_cost import (
    CostBook,
    CostRecord,
    annotate_span,
    cost_book,
    count_collectives,
    set_cost_book,
)

__all__ = [
    "Counter",
    "Gauge",
    "LatencyHistogram",
    "MetricsRegistry",
    "registry",
    "set_registry",
    "Span",
    "Tracer",
    "emit_event",
    "get_tracer",
    "set_tracer",
    "span",
    "trace",
    "install_compile_listener",
    "xla_compile_events",
    "CostBook",
    "CostRecord",
    "annotate_span",
    "cost_book",
    "count_collectives",
    "set_cost_book",
    "HbmSampler",
    "HbmWatermark",
    "hbm_supported",
    "hbm_watermark",
    "read_memory_stats",
    "sample_hbm",
    "MetricsDumper",
    "observe",
    # name taxonomy registry (obs.taxonomy; photon-lint PL006)
    "taxonomy",
    # distributed observability (obs.dist)
    "dist",
    "emit_clock_sync",
    "host_metric_prefix",
    "merge_trace_shards",
    "process_identity",
    "set_process_identity",
    # collective profiler (obs.collectives)
    "collectives",
    "collective_span",
    "note_traced_collective",
    "record_collective",
    # flight recorder (obs.flight)
    "FlightRecorder",
    "flight_dump",
    "flight_recorder",
    "install_flight_recorder",
    "uninstall_flight_recorder",
    # ambient span context
    "span_context",
    "current_span_context",
    # convergence-health layer (obs.convergence)
    "convergence",
    "ConvergenceReport",
    "ConvergenceTracker",
    "FleetSummary",
    "convergence_tracker",
    "decode_result",
    "fleet_summary",
    "install_convergence_tracker",
    "uninstall_convergence_tracker",
    # executable-dispatch counting (obs.dispatch_count)
    "DispatchCounts",
    "count_dispatches",
    # model/data-quality layer (obs.sketches, obs.quality)
    "sketches",
    "quality",
    "MomentSketch",
    "HistogramSketch",
    "TopKSketch",
    "BaselineFingerprint",
    "DriftMonitor",
    "OnlineQuality",
    "fingerprint_collector",
    "install_fingerprint_collector",
    "uninstall_fingerprint_collector",
    "try_load_fingerprint",
    # request-trace propagation + reconstruction (obs.reqtrace)
    "reqtrace",
    "ensure_trace_id",
    "new_trace_id",
    "reconstruct_timeline",
    "valid_trace_id",
    # tail-based exemplar sampling (obs.exemplars)
    "exemplars",
    "ExemplarStore",
    "exemplar_store",
    "install_exemplar_store",
    "set_exemplar_store",
]


class MetricsDumper:
    """Background thread writing periodic registry snapshots to a JSON
    file (the ``--metrics-every`` surface). Daemonized and event-driven so
    ``stop()`` returns promptly instead of waiting out the interval; a
    final dump on stop means the file always reflects the completed run.
    """

    def __init__(
        self,
        path: str,
        every_s: float,
        reg: Optional[MetricsRegistry] = None,
    ):
        self.path = path
        self.every_s = every_s
        self._registry = reg if reg is not None else registry()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _run(self) -> None:
        while not self._stop.wait(self.every_s):
            try:
                self._registry.dump(self.path)
            except OSError:
                pass  # a full disk must not kill the training loop

    def start(self) -> "MetricsDumper":
        if self.every_s > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="obs-metrics-dumper", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._registry.dump(self.path)


@contextlib.contextmanager
def observe(
    trace_dir: Optional[str] = None,
    metrics_path: Optional[str] = None,
    metrics_every: float = 0.0,
    profile_dir: Optional[str] = None,
    hbm_every_s: float = 0.5,
    process_name: str = "photon_ml_tpu",
    flight_dir: Optional[str] = None,
    flight_records: int = 2048,
):
    """Driver-level enable-everything context.

    - ``trace_dir``: install the span tracer; ``trace.json`` +
      ``events.jsonl`` land there on exit. Also installs the compile
      listener so recompiles show up in the timeline and registry, and —
      on platforms whose devices report ``memory_stats()`` — a live HBM
      sampler emitting counter tracks every ``hbm_every_s`` seconds
      (0 disables; unsupported platforms cost one probe). A
      ``clock.sync`` event anchors the shard for pod-level merging
      (``photon-obs merge``; obs.dist).
    - ``metrics_path`` (+ ``metrics_every`` seconds): periodic default-
      registry snapshots; a final snapshot is always written on exit.
      With only ``trace_dir`` set, ``metrics.json`` defaults into it.
    - ``profile_dir``: a ``jax.profiler`` capture window around the block
      (TensorBoard/Perfetto-loadable device profile — the deep tool under
      the span timeline).
    - ``flight_dir``/``flight_records``: install a crash flight recorder
      (obs.flight) holding the last ``flight_records`` observations;
      ``flight-<reason>.json`` dumps land in ``flight_dir`` (default:
      ``trace_dir``). With ``flight_dir`` set but no ``trace_dir``, a
      ring-only tracer is installed so spans still feed the recorder
      without accumulating an unbounded trace. ``flight_records=0``
      disables.

    All-None is a no-op: drivers wrap their body unconditionally and let
    flags decide.
    """
    if metrics_path is None and trace_dir is not None:
        metrics_path = os.path.join(trace_dir, "metrics.json")
    dumper = None
    hbm = None
    flight = None
    installed_tracer = False
    with contextlib.ExitStack() as stack:
        if trace_dir is not None:
            install_compile_listener()
            stack.enter_context(trace(trace_dir, process_name=process_name))
            hbm = HbmSampler(hbm_every_s).start()
            installed_tracer = True
        elif flight_dir is not None and flight_records > 0:
            # ring-only tracer: spans/events route to the flight
            # recorder, nothing accumulates, nothing is written unless
            # a dump fires
            ring_tracer = Tracer(None, process_name=process_name,
                                 keep_events=False)
            prev = set_tracer(ring_tracer)
            stack.callback(set_tracer, prev)
            installed_tracer = True
        if (trace_dir is not None or flight_dir is not None) and (
            flight_records > 0
        ):
            flight = install_flight_recorder(
                capacity=flight_records,
                flight_dir=flight_dir if flight_dir is not None else trace_dir,
            )
            stack.callback(uninstall_flight_recorder)
        if installed_tracer:
            # anchor this shard for pod-trace merging (barrier-backed
            # sync is emitted by parallel.multihost when a pod joins)
            emit_clock_sync(sync_id="observe-start")
        if profile_dir is not None:
            import jax

            os.makedirs(profile_dir, exist_ok=True)
            stack.enter_context(jax.profiler.trace(profile_dir))
        if metrics_path is not None:
            os.makedirs(
                os.path.dirname(os.path.abspath(metrics_path)), exist_ok=True
            )
            dumper = MetricsDumper(metrics_path, metrics_every).start()
        try:
            yield
        except BaseException as e:
            # the envelope unwinds BEFORE sys.excepthook runs, so the
            # crash hook would fire with the recorder already
            # uninstalled — dump here, while the ring still holds the
            # spans leading into the crash. GeneratorExit and
            # SystemExit are deliberate exits, not crashes (signal
            # paths dump "preemption" from the GracefulShutdown
            # handler while the recorder is still installed)
            if flight is not None and not isinstance(
                e, (GeneratorExit, SystemExit)
            ):
                try:
                    flight.note(
                        {
                            "kind": "event",
                            "name": "crash",
                            "exception": f"{type(e).__name__}: {e}",
                        }
                    )
                    flight.dump("crash")
                except Exception:
                    pass
            raise
        finally:
            if flight is not None:
                flight.sample_metrics()
            if hbm is not None:
                hbm.stop()
            if dumper is not None:
                dumper.stop()
