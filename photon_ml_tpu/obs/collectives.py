"""Collective profiler: per-reduction timing, bytes, and counts.

BENCH_r05's ``sparse_fs_scaling`` regressed at 2 devices (3.08s -> 5.32s)
and reached only 1.2x at 8 — and nothing in the tree could say WHERE the
collective time went: the cost book counts all-reduce instructions per
executable, but no artifact carried per-reduction wall time, payload
bytes, or how either scales with mesh width. This module is that
instrument. Three recording surfaces, all landing in the metrics
registry under one taxonomy (docs/OBSERVABILITY.md):

- ``collective.<name>.w<W>.count``   — executions (counter)
- ``collective.<name>.w<W>.bytes``   — cumulative payload bytes (counter)
- ``collective.<name>.w<W>.wall_ms`` — blocked wall per execution
  (histogram), present only for host-observable collectives

keyed by reduction name and mesh width ``W``, so the 1-vs-2-vs-8-device
story is a metric query, not a rerun.

:func:`record_collective` is the primitive; :func:`collective_span`
brackets a host-level dispatch (a ``process_allgather``, an eager
shard-mapped psum) with a span + the metrics. In-program collectives —
psums the XLA partitioner fuses into a jitted solve — have no
per-execution host seam, so they report through
:func:`note_traced_collective`: called at TRACE time from the op that
builds the reduction (``ops.sparse.matvec_and_feature_dots``), it records
the payload geometry under ``collective.traced.<name>.w<W>.*`` once per
compilation; callers that know their pass counts (bench.py) multiply.
Wall time for those lives at the dispatch granularity: ``bench.py``
times a blocked objective-pass execution per mesh width and records it
as the ``collective.sparse.objective_pass.w<W>.wall_ms`` proxy.

Everything here is registry writes — cheap, lock-guarded, and always on
(no tracer required): collective telemetry is exactly what you need from
the runs you did not think to trace.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

from photon_ml_tpu.obs.metrics import MetricsRegistry
from photon_ml_tpu.obs.metrics import registry as _registry
from photon_ml_tpu.obs.trace import span as _span

__all__ = [
    "collective_metric_key",
    "record_collective",
    "record_collective_share",
    "collective_span",
    "note_traced_collective",
    "tree_bytes",
]


def collective_metric_key(name: str, mesh_width: int) -> str:
    """``collective.<name>.w<W>`` — the metric-name stem shared by the
    count/bytes/wall_ms series of one (reduction, mesh width) pair."""
    return f"collective.{name}.w{int(mesh_width)}"


def record_collective(
    name: str,
    mesh_width: int = 1,
    count: float = 1,
    nbytes: float = 0,
    wall_s: Optional[float] = None,
    registry: Optional[MetricsRegistry] = None,
) -> None:
    """Record one (or ``count``) executions of a collective: increments
    the count/bytes counters and, when ``wall_s`` is given, observes the
    wall histogram. The one write path every profiling surface uses."""
    reg = registry if registry is not None else _registry()
    key = collective_metric_key(name, mesh_width)
    reg.inc(f"{key}.count", count)
    if nbytes:
        reg.inc(f"{key}.bytes", float(nbytes))
    if wall_s is not None:
        reg.observe(f"{key}.wall_ms", wall_s * 1e3)


def record_collective_share(
    name: str,
    mesh_width: int,
    collective_wall_s: float,
    pass_wall_s: float,
    registry: Optional[MetricsRegistry] = None,
) -> float:
    """Record ``collective_wall_frac`` — collective wall as a share of
    the ENCLOSING pass wall — as the gauge
    ``collective.<name>.w<W>.wall_frac`` (plus the underlying wall
    histogram via :func:`record_collective`). THE direct overlap gate:
    ``scaling_efficiency`` only infers that communication hid under
    compute; this measures it, and the sentinel holds it lower-is-better
    (``obs.sentinel``), so an overlap regression fails the gate even
    when wall clocks are noisy. Clamped to [0, 1]; a degenerate pass
    wall records 0."""
    frac = 0.0
    if pass_wall_s > 0:
        frac = min(max(collective_wall_s / pass_wall_s, 0.0), 1.0)
    reg = registry if registry is not None else _registry()
    key = collective_metric_key(name, mesh_width)
    reg.set_gauge(f"{key}.wall_frac", round(frac, 6))
    record_collective(
        name,
        mesh_width=mesh_width,
        wall_s=max(collective_wall_s, 0.0),
        registry=reg,
    )
    return frac


@contextlib.contextmanager
def collective_span(
    name: str,
    mesh_width: int = 1,
    nbytes: float = 0,
    registry: Optional[MetricsRegistry] = None,
):
    """Bracket a HOST-OBSERVABLE collective (the call blocks until the
    exchange completes — ``process_allgather``, an eager shard_map psum)
    with a ``collective.<name>`` span and the count/bytes/wall metrics.
    The caller must actually block inside the body; an async dispatch
    would time the enqueue, not the exchange."""
    t0 = time.perf_counter()
    with _span(
        f"collective.{name}",
        cat="collective",
        mesh_width=int(mesh_width),
        bytes=float(nbytes),
    ):
        yield
    record_collective(
        name,
        mesh_width=mesh_width,
        nbytes=nbytes,
        wall_s=time.perf_counter() - t0,
        registry=registry,
    )


def note_traced_collective(
    name: str,
    mesh_width: int,
    nbytes: float,
    registry: Optional[MetricsRegistry] = None,
) -> None:
    """Trace-time note for an IN-PROGRAM collective: the op building a
    sharded reduction calls this while tracing, recording the payload
    geometry under ``collective.traced.<name>.w<W>.{count,bytes}`` —
    once per compilation, zero runtime cost in the compiled program.
    ``nbytes`` is the per-execution payload (what one all-reduce of the
    built array moves per device)."""
    record_collective(
        f"traced.{name}",
        mesh_width=mesh_width,
        nbytes=nbytes,
        registry=registry,
    )


def tree_bytes(tree) -> int:
    """Total buffer bytes across a pytree of arrays (payload-size helper
    for :func:`collective_span` callers). Leaves without ``nbytes``/
    ``size`` contribute 0."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is None:
            size = getattr(leaf, "size", None)
            itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
            nb = size * itemsize if size and itemsize else 0
        total += int(nb)
    return total
